"""Metrics collection (result JSON → CSV) and phase tracing."""

from skyline_tpu.metrics.collector import CSV_HEADERS, append_result_row, collect

__all__ = ["CSV_HEADERS", "append_result_row", "collect"]
