"""Reference-parity metrics: result-JSON → CSV collector and phase tracing.

The distribution/trace side of observability (histograms, per-query spans,
Prometheus exposition) lives in ``skyline_tpu.telemetry``, which absorbs
and extends this package; what stays here is the reference-parity surface:
the CSV collector (10-column schema), ``Counters``, the phase-total
``Tracer``, and the /stats HTTP server (``httpstats``).
"""

from skyline_tpu.metrics.collector import (
    CSV_HEADERS,
    Counters,
    append_result_row,
    collect,
)

__all__ = ["CSV_HEADERS", "Counters", "append_result_row", "collect"]
