"""Per-phase tracing: device/host/transfer time accounting.

The reference's tracing is manual nanoTime deltas around BNL work plus the
aggregator's ingestion/local/global decomposition, surfaced as a product
feature in the result JSON (SURVEY.md §5). This module generalizes that into
named phase timers the engine/worker/bench can nest, with the same
"breakdown is a feature" stance: ``report()`` returns totals suitable for
logging or embedding in results.

Device timing caveat: JAX dispatch is async; a phase that should count
device time must close over ``block_until_ready`` (use ``device_phase``) or
the time lands in whichever phase later forces the sync.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager


class Tracer:
    """Named phase timers.

    ``sync_device=True`` (default) makes ``device_phase`` block on its
    arrays, attributing async device work to the phase that launched it —
    the honest-profiling mode. ``sync_device=False`` records dispatch wall
    only, leaving the device pipeline undisturbed (device time then lands
    in whichever later phase forces the sync, e.g. the snapshot transfer).
    """

    def __init__(self, sync_device: bool = True):
        self.sync_device = sync_device
        self._total_ns: dict[str, int] = defaultdict(int)
        self._count: dict[str, int] = defaultdict(int)
        self._stack: list[str] = []

    @contextmanager
    def phase(self, name: str):
        """Accumulate host wall time under ``name`` (exclusive of nothing —
        nested phases overlap their parents by design, like the reference's
        ingestion = wall - local arithmetic)."""
        t0 = time.perf_counter_ns()
        self._stack.append(name)
        try:
            yield
        finally:
            self._stack.pop()
            self._total_ns[name] += time.perf_counter_ns() - t0
            self._count[name] += 1

    @contextmanager
    def device_phase(self, name: str, *arrays_to_sync):
        """Like ``phase`` but blocks on the given jax arrays before closing,
        so async-dispatched device work is attributed here."""
        import jax

        t0 = time.perf_counter_ns()
        self._stack.append(name)
        try:
            yield
        finally:
            self._stack.pop()
            if arrays_to_sync and self.sync_device:
                jax.block_until_ready(arrays_to_sync)
            self._total_ns[name] += time.perf_counter_ns() - t0
            self._count[name] += 1

    def add_ns(self, name: str, ns: int) -> None:
        self._total_ns[name] += ns
        self._count[name] += 1

    def report(self) -> dict[str, dict[str, float]]:
        return {
            name: {
                "total_ms": self._total_ns[name] / 1e6,
                "count": self._count[name],
                "mean_ms": self._total_ns[name] / 1e6 / max(1, self._count[name]),
            }
            for name in sorted(self._total_ns)
        }

    def reset(self) -> None:
        self._total_ns.clear()
        self._count.clear()


class _NullTracer:
    """Zero-overhead stand-in so hot paths can call ``tracer.phase(...)``
    unconditionally; ``SkylineEngine``/``PartitionSet`` default to this."""

    sync_device = False

    @contextmanager
    def phase(self, name: str):
        yield

    @contextmanager
    def device_phase(self, name: str, *arrays_to_sync):
        yield

    def add_ns(self, name: str, ns: int) -> None:
        pass

    def report(self) -> dict:
        return {}

    def reset(self) -> None:
        pass


NULL_TRACER = _NullTracer()
