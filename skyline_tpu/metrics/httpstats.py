"""Live observability endpoint — the Flink Web UI role, minimally.

The reference operator watches Flink's Web UI on :8081
(/root/reference/docker-setup/docker-compose.yml:26) while a job runs. The
TPU worker's equivalent surface is ``SkylineEngine.stats()`` — this module
serves it (plus any caller-supplied counters) as JSON over a stdlib
``http.server`` thread, so ``curl localhost:<port>/stats`` works during a
``deploy/launch.py`` run.

Endpoints:
  GET /stats    full stats JSON (engine counters, partitions, worker I/O)
  GET /healthz  {"ok": true} once serving — readiness probe for supervisors
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class StatsServer:
    """Background JSON stats server.

    ``callback`` is invoked per /stats request and must return a
    JSON-serializable dict; exceptions become a 500 with the error message
    (the server never takes the worker down).
    """

    def __init__(self, callback, port: int, host: str = "127.0.0.1"):
        self._callback = callback

        class Handler(BaseHTTPRequestHandler):
            def do_GET(handler):  # noqa: N805 — http.server API
                if handler.path == "/healthz":
                    handler._reply(200, {"ok": True})
                elif handler.path in ("/", "/stats"):
                    try:
                        handler._reply(200, callback())
                    except Exception as e:  # pragma: no cover - defensive
                        handler._reply(500, {"error": str(e)})
                else:
                    handler._reply(404, {"error": "not found"})

            def _reply(handler, code: int, doc: dict):
                body = json.dumps(doc).encode()
                handler.send_response(code)
                handler.send_header("Content-Type", "application/json")
                handler.send_header("Content-Length", str(len(body)))
                handler.end_headers()
                handler.wfile.write(body)

            def log_message(handler, *args):  # silence per-request stderr
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]  # resolved when port=0
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
