"""Live observability endpoint — the Flink Web UI role.

The reference operator watches Flink's Web UI on :8081
(/root/reference/docker-setup/docker-compose.yml:26) while a job runs. The
TPU worker's equivalent surface is ``SkylineEngine.stats()`` — this module
serves it (plus any caller-supplied counters) as JSON over a stdlib
``http.server`` thread, plus a self-contained human-facing dashboard, so
both ``curl localhost:<port>/stats`` and a browser on the root URL work
during a ``deploy/launch.py`` run.

Endpoints:
  GET /         human dashboard (single self-contained HTML page polling
                /stats — headline counters, serve-plane counters, p50/p99
                latency tiles + per-partition load bars; the Flink-Web-UI
                role for an operator's browser)
  GET /stats    full stats JSON (engine counters, partitions, worker I/O,
                serve counters, latency histogram summaries)
  GET /metrics  Prometheus text exposition (stats flattened to gauges +
                telemetry counters/histograms), for a standard scraper
  GET /trace    Chrome trace-event JSON of the telemetry span ring
                (load at https://ui.perfetto.dev)
  GET /profile  per-compiled-kernel dispatch registry (wall-time EMA,
                compile-time canary, optional AOT cost_analysis figures)
  GET /slo      declarative SLO table with multi-window burn rates
  GET /debug/flight  bounded flight-recorder ring of dispatch decisions
  GET /explain  one per-query EXPLAIN plan from the hub ring
                (?version=N | ?trace_id=... | latest)
  GET /audit    audit-plane verdict: shadow-verification totals, canary
                path coverage, divergence bundles
                (?trace_id=... for one check record)
  GET /fleet    per-chip fleet join: ingest/flush/merge loads per chip,
                imbalance index + skew score, freshness watermark, last
                EXPLAIN chip attribution (sharded workers; a flat worker
                reports {"enabled": false})
  GET /health   chip-health block (RUNBOOK §2p): per-chip score/status +
                quarantine state (flat workers report {"enabled": false})
  GET /cluster  cluster block (RUNBOOK §2r): lease/role state, fenced
                writes, promotions, per-host ingest/merge/prune stats
                (non-cluster workers report {"enabled": false})
  GET /ops      durable cross-process ops journal (RUNBOOK §2s): every
                control-plane transition, merged across writers
                (?since_seq=N per-writer floor, ?limit=N newest records;
                workers without a journal report {"enabled": false})
  GET /cluster/overview  fleet-wide aggregation (RUNBOOK §2s): every
                member's role/epoch/fence/head + replication lag + the
                epoch-agreement (split-brain) findings; members come from
                an attached ClusterView or $SKYLINE_CLUSTERVIEW_MEMBERS
  GET /healthz  {"ok": true} once serving — readiness probe for supervisors
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

_DASHBOARD = """<!doctype html>
<html><head><meta charset="utf-8"><title>tpu-skyline worker</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;background:#14171c;color:#e6e6e6}
 h1{font-size:1.2rem;font-weight:600} h2{font-size:.8rem;color:#8a93a3;
 text-transform:uppercase;letter-spacing:.05em;margin:1.2rem 0 .3rem}
 .muted{color:#8a93a3}
 .tiles{display:flex;gap:1rem;flex-wrap:wrap;margin:.4rem 0}
 .tile{background:#1e232b;border-radius:8px;padding:.8rem 1.1rem;min-width:9rem}
 .tile .v{font-size:1.5rem;font-variant-numeric:tabular-nums}
 .tile .s{font-size:.85rem;color:#b9c2d0;font-variant-numeric:tabular-nums}
 .tile .k{font-size:.75rem;color:#8a93a3;text-transform:uppercase;letter-spacing:.05em}
 table{border-collapse:collapse;margin-top:.6rem;font-variant-numeric:tabular-nums}
 td,th{padding:.25rem .7rem;text-align:right;font-size:.85rem}
 th{color:#8a93a3;font-weight:500} td:first-child,th:first-child{text-align:left}
 .bar{height:.55rem;border-radius:3px;background:#3fb68b;min-width:2px;display:inline-block}
 #err{color:#e07676}
</style></head><body>
<h1>tpu-skyline worker <span class="muted" id="ts"></span></h1>
<div class="tiles" id="tiles"></div>
<div id="serveblock" style="display:none"><h2>serving plane</h2>
<div class="tiles" id="servetiles"></div></div>
<div id="latblock" style="display:none"><h2>latency (p50 / p99 ms)</h2>
<div class="tiles" id="lattiles"></div></div>
<table id="parts"></table>
<div id="err"></div>
<script>
const fmt = n => typeof n === "number" ? n.toLocaleString("en-US") : n;
async function tick() {
  try {
    const resp = await fetch("/stats");
    const s = await resp.json();
    if (!resp.ok || s.error) throw new Error(s.error || resp.status);
    document.getElementById("err").textContent = "";
    document.getElementById("ts").textContent = new Date().toLocaleTimeString();
    const tiles = [
      ["records in", s.records_in], ["results", s.results_emitted],
      ["in-flight queries", s.inflight_queries],
      ["pending rows", s.pending_flush_rows],
      ["dropped", s.dropped], ["prefiltered", s.prefiltered],
      ["device ms", s.processing_ms && Math.round(s.processing_ms)],
      ["meshed", s.meshed],
      ["slides closed", s.slides_closed],
      ["merge cache hits", s.merge_cache && s.merge_cache.hits],
      ["merge cache misses", s.merge_cache && s.merge_cache.misses],
      ["delta merges", s.merge_cache && s.merge_cache.delta_merges],
      ["dirty fraction", s.merge_cache && s.merge_cache.last_dirty_fraction],
      ["prefilter dropped", s.flush_cascade && s.flush_cascade.prefilter_dropped],
      ["prefilter drop frac", s.flush_cascade && s.flush_cascade.prefilter_drop_fraction],
      ["bf16 resolved", s.flush_cascade && s.flush_cascade.bf16_resolved],
    ].filter(([, v]) => v !== undefined && v !== null);
    document.getElementById("tiles").innerHTML = tiles.map(
      ([k, v]) => `<div class="tile"><div class="v">${fmt(v)}</div><div class="k">${k}</div></div>`
    ).join("");
    const sv = s.serve, st = s.snapshot_store;
    const serveTiles = sv === undefined ? [] : [
      ["reads served", sv.reads_served || 0],
      ["reads shed (429)", sv.reads_shed || 0],
      ["stale rejected (503)", sv.stale_rejected || 0],
      ["delta re-baselines (410)", sv.deltas_gone || 0],
      ["queries shed (429)", sv.queries_shed || 0],
      ["read-cache hits", sv.read_cache_hits],
      ["snapshot version", st && st.head_version],
      ["version lag", st && st.version_lag],
      ["publishes deduped", st && st.deduped],
    ].filter(([, v]) => v !== undefined);
    document.getElementById("serveblock").style.display =
      serveTiles.length ? "" : "none";
    document.getElementById("servetiles").innerHTML = serveTiles.map(
      ([k, v]) => `<div class="tile"><div class="v">${fmt(v)}</div><div class="k">${k}</div></div>`
    ).join("");
    const lat = s.latency_ms || {};
    const latTiles = Object.entries(lat).filter(([, h]) => h.count > 0).map(
      ([name, h]) =>
        `<div class="tile"><div class="s">${fmt(h.p50)} / ${fmt(h.p99)}</div>` +
        `<div class="k">${name} (n=${fmt(h.count)})</div></div>`
    );
    document.getElementById("latblock").style.display =
      latTiles.length ? "" : "none";
    document.getElementById("lattiles").innerHTML = latTiles.join("");
    const p = s.partitions || {};
    const seen = p.records_seen || [], ids = p.max_seen_id || [],
          sky = p.skyline_counts;
    const mx = Math.max(1, ...seen);
    let rows = `<tr><th>partition</th><th>records</th><th style="text-align:left">load</th><th>max id</th>${sky ? "<th>skyline</th>" : ""}</tr>`;
    for (let i = 0; i < seen.length; i++) {
      rows += `<tr><td>p${i}</td><td>${fmt(seen[i])}</td>` +
        `<td style="text-align:left"><span class="bar" style="width:${Math.round(140 * seen[i] / mx)}px"></span></td>` +
        `<td>${fmt(ids[i])}</td>${sky ? `<td>${fmt(sky[i])}</td>` : ""}</tr>`;
    }
    document.getElementById("parts").innerHTML = rows;
  } catch (e) { document.getElementById("err").textContent = "stats fetch failed: " + e; }
}
tick(); setInterval(tick, 1000);
</script></body></html>"""


class StatsServer:
    """Background stats server: JSON (/stats, /healthz), Prometheus
    (/metrics), Chrome trace JSON (/trace) + dashboard (/).

    ``callback`` is invoked per /stats (and /metrics) request and must
    return a JSON-serializable dict; exceptions become a 500 with the error
    message (the server never takes the worker down). ``telemetry`` is an
    optional ``telemetry.Telemetry`` hub — its counters and histograms join
    the exposition and its span ring backs /trace.
    """

    def __init__(self, callback, port: int, host: str = "127.0.0.1", telemetry=None):
        self._callback = callback
        self.telemetry = telemetry
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(handler):  # noqa: N805 — http.server API
                # handler.path carries the raw query string; split it so
                # parameterized endpoints (/explain?version=) route like
                # their bare forms
                path, _, qs = handler.path.partition("?")
                if path == "/healthz":
                    handler._reply(200, {"ok": True})
                elif path == "/stats":
                    try:
                        handler._reply(200, callback())
                    except Exception as e:
                        handler._reply(500, {"error": str(e)})
                elif path == "/metrics":
                    try:
                        body, ctype = outer._render_metrics()
                        handler._reply_raw(200, body, ctype)
                    except Exception as e:
                        handler._reply(500, {"error": str(e)})
                elif path == "/trace":
                    doc = (
                        outer.telemetry.spans.to_chrome()
                        if outer.telemetry is not None
                        else {"traceEvents": []}
                    )
                    handler._reply(200, doc)
                elif path == "/profile":
                    if outer.telemetry is None:
                        handler._reply(404, {"error": "no telemetry hub"})
                    else:
                        handler._reply(200, outer.telemetry.profiler.doc())
                elif path == "/slo":
                    if outer.telemetry is None:
                        handler._reply(404, {"error": "no telemetry hub"})
                    else:
                        handler._reply(200, outer.telemetry.slo.evaluate())
                elif path == "/debug/flight":
                    if outer.telemetry is None:
                        handler._reply(404, {"error": "no telemetry hub"})
                    else:
                        handler._reply(200, outer.telemetry.flight.doc())
                elif path == "/explain":
                    if outer.telemetry is None:
                        handler._reply(404, {"error": "no telemetry hub"})
                    else:
                        code, doc = outer._explain_doc(qs)
                        handler._reply(code, doc)
                elif path == "/audit":
                    if outer.telemetry is None:
                        handler._reply(404, {"error": "no telemetry hub"})
                    else:
                        code, doc = outer._audit_doc(qs)
                        handler._reply(code, doc)
                elif path == "/dispatch":
                    # the declarative cascade table + live tuner decisions
                    # (ISSUE 20) — works even without a hub: the table is
                    # module state, only the tuner block needs telemetry
                    from skyline_tpu.telemetry.tuner import dispatch_doc

                    handler._reply(200, dispatch_doc(outer.telemetry))
                elif path == "/fleet":
                    if outer.telemetry is None:
                        handler._reply(404, {"error": "no telemetry hub"})
                    else:
                        try:
                            handler._reply(200, outer._fleet_doc())
                        except Exception as e:
                            handler._reply(500, {"error": str(e)})
                elif path == "/health":
                    handler._reply(200, outer._health_doc())
                elif path == "/cluster":
                    try:
                        handler._reply(200, outer._cluster_doc())
                    except Exception as e:
                        handler._reply(500, {"error": str(e)})
                elif path == "/ops":
                    code, doc = outer._ops_doc(qs)
                    handler._reply(code, doc)
                elif path == "/cluster/overview":
                    try:
                        handler._reply(200, outer._overview_doc())
                    except Exception as e:
                        handler._reply(500, {"error": str(e)})
                elif path in ("/", "/ui"):
                    handler._reply_raw(
                        200, _DASHBOARD.encode(), "text/html; charset=utf-8"
                    )
                else:
                    handler._reply(404, {"error": "not found"})

            def _reply(handler, code: int, doc: dict):
                handler._reply_raw(
                    code, json.dumps(doc).encode(), "application/json"
                )

            def _reply_raw(handler, code: int, body: bytes, ctype: str):
                handler.send_response(code)
                handler.send_header("Content-Type", ctype)
                handler.send_header("Content-Length", str(len(body)))
                handler.end_headers()
                handler.wfile.write(body)

            def log_message(handler, *args):  # silence per-request stderr
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]  # resolved when port=0
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def _explain_doc(self, qs: str) -> tuple[int, dict]:
        """Resolve an /explain request against the hub's plan ring:
        ``version=N`` → newest plan published under snapshot version N,
        ``trace_id=...`` → span/flight join, neither → latest plan."""
        params = {k: v[-1] for k, v in parse_qs(qs).items()}
        rec = self.telemetry.explain
        version = params.get("version")
        if version is not None:
            try:
                version = int(version)
            except ValueError:
                return 400, {"error": f"bad version {version!r}"}
            plan = rec.by_version(version)
        elif params.get("trace_id"):
            plan = rec.by_trace(params["trace_id"])
        else:
            plan = rec.latest()
        if plan is None:
            return 404, {"error": "no matching plan", "ring": rec.doc()}
        return 200, plan

    def _audit_doc(self, qs: str) -> tuple[int, dict]:
        """Resolve an /audit request against the hub's verdict ring:
        ``trace_id=...`` → the check record for that snapshot's trace
        (the /explain and /trace join), no params → the full verdict."""
        params = {k: v[-1] for k, v in parse_qs(qs).items()}
        rec = self.telemetry.audit
        if params.get("trace_id"):
            check = rec.by_trace(params["trace_id"])
            if check is None:
                return 404, {"error": "no matching check", "ring": rec.doc()}
            return 200, check
        return 200, rec.doc()

    def _fleet_doc(self) -> dict:
        """The /fleet join: per-chip stats + freshness watermark + last
        EXPLAIN chip attribution (telemetry/fleet.py)."""
        from skyline_tpu.telemetry import fleet_doc

        return fleet_doc(self.telemetry, self._callback())

    def _health_doc(self) -> dict:
        """The /health chip block (RUNBOOK §2p): per-chip health scores +
        quarantine state. Probe-friendly on flat workers — ``enabled`` is
        false and the chip list is absent when no ChipHealth is attached."""
        health = (
            getattr(self.telemetry, "health", None)
            if self.telemetry is not None
            else None
        )
        if health is None:
            return {"ok": True, "enabled": False}
        doc = health.doc()
        doc["ok"] = not doc.get("quarantined")
        doc["enabled"] = True
        return doc

    def _cluster_doc(self) -> dict:
        """The /cluster block (RUNBOOK §2r): lease/role state + per-host
        ingest/merge/prune stats. Probe-friendly on non-cluster workers —
        ``enabled`` is false when no ClusterStatus is attached."""
        status = (
            getattr(self.telemetry, "cluster", None)
            if self.telemetry is not None
            else None
        )
        if status is None:
            return {"ok": True, "enabled": False}
        return status.doc()

    def _ops_doc(self, qs: str) -> tuple[int, dict]:
        """The /ops journal tail (RUNBOOK §2s): the merged cross-process
        timeline from the hub's attached OpsLog. Probe-friendly —
        ``enabled`` is false when this process opened no journal."""
        from skyline_tpu.telemetry.opslog import ops_doc

        params = {k: v[-1] for k, v in parse_qs(qs).items()}
        try:
            since = (
                int(params["since_seq"]) if "since_seq" in params else None
            )
            limit = int(params["limit"]) if "limit" in params else None
        except ValueError:
            return 400, {"error": "since_seq/limit must be integers"}
        ops = (
            getattr(self.telemetry, "opslog", None)
            if self.telemetry is not None
            else None
        )
        if ops is None:
            return 200, {"ok": True, "enabled": False}
        return 200, ops_doc(ops.wal_dir, since_seq=since, limit=limit)

    def _overview_doc(self) -> dict:
        """The /cluster/overview fleet aggregation (RUNBOOK §2s)."""
        from skyline_tpu.telemetry.clusterview import overview_doc

        return overview_doc(self.telemetry)

    def _render_metrics(self) -> tuple[bytes, str]:
        """Prometheus text: the stats dict flattened to gauges, plus the
        telemetry hub's counters and histograms when attached."""
        from skyline_tpu.telemetry import (
            PROMETHEUS_CONTENT_TYPE,
            flatten_gauges,
            render_prometheus,
        )

        stats = self._callback()
        # latency summaries are already exposed as real histogram series
        # below; don't double-flatten their p50/p99 into gauges
        gauges = flatten_gauges(
            {k: v for k, v in stats.items() if k != "latency_ms"}
        )
        if self.telemetry is not None:
            body = self.telemetry.render_prometheus(gauges=gauges)
        else:
            body = render_prometheus(gauges=gauges)
        return body.encode(), PROMETHEUS_CONTENT_TYPE

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
