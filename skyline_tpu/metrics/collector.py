"""Result-JSON → CSV collector.

Parity with python/metrics_collector.py: same 10-column schema (:60-71), one
row per completed query, per-row flush (:123). The ``Latency(ms)`` column is
populated for real here because the engine actually emits
``query_latency_ms`` (the reference computes it at FlinkSkyline.java:588 but
omits it from the JSON, so the reference's column is always 0 — SURVEY.md
§3.5).

Usable as a library (``append_result_row``) against any bus, or as a CLI
(``python -m skyline_tpu.metrics.collector out.csv``) against Kafka or a
JSON-lines file/stdin.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys
import threading


class Counters:
    """Thread-safe named monotonic counters — the serving plane's metric
    surface (shed / queue-depth / staleness counts, ``serve/admission.py``),
    snapshotted into ``/stats`` and the bench artifact. Deliberately tiny:
    ``inc`` on hot paths is one lock + one dict add."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}  # guarded-by: self._lock

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._counts)

CSV_HEADERS = [
    "QueryID",
    "Records",
    "SkylineSize",
    "Optimality",
    "IngestTime(ms)",
    "LocalTime(ms)",
    "GlobalTime(ms)",
    "TotalTime(ms)",
    "Latency(ms)",
    "SkylinePoints",
]

# guards the isfile-check-then-write in append_result_row: two concurrent
# writers (collector CLI + an embedded worker, or two worker threads) could
# both see "no file" and both write the header
_append_lock = threading.Lock()


def result_to_row(data: dict) -> list:
    row = [
        data.get("query_id", "N/A"),
        data.get("record_count", 0),
        data.get("skyline_size", 0),
        data.get("optimality", 0.0),
        data.get("ingestion_time_ms", 0),
        data.get("local_processing_time_ms", 0),
        data.get("global_processing_time_ms", 0),
        data.get("total_processing_time_ms", 0),
        data.get("query_latency_ms", 0),
        json.dumps(data.get("skyline_points", [])),
    ]
    # trace_id (telemetry plane) rides as a trailing column ONLY when the
    # result carries one, so reference-parity consumers of the 10-column
    # schema see byte-identical output for untraced streams
    if "trace_id" in data:
        row.append(data["trace_id"])
    return row


def append_result_row(path: str, data: dict) -> None:
    """Append one result to a CSV file, writing the header on first touch."""
    with _append_lock:
        exists = os.path.isfile(path)
        with open(path, mode="a", newline="") as f:
            w = csv.writer(f)
            if not exists:
                headers = (
                    CSV_HEADERS + ["TraceID"]
                    if "trace_id" in data
                    else CSV_HEADERS
                )
                w.writerow(headers)
            w.writerow(result_to_row(data))
            f.flush()


def collect(messages, path: str, echo: bool = True) -> int:
    """Drain an iterable of result-JSON strings (or dicts) into the CSV."""
    n = 0
    for m in messages:
        data = json.loads(m) if isinstance(m, str) else m
        append_result_row(path, data)
        if echo:
            print(
                f"[Query {data.get('query_id')}] Records: {data.get('record_count')} "
                f"| Size: {data.get('skyline_size')} "
                f"| TotalTime: {data.get('total_processing_time_ms')}ms"
            )
        n += 1
    return n


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("output_csv")
    ap.add_argument("--source", choices=["kafka", "stdin"], default="kafka")
    ap.add_argument("--topic", default="output-skyline")
    ap.add_argument("--bootstrap", default="localhost:9092")
    args = ap.parse_args(argv)

    if args.source == "stdin":
        collect((ln for ln in sys.stdin if ln.strip()), args.output_csv)
        return 0

    from skyline_tpu.bridge.kafka import KafkaBus

    consumer = KafkaBus(args.bootstrap).consumer(args.topic, from_beginning=False)
    print(f"--- Listening on topic '{args.topic}' ---", file=sys.stderr)
    try:
        while True:
            batch = consumer.poll()
            if batch:
                collect(batch, args.output_csv)
    except KeyboardInterrupt:
        print("\nStopping collector...", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
