"""Admission control and backpressure for the serving plane.

Two distinct costs, two distinct limiters:

- Snapshot reads (``GET /skyline``, ``GET /deltas``) are cheap — one
  lock-free reference load — but unbounded fan-in is still unbounded
  work (JSON encoding, socket writes). A token bucket rate-limits them;
  exhaustion sheds with 429 + Retry-After computed from the refill rate.
- Forced consistency merges (``POST /query``) are the expensive path (a
  full engine merge each). A concurrency gate bounds in-flight + queued
  requests and every admitted request carries a deadline; over-bound
  requests shed immediately (429) instead of growing an invisible queue.

Shed / queue-depth / staleness counts go through
``metrics.collector.Counters`` so ``/stats`` and the bench artifact report
the same numbers.
"""

from __future__ import annotations

import threading
import time

from skyline_tpu.metrics.collector import Counters


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s, ``burst`` capacity.

    ``rate <= 0`` disables limiting (every acquire succeeds). ``try_acquire``
    returns ``(admitted, retry_after_s)`` — ``retry_after_s`` is how long
    until one token exists again, the 429 Retry-After value.
    """

    def __init__(self, rate: float, burst: int):
        self.rate = float(rate)
        self.burst = max(1, int(burst))
        self._tokens = float(self.burst)
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def try_acquire(self, n: int = 1) -> tuple[bool, float]:
        if self.rate <= 0:
            return True, 0.0
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                float(self.burst), self._tokens + (now - self._stamp) * self.rate
            )
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            return False, max(0.01, (n - self._tokens) / self.rate)


class QueryGate:
    """Concurrency limiter + bounded queue for the expensive query path.

    At most ``max_concurrent`` queries execute while up to ``max_queue``
    more wait; anything beyond that sheds immediately. ``enter`` returns
    True when admitted (caller MUST ``leave()`` when done, success or not).
    """

    def __init__(self, max_concurrent: int, max_queue: int, counters: Counters):
        self.max_concurrent = max(1, int(max_concurrent))
        self.max_queue = max(0, int(max_queue))
        self._active = 0
        self._lock = threading.Lock()
        self._counters = counters

    def enter(self) -> bool:
        with self._lock:
            if self._active >= self.max_concurrent + self.max_queue:
                self._counters.inc("queries_shed")
                return False
            self._active += 1
            self._counters.inc("queries_admitted")
            return True

    def leave(self) -> None:
        with self._lock:
            self._active = max(0, self._active - 1)

    @property
    def depth(self) -> int:
        with self._lock:
            return self._active


class AdmissionController:
    """The serving plane's policy bundle: read bucket + query gate + counters."""

    def __init__(
        self,
        read_rate: float = 0.0,  # tokens/s; 0 = unlimited
        read_burst: int = 256,
        max_concurrent_queries: int = 2,
        max_query_queue: int = 8,
        query_deadline_ms: float = 10_000.0,
        counters: Counters | None = None,
    ):
        self.counters = counters if counters is not None else Counters()
        self.reads = TokenBucket(read_rate, read_burst)
        self.queries = QueryGate(
            max_concurrent_queries, max_query_queue, self.counters
        )
        self.query_deadline_ms = float(query_deadline_ms)

    def admit_read(self) -> tuple[bool, float]:
        ok, retry = self.reads.try_acquire()
        if ok:
            self.counters.inc("reads_admitted")
        else:
            self.counters.inc("reads_shed")
        return ok, retry

    def stats(self) -> dict:
        out = self.counters.snapshot()
        out["query_depth"] = self.queries.depth
        out["query_deadline_ms"] = self.query_deadline_ms
        return out
