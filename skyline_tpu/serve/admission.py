"""Admission control and backpressure for the serving plane.

Two distinct costs, two distinct limiters:

- Snapshot reads (``GET /skyline``, ``GET /deltas``) are cheap — one
  lock-free reference load — but unbounded fan-in is still unbounded
  work (JSON encoding, socket writes). A token bucket rate-limits them;
  exhaustion sheds with 429 + Retry-After computed from the refill rate.
- Forced consistency merges (``POST /query``) are the expensive path (a
  full engine merge each). A concurrency gate bounds in-flight + queued
  requests and every admitted request carries a deadline; over-bound
  requests shed immediately (429) instead of growing an invisible queue.

Shed / queue-depth / staleness counts go through
``metrics.collector.Counters`` so ``/stats`` and the bench artifact report
the same numbers.
"""

from __future__ import annotations

import threading
import time

from skyline_tpu.metrics.collector import Counters


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s, ``burst`` capacity.

    ``rate <= 0`` disables limiting (every acquire succeeds). ``try_acquire``
    returns ``(admitted, retry_after_s)`` — ``retry_after_s`` is how long
    until one token exists again, the 429 Retry-After value.
    """

    def __init__(self, rate: float, burst: int):
        self.rate = float(rate)
        self.burst = max(1, int(burst))
        self._tokens = float(self.burst)
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def try_acquire(self, n: int = 1) -> tuple[bool, float]:
        if self.rate <= 0:
            return True, 0.0
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                float(self.burst), self._tokens + (now - self._stamp) * self.rate
            )
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            return False, max(0.01, (n - self._tokens) / self.rate)


class QueryGate:
    """Concurrency limiter + bounded queue for the expensive query path.

    At most ``max_concurrent`` queries execute while up to ``max_queue``
    more wait; anything beyond that sheds immediately. ``enter`` returns
    True when admitted (caller MUST ``leave()`` when done, success or not).
    """

    def __init__(self, max_concurrent: int, max_queue: int, counters: Counters):
        self.max_concurrent = max(1, int(max_concurrent))
        self.max_queue = max(0, int(max_queue))
        self._active = 0
        self._lock = threading.Lock()
        self._counters = counters

    def enter(self) -> bool:
        with self._lock:
            if self._active >= self.max_concurrent + self.max_queue:
                self._counters.inc("queries_shed")
                return False
            self._active += 1
            self._counters.inc("queries_admitted")
            return True

    def leave(self) -> None:
        with self._lock:
            self._active = max(0, self._active - 1)

    @property
    def depth(self) -> int:
        with self._lock:
            return self._active


class AdmissionController:
    """The serving plane's policy bundle: read bucket + query gate + counters.

    Per-tenant admission: when ``tenant_rate > 0``, requests carrying an
    ``X-Tenant`` header are additionally charged against that tenant's own
    token bucket (created on first sight, bounded by ``max_tenants`` with
    LRU-less first-come retention — a flood of fresh tenant names cannot
    grow memory unboundedly; over-bound names share the ``__other__``
    bucket). The global bucket still applies first: tenants compete for
    the plane's total budget, then within their own slice. Per-tenant
    admit/shed counts surface via ``tenant_stats()`` (→ ``/metrics``
    labeled series and the ``/slo`` tenant burn row)."""

    OVERFLOW_TENANT = "__other__"

    def __init__(
        self,
        read_rate: float = 0.0,  # tokens/s; 0 = unlimited
        read_burst: int = 256,
        max_concurrent_queries: int = 2,
        max_query_queue: int = 8,
        query_deadline_ms: float = 10_000.0,
        counters: Counters | None = None,
        tenant_rate: float = 0.0,  # per-tenant tokens/s; 0 = no tenant plane
        tenant_burst: int = 64,
        max_tenants: int = 256,
    ):
        self.counters = counters if counters is not None else Counters()
        self.reads = TokenBucket(read_rate, read_burst)
        self.queries = QueryGate(
            max_concurrent_queries, max_query_queue, self.counters
        )
        self.query_deadline_ms = float(query_deadline_ms)
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = int(tenant_burst)
        self.max_tenants = max(1, int(max_tenants))
        self._tenants: dict[str, TokenBucket] = {}
        self._tenant_admitted: dict[str, int] = {}
        self._tenant_shed: dict[str, int] = {}
        self._tenant_lock = threading.Lock()

    def _tenant_bucket(self, tenant: str) -> TokenBucket:
        with self._tenant_lock:
            b = self._tenants.get(tenant)
            if b is None:
                if len(self._tenants) >= self.max_tenants:
                    tenant = self.OVERFLOW_TENANT
                    b = self._tenants.get(tenant)
                if b is None:
                    b = TokenBucket(self.tenant_rate, self.tenant_burst)
                    self._tenants[tenant] = b
            return b

    def _tenant_count(self, table: dict[str, int], tenant: str) -> None:
        with self._tenant_lock:
            if tenant not in self._tenants and len(
                self._tenants
            ) >= self.max_tenants:
                tenant = self.OVERFLOW_TENANT
            table[tenant] = table.get(tenant, 0) + 1

    def admit_read(self, tenant: str | None = None) -> tuple[bool, float]:
        ok, retry = self.reads.try_acquire()
        if ok and tenant is not None and self.tenant_rate > 0:
            ok, retry = self._tenant_bucket(tenant).try_acquire()
            if not ok:
                # aggregate across tenants; the per-tenant split lives in
                # tenant_stats() / the labeled /metrics families (distinct
                # name — the labeled family owns *_tenant_reads_shed)
                self.counters.inc("tenant_shed")
        if tenant is not None and self.tenant_rate > 0:
            self._tenant_count(
                self._tenant_admitted if ok else self._tenant_shed, tenant
            )
        if ok:
            self.counters.inc("reads_admitted")
        else:
            self.counters.inc("reads_shed")
        return ok, retry

    def tenant_stats(self) -> dict:
        """{tenant: {"admitted": n, "shed": n}} snapshot (tenant plane off
        → empty)."""
        with self._tenant_lock:
            names = set(self._tenant_admitted) | set(self._tenant_shed)
            return {
                t: {
                    "admitted": self._tenant_admitted.get(t, 0),
                    "shed": self._tenant_shed.get(t, 0),
                }
                for t in sorted(names)
            }

    def stats(self) -> dict:
        out = self.counters.snapshot()
        out["query_depth"] = self.queries.depth
        out["query_deadline_ms"] = self.query_deadline_ms
        tenants = self.tenant_stats()
        if tenants:
            out["tenants"] = tenants
        return out
