"""Shared snapshot body store: preserialized wire bodies, zero-copy reads.

The serve tier's answer to ROADMAP's "native zero-copy serve hot path":
instead of every ``/skyline`` read running ``tolist()`` + ``json.dumps``
(or the csv line join) over the snapshot, the PUBLISHER serializes each
snapshot's wire bodies exactly once — at publish time, off the read path —
and every reader serves those bytes back with a fence check and a buffer
handoff. Three reader populations share one store:

- in-process readers (the primary's ``SkylineServer``) get the retained
  ``bytes`` objects directly — zero copies, no mmap traffic;
- ``--replicas N`` in-process replicas and ``--replica-of`` processes map
  the store file read-only (``BodyStoreReader``) and serve the PRIMARY's
  exact bytes — a replica stops re-serializing what the WAL already
  delivered byte-verified.

Bodies are keyed ``(version, format, points, explain)`` via :func:`fmt_code`.
The JSON bodies are the cached *prefix* the server splices its volatile
tail onto (``json.dumps(to_doc())[:-1]`` — see server._skyline); the two
explain flavors are byte-identical to their plain twins (the plan rides the
tail, never the prefix) and share one body frame under two directory
entries, preserving the four-tuple key scheme at zero extra bytes.

On-disk layout (``bodystore.dat``), all integers little-endian u64 unless
noted:

  [0, 4096)    header: magic ``SKYBODY1``, dir_slots, data_cap, data_off,
               generation, write_counter, data_cursor, reclaim_floor
  [4096, D)    directory: dir_slots 64-byte entries
               (seq, version, fmt u32, len u32, frame_off, fence)
  [D, D+cap)   body ring: frames ``fence | body | fence`` allocated
               cursor-forward with wraparound

Seqlock discipline. Each directory entry carries a seq word the writer
makes odd before mutating and even after — a reader seeing an odd or
changed seq retries. Each body frame carries its fence word (the monotone
frame counter) before and after the body, so a reader that copied bytes
mid-overwrite sees torn fences. Fences alone cannot catch a NEW frame
written strictly inside an old frame's span (old fences intact, body
scribbled), so the writer additionally publishes ``reclaim_floor`` — the
smallest fence value still intact — BEFORE reusing any ring region; a
reader accepts a copy only if ``entry.fence >= reclaim_floor`` after the
copy completed. Torn/retried/missed reads are counted and fall back to the
Python serialization path — the store can only ever serve exact bytes or
nothing.

Native fast path. ``native/fastcsv.cpp``'s ``sky_format_rows`` serializes
the points array (the measured hot ~90% of body bytes) in C, byte-identical
to ``json.dumps(points.tolist())`` / the csv line join; the first use per
process is verified against the Python encoder and the native path is
disabled on any mismatch (``SKYLINE_BODYSTORE_VERIFY=1`` verifies every
publish). With no compiler or a stale .so the pure-Python encoders produce
the same bytes — the store never hard-requires the native component.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import threading

import numpy as np

_MAGIC = b"SKYBODY1"
_HEADER_BYTES = 4096
_ENTRY_BYTES = 64
_ENTRY = struct.Struct("<QQIIQQ")  # seq, version, fmt, len, frame_off, fence
_U64 = struct.Struct("<Q")

# header field offsets (after the 8-byte magic)
_H_DIR_SLOTS = 8
_H_DATA_CAP = 16
_H_DATA_OFF = 24
_H_GENERATION = 32
_H_WRITE_COUNTER = 40
_H_DATA_CURSOR = 48
_H_RECLAIM_FLOOR = 56

FMT_JSON_POINTS = 0
FMT_JSON_NOPOINTS = 1
FMT_JSON_POINTS_EXPLAIN = 2
FMT_JSON_NOPOINTS_EXPLAIN = 3
FMT_CSV = 4
_FMT_COUNT = 5


def fmt_code(fmt: str, include_points: bool = True, explain: bool = False) -> int:
    """Map the serve plane's ``(format, points, explain)`` read key onto a
    directory format code (``version`` completes the four-tuple)."""
    if fmt == "csv":
        return FMT_CSV
    code = FMT_JSON_POINTS if include_points else FMT_JSON_NOPOINTS
    if explain:
        code += 2
    return code


# -- wire-body encoders (native with byte-identical Python fallback) --------

_native_state = {"checked": False, "ok": False}
_native_lock = threading.Lock()


def _rows_native(points: np.ndarray, mode: int):
    """``native.format_rows_native`` behind the first-use parity check:
    the first array each process serializes is re-encoded in Python and
    compared byte-for-byte; any mismatch permanently disables the native
    path (counted by the caller). Serving plausible-but-wrong bytes is the
    one failure mode a body cache must not have."""
    from skyline_tpu.analysis.registry import env_bool

    if not env_bool("SKYLINE_BODYSTORE_NATIVE", True):
        return None
    from skyline_tpu.native import format_rows_native

    out = format_rows_native(points, mode)
    if out is None:
        return None
    verify_always = env_bool("SKYLINE_BODYSTORE_VERIFY", False)
    if not _native_state["checked"] or verify_always:
        ref = _rows_python(points, mode)
        with _native_lock:
            _native_state["checked"] = True
            _native_state["ok"] = out == ref
        if out != ref:
            return None
    elif not _native_state["ok"]:
        return None
    return out


def _rows_python(points: np.ndarray, mode: int) -> bytes:
    from skyline_tpu.native import ROWS_JSON

    if mode == ROWS_JSON:
        return json.dumps(points.tolist()).encode()
    from skyline_tpu.bridge.wire import format_tuple_line

    return "\n".join(
        format_tuple_line(i, row) for i, row in enumerate(points)
    ).encode()


def points_json(points: np.ndarray, counters=None) -> bytes:
    """The JSON points array, byte-identical to
    ``json.dumps(points.tolist())``."""
    from skyline_tpu.native import ROWS_JSON

    out = _rows_native(points, ROWS_JSON)
    if out is not None:
        if counters is not None:
            counters["native_rows"] += 1
        return out
    if counters is not None:
        counters["python_rows"] += 1
    return _rows_python(points, ROWS_JSON)


def csv_body(snap, counters=None) -> bytes:
    """The full ``format=csv`` response body, byte-identical to the serve
    handler's newline-joined ``format_tuple_line`` loop."""
    from skyline_tpu.native import ROWS_CSV

    out = _rows_native(snap.points, ROWS_CSV)
    if out is not None:
        if counters is not None:
            counters["native_rows"] += 1
        return out
    if counters is not None:
        counters["python_rows"] += 1
    return _rows_python(snap.points, ROWS_CSV)


def json_prefix(snap, include_points: bool = True, counters=None) -> bytes:
    """The cacheable JSON body prefix — the full doc minus its closing
    brace, byte-identical to ``json.dumps(snap.to_doc(...))[:-1].encode()``.
    Splicing the preserialized points array after ``doc_head()`` relies on
    the Snapshot contract that ``points`` is the doc's final key."""
    head = json.dumps(snap.doc_head()).encode()
    if not include_points:
        return head[:-1]
    return head[:-1] + b', "points": ' + points_json(snap.points, counters)


def _new_counters() -> dict:
    return {
        "hits": 0,
        "misses": 0,
        "torn_reads": 0,
        "retries": 0,
        "publishes": 0,
        "bodies_published": 0,
        "bytes_published": 0,
        "ring_wraps": 0,
        "oversize_skipped": 0,
        "native_rows": 0,
        "python_rows": 0,
        "remaps": 0,
    }


class _Mapped:
    """Shared mmap plumbing: header field access + the seqlock read path."""

    def __init__(self):
        self._mm = None
        self._dir_slots = 0
        self._data_cap = 0
        self._data_off = 0
        self.counters = _new_counters()

    # -- raw field access --------------------------------------------------

    def _h_get(self, off: int) -> int:
        return _U64.unpack_from(self._mm, off)[0]

    def _h_put(self, off: int, value: int) -> None:
        _U64.pack_into(self._mm, off, value)

    def _slot_off(self, version: int, fmt: int) -> int:
        slot = (version * _FMT_COUNT + fmt) % self._dir_slots
        return _HEADER_BYTES + slot * _ENTRY_BYTES

    # -- seqlock read path -------------------------------------------------

    def _read_entry(self, version: int, fmt: int, retries: int):
        """One (version, fmt) lookup under the seqlock + fence + reclaim
        discipline. Returns the body bytes (one buffer copy, zero
        serialization) or None (miss / torn past the retry bound)."""
        mm = self._mm
        if mm is None:
            return None
        eoff = self._slot_off(version, fmt)
        c = self.counters
        for _ in range(max(1, retries)):
            s1 = _U64.unpack_from(mm, eoff)[0]
            if s1 & 1:  # writer mid-update
                c["retries"] += 1
                continue
            _, ver, efmt, ln, frame, fence = _ENTRY.unpack_from(mm, eoff)
            if _U64.unpack_from(mm, eoff)[0] != s1:
                c["retries"] += 1
                continue
            if ver != version or efmt != fmt or s1 == 0:
                return None  # slot holds another key: a plain miss
            if self._h_get(_H_RECLAIM_FLOOR) > fence:
                c["torn_reads"] += 1
                return None  # ring already swept this frame
            pre = _U64.unpack_from(mm, frame)[0]
            body = bytes(mm[frame + 8 : frame + 8 + ln])
            post = _U64.unpack_from(mm, frame + 8 + ln)[0]
            if (
                pre != fence
                or post != fence
                or self._h_get(_H_RECLAIM_FLOOR) > fence
            ):
                # overwritten under us: the fence words (or the reclaim
                # floor published before any reuse) caught the tear
                c["torn_reads"] += 1
                continue
            return body
        return None

    def stats(self) -> dict:
        return dict(self.counters)


class BodyStore(_Mapped):
    """Writer side (plus the in-process zero-copy read side).

    ``path=None`` keeps the store purely in-process (no replicas to feed —
    bodies are still preserialized at publish time and retained for the
    local server). ``attach(store)`` subscribes to the snapshot store's
    publish hook; every publish serializes the JSON prefixes (with and
    without points) and the csv body once and installs five directory keys.
    """

    def __init__(
        self,
        path: str | None = None,
        data_bytes: int | None = None,
        dir_slots: int | None = None,
        keep: int | None = None,
        retries: int | None = None,
    ):
        super().__init__()
        from skyline_tpu.analysis.registry import env_int

        self.path = path
        self._data_cap = (
            env_int("SKYLINE_BODYSTORE_BYTES", 8 << 20)
            if data_bytes is None
            else int(data_bytes)
        )
        self._dir_slots = max(
            _FMT_COUNT,
            env_int("SKYLINE_BODYSTORE_SLOTS", 512)
            if dir_slots is None
            else int(dir_slots),
        )
        self._keep = max(
            1,
            env_int("SKYLINE_BODYSTORE_KEEP", 4) if keep is None else int(keep),
        )
        self._retries = (
            env_int("SKYLINE_BODYSTORE_RETRIES", 4)
            if retries is None
            else int(retries)
        )
        self._lock = threading.Lock()
        # in-process retained bodies: {(version, fmt): bytes} for the last
        # ``keep`` versions — the primary's server serves these with zero
        # copies; the mmap ring below exists for the replica processes
        self._recent: dict[tuple[int, int], bytes] = {}
        self._file = None
        self._frames: list[tuple[int, int, int]] = []  # (fence, start, end)
        self._cursor = 0
        self._fence = 0
        if path is not None:
            self._create(path)

    def _create(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        data_off = _HEADER_BYTES + self._dir_slots * _ENTRY_BYTES
        data_off = (data_off + 4095) // 4096 * 4096
        total = data_off + self._data_cap + 16
        # recreate under a FRESH inode (never truncate in place): a reader
        # still mapping the old incarnation keeps a fully valid frozen view
        # of the old bytes (no SIGBUS if the new file is smaller), misses
        # on new versions, re-stats, sees the new inode, and remaps
        try:
            os.unlink(path)
        except OSError:
            pass
        f = open(path, "w+b")
        f.truncate(total)
        self._file = f
        self._mm = mmap.mmap(f.fileno(), total)
        self._mm[0:8] = _MAGIC
        self._h_put(_H_DIR_SLOTS, self._dir_slots)
        self._h_put(_H_DATA_CAP, self._data_cap)
        self._h_put(_H_DATA_OFF, data_off)
        self._h_put(_H_GENERATION, int.from_bytes(os.urandom(7), "little"))
        self._h_put(_H_RECLAIM_FLOOR, 0)
        self._data_off = data_off

    # -- publish side ------------------------------------------------------

    def attach(self, store) -> "BodyStore":
        """Subscribe to a ``SnapshotStore``: every publish lands its wire
        bodies here synchronously (publish-time serialization is the whole
        point — the cost moves off the read path)."""
        store.on_publish(lambda prev, snap: self.put_snapshot(snap))
        return self

    def put_snapshot(self, snap) -> None:
        c = self.counters
        with self._lock:
            head = json_prefix(snap, include_points=False, counters=c)
            pts = points_json(snap.points, counters=c)
            prefix_points = head + b', "points": ' + pts
            csv = csv_body(snap, counters=c)
            v = snap.version
            self._put_body(
                v, (FMT_JSON_POINTS, FMT_JSON_POINTS_EXPLAIN), prefix_points
            )
            self._put_body(
                v, (FMT_JSON_NOPOINTS, FMT_JSON_NOPOINTS_EXPLAIN), head
            )
            self._put_body(v, (FMT_CSV,), csv)
            for fmt, body in (
                (FMT_JSON_POINTS, prefix_points),
                (FMT_JSON_NOPOINTS, head),
                (FMT_JSON_POINTS_EXPLAIN, prefix_points),
                (FMT_JSON_NOPOINTS_EXPLAIN, head),
                (FMT_CSV, csv),
            ):
                self._recent[(v, fmt)] = body
            floor = v - self._keep + 1
            for key in [k for k in self._recent if k[0] < floor]:
                del self._recent[key]
            c["publishes"] += 1
            c["bodies_published"] += 3
            c["bytes_published"] += len(prefix_points) + len(head) + len(csv)

    def _put_body(self, version: int, fmts: tuple, body: bytes) -> None:
        """Write one body frame and point each fmt's directory entry at it.
        Caller holds the writer lock."""
        if self._mm is None:
            return
        need = 8 + len(body) + 8
        if need > self._data_cap:
            self.counters["oversize_skipped"] += 1
            return
        if self._cursor + need > self._data_cap:
            # wrap: frames stranded between the cursor and capacity stay
            # intact (and readable) until the new cycle sweeps over them
            self._cursor = 0
            self.counters["ring_wraps"] += 1
        start, end = self._cursor, self._cursor + need
        # reclaim: any frame whose span the new one touches is about to be
        # scribbled — publish the new floor BEFORE the first byte lands so
        # a reader mid-copy can detect the sweep (see module docstring)
        floor = None
        while self._frames and self._overlaps(self._frames[0], start, end):
            floor = self._frames.pop(0)[0] + 1
        if floor is not None:
            self._h_put(_H_RECLAIM_FLOOR, floor)
        self._fence += 1
        fence = self._fence
        frame = self._data_off + start
        _U64.pack_into(self._mm, frame, fence)
        self._mm[frame + 8 : frame + 8 + len(body)] = body
        _U64.pack_into(self._mm, frame + 8 + len(body), fence)
        self._frames.append((fence, start, end))
        self._cursor = end
        self._h_put(_H_WRITE_COUNTER, fence)
        self._h_put(_H_DATA_CURSOR, self._cursor)
        for fmt in fmts:
            eoff = self._slot_off(version, fmt)
            seq = _U64.unpack_from(self._mm, eoff)[0]
            _U64.pack_into(self._mm, eoff, seq + 1)  # odd: update in flight
            _ENTRY.pack_into(
                self._mm, eoff, seq + 1, version, fmt, len(body), frame, fence
            )
            _U64.pack_into(self._mm, eoff, seq + 2)

    @staticmethod
    def _overlaps(frame: tuple, start: int, end: int) -> bool:
        return frame[1] < end and frame[2] > start

    # -- read side ---------------------------------------------------------

    def get(self, version: int, fmt: int):
        """In-process read: the retained bytes object when the version is
        recent (zero copies), else the mmap ring (one copy)."""
        body = self._recent.get((version, fmt))
        if body is None:
            body = self._read_entry(version, fmt, self._retries)
        if body is None:
            self.counters["misses"] += 1
            return None
        self.counters["hits"] += 1
        return body

    def close(self) -> None:
        if self._mm is not None:
            self._mm.close()
            self._mm = None
        if self._file is not None:
            self._file.close()
            self._file = None


class BodyStoreReader(_Mapped):
    """Read-only cross-process view: maps the primary's store file and
    serves the primary's exact bytes by ``(version, fmt)``. Opens lazily
    and re-stats on miss, so a replica started before the primary (or
    across a primary restart, which recreates the file under a fresh
    generation) converges without coordination."""

    def __init__(self, path: str, retries: int | None = None):
        super().__init__()
        from skyline_tpu.analysis.registry import env_int

        self.path = path
        self._retries = (
            env_int("SKYLINE_BODYSTORE_RETRIES", 4)
            if retries is None
            else int(retries)
        )
        self._ino = None
        self._generation = None
        self._open()

    def _open(self) -> bool:
        self.close()
        try:
            st = os.stat(self.path)
            f = open(self.path, "rb")
        except OSError:
            return False
        try:
            mm = mmap.mmap(f.fileno(), st.st_size, prot=mmap.PROT_READ)
        except (OSError, ValueError):
            f.close()
            return False
        if mm[0:8] != _MAGIC:
            mm.close()
            f.close()
            return False
        self._file = f
        self._mm = mm
        self._ino = st.st_ino
        self._dir_slots = _U64.unpack_from(mm, _H_DIR_SLOTS)[0]
        self._data_off = _U64.unpack_from(mm, _H_DATA_OFF)[0]
        self._generation = _U64.unpack_from(mm, _H_GENERATION)[0]
        return True

    def _maybe_remap(self) -> None:
        """On miss: if the primary recreated the file (new inode or
        generation), swing the mapping over to the live incarnation."""
        try:
            st = os.stat(self.path)
        except OSError:
            return
        if self._mm is None or st.st_ino != self._ino:
            if self._open():
                self.counters["remaps"] += 1

    def get(self, version: int, fmt: int):
        body = self._read_entry(version, fmt, self._retries)
        if body is None:
            self._maybe_remap()
            body = self._read_entry(version, fmt, self._retries)
        if body is None:
            self.counters["misses"] += 1
            return None
        self.counters["hits"] += 1
        return body

    def close(self) -> None:
        if getattr(self, "_mm", None) is not None:
            self._mm.close()
            self._mm = None
        if getattr(self, "_file", None) is not None:
            self._file.close()
            self._file = None


__all__ = [
    "BodyStore",
    "BodyStoreReader",
    "FMT_CSV",
    "FMT_JSON_NOPOINTS",
    "FMT_JSON_NOPOINTS_EXPLAIN",
    "FMT_JSON_POINTS",
    "FMT_JSON_POINTS_EXPLAIN",
    "csv_body",
    "fmt_code",
    "json_prefix",
    "points_json",
]
