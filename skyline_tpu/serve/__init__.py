"""Query-serving plane: versioned snapshots, delta subscriptions, admission.

The streaming engine maintains the skyline; this package turns the
maintained set into a read-heavy service (the read/maintain split of
"Computing Skylines on Distributed Data", PAPERS.md): the engine publishes
each completed global skyline as an immutable versioned snapshot
(``snapshot.SnapshotStore``), readers are served lock-free from the latest
published version under a client staleness bound, subscribers catch up on
what entered/left between versions (``deltas.DeltaRing``), and the
expensive forced-merge path is admission-controlled with explicit load
shedding (``admission``). ``server.SkylineServer`` exposes all of it over a
stdlib asyncio HTTP server; ``bridge/worker.py --serve <port>`` wires it
into the worker loop.
"""

from skyline_tpu.serve.admission import AdmissionController, QueryGate, TokenBucket
from skyline_tpu.serve.deltas import (
    DeltaRing,
    apply_delta_record,
    delta_wal_record,
    snapshot_delta,
    snapshot_wal_record,
)
from skyline_tpu.serve.server import QueryBridge, ServeConfig, SkylineServer
from skyline_tpu.serve.snapshot import Snapshot, SnapshotStore


def __getattr__(name):
    # replica pulls in the resilience plane; load it lazily so plain serve
    # users don't pay for (or depend on) the WAL machinery
    if name in ("SkylineReplica", "ReplicaDivergence", "run_replica"):
        from skyline_tpu.serve import replica as _replica

        return getattr(_replica, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "AdmissionController",
    "DeltaRing",
    "QueryBridge",
    "QueryGate",
    "ReplicaDivergence",
    "ServeConfig",
    "SkylineReplica",
    "Snapshot",
    "SnapshotStore",
    "TokenBucket",
    "apply_delta_record",
    "delta_wal_record",
    "run_replica",
    "snapshot_delta",
    "snapshot_wal_record",
]
