"""WAL-tailing read replicas: the serve plane's availability layer.

The write path stays single-owner (the engine publishes snapshots; PR 7's
WAL persists every publish transition with its row ORDER, digest, and
meta) — read replication is therefore a log-tailing problem: each
``SkylineReplica`` bootstraps from the newest checkpoint barrier inlined
in the WAL, then live-tails the publish-delta stream through
``resilience.wal.WalTailer`` to maintain its own ``SnapshotStore`` +
``DeltaRing`` + read cache, serving ``/skyline`` / ``/deltas`` /
``/subscribe`` / ``/metrics`` on its own port.

Honesty contract (the same spirit as the ``partial:true`` degraded-answer
contract, RUNBOOK §2p):

- every response carries the freshness watermark (``staleness_ms``), which
  ages monotonically while the primary is down;
- reads older than the staleness fence (``SKYLINE_REPLICA_MAX_STALE_MS``)
  are refused with 503 + Retry-After — ``allow_stale`` bounds the client's
  tolerance, never the replica's own;
- replica snapshot bytes are identical to the primary's at every common
  version (delta records carry the published permutation; each fold is
  digest-verified), so a replica can never serve a plausible-but-wrong
  skyline;
- ``restored`` / ``partial`` / ``excluded_chips`` propagate byte-faithfully
  — a degraded primary answer is never laundered clean by a replica.

Failure handling: a torn WAL tail holds position (the writer is
mid-append); real corruption (``WalTailCorruption``), a pruned-under-us
segment (``WalSegmentGone``), a digest mismatch, or a broken version chain
all fall back to checkpoint re-bootstrap. The tail loop runs under the
PR-7 ``Supervisor`` (backoff, restart budget), and the subprocess CLI mode
(``bridge.worker --replica-of``) drains on SIGTERM.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from skyline_tpu.resilience.faults import fault_point
from skyline_tpu.resilience.wal import (
    WalError,
    WalTailer,
    rows_from_b64,
)


class ReplicaDivergence(WalError):
    """A tailed delta cannot extend the replica's state: version-chain gap
    or post-fold digest mismatch. Recovery is a full re-bootstrap."""


class SkylineReplica:
    """One read replica: WAL tailer + snapshot store + HTTP server.

    ``wal_dir``: the primary's WAL directory (shared filesystem).
    ``serve_config``: admission/ring knobs for the replica's own server
    (per-tenant buckets included). ``max_stale_ms``: the staleness fence;
    None reads ``SKYLINE_REPLICA_MAX_STALE_MS``. ``start=True`` launches
    the supervised tail thread; ``start=False`` lets tests drive
    ``bootstrap()`` / ``apply_available()`` deterministically.
    """

    def __init__(
        self,
        wal_dir: str,
        port: int = 0,
        host: str = "127.0.0.1",
        serve_config=None,
        telemetry=None,
        replica_id: str | None = None,
        max_stale_ms: float | None = None,
        poll_interval_s: float | None = None,
        max_restarts: int | None = None,
        backoff_base_s: float | None = None,
        start: bool = True,
        opslog=None,
        primary_head_cb=None,
    ):
        from skyline_tpu.analysis.registry import env_float
        from skyline_tpu.serve import (
            DeltaRing,
            ServeConfig,
            SkylineServer,
            SnapshotStore,
        )
        from skyline_tpu.telemetry import Telemetry

        self.wal_dir = wal_dir
        self.replica_id = (
            replica_id if replica_id is not None else f"replica-{os.getpid()}"
        )
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        scfg = serve_config if serve_config is not None else ServeConfig()
        if max_stale_ms is None:
            max_stale_ms = env_float("SKYLINE_REPLICA_MAX_STALE_MS", 30_000.0)
        self.max_stale_ms = float(max_stale_ms)
        self.poll_interval_s = (
            env_float("SKYLINE_REPLICA_POLL_MS", 25.0) / 1000.0
            if poll_interval_s is None
            else poll_interval_s
        )
        self._max_restarts = max_restarts
        self._backoff_base_s = backoff_base_s
        self.store = SnapshotStore(history=scfg.history)
        self.ring = DeltaRing(self.store, capacity=scfg.delta_ring)
        # zero-copy read path (RUNBOOK §2u): map the PRIMARY's body store
        # (it lives beside the WAL, same shared filesystem) read-only and
        # serve the primary's exact bytes — the replica stops
        # re-serializing what the WAL already delivered byte-verified.
        # Staleness honesty is unaffected: version selection and the fence
        # still come from the replica's own folded store; the mapping only
        # replaces how a chosen version's bytes are produced.
        from skyline_tpu.analysis.registry import env_bool

        self.bodystore = None
        if env_bool("SKYLINE_BODYSTORE", True):
            from skyline_tpu.serve.bodystore import BodyStoreReader

            self.bodystore = BodyStoreReader(
                os.path.join(wal_dir, "bodystore.dat")
            )
        self.server = SkylineServer(
            self.store,
            deltas=self.ring,
            admission=scfg.admission(),
            stats_cb=self.stats,
            bridge=None,  # replicas cannot force merges: reads only
            port=port,
            host=host,
            telemetry=self.telemetry,
            read_cache=scfg.read_cache_entries,
            max_stale_ms=self.max_stale_ms,
            role="replica",
            bodystore=self.bodystore,
        )
        self.port = self.server.port
        # cluster role (RUNBOOK §2r): "replica" until a ClusterSupervisor
        # promotes this node to own the write path under a lease epoch
        self.role = "replica"
        self.promoted_epoch: int | None = None
        self._tailer: WalTailer | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.records_applied = 0
        self.bootstraps = 0
        self.rebootstraps = 0
        self.last_error: str | None = None
        self.supervisor = None
        # ops plane (RUNBOOK §2s): the shared cross-process journal (None
        # outside a cluster) and the primary-head callback that turns
        # "my head" into "versions behind the primary" for
        # skyline_replica_lag_versions{replica=...}
        self.opslog = opslog
        self.primary_head_cb = primary_head_cb
        self.last_lag_ms: float | None = None
        repl = getattr(self.telemetry, "replication", None)
        if repl is not None:
            repl.append(self)
        if start:
            self.start()

    # -- state maintenance (tail thread) -----------------------------------

    def bootstrap(self) -> None:
        """(Re-)build serving state from the WAL: newest checkpoint barrier
        snapshot + every delta after it, byte-exact, then leave the tailer
        positioned at the live tail.

        Starting at the newest barrier (not the oldest segment) is what
        makes corruption recoverable: a corrupt frame BEFORE the newest
        barrier is simply never re-read, and one AFTER it raises — the tail
        loop keeps serving the last verified state (honestly aging into the
        staleness fence) and retries until the primary's next barrier lands
        past the damage."""
        fault_point("replica.restore")
        if self._tailer is not None:
            self._tailer.close()
        self._tailer = WalTailer(self.wal_dir, self.replica_id)
        barrier_seq = self._newest_barrier_seq()
        if barrier_seq is not None:
            self._tailer.seek_to_segment(barrier_seq)
        records = self._tailer.poll()
        self._fold(records)
        self.bootstraps += 1
        if self.opslog is not None:
            self.opslog.record(
                "replica_bootstrap",
                replica=self.replica_id,
                head_version=self.store.head_version,
            )

    def _newest_barrier_seq(self) -> int | None:
        from skyline_tpu.resilience.wal import (
            list_segments,
            segment_first_record,
        )

        best = None
        for seq, path in list_segments(self.wal_dir):
            rec = segment_first_record(path)
            if rec is not None and rec.get("type") == "ckpt" and "snap" in rec:
                best = seq
        return best

    def _fold(self, records: list) -> None:
        import numpy as np

        from skyline_tpu.serve.deltas import Delta, apply_delta_record
        from skyline_tpu.serve.snapshot import points_digest

        base = None
        base_idx = -1
        for i, rec in enumerate(records):
            if rec.get("type") == "ckpt" and "snap" in rec:
                base, base_idx = rec["snap"], i
        delta_recs = [
            r for r in records[base_idx + 1 :] if r.get("type") == "delta"
        ]
        if base is None and not delta_recs:
            return  # nothing published yet; keep tailing
        d = int(base["d"] if base is not None else delta_recs[0]["d"])
        points = (
            rows_from_b64(base["rows"], d)
            if base is not None
            else np.empty((0, d), dtype=np.float32)
        )
        version = int(base["version"]) if base is not None else 0
        watermark = int(base.get("watermark_id", -1)) if base is not None else -1
        ts = float(base["timestamp_ms"]) if base is not None else None
        event_wm = base.get("event_wm_ms") if base is not None else None
        meta = dict(base.get("meta", {})) if base is not None else {}
        ring_deltas = []
        for rec in delta_recs:
            entered = rows_from_b64(rec["entered"], int(rec["d"]))
            left = rows_from_b64(rec["left"], int(rec["d"]))
            ring_deltas.append(
                Delta(int(rec["from"]), int(rec["to"]), entered, left)
            )
            points = apply_delta_record(points, rec)
            if "digest" in rec and points_digest(points) != rec["digest"]:
                raise ReplicaDivergence(
                    f"bootstrap digest mismatch at version {rec['to']}"
                )
            version = int(rec["to"])
            watermark = int(rec.get("wm", watermark))
            ts = float(rec.get("ts", ts)) if rec.get("ts") is not None else ts
            event_wm = rec.get("ewm", event_wm)
            meta = dict(rec.get("meta", {}))
        self.store.restore_state(
            points,
            version,
            watermark_id=watermark,
            timestamp_ms=ts,
            meta=meta,
            event_wm_ms=event_wm,
        )
        self.ring.seed(ring_deltas, version)

    def _apply(self, rec: dict) -> None:
        """Fold one live-tailed record into the serving state."""
        from skyline_tpu.serve.deltas import apply_delta_record
        from skyline_tpu.serve.snapshot import points_digest

        kind = rec.get("type")
        if kind == "ckpt" and "snap" in rec:
            # a barrier we tailed PAST is redundant with the state we
            # already hold; cross-check the head version instead of
            # re-seating (re-seating would launder ``restored`` semantics)
            snap = rec["snap"]
            if int(snap["version"]) < self.store.head_version:
                raise ReplicaDivergence(
                    f"barrier regressed: {snap['version']} < "
                    f"{self.store.head_version}"
                )
            if int(snap["version"]) > self.store.head_version:
                # publishes we never saw (records lost to a skipped tear):
                # the barrier carries the full state — fold from it
                self._fold([rec])
            return
        if kind != "delta":
            return  # batch/commit/start records are ingest-plane lineage
        head = self.store.head_version
        if head == 0 and self.store.published == 0 and self.store.restores == 0:
            # tailer joined mid-stream with no barrier yet: fold from zero
            self._fold([rec])
            self.records_applied += 1
            return
        if int(rec["from"]) != head:
            raise ReplicaDivergence(
                f"version chain break: delta from {rec['from']} "
                f"but head is {head}"
            )
        prev = self.store.latest()
        points = apply_delta_record(
            prev.points if prev is not None else _empty(int(rec["d"])), rec
        )
        if "digest" in rec and points_digest(points) != rec["digest"]:
            raise ReplicaDivergence(
                f"digest mismatch applying delta to version {rec['to']}"
            )
        self.store.publish(
            points,
            watermark_id=int(rec["wm"]),
            now_ms=rec.get("ts"),
            event_wm_ms=rec.get("ewm"),
            **dict(rec.get("meta", {})),
        )
        if self.store.head_version != int(rec["to"]):
            raise ReplicaDivergence(
                f"version drift: published {self.store.head_version}, "
                f"record says {rec['to']}"
            )
        self.records_applied += 1
        if rec.get("ts") is not None:
            lag_ms = max(0.0, time.time() * 1000.0 - float(rec["ts"]))
            self.last_lag_ms = lag_ms
            self.telemetry.histogram("replica_tail_lag_ms", unit="ms").observe(
                lag_ms
            )

    def apply_available(self) -> int:
        """One tail-poll step: apply every newly completed record. Returns
        how many were applied. Raises on corruption/divergence (the
        supervised loop converts that to a re-bootstrap)."""
        if self._tailer is None:
            self.bootstrap()
            return 0
        recs = self._tailer.poll()
        for rec in recs:
            self._apply(rec)
        return len(recs)

    def _rebootstrap(self, err: Exception) -> None:
        """Corruption/divergence fallback: count it, then retry bootstrap
        until one verifies (the replica keeps serving its last good state,
        honestly aging, while damaged history waits for the primary's next
        barrier to land past it)."""
        self.last_error = f"{type(err).__name__}: {err}"
        self.rebootstraps += 1
        self.telemetry.inc("replica.rebootstraps")
        if self.opslog is not None:
            self.opslog.record(
                "replica_rebootstrap",
                replica=self.replica_id, error=self.last_error,
            )
        print(
            f"replica {self.replica_id}: {self.last_error}; re-bootstrapping",
            file=sys.stderr,
        )
        while not self._stop.is_set():
            try:
                self.bootstrap()
                return
            except WalError as e:
                self.last_error = f"{type(e).__name__}: {e}"
                self._stop.wait(self.poll_interval_s)

    def _incarnation(self, attempt: int):
        """One supervised life: bootstrap, then tail until stopped.
        WAL corruption and divergence re-bootstrap in place (counted);
        injected crashes propagate to the supervisor."""
        try:
            self.bootstrap()
        except WalError as e:
            self._rebootstrap(e)
        if attempt > 0:
            self.rebootstraps += 1
        while not self._stop.is_set():
            fault_point("replica.tail")
            try:
                n = self.apply_available()
            except WalError as e:
                self._rebootstrap(e)
                continue
            if n == 0:
                self._stop.wait(self.poll_interval_s)
        return None

    def start(self) -> None:
        from skyline_tpu.resilience.supervisor import Supervisor

        self.supervisor = Supervisor(
            self._incarnation,
            max_restarts=self._max_restarts,
            backoff_base_s=self._backoff_base_s,
            telemetry=self.telemetry,
        )

        def _run():
            try:
                self.supervisor.run()
            except Exception as e:
                self.last_error = f"{type(e).__name__}: {e}"
                print(
                    f"replica {self.replica_id}: tail loop gave up: "
                    f"{self.last_error}",
                    file=sys.stderr,
                )

        self._thread = threading.Thread(
            target=_run, name=f"skyline-{self.replica_id}", daemon=True
        )
        self._thread.start()

    def promote(self, epoch: int) -> dict:
        """Promotion hook for the ``ClusterSupervisor`` (the fence is
        already raised past the deposed epoch, so the durable WAL tail
        can no longer move under us): stop the supervised tail thread,
        drain every durable record, and switch to the primary role.

        Returns ``{head_version, head_digest}`` — the byte-identity
        witness: because every fold is digest-verified against the
        primary's published bytes, the promoted head IS the deposed
        primary's last durable state plus replayed deltas, and the drills
        assert the sha256 matches an independent fold of the WAL."""
        from skyline_tpu.serve.snapshot import points_digest

        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        try:
            if self._tailer is None:
                self.bootstrap()
            while self.apply_available():
                pass
        except WalError as e:
            # damaged tail at the worst moment: re-bootstrap from the
            # newest barrier (state before the damage stays byte-exact)
            self.last_error = f"{type(e).__name__}: {e}"
            self.rebootstraps += 1
            self.bootstrap()
        self.role = "primary"
        self.promoted_epoch = int(epoch)
        self.server.role = "primary"
        latest = self.store.latest()
        return {
            "head_version": self.store.head_version,
            "head_digest": (
                points_digest(latest.points) if latest is not None else None
            ),
        }

    def demote(self) -> None:
        """Rejoin as a follower after deposition — the honest path once
        this node's writer starts raising ``WalFencedError``. Restarts
        the supervised tail loop."""
        was_epoch = self.promoted_epoch
        self.role = "replica"
        self.promoted_epoch = None
        self.server.role = "replica"
        if self.opslog is not None:
            self.opslog.record(
                "demoted", replica=self.replica_id, epoch=was_epoch
            )
        if self._thread is None:
            self._stop = threading.Event()
            self.start()

    def wait_for_version(self, version: int, timeout_s: float = 10.0) -> bool:
        """Test/drill helper: block until the replica's head reaches
        ``version`` (True) or the timeout passes (False)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.store.head_version >= version:
                return True
            time.sleep(0.005)
        return False

    def labeled_series(self):
        """Per-replica Prometheus families (RUNBOOK §2s) — the tailer's
        in-memory stats made scrapable: ``skyline_replica_lag_ms{replica=}``,
        ``skyline_replica_lag_versions{replica=}`` (when a primary-head
        callback is wired), and the ``stale_frames_skipped`` /
        ``partial_retries`` / rebootstrap counts that were previously
        visible only in the stats dict."""
        labels = (("replica", str(self.replica_id)),)
        counters: dict = {}
        gauges: dict = {}

        def _c(name, value):
            counters.setdefault(name, []).append((labels, float(value)))

        def _g(name, value):
            gauges.setdefault(name, []).append((labels, float(value)))

        _c("replica_records_applied", self.records_applied)
        _c("replica_bootstraps", self.bootstraps)
        _c("replica_rebootstraps", self.rebootstraps)
        _g("replica_head_version", self.store.head_version)
        if self.last_lag_ms is not None:
            _g("replica_lag_ms", self.last_lag_ms)
        if self.primary_head_cb is not None:
            try:
                primary_head = int(self.primary_head_cb())
            except Exception:
                primary_head = None
            if primary_head is not None:
                _g(
                    "replica_lag_versions",
                    max(0, primary_head - self.store.head_version),
                )
        tailer = self._tailer
        if tailer is not None:
            try:
                ts = tailer.stats()
            except Exception:
                ts = {}
            _c("replica_stale_frames_skipped", ts.get("stale_frames_skipped", 0))
            _c("replica_partial_retries", ts.get("partial_retries", 0))
            _c("replica_frames_read", ts.get("frames_read", 0))
            _c("replica_segments_finished", ts.get("segments_finished", 0))
            _g("replica_tailer_segment_seq", ts.get("segment_seq", 0))
            _g("replica_tailer_position", ts.get("position", 0))
        return counters, gauges

    def stats(self) -> dict:
        out = {
            "replica": {
                "id": self.replica_id,
                "wal_dir": self.wal_dir,
                "role": self.role,
                "promoted_epoch": self.promoted_epoch,
                "head_version": self.store.head_version,
                "records_applied": self.records_applied,
                "bootstraps": self.bootstraps,
                "rebootstraps": self.rebootstraps,
                "max_stale_ms": self.max_stale_ms,
                "last_error": self.last_error,
            }
        }
        if self._tailer is not None:
            out["replica"]["tailer"] = self._tailer.stats()
        if self.supervisor is not None:
            out["replica"]["supervisor"] = self.supervisor.stats()
        return out

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        if self._tailer is not None:
            self._tailer.close()
        repl = getattr(self.telemetry, "replication", None)
        if repl is not None and self in repl:
            repl.remove(self)
        self.server.close()
        if self.bodystore is not None:
            self.bodystore.close()


def _empty(d: int):
    import numpy as np

    return np.empty((0, max(d, 1)), dtype=np.float32)


def run_replica(
    wal_dir: str,
    port: int = 0,
    host: str = "127.0.0.1",
    serve_config=None,
    replica_id: str | None = None,
    install_signal_handlers: bool = True,
) -> int:
    """Blocking CLI entry (``bridge.worker --replica-of <wal_dir>``): run
    one replica until SIGTERM/SIGINT, then drain (close the tailer —
    withdrawing its retention ack — and the server) and exit 0."""
    import signal

    from skyline_tpu.telemetry.opslog import OpsLog, opslog_enabled

    stop = threading.Event()
    ops = None
    if opslog_enabled():
        ops = OpsLog(wal_dir)
    replica = SkylineReplica(
        wal_dir,
        port=port,
        host=host,
        serve_config=serve_config,
        replica_id=replica_id,
        opslog=ops,
    )
    # this process's journal behind the replica surface's GET /ops
    replica.telemetry.opslog = ops
    if install_signal_handlers:

        def _drain(signum, frame):
            stop.set()

        signal.signal(signal.SIGTERM, _drain)
        signal.signal(signal.SIGINT, _drain)
    print(
        f"skyline replica {replica.replica_id}: serving on "
        f"{host}:{replica.port} (wal: {wal_dir})",
        file=sys.stderr,
    )
    try:
        while not stop.is_set():
            stop.wait(0.2)
    finally:
        replica.close()
        if ops is not None:
            ops.close()
    return 0
