"""Versioned skyline snapshot store — the serving plane's source of truth.

The engine (``stream/engine.py`` / ``stream/sliding_engine.py``) publishes
each completed global skyline here as an immutable, monotonically-versioned
``Snapshot``; readers never touch the engine. A read serves the latest
published version lock-free — publication is a single reference swap, and
snapshots are frozen (read-only numpy arrays + a content digest stamped at
publish, so a torn read is detectable as a digest mismatch, which the swap
makes impossible to begin with).

Staleness contract. Two client-specified bounds, both optional:

- ``max_age_ms``: the snapshot's publish timestamp must be within this many
  milliseconds of now.
- ``max_version_lag``: the number of ingest advances (micro-batches the
  engine has absorbed since the snapshot was cut — ``note_ingest`` calls)
  must not exceed this. Lag 0 means "exact": nothing has entered the
  engine since the snapshot. The engine bumps this counter from its data
  plane, so the bound is enforceable without any device sync.

A read that violates its bound is reported stale; the HTTP layer
(``serve/server.py``) then either rejects it (503), serves it flagged
(``allow_stale``), and/or fires a refresh merge instead of blocking on one.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from skyline_tpu.resilience.faults import fault_fired, fault_point


def _now_ms() -> float:
    return time.time() * 1000.0


@dataclass(frozen=True)
class Snapshot:
    """One immutable published global skyline."""

    version: int
    watermark_id: int  # max tuple id ingested when the snapshot was cut
    timestamp_ms: float
    points: np.ndarray  # (k, d) float32, read-only
    digest: str  # sha1 of the points buffer, stamped at publish
    meta: dict = field(default_factory=dict)
    # newest producer EVENT time (ms epoch) reflected in these points —
    # the freshness lineage's published watermark (None when the engine
    # runs without the tracker); rides the WAL so restores keep lineage
    event_wm_ms: float | None = None
    # opaque identity of the engine state these points were merged from
    # (the partition-epoch key). Raw bytes, so it stays OFF to_doc/meta;
    # the audit plane compares it against the live epoch key to tell a
    # still-current snapshot from one the state has moved past. None on
    # restored snapshots (recovered bytes carry no epoch lineage).
    source_key: bytes | None = None

    @property
    def size(self) -> int:
        return int(self.points.shape[0])

    def doc_head(self) -> dict:
        """The wire doc minus ``points``. CONTRACT: ``points`` is always the
        doc's FINAL key, so ``json.dumps(doc_head())[:-1]`` + a preserialized
        ``, "points": [...]`` fragment is byte-identical to
        ``json.dumps(to_doc(include_points=True))[:-1]`` — the splice the
        body store (serve/bodystore.py) builds cached prefixes from. Meta
        keys must therefore never be named ``points``."""
        doc = {
            "version": self.version,
            "watermark_id": self.watermark_id,
            "timestamp_ms": self.timestamp_ms,
            "skyline_size": self.size,
            "digest": self.digest,
        }
        if self.event_wm_ms is not None:
            doc["event_wm_ms"] = self.event_wm_ms
        doc.update(self.meta)
        return doc

    def to_doc(self, include_points: bool = True) -> dict:
        doc = self.doc_head()
        if include_points:
            doc["points"] = self.points.tolist()
        return doc


def points_digest(points: np.ndarray) -> str:
    """Content hash of a points buffer (row order included — snapshots are
    published in the engine's canonical order, so equality is byte equality)."""
    return hashlib.sha1(np.ascontiguousarray(points).tobytes()).hexdigest()


class ReadStatus:
    """Outcome of a bounded read: the snapshot plus why/whether it's fresh."""

    __slots__ = ("snapshot", "fresh", "age_ms", "version_lag", "staleness_ms")

    def __init__(self, snapshot, fresh, age_ms, version_lag, staleness_ms=None):
        self.snapshot = snapshot
        self.fresh = fresh
        self.age_ms = age_ms
        self.version_lag = version_lag
        # event-time staleness: now - snapshot.event_wm_ms when the engine
        # publishes watermarks, else the processing-time age (the honest
        # fallback — without event stamps the publish instant is the newest
        # event knowledge we have)
        self.staleness_ms = age_ms if staleness_ms is None else staleness_ms


class SnapshotStore:
    """Single-writer (the engine thread), many-reader snapshot store.

    Writers call ``publish`` / ``note_ingest``; readers call ``latest`` /
    ``read`` / ``get``. The read path takes no lock: ``_latest`` is swapped
    atomically (one reference assignment) and every ``Snapshot`` is frozen.
    ``history`` bounds the versions kept for ``get``-by-version catch-up
    (the delta ring in ``serve/deltas.py`` subscribes via ``on_publish``).
    """

    def __init__(self, history: int = 64):
        self._latest: Snapshot | None = None  # guarded-by: self._write_lock
        self._history: deque[Snapshot] = deque(  # guarded-by: self._write_lock
            maxlen=max(1, history)
        )
        self._version = 0  # guarded-by: self._write_lock
        # _advances/_stream_watermark are deliberately NOT lock-guarded:
        # note_ingest runs on the hot ingest path and tolerates torn reads
        # (they feed monotonic lag gauges, not correctness)
        self._advances = 0  # ingest advances since the last publish
        self._stream_watermark = -1
        self._event_watermark_ms: float | None = None  # same discipline
        self._write_lock = threading.Lock()
        self._subscribers: list = []  # publish callbacks (delta ring, tests)
        self.published = 0  # guarded-by: self._write_lock
        # opaque identity of the engine state behind _latest (the partition
        # epoch key): a publish with the same key is a byte-identical repeat
        # (merge-cache hit upstream) and dedupes instead of minting a version
        self._source_key = None  # guarded-by: self._write_lock
        self.deduped = 0  # guarded-by: self._write_lock
        # True while _latest was rebuilt from the WAL (restore_state) and no
        # real publish has confirmed it yet — surfaced on /skyline so
        # clients can distinguish a recovered head from a live one
        self.restored = False  # guarded-by: self._write_lock
        self.restores = 0  # guarded-by: self._write_lock
        # outcome flag of the most recent publish (True = deduped against
        # the live snapshot): the EXPLAIN plane reads it right after its
        # own publish call on the same engine thread, so the
        # read-after-write is ordered; other readers tolerate torn reads
        self.last_publish_deduped = False  # guarded-by: self._write_lock

    # -- writer side (engine thread) --------------------------------------

    def on_publish(self, callback) -> None:
        """Register ``callback(prev: Snapshot | None, new: Snapshot)`` to run
        synchronously on the publishing thread after each swap."""
        self._subscribers.append(callback)

    def note_ingest(
        self,
        watermark_id: int | None = None,
        batches: int = 1,
        event_ms: float | None = None,
    ) -> None:
        """The engine absorbed new data: the latest snapshot is now one
        (more) version-lag unit behind. ``event_ms`` (optional) advances the
        unpublished event-time high watermark the same torn-read-tolerant
        way. Cheap — a few scalar updates."""
        self._advances += batches
        if watermark_id is not None and watermark_id > self._stream_watermark:
            self._stream_watermark = watermark_id
        if event_ms is not None and (
            self._event_watermark_ms is None
            or event_ms > self._event_watermark_ms
        ):
            self._event_watermark_ms = event_ms

    def publish(
        self,
        points: np.ndarray,
        watermark_id: int | None = None,
        now_ms: float | None = None,
        source_key=None,
        event_wm_ms: float | None = None,
        **meta,
    ) -> Snapshot:
        """Freeze ``points`` as the next version and swap it in.

        ``source_key``: optional opaque identity of the source state (the
        engine's partition-epoch key). Publishing the SAME key as the live
        snapshot is a no-op returning the existing snapshot — version
        numbering stays dense, the delta ring sees no spurious full-replace
        delta, and subscribers don't re-fire for bytes they already have.
        ``None`` (default) never dedupes."""
        fault_point("snapshot.publish")
        with self._write_lock:
            if (
                source_key is not None
                and self._latest is not None
                and self._source_key == source_key
            ):
                self.deduped += 1
                self._advances = 0
                self.last_publish_deduped = True
                return self._latest
            pts = np.ascontiguousarray(points, dtype=np.float32)
            if pts.base is None or pts is points:
                pts = pts.copy()  # never alias the engine's buffer
            if fault_fired("audit.corrupt") and pts.size:
                # divergence drill (RUNBOOK §2l): flip one byte in the
                # published body AFTER the copy so the engine's own state
                # stays sound and only the served bytes lie — exactly the
                # failure class the audit plane exists to catch. The
                # digest below is computed over the corrupted bytes, so
                # the snapshot is self-consistent and only the oracle
                # comparison can see the lie.
                pts = pts.copy()
                pts.view(np.uint8)[0] ^= 0x01
            pts.setflags(write=False)
            self._version += 1
            if watermark_id is None:
                watermark_id = self._stream_watermark
            if event_wm_ms is None:
                event_wm_ms = self._event_watermark_ms
            snap = Snapshot(
                version=self._version,
                watermark_id=int(watermark_id),
                timestamp_ms=_now_ms() if now_ms is None else now_ms,
                points=pts,
                digest=points_digest(pts),
                meta=dict(meta),
                event_wm_ms=event_wm_ms,
                source_key=source_key,
            )
            prev = self._latest
            self._history.append(snap)
            self._advances = 0
            self._latest = snap  # the atomic swap readers key off
            self._source_key = source_key
            self.published += 1
            self.restored = False  # a live publish supersedes a recovered head
            self.last_publish_deduped = False
        for cb in self._subscribers:
            cb(prev, snap)
        return snap

    def restore_state(
        self,
        points: np.ndarray,
        version: int,
        watermark_id: int = -1,
        timestamp_ms: float | None = None,
        meta: dict | None = None,
        advances: int = 0,
        event_wm_ms: float | None = None,
    ) -> Snapshot:
        """Re-seat the store from recovered state (checkpoint barrier + WAL
        deltas) WITHOUT firing subscribers: the delta ring is re-seeded
        separately from the same WAL records, so firing here would mint a
        bogus everything-entered transition. Version numbering continues
        from ``max(current, version)`` so post-restart publishes never reuse
        a version number a pre-crash subscriber already saw."""
        pts = np.ascontiguousarray(points, dtype=np.float32).copy()
        pts.setflags(write=False)
        with self._write_lock:
            self._version = max(self._version, int(version))
            snap = Snapshot(
                version=self._version,
                watermark_id=int(watermark_id),
                timestamp_ms=_now_ms() if timestamp_ms is None else timestamp_ms,
                points=pts,
                digest=points_digest(pts),
                meta=dict(meta or {}),
                event_wm_ms=event_wm_ms,
            )
            self._history.append(snap)
            self._latest = snap
            self._source_key = None  # recovered bytes never dedupe a publish
            self._advances = advances
            if event_wm_ms is not None and (
                self._event_watermark_ms is None
                or event_wm_ms > self._event_watermark_ms
            ):
                self._event_watermark_ms = event_wm_ms
            self.restored = True
            self.restores += 1
        return snap

    # -- reader side (any thread, lock-free) ------------------------------

    def latest(self) -> Snapshot | None:
        return self._latest

    def get(self, version: int) -> Snapshot | None:
        """A specific retained version (None once it ages out of history)."""
        for snap in reversed(self._history):
            if snap.version == version:
                return snap
        return None

    @property
    def head_version(self) -> int:
        return self._version

    @property
    def version_lag(self) -> int:
        """Ingest advances since the latest publish (0 = snapshot is exact)."""
        return self._advances

    @property
    def stream_watermark(self) -> int:
        return self._stream_watermark

    def read(
        self,
        max_age_ms: float | None = None,
        max_version_lag: int | None = None,
        now_ms: float | None = None,
    ) -> ReadStatus | None:
        """Bounded read of the latest snapshot; None if nothing published."""
        snap = self._latest  # one atomic load; everything below is frozen
        if snap is None:
            return None
        now = _now_ms() if now_ms is None else now_ms
        age_ms = max(0.0, now - snap.timestamp_ms)
        lag = self._advances
        fresh = True
        if max_age_ms is not None and age_ms > max_age_ms:
            fresh = False
        if max_version_lag is not None and lag > max_version_lag:
            fresh = False
        staleness = (
            max(0.0, now - snap.event_wm_ms)
            if snap.event_wm_ms is not None
            else None
        )
        return ReadStatus(snap, fresh, age_ms, lag, staleness_ms=staleness)

    def stats(self) -> dict:
        snap = self._latest
        return {
            "head_version": self._version,
            "published": self.published,
            "deduped": self.deduped,
            "restored": self.restored,
            "restores": self.restores,
            "version_lag": self._advances,
            "stream_watermark": self._stream_watermark,
            "event_watermark_ms": self._event_watermark_ms,
            "published_event_wm_ms": (
                snap.event_wm_ms if snap is not None else None
            ),
            "history_depth": len(self._history),
            "latest_size": snap.size if snap is not None else 0,
            "latest_age_ms": (
                round(max(0.0, _now_ms() - snap.timestamp_ms), 1)
                if snap is not None
                else None
            ),
        }
