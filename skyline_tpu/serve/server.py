"""Asyncio HTTP server for the query-serving plane (stdlib only).

The read-heavy counterpart of ``metrics/httpstats.py``: that module serves
operator observability; this one serves the skyline itself. Endpoints:

  GET  /skyline   snapshot read — latest published version, lock-free.
                  Query params: ``max_age_ms`` / ``max_version_lag``
                  (staleness bound; violating it is a 503 unless
                  ``allow_stale=1``), ``refresh=1`` (a stale read fires a
                  refresh merge through the worker instead of blocking on
                  one), ``points=0`` (headers only), ``format=csv`` (the
                  wire.py data-plane line format instead of JSON).
  POST /query     force a fresh consistency merge (reference-parity
                  semantics: an immediate trigger through the engine's
                  query plane) — admission-controlled, deadline-bounded.
  GET  /deltas    ``?since=<version>``: what entered/left the skyline
                  between that version and the head, from the bounded
                  delta ring; 410 Gone + ``"resync": true`` once ``since``
                  fell behind the ring (re-baseline with GET /skyline).
  GET  /subscribe SSE push of published deltas (``event: delta`` per
                  publish; ``event: resync`` when the subscriber must
                  re-baseline — slow consumer or ring overrun).
                  ``?since=V`` replays the net ring catch-up first.
  GET  /healthz   readiness probe.
  GET  /stats     worker + engine counters plus serve-plane counters.
  GET  /metrics   Prometheus text exposition (admission counters, snapshot
                  store gauges, latency histograms incl. serve read p50/p99).
  GET  /trace     Chrome trace-event JSON of the telemetry span ring
                  (Perfetto-loadable): ingest → local → merge → publish
                  spans per query when the worker shares its hub here.
  GET  /profile   per-dispatch-signature kernel profile (variant, d,
                  N-bucket, backend, mp → calls / wall / EMA / retrace
                  canary, optional cost_analysis columns).
  GET  /slo       declarative SLO table with multi-window burn rates
                  (read p99, freshness lag p99, shed fraction, restarts,
                  audit divergence).
  GET  /debug/flight  the flight recorder — last N engine decisions
                  (dispatch / cascade / prune / cache), crash black box.
  GET  /audit     audit-plane verdict: shadow-verification totals, canary
                  path coverage, divergence bundles (``?trace_id=`` joins
                  one check back to /explain and /trace).
  GET  /fleet     per-chip fleet join: ingest/flush/merge loads per chip,
                  imbalance index + skew score, freshness watermark, last
                  EXPLAIN chip attribution (sharded workers; flat workers
                  report {"enabled": false}).
  GET  /health    chip-health block (RUNBOOK §2p): per-chip score/status +
                  quarantine state (flat workers report {"enabled": false}).
  GET  /cluster   cluster block (RUNBOOK §2r): lease/role state, fenced
                  writes, promotions, per-host ingest/merge/prune stats
                  (non-cluster workers report {"enabled": false}).
  GET  /ops       durable cross-process ops journal (RUNBOOK §2s): the
                  merged control-plane timeline (``?since_seq=N``
                  per-writer floor, ``?limit=N`` newest records; workers
                  without a journal report {"enabled": false}).
  GET  /cluster/overview  fleet-wide aggregation (RUNBOOK §2s): member
                  roles/epochs/fences/heads, replication lag, and the
                  epoch-agreement (split-brain) findings.

Requests never touch the engine: reads come off the ``SnapshotStore``;
forced queries cross to the worker thread through ``QueryBridge`` (the
worker loop drains it between poll cycles), so the engine stays
single-threaded. Load shedding is explicit: 429 + Retry-After from the
admission controller, never an unbounded queue.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from collections import OrderedDict, deque
from urllib.parse import parse_qs, urlsplit

from skyline_tpu.serve.admission import AdmissionController
from skyline_tpu.telemetry import (
    PROMETHEUS_CONTENT_TYPE,
    Telemetry,
    flatten_gauges,
)

_MAX_HEADER = 16_384
_MAX_BODY = 1_048_576


class ServeConfig:
    """Knob bundle for the serving plane (mirrored by ``--serve-*`` flags)."""

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        read_rate: float = 0.0,  # snapshot-read tokens/s; 0 = unlimited
        read_burst: int = 256,
        max_concurrent_queries: int = 2,
        max_query_queue: int = 8,
        query_deadline_ms: float = 10_000.0,
        delta_ring: int = 128,
        history: int = 64,
        read_cache_entries: int = 64,
        tenant_rate: float = 0.0,  # per-tenant read tokens/s; 0 disables
        tenant_burst: int = 64,
    ):
        self.port = port
        self.host = host
        self.read_rate = read_rate
        self.read_burst = read_burst
        self.max_concurrent_queries = max_concurrent_queries
        self.max_query_queue = max_query_queue
        self.query_deadline_ms = query_deadline_ms
        self.delta_ring = delta_ring
        self.history = history
        self.read_cache_entries = read_cache_entries
        self.tenant_rate = tenant_rate
        self.tenant_burst = tenant_burst

    def admission(self, counters=None) -> AdmissionController:
        return AdmissionController(
            read_rate=self.read_rate,
            read_burst=self.read_burst,
            max_concurrent_queries=self.max_concurrent_queries,
            max_query_queue=self.max_query_queue,
            query_deadline_ms=self.query_deadline_ms,
            counters=counters,
            tenant_rate=self.tenant_rate,
            tenant_burst=self.tenant_burst,
        )


class _PendingQuery:
    __slots__ = ("qid", "event", "result")

    def __init__(self, qid: str):
        self.qid = qid
        self.event = threading.Event()
        self.result = None

    def wait(self, timeout_s: float) -> bool:
        return self.event.wait(timeout_s)


class QueryBridge:
    """Hands forced queries from HTTP threads to the engine-owner thread.

    HTTP side: ``submit()`` returns a pending handle to wait on. Engine
    side (the worker loop): ``inject(engine)`` turns submissions into
    immediate triggers, ``fulfill(results)`` routes the engine's completed
    results back to their waiters and returns the non-serve leftovers
    (which the worker emits to the output topic as before). Forced-query
    qids are namespaced so they can never collide with bus triggers.
    """

    PREFIX = "__serve-"

    def __init__(self):
        self._lock = threading.Lock()
        self._seq = 0
        self._to_inject: deque[_PendingQuery] = deque()
        self._awaiting: dict[str, _PendingQuery] = {}

    def submit(self) -> _PendingQuery:
        with self._lock:
            self._seq += 1
            p = _PendingQuery(f"{self.PREFIX}{self._seq}")
            self._to_inject.append(p)
            return p

    def inject(self, engine) -> int:
        """Dispatch queued submissions as immediate (required=0) triggers —
        reference-parity consistency-merge semantics. Engine thread only."""
        n = 0
        while True:
            with self._lock:
                if not self._to_inject:
                    return n
                p = self._to_inject.popleft()
                self._awaiting[p.qid] = p
            engine.process_trigger(f"{p.qid},0")
            n += 1

    def fulfill(self, results: list[dict]) -> list[dict]:
        """Route completed serve queries to their waiters; return the rest."""
        out = []
        for r in results:
            qid = str(r.get("query_id", ""))
            if qid.startswith(self.PREFIX):
                with self._lock:
                    p = self._awaiting.pop(qid, None)
                if p is not None:
                    p.result = r
                    p.event.set()
            else:
                out.append(r)
        return out

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._to_inject) + len(self._awaiting)

    @property
    def pending_injections(self) -> int:
        """Submissions not yet dispatched to the engine (the slice of
        ``depth`` the next ``inject`` call will actually run)."""
        with self._lock:
            return len(self._to_inject)


class SkylineServer:
    """The serving-plane HTTP front end (asyncio loop on a daemon thread)."""

    def __init__(
        self,
        store,
        deltas=None,
        admission: AdmissionController | None = None,
        stats_cb=None,
        bridge: QueryBridge | None = None,
        port: int = 0,
        host: str = "127.0.0.1",
        telemetry=None,
        read_cache: int = 64,
        max_stale_ms: float | None = None,
        role: str = "primary",
        bodystore=None,
    ):
        """``max_stale_ms``: the staleness fence — any ``/skyline`` read
        whose snapshot is older than this (event-time watermark when
        available, publish age otherwise) is refused with 503 +
        Retry-After, regardless of ``allow_stale``. The replica plane's
        honesty contract; None (primary default) disables. ``role`` rides
        ``/healthz`` and fence rejections so probes can tell a replica
        from the primary. ``bodystore``: a ``serve/bodystore.py``
        BodyStore (primary, publish-time serialized bodies) or
        BodyStoreReader (replica, the PRIMARY's exact bytes via the shared
        mmap) consulted between the LRU and the serialize-on-miss path."""
        self.store = store
        self.deltas = deltas
        self.bodystore = bodystore
        self.admission = admission if admission is not None else AdmissionController()
        self.stats_cb = stats_cb
        self.bridge = bridge
        self.max_stale_ms = max_stale_ms
        self.role = role
        # read-side result cache: serialized response bodies keyed by
        # (snapshot version, format/projection) — snapshots are immutable,
        # so repeated reads of the same version skip re-serialization (the
        # points tolist + json.dumps dominate big-skyline reads). Every
        # handler runs on the single asyncio loop thread, so the
        # OrderedDict LRU needs no lock. ``read_cache`` bounds entries;
        # 0 disables.
        self._read_cache: "OrderedDict[tuple, bytes]" = OrderedDict()
        self._read_cache_cap = max(0, int(read_cache))
        # the worker shares its hub so engine spans/histograms surface on
        # /metrics and /trace here; a standalone server gets its own (the
        # read-latency histogram still works)
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        # the SLO engine samples shed/served counts from this plane's
        # admission controller (they live on it, not the hub)
        self.telemetry.slo.attach_admission(self.admission)
        from skyline_tpu.analysis.registry import env_float

        self._ready_timeout_s = env_float("SKYLINE_SERVE_READY_TIMEOUT_S", 10.0)
        self._shutdown_timeout_s = env_float(
            "SKYLINE_SERVE_SHUTDOWN_TIMEOUT_S", 10.0
        )
        self._header_timeout_s = env_float(
            "SKYLINE_SERVE_HEADER_TIMEOUT_S", 10.0
        )
        from skyline_tpu.analysis.registry import env_int

        # SSE push (GET /subscribe): per-subscriber bounded queues fed from
        # the store's publish hook. Overflow (a slow consumer) clears the
        # queue and enqueues a resync marker — the stream never silently
        # drops a delta without telling the subscriber to re-baseline.
        self._sse_queue_cap = max(1, env_int("SKYLINE_SERVE_SSE_QUEUE", 64))
        self._sse_queues: set = set()  # mutated on the loop thread only
        self._sse_events = 0
        self._loop = asyncio.new_event_loop()
        self._server = None
        self._startup_error: BaseException | None = None
        self.port = None
        ready = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(host, port, ready), daemon=True
        )
        self._thread.start()
        ready.wait(timeout=self._ready_timeout_s)
        if self._startup_error is not None:
            raise self._startup_error
        # subscribe only once the loop is live (never on a failed startup):
        # the hook bounces publish events onto the loop thread for SSE fanout
        store.on_publish(self._sse_on_publish)

    def _run(self, host, port, ready):
        asyncio.set_event_loop(self._loop)
        try:
            self._server = self._loop.run_until_complete(
                asyncio.start_server(self._handle, host, port)
            )
            self.port = self._server.sockets[0].getsockname()[1]
        except BaseException as e:  # surfaced to __init__
            self._startup_error = e
            ready.set()
            return
        ready.set()
        try:
            self._loop.run_forever()
        finally:
            self._server.close()
            self._loop.run_until_complete(self._server.wait_closed())
            # long-lived /subscribe streams outlive run_forever: cancel and
            # reap them so loop.close() never destroys a pending task
            pending = [
                t for t in asyncio.all_tasks(self._loop) if not t.done()
            ]
            for t in pending:
                t.cancel()
            if pending:
                self._loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
            self._loop.close()

    def close(self) -> None:
        if self._startup_error is not None:
            return
        self._loop.call_soon_threadsafe(self._sse_shutdown)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=self._shutdown_timeout_s)

    # -- SSE push (/subscribe) ---------------------------------------------

    def _sse_on_publish(self, prev, snap) -> None:
        """Store publish hook (engine thread): shape one SSE event and hand
        it to the loop thread. The ring subscribed before this server, so
        its tail delta is the one for ``snap``."""
        if self._startup_error is not None or not self._sse_queues:
            return
        tail = self.deltas.latest() if self.deltas is not None else None
        if tail is not None and tail.to_version == snap.version:
            # preserialize the payload ONCE here (publish time) via the
            # Delta's memoized row fragments — every subscriber then gets
            # the same bytes with no per-connection serialization. The
            # splice is byte-identical to json.dumps of the equivalent
            # doc (test-asserted).
            payload = (
                b'{"from_version": ' + str(tail.from_version).encode()
                + b', "to_version": ' + str(tail.to_version).encode()
                + b', "watermark_id": ' + str(snap.watermark_id).encode()
                + b', "entered": ' + tail.entered_json()
                + b', "left": ' + tail.left_json()
                + b', "meta": ' + json.dumps(snap.meta).encode()
                + b"}"
            )
            event = ("delta", payload)
        else:  # no ring: announce the version; subscribers re-read
            event = ("resync", {"head_version": snap.version})
        try:
            self._loop.call_soon_threadsafe(self._sse_fanout, event)
        except RuntimeError:  # loop already closed (shutdown race)
            pass

    def _sse_fanout(self, event) -> None:
        """Loop thread: enqueue to every subscriber; a full queue (slow
        consumer) is cleared and handed an explicit resync marker instead
        of silently dropping deltas."""
        self._sse_events += 1
        for q in list(self._sse_queues):
            if q.full():
                while not q.empty():
                    q.get_nowait()
                q.put_nowait(
                    (
                        "resync",
                        {
                            "head_version": self.store.head_version,
                            "reason": "subscriber fell behind",
                        },
                    )
                )
            else:
                q.put_nowait(event)

    def _sse_shutdown(self) -> None:
        for q in list(self._sse_queues):
            if q.full():
                while not q.empty():
                    q.get_nowait()
            q.put_nowait(None)  # sentinel: stream handlers exit cleanly

    async def _subscribe(self, writer, params):
        """SSE stream of published deltas. ``?since=V`` replays the net
        catch-up from the ring first; a ``since`` behind the ring (or no
        ring) opens with an explicit ``resync`` event — same contract as
        the 410 on ``/deltas``."""
        try:
            since = _int_param(params, "since")
        except ValueError as e:
            await self._reply(writer, 400, {"error": str(e)})
            return
        head = [
            "HTTP/1.1 200 OK",
            "Content-Type: text/event-stream",
            "Cache-Control: no-cache",
            "Connection: close",
        ]
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode())
        q: asyncio.Queue = asyncio.Queue(maxsize=self._sse_queue_cap)
        self._sse_queues.add(q)
        try:
            if since is not None:
                res = self.deltas.since(since) if self.deltas is not None else None
                if res is None:
                    await self._sse_write(
                        writer,
                        "resync",
                        {
                            "since": since,
                            "head_version": self.store.head_version,
                            "hint": "re-baseline with GET /skyline",
                        },
                    )
                else:
                    entered, left, hv = res
                    from skyline_tpu.serve.bodystore import points_json

                    await self._sse_write(
                        writer,
                        "delta",
                        b'{"from_version": ' + str(since).encode()
                        + b', "to_version": ' + str(hv).encode()
                        + b', "entered": ' + points_json(entered)
                        + b', "left": ' + points_json(left)
                        + b"}",
                    )
            while True:
                try:
                    item = await asyncio.wait_for(q.get(), timeout=15.0)
                except asyncio.TimeoutError:
                    writer.write(b": keepalive\n\n")
                    await writer.drain()
                    continue
                if item is None:
                    break
                await self._sse_write(writer, item[0], item[1])
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            self._sse_queues.discard(q)

    async def _sse_write(self, writer, kind: str, doc) -> None:
        """``doc``: a dict (serialized here) or preserialized payload bytes
        (the publish-time fast path — one encode shared by every stream)."""
        data = doc if isinstance(doc, bytes) else json.dumps(doc).encode()
        writer.write(b"event: " + kind.encode() + b"\ndata: " + data + b"\n\n")
        await writer.drain()

    # -- request plumbing --------------------------------------------------

    async def _handle(self, reader, writer):
        try:
            try:
                head = await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"),
                    timeout=self._header_timeout_s,
                )
            except (
                asyncio.IncompleteReadError,
                asyncio.LimitOverrunError,
                asyncio.TimeoutError,
            ):
                return
            if len(head) > _MAX_HEADER:
                await self._reply(writer, 431, {"error": "headers too large"})
                return
            lines = head.decode("latin-1").split("\r\n")
            parts = lines[0].split(" ")
            if len(parts) != 3:
                await self._reply(writer, 400, {"error": "bad request line"})
                return
            method, target, _version = parts
            headers = {}
            for ln in lines[1:]:
                if ":" in ln:
                    k, _, v = ln.partition(":")
                    headers[k.strip().lower()] = v.strip()
            clen = int(headers.get("content-length", "0") or "0")
            if clen > _MAX_BODY:
                await self._reply(writer, 413, {"error": "body too large"})
                return
            if clen:
                await reader.readexactly(clen)  # body currently unused
            url = urlsplit(target)
            params = {k: v[-1] for k, v in parse_qs(url.query).items()}
            await self._route(writer, method, url.path, params, headers)
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _route(self, writer, method, path, params, headers=None):
        tenant = (headers or {}).get("x-tenant")
        if path == "/healthz":
            await self._reply(
                writer,
                200,
                {
                    "ok": True,
                    "published": self.store.published > 0,
                    "role": self.role,
                },
            )
        elif path == "/stats" and method == "GET":
            await self._reply(writer, 200, self._stats())
        elif path == "/metrics" and method == "GET":
            await self._metrics(writer)
        elif path == "/trace" and method == "GET":
            await self._reply(writer, 200, self.telemetry.spans.to_chrome())
        elif path == "/skyline" and method == "GET":
            t0 = time.perf_counter_ns()
            await self._skyline(writer, params, tenant=tenant)
            self.telemetry.histogram("serve_read_ms").observe(
                (time.perf_counter_ns() - t0) / 1e6
            )
        elif path == "/deltas" and method == "GET":
            t0 = time.perf_counter_ns()
            await self._deltas(writer, params, tenant=tenant)
            self.telemetry.histogram("serve_read_ms").observe(
                (time.perf_counter_ns() - t0) / 1e6
            )
        elif path == "/subscribe" and method == "GET":
            await self._subscribe(writer, params)
        elif path == "/query" and method == "POST":
            await self._query(writer)
        elif path == "/profile" and method == "GET":
            await self._reply(writer, 200, self.telemetry.profiler.doc())
        elif path == "/slo" and method == "GET":
            await self._reply(writer, 200, self.telemetry.slo.evaluate())
        elif path == "/debug/flight" and method == "GET":
            await self._reply(writer, 200, self.telemetry.flight.doc())
        elif path == "/explain" and method == "GET":
            await self._explain(writer, params)
        elif path == "/audit" and method == "GET":
            await self._audit(writer, params)
        elif path == "/dispatch" and method == "GET":
            await self._dispatch(writer)
        elif path == "/fleet" and method == "GET":
            await self._fleet(writer)
        elif path == "/health" and method == "GET":
            await self._health(writer)
        elif path == "/cluster" and method == "GET":
            await self._cluster(writer)
        elif path == "/ops" and method == "GET":
            await self._ops(writer, params)
        elif path == "/cluster/overview" and method == "GET":
            await self._overview(writer)
        else:
            await self._reply(writer, 404, {"error": "not found"})

    def _stats(self) -> dict:
        try:
            out = dict(self.stats_cb()) if self.stats_cb is not None else {}
        except Exception as e:  # observability must not 500 the plane down
            out = {"stats_error": str(e)}
        out["serve"] = self.admission.stats()
        out["serve"]["role"] = self.role
        out["serve"]["sse_subscribers"] = len(self._sse_queues)
        out["serve"]["sse_events"] = self._sse_events
        if self.max_stale_ms is not None:
            out["serve"]["max_stale_ms"] = self.max_stale_ms
        out["snapshot_store"] = self.store.stats()
        if self.bodystore is not None:
            out["bodystore"] = self.bodystore.stats()
        if self.deltas is not None:
            out["delta_ring"] = self.deltas.stats()
        if self.bridge is not None:
            out["serve"]["bridge_depth"] = self.bridge.depth
        return out

    # -- read-side result cache --------------------------------------------

    def _cache_get(self, key) -> bytes | None:
        body = self._read_cache.get(key)
        if body is None:
            self.admission.counters.inc("read_cache_misses")
            return None
        self._read_cache.move_to_end(key)
        self.admission.counters.inc("read_cache_hits")
        return body

    def _cache_put(self, key, body: bytes) -> None:
        if self._read_cache_cap == 0:
            return
        self._read_cache[key] = body
        self._read_cache.move_to_end(key)
        while len(self._read_cache) > self._read_cache_cap:
            self._read_cache.popitem(last=False)

    def _body_get(self, version: int, fmt: int) -> bytes | None:
        """The body store tier between the LRU and serialize-on-miss: the
        publisher's preserialized bytes (primary: retained objects;
        replica: the primary's mmap frames behind the seqlock+fence
        check). Hits/misses/torn reads are counted on the store itself and
        surfaced by /metrics as ``skyline_serve_bodystore_*``."""
        if self.bodystore is None:
            return None
        return self.bodystore.get(version, fmt)

    # -- endpoints ---------------------------------------------------------

    async def _metrics(self, writer):
        """Prometheus text exposition: admission counters (as counters),
        snapshot-store / delta-ring stats (as gauges), histograms."""
        gauges = flatten_gauges({"snapshot_store": self.store.stats()})
        if self.deltas is not None:
            gauges.update(flatten_gauges({"delta_ring": self.deltas.stats()}))
        if self.bridge is not None:
            gauges["serve_bridge_depth"] = float(self.bridge.depth)
        gauges["serve_query_depth"] = float(self.admission.queries.depth)
        gauges["serve_sse_subscribers"] = float(len(self._sse_queues))
        counters = {
            f"serve_{k}": v
            for k, v in self.admission.counters.snapshot().items()
        }
        if self.bodystore is not None:
            # zero-copy body-store families: hits/misses/torn_reads/retries
            # plus the publish-side serializer tallies (RUNBOOK §2u)
            counters.update(
                {
                    f"serve_bodystore_{k}": v
                    for k, v in self.bodystore.stats().items()
                }
            )
        # per-tenant admission series: one labeled family per outcome, so
        # dashboards see exactly who is being shed
        tenants = self.admission.tenant_stats()
        labeled = None
        if tenants:
            labeled = {
                "serve_tenant_reads_admitted": [
                    ((("tenant", t),), row["admitted"])
                    for t, row in tenants.items()
                ],
                "serve_tenant_reads_shed": [
                    ((("tenant", t),), row["shed"])
                    for t, row in tenants.items()
                ],
            }
        body = self.telemetry.render_prometheus(
            gauges=gauges,
            extra_counters=counters,
            extra_labeled_counters=labeled,
        ).encode()
        await self._reply_raw(writer, 200, body, PROMETHEUS_CONTENT_TYPE)

    async def _skyline(self, writer, params, tenant=None):
        ok, retry = self.admission.admit_read(tenant=tenant)
        if not ok:
            await self._reply(
                writer,
                429,
                {"error": "rate limited", "retry_after_s": round(retry, 3)},
                retry_after=retry,
            )
            return
        try:
            max_age = _float_param(params, "max_age_ms")
            max_lag = _int_param(params, "max_version_lag")
        except ValueError as e:
            await self._reply(writer, 400, {"error": str(e)})
            return
        rs = self.store.read(max_age_ms=max_age, max_version_lag=max_lag)
        if rs is None:
            await self._reply(
                writer, 503, {"error": "no snapshot published yet"}
            )
            return
        if (
            self.max_stale_ms is not None
            and rs.staleness_ms is not None
            and rs.staleness_ms > self.max_stale_ms
        ):
            # the staleness fence: a replica that fell too far behind the
            # WAL refuses to answer rather than serve silently ancient
            # data — allow_stale bounds the CLIENT's tolerance, never the
            # server's own honesty contract
            self.admission.counters.inc("fence_rejected")
            await self._reply(
                writer,
                503,
                {
                    "error": "staleness fence exceeded",
                    "role": self.role,
                    "version": rs.snapshot.version,
                    "staleness_ms": round(rs.staleness_ms, 1),
                    "max_stale_ms": self.max_stale_ms,
                    "stale": True,
                },
                retry_after=1.0,
            )
            return
        refresh_triggered = False
        if not rs.fresh:
            self.admission.counters.inc("stale_reads")
            if params.get("refresh") == "1" and self.bridge is not None:
                # fire the refresh merge, serve (or reject) without blocking
                self.bridge.submit()
                self.admission.counters.inc("refreshes_triggered")
                refresh_triggered = True
            if params.get("allow_stale") != "1":
                self.admission.counters.inc("stale_rejected")
                await self._reply(
                    writer,
                    503,
                    {
                        "error": "snapshot stale for requested bound",
                        "version": rs.snapshot.version,
                        "age_ms": round(rs.age_ms, 1),
                        "version_lag": rs.version_lag,
                        "refresh_triggered": refresh_triggered,
                    },
                )
                return
        self.admission.counters.inc("reads_served")
        snap = rs.snapshot
        if params.get("format") == "csv":
            body = self._cache_get((snap.version, "csv"))
            if body is None:
                from skyline_tpu.serve import bodystore as bs

                body = self._body_get(snap.version, bs.FMT_CSV)
                if body is None:
                    body = bs.csv_body(snap)
                self._cache_put((snap.version, "csv"), body)
            await self._reply_raw(
                writer,
                200,
                body,
                "text/plain; charset=utf-8",
                extra_headers={
                    "X-Skyline-Version": str(snap.version),
                    "X-Skyline-Digest": snap.digest,
                    "X-Skyline-Size": str(snap.size),
                    "X-Skyline-Staleness-Ms": str(round(rs.staleness_ms, 1)),
                },
            )
            return
        # the snapshot-derived fields are immutable per version, so the
        # serialized doc caches minus its closing brace; the read-dependent
        # fields (age/lag/staleness) splice on as a tiny per-request suffix
        include_points = params.get("points") != "0"
        # explain bodies MUST NOT share cache entries with plain reads:
        # one flavor cached under the other's key would break the plain
        # body's byte-stability (ISSUE 9 satellite). The plan itself also
        # rides the volatile tail, never the cached prefix — deduped
        # publishes can map several plans onto one snapshot version.
        want_explain = params.get("explain") == "1"
        prefix = self._cache_get(
            (snap.version, "json", include_points, want_explain)
        )
        if prefix is None:
            from skyline_tpu.serve import bodystore as bs

            prefix = self._body_get(
                snap.version, bs.fmt_code("json", include_points, want_explain)
            )
            if prefix is None:
                prefix = bs.json_prefix(snap, include_points=include_points)
            self._cache_put(
                (snap.version, "json", include_points, want_explain), prefix
            )
        tail = (
            f', "age_ms": {round(rs.age_ms, 1)}'
            f', "version_lag": {rs.version_lag}'
            f', "staleness_ms": {round(rs.staleness_ms, 1)}'
            f', "stale": {"true" if not rs.fresh else "false"}'
        )
        if want_explain:
            plan = self.telemetry.explain.by_version(snap.version)
            tail += ', "explain": ' + (
                json.dumps(plan) if plan is not None else "null"
            )
        # the freshness lineage's terminal stage: how old the newest event
        # a CLIENT actually saw was at response time (event-time when the
        # snapshot carries a watermark, publish-age otherwise)
        self.telemetry.histogram(
            "freshness_lag_ms", labels=(("stage", "read"),)
        ).observe(rs.staleness_ms)
        if refresh_triggered:
            tail += ', "refresh_triggered": true'
        if self.store.restored:
            # head was rebuilt from checkpoint + WAL and no live publish has
            # confirmed it yet (crash recovery)
            tail += ', "restored": true'
        await self._reply_raw(
            writer, 200, prefix + tail.encode() + b"}", "application/json"
        )

    async def _explain(self, writer, params):
        """One finalized QueryPlan from the hub's EXPLAIN ring:
        ``?version=N`` maps a snapshot version to the newest plan that
        published it, ``?trace_id=`` joins from a span / flight-ring row,
        and no selector returns the latest plan. 404 carries the ring
        summary so "evicted" vs "never recorded" is diagnosable."""
        try:
            version = _int_param(params, "version")
        except ValueError as e:
            await self._reply(writer, 400, {"error": str(e)})
            return
        trace = params.get("trace_id")
        rec = self.telemetry.explain
        if version is not None:
            plan = rec.by_version(version)
        elif trace:
            plan = rec.by_trace(trace)
        else:
            plan = rec.latest()
        if plan is None:
            await self._reply(
                writer, 404, {"error": "no matching plan", "ring": rec.doc()}
            )
            return
        await self._reply(writer, 200, plan)

    async def _audit(self, writer, params):
        """The audit-plane verdict from the hub's check ring: totals,
        canary path coverage, divergence bundles. ``?trace_id=`` returns
        the single check record for that snapshot's trace — the join back
        into /explain and /trace."""
        rec = self.telemetry.audit
        trace = params.get("trace_id")
        if trace:
            check = rec.by_trace(trace)
            if check is None:
                await self._reply(
                    writer, 404,
                    {"error": "no matching check", "ring": rec.doc()},
                )
                return
            await self._reply(writer, 200, check)
            return
        await self._reply(writer, 200, rec.doc())

    async def _fleet(self, writer):
        """The per-chip fleet join (telemetry/fleet.py): chip loads +
        imbalance index + freshness watermark + last EXPLAIN chip
        attribution. Observability must not 500 the plane down, so the
        stats callback failure degrades to a watermark-less doc."""
        from skyline_tpu.telemetry import fleet_doc

        try:
            stats = dict(self.stats_cb()) if self.stats_cb is not None else {}
        except Exception:
            stats = {}
        await self._reply(writer, 200, fleet_doc(self.telemetry, stats))

    async def _dispatch(self, writer):
        """The declarative cascade table + live tuner decisions (ISSUE
        20): every dispatch row's applicability/oracle, the active pins
        and knob overrides, and the controller's recent moves."""
        from skyline_tpu.telemetry.tuner import dispatch_doc

        await self._reply(writer, 200, dispatch_doc(self.telemetry))

    async def _health(self, writer):
        """The /health chip block (RUNBOOK §2p): per-chip health scores +
        quarantine state. Flat workers report {"enabled": false} so probes
        can distinguish "plane off" from "all healthy"."""
        health = getattr(self.telemetry, "health", None)
        if health is None:
            await self._reply(writer, 200, {"ok": True, "enabled": False})
            return
        doc = health.doc()
        doc["ok"] = not doc.get("quarantined")
        doc["enabled"] = True
        await self._reply(writer, 200, doc)

    async def _cluster(self, writer):
        """The /cluster block (RUNBOOK §2r): lease/role state, fenced-write
        and promotion counters, per-host ingest/merge/prune stats.
        Non-cluster workers report {"enabled": false} so probes can
        distinguish "plane off" from "healthy single-host"."""
        status = getattr(self.telemetry, "cluster", None)
        if status is None:
            await self._reply(writer, 200, {"ok": True, "enabled": False})
            return
        try:
            await self._reply(writer, 200, status.doc())
        except Exception as e:  # observability must not 500 the plane down
            await self._reply(writer, 500, {"error": str(e)})

    async def _ops(self, writer, params):
        """The /ops journal tail (RUNBOOK §2s): the merged cross-process
        control-plane timeline. Probe-friendly — {"enabled": false} when
        this process opened no journal."""
        from skyline_tpu.telemetry.opslog import ops_doc

        try:
            since = _int_param(params, "since_seq")
            limit = _int_param(params, "limit")
        except ValueError as e:
            await self._reply(writer, 400, {"error": str(e)})
            return
        ops = getattr(self.telemetry, "opslog", None)
        if ops is None:
            await self._reply(writer, 200, {"ok": True, "enabled": False})
            return
        await self._reply(
            writer, 200, ops_doc(ops.wal_dir, since_seq=since, limit=limit)
        )

    async def _overview(self, writer):
        """The /cluster/overview fleet aggregation (RUNBOOK §2s):
        per-member role/epoch/fence/head, replication lag, and the
        epoch-agreement (split-brain) findings. The scrape is blocking
        network I/O, so it runs in an executor — a member whose view
        lists its own URL must not stall the loop that would answer
        that self-scrape."""
        from skyline_tpu.telemetry.clusterview import overview_doc

        loop = asyncio.get_running_loop()
        doc = await loop.run_in_executor(None, overview_doc, self.telemetry)
        await self._reply(writer, 200, doc)

    async def _deltas(self, writer, params, tenant=None):
        ok, retry = self.admission.admit_read(tenant=tenant)
        if not ok:
            await self._reply(
                writer,
                429,
                {"error": "rate limited", "retry_after_s": round(retry, 3)},
                retry_after=retry,
            )
            return
        if self.deltas is None:
            await self._reply(writer, 503, {"error": "no delta ring attached"})
            return
        try:
            since = _int_param(params, "since")
        except ValueError as e:
            await self._reply(writer, 400, {"error": str(e)})
            return
        if since is None:
            await self._reply(writer, 400, {"error": "missing ?since=<version>"})
            return
        res = self.deltas.since(since)
        if res is None:
            self.admission.counters.inc("deltas_gone")
            await self._reply(
                writer,
                410,
                {
                    "error": "version fell behind the delta ring",
                    # explicit machine-readable marker: a catch-up past the
                    # ring MUST re-baseline from a full snapshot — never
                    # interpret this body as an empty/partial delta list
                    "resync": True,
                    "since": since,
                    "oldest_since": self.deltas.oldest_since,
                    "head_version": self.deltas.head_version,
                    "hint": "re-baseline with GET /skyline",
                },
            )
            return
        entered, left, head = res
        self.admission.counters.inc("deltas_served")
        rs = self.store.read()
        # spliced assembly (byte-identical to json.dumps of the doc —
        # test-asserted): the row arrays go through the body store's
        # native-backed encoder instead of tolist() + json.dumps
        from skyline_tpu.serve.bodystore import points_json

        sms = round(rs.staleness_ms, 1) if rs is not None else None
        body = (
            b'{"from_version": ' + str(since).encode()
            + b', "to_version": ' + str(head).encode()
            + b', "resync": false'
            + b', "count_entered": ' + str(int(entered.shape[0])).encode()
            + b', "count_left": ' + str(int(left.shape[0])).encode()
            + b', "entered": ' + points_json(entered)
            + b', "left": ' + points_json(left)
            # the freshness watermark rides every read surface
            + b', "staleness_ms": ' + json.dumps(sms).encode()
            + b"}"
        )
        await self._reply_raw(writer, 200, body, "application/json")

    async def _query(self, writer):
        if self.bridge is None:
            await self._reply(
                writer, 503, {"error": "no query plane attached"}
            )
            return
        gate = self.admission.queries
        if not gate.enter():
            await self._reply(
                writer,
                429,
                {"error": "query admission limit exceeded"},
                retry_after=1.0,
            )
            return
        try:
            pending = self.bridge.submit()
            deadline_s = self.admission.query_deadline_ms / 1000.0
            done = await self._loop.run_in_executor(
                None, pending.wait, deadline_s
            )
            if not done:
                self.admission.counters.inc("queries_timed_out")
                await self._reply(
                    writer,
                    503,
                    {
                        "error": "query deadline exceeded",
                        "deadline_ms": self.admission.query_deadline_ms,
                    },
                )
                return
            self.admission.counters.inc("queries_served")
            await self._reply(writer, 200, pending.result)
        finally:
            gate.leave()

    # -- response helpers --------------------------------------------------

    async def _reply(self, writer, code, doc, retry_after=None):
        extra = (
            {"Retry-After": str(max(1, int(retry_after + 0.999)))}
            if retry_after is not None
            else None
        )
        await self._reply_raw(
            writer,
            code,
            json.dumps(doc).encode(),
            "application/json",
            extra_headers=extra,
        )

    async def _reply_raw(self, writer, code, body, ctype, extra_headers=None):
        reason = {
            200: "OK", 400: "Bad Request", 404: "Not Found",
            410: "Gone", 413: "Payload Too Large", 429: "Too Many Requests",
            431: "Request Header Fields Too Large", 503: "Service Unavailable",
        }.get(code, "OK")
        head = [
            f"HTTP/1.1 {code} {reason}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(body)}",
            "Connection: close",
        ]
        for k, v in (extra_headers or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
        await writer.drain()


def _float_param(params, name):
    v = params.get(name)
    if v is None:
        return None
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"bad {name}: {v!r}")


def _int_param(params, name):
    v = params.get(name)
    if v is None:
        return None
    try:
        return int(v)
    except ValueError:
        raise ValueError(f"bad {name}: {v!r}")
