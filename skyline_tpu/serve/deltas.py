"""Delta computation between consecutive skyline snapshots.

A subscriber that has version V and wants the head doesn't need the full
snapshot again — skylines evolve slowly relative to their size (most
points survive each merge), so the (entered, left) set difference is the
cheap catch-up currency. Snapshots don't carry tuple ids (the engine's
device buffers hold values only — skyline membership is a property of the
point, and duplicates merge), so rows are keyed by their byte image: each
(d,) float32 row viewed as one opaque void scalar, which numpy sorts and
set-intersects with memcmp — the vectorized path, no per-row Python
objects.

``DeltaRing`` subscribes to a ``SnapshotStore`` and keeps the last
``capacity`` per-transition deltas, so ``/deltas?since=V`` answers from the
ring; a subscriber that fell further behind than the ring gets a "gone"
signal and re-baselines from a full snapshot read.
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np


def _row_keys(points: np.ndarray) -> np.ndarray:
    """(n, d) float32 -> (n,) void keys (one memcmp-comparable scalar/row)."""
    pts = np.ascontiguousarray(points, dtype=np.float32)
    if pts.shape[0] == 0:
        return np.empty(0, dtype=np.dtype((np.void, max(pts.shape[1], 1) * 4)))
    return pts.view(np.dtype((np.void, pts.shape[1] * pts.itemsize))).reshape(-1)


def snapshot_delta(old_points, new_points):
    """(entered, left) between two point sets, vectorized on void row-keys.

    entered = rows of ``new`` absent from ``old``; left = rows of ``old``
    absent from ``new``. Duplicate rows within a set collapse (a skyline is
    a set; the engine never emits duplicates, but the delta law shouldn't
    depend on it).
    """
    old = np.ascontiguousarray(old_points, dtype=np.float32)
    new = np.ascontiguousarray(new_points, dtype=np.float32)
    if old.shape[0] == 0:
        return np.unique(new, axis=0) if new.shape[0] else new, old
    if new.shape[0] == 0:
        return new, np.unique(old, axis=0)
    ok, nk = _row_keys(old), _row_keys(new)
    entered = new[~np.isin(nk, ok)]
    left = old[~np.isin(ok, nk)]
    if entered.shape[0]:
        entered = np.unique(entered, axis=0)
    if left.shape[0]:
        left = np.unique(left, axis=0)
    return entered, left


def order_permutation(candidate: np.ndarray, target: np.ndarray):
    """Indices ``perm`` with ``candidate[perm]`` byte-equal to ``target``,
    or None when the row multisets differ. Duplicate rows are matched
    positionally (first unclaimed candidate slot wins) — any assignment of
    equal rows is byte-equivalent."""
    if candidate.shape != target.shape:
        return None
    from collections import defaultdict, deque as _deque

    slots: dict[bytes, _deque] = defaultdict(_deque)
    for i, k in enumerate(_row_keys(candidate)):
        slots[k.tobytes()].append(i)
    perm = np.empty(target.shape[0], dtype=np.int64)
    for j, k in enumerate(_row_keys(target)):
        q = slots.get(k.tobytes())
        if not q:
            return None
        perm[j] = q.popleft()
    return perm


def apply_delta_record(points: np.ndarray, rec: dict) -> np.ndarray:
    """Fold one WAL ``delta`` record into ``points``, reproducing the
    primary's snapshot bytes exactly when the record carries ordering info
    (``rows`` full override, or ``perm`` over [kept-in-prev-order,
    entered]); set-exact otherwise — the pre-replication WAL format."""
    from skyline_tpu.resilience.wal import rows_from_b64

    d = int(rec["d"])
    if "rows" in rec:  # perm construction failed on the primary: full copy
        return rows_from_b64(rec["rows"], d)
    entered = rows_from_b64(rec["entered"], d)
    left = rows_from_b64(rec["left"], d)
    kept = points
    if left.shape[0] and points.shape[0]:
        kept = points[~np.isin(_row_keys(points), _row_keys(left))]
    if entered.shape[0]:
        new = np.concatenate([kept, entered]) if kept.shape[0] else entered
    else:
        new = kept
    if "perm" in rec:
        new = new[np.asarray(rec["perm"], dtype=np.int64)]
    return np.ascontiguousarray(new, dtype=np.float32)


def delta_wal_record(prev, snap) -> dict:
    """Build the WAL ``delta`` record for one publish transition.

    Inverse of :func:`apply_delta_record`: besides the (entered, left) set
    difference it carries the ordering info (``perm`` over
    [kept-in-prev-order, entered], or full ``rows`` when the multisets defy
    a permutation) so a WAL follower reproduces the snapshot BYTES, not
    just the set. Shared by the worker's publish hook, the replica bench
    leg, and the replica tests — one encoder, one decoder.
    """
    from skyline_tpu.resilience.wal import rows_to_b64

    entered, left = snapshot_delta(
        prev.points
        if prev is not None
        else np.empty((0, snap.points.shape[1]), dtype=np.float32),
        snap.points,
    )
    rec = {
        "type": "delta",
        "from": prev.version if prev is not None else 0,
        "to": snap.version,
        "wm": snap.watermark_id,
        "ts": snap.timestamp_ms,
        "d": int(snap.points.shape[1]),
        "entered": rows_to_b64(entered),
        "left": rows_to_b64(left),
        "digest": snap.digest,
    }
    if snap.event_wm_ms is not None:
        rec["ewm"] = snap.event_wm_ms  # freshness lineage survives restart
    if snap.meta:
        rec["meta"] = snap.meta  # partial/excluded_chips survive the tail
    kept = (
        prev.points
        if prev is not None and not left.shape[0]
        else (
            prev.points[~np.isin(_row_keys(prev.points), _row_keys(left))]
            if prev is not None and prev.points.shape[0]
            else np.empty((0, snap.points.shape[1]), dtype=np.float32)
        )
    )
    candidate = np.concatenate([kept, entered]) if kept.shape[0] else entered
    perm = order_permutation(candidate, snap.points)
    if perm is None:
        rec["rows"] = rows_to_b64(snap.points)
    elif not np.array_equal(perm, np.arange(perm.shape[0])):
        rec["perm"] = perm.tolist()
    return rec


def snapshot_wal_record(snap) -> dict:
    """The ``snap`` block of a WAL ``ckpt`` barrier: the exact serve head
    (bytes, lineage, and honesty meta) a bootstrap restores from."""
    from skyline_tpu.resilience.wal import rows_to_b64

    rec = {
        "version": snap.version,
        "watermark_id": snap.watermark_id,
        "timestamp_ms": snap.timestamp_ms,
        "d": int(snap.points.shape[1]),
        "rows": rows_to_b64(snap.points),
    }
    if snap.event_wm_ms is not None:
        rec["event_wm_ms"] = snap.event_wm_ms
    if snap.meta:
        # degraded heads (partial/excluded_chips) must survive a bootstrap
        # honestly — never laundered clean by recovery
        rec["meta"] = snap.meta
    return rec


class Delta:
    """One published transition: what changed going from_version -> to_version."""

    __slots__ = (
        "from_version",
        "to_version",
        "entered",
        "left",
        "_entered_json",
        "_left_json",
    )

    def __init__(self, from_version, to_version, entered, left):
        self.from_version = from_version
        self.to_version = to_version
        self.entered = entered
        self.left = left
        self._entered_json = None
        self._left_json = None

    # preserialized wire fragments, byte-identical to
    # ``json.dumps(arr.tolist()).encode()`` — memoized so the /deltas
    # handler, the SSE fanout, and replica re-serves of one transition pay
    # the row encoding once (through the body store's native row encoder
    # when the .so is present)

    def entered_json(self) -> bytes:
        if self._entered_json is None:
            from skyline_tpu.serve.bodystore import points_json

            self._entered_json = points_json(self.entered)
        return self._entered_json

    def left_json(self) -> bytes:
        if self._left_json is None:
            from skyline_tpu.serve.bodystore import points_json

            self._left_json = points_json(self.left)
        return self._left_json


class DeltaRing:
    """Bounded ring of recent snapshot transitions.

    Attach with ``ring = DeltaRing(store)`` — it subscribes to the store's
    publish hook and computes each transition's delta on the publishing
    thread (one vectorized set-diff per publish). ``since(v)`` merges the
    transitions v -> head into one net (entered, left) pair: a point that
    entered and then left inside the span cancels out, so the merge result
    is exactly the set difference between snapshot v and the head.
    """

    def __init__(self, store=None, capacity: int = 128):
        self._ring: deque[Delta] = deque(  # guarded-by: self._lock
            maxlen=max(1, capacity)
        )
        self._lock = threading.Lock()
        self.head_version = 0  # guarded-by: self._lock
        if store is not None:
            store.on_publish(self.on_publish)

    def on_publish(self, prev, snap) -> None:
        entered, left = snapshot_delta(
            prev.points if prev is not None else np.empty((0, snap.points.shape[1]), np.float32),
            snap.points,
        )
        with self._lock:
            self._ring.append(
                Delta(prev.version if prev is not None else 0, snap.version, entered, left)
            )
            self.head_version = snap.version

    def seed(self, deltas, head_version: int) -> None:
        """Replace the ring's content with recovered transitions (WAL
        ``delta`` records) — the restart half of delta persistence: a
        subscriber holding a pre-crash version keeps catching up through
        ``since`` as if the process never died."""
        with self._lock:
            self._ring.clear()
            self._ring.extend(deltas)
            self.head_version = max(int(head_version), 0)

    def latest(self) -> Delta | None:
        """Most recent transition (None when the ring is empty). The SSE
        fanout reads this in the store's publish hook: the ring subscribes
        to the store before the server does, so at callback time the tail
        delta is the one for the snapshot just published."""
        with self._lock:
            return self._ring[-1] if self._ring else None

    @property
    def oldest_since(self) -> int | None:
        """The smallest ``since`` the ring can still answer (None = empty)."""
        with self._lock:
            return self._ring[0].from_version if self._ring else None

    def since(self, version: int):
        """Net (entered, left, to_version) from ``version`` to the head.

        Returns None when ``version`` fell behind the ring (subscriber must
        re-baseline from a snapshot). ``version >= head`` returns empty
        arrays — the caller is current.
        """
        with self._lock:
            transitions = [t for t in self._ring if t.from_version >= version]
            head = self.head_version
            covered = bool(self._ring) and self._ring[0].from_version <= version
        if version >= head:
            return (
                np.empty((0, 0), np.float32),
                np.empty((0, 0), np.float32),
                head,
            )
        if not covered:
            return None
        # merge transitions oldest-first: membership flips cancel pairwise
        state: dict[bytes, tuple[int, np.ndarray]] = {}
        for t in transitions:
            for row in t.entered:
                k = row.tobytes()
                if k in state and state[k][0] < 0:
                    del state[k]  # left earlier in the span: net no-op
                else:
                    state[k] = (1, row)
            for row in t.left:
                k = row.tobytes()
                if k in state and state[k][0] > 0:
                    del state[k]  # entered earlier in the span: net no-op
                else:
                    state[k] = (-1, row)
        entered = [r for s, r in state.values() if s > 0]
        left = [r for s, r in state.values() if s < 0]
        d = transitions[0].entered.shape[1] if transitions and transitions[0].entered.ndim == 2 else 0
        stack = lambda rows: (  # noqa: E731 — tiny local shaping helper
            np.stack(rows) if rows else np.empty((0, d), np.float32)
        )
        return stack(entered), stack(left), head

    def stats(self) -> dict:
        with self._lock:
            return {
                "ring_depth": len(self._ring),
                "ring_capacity": self._ring.maxlen,
                "head_version": self.head_version,
                "oldest_since": self._ring[0].from_version if self._ring else None,
            }
