"""Fully-jitted windowed query pipeline over a mesh (partition → local → merge).

The one-call on-device equivalent of the engine's per-window work, used by the
flagship entry point and the multi-chip dry run: compute partition ids with
the configured MR-* strategy, group rows by their target device with one
argsort (the keyBy shuffle, FlinkSkyline.java:138), equal-split the grouped
rows across the mesh, run the sharded two-phase skyline, and report the
global mask plus per-phase counts.

Shard-size note: real partitions are data-dependent in size, so the SPMD
split assigns each device an equal contiguous slice of the partition-sorted
order. Rows of one logical partition can straddle two devices at slice
boundaries; the global skyline is provably invariant to placement (the merge
law, SURVEY.md §4), so this only marginally affects local-phase pruning
rates, not results.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skyline_tpu.parallel.mesh import AXIS, build_two_phase
from skyline_tpu.parallel.partitioners import partition_ids


def build_window_pipeline(
    mesh: Mesh,
    *,
    algo: str = "mr-angle",
    num_partitions: int | None = None,
    domain_max: float = 1000.0,
    axis: str = AXIS,
    local_block: int = 2048,
    cross_block: int = 8192,
):
    """Returns jitted ``step(x, valid) ->
    (global_keep, local_count, global_count, order)``.

    x: (N, d) window (replicated input), N divisible by the mesh size.
    ``global_keep`` is aligned to the *partition-sorted* row order given by
    ``order`` (``x[order]`` are the sorted rows); invert with
    ``argsort(order)`` to map the mask back to input order.
    """
    n_dev = int(mesh.devices.size)
    if num_partitions is None:
        num_partitions = 2 * n_dev  # reference's 2x over-partitioning

    two_phase = build_two_phase(
        mesh, axis=axis, local_block=local_block, cross_block=cross_block
    )
    x_sharding = NamedSharding(mesh, P(axis))

    @jax.jit
    def step(x, valid):
        pids = partition_ids(x, algo, num_partitions, domain_max)
        dev = pids % n_dev  # logical partition -> device, round-robin
        order = jnp.argsort(jnp.where(valid, dev, n_dev), stable=True)
        xs = jax.lax.with_sharding_constraint(x[order], x_sharding)
        vs = jax.lax.with_sharding_constraint(valid[order], x_sharding)
        local_keep, global_keep = two_phase(xs, vs)
        return global_keep, jnp.sum(local_keep), jnp.sum(global_keep), order

    return step
