"""Sharded two-phase skyline over a ``jax.sharding.Mesh``.

This is the TPU-native replacement for the reference's distributed topology
(SURVEY.md §2.5-2.6): Flink's ``keyBy`` hash shuffle becomes host-side
partition-id computation + a sharded ``device_put`` onto the mesh; the
per-subtask ``SkylineLocalProcessor`` becomes a per-device blocked skyline
kernel; and the single-reducer ``GlobalSkylineAggregator`` bottleneck
(FlinkSkyline.java:460-660, pdf §5.5 "global merge time >> local CPU time")
becomes an ``all_gather`` of per-device local skylines over ICI followed by a
distributed masked cross-prune — every device finalizes its own rows, so the
merge itself is parallel instead of funneling into one JVM subtask.

All shapes are static: the window arrives padded to ``P * rows_per_shard`` and
results are (local_keep, global_keep) boolean masks from which the engine
derives skyline sizes and per-partition optimality (survivors_i / local_i,
FlinkSkyline.java:592-608).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skyline_tpu.ops.block_skyline import (
    dominated_by_blocked,
    skyline_mask_blocked,
)
from skyline_tpu.utils.jax_compat import shard_map

AXIS = "p"


def make_mesh(n_devices: int | None = None, axis: str = AXIS) -> Mesh:
    """1-D device mesh over the first ``n_devices`` local devices.

    The reference's analogue is Flink ``env.setParallelism(p)``
    (FlinkSkyline.java:80); here parallel workers are mesh devices and the
    ``2 x parallelism`` logical partitions round-robin onto them (see
    ``skyline_tpu.stream.engine``).
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def build_two_phase(
    mesh: Mesh,
    *,
    axis: str = AXIS,
    local_block: int = 2048,
    cross_block: int = 8192,
):
    """Build a jitted sharded two-phase skyline step for ``mesh``.

    Returns ``step(x, valid) -> (local_keep, global_keep)`` where
    ``x: (N, d)`` and ``valid: (N,)`` are sharded along rows across the mesh
    (N divisible by mesh size). ``local_keep[j]`` marks survivors of the
    per-device local phase; ``global_keep[j]`` marks rows in the global
    skyline. ``global_keep`` is exact and identical to an unsharded
    ``skyline_mask`` (partitioner- and device-count-invariant — the invariant
    the reference only checks by eyeballing CSVs, SURVEY.md §4).
    """
    n_dev = mesh.devices.size

    def per_device(x_shard, valid_shard):
        # Phase 1: local skyline on this device's rows.
        local_keep = skyline_mask_blocked(x_shard, valid_shard, block=local_block)
        # Phase 2: gather every device's local survivors over ICI and prune
        # this device's survivors against them. Local non-survivors need no
        # check (dominance is transitive), and gathered non-survivors are
        # masked out as dominators.
        all_x = lax.all_gather(x_shard, axis, tiled=True)
        all_keep = lax.all_gather(local_keep, axis, tiled=True)
        dominated = dominated_by_blocked(
            x_shard, all_x, x_valid=all_keep, block=cross_block
        )
        global_keep = local_keep & ~dominated
        return local_keep, global_keep

    if n_dev == 1:
        # Degenerate mesh: skip shard_map so single-chip benches avoid any
        # collective overhead.
        @jax.jit
        def step(x, valid):
            local_keep = skyline_mask_blocked(x, valid, block=local_block)
            return local_keep, local_keep

        return step

    sharded = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis)),
        # scan carries inside the blocked kernels start from replicated
        # constants; skip the varying-manual-axes type check rather than
        # pvary-ing every carry init.
        check_vma=False,
    )
    return jax.jit(sharded)


def shard_rows(mesh: Mesh, x: np.ndarray, valid: np.ndarray, axis: str = AXIS):
    """Place (N, d) rows row-sharded across the mesh (N % mesh size == 0)."""
    sh = NamedSharding(mesh, P(axis))
    return jax.device_put(x, sh), jax.device_put(valid, sh)


# Mesh is hashable by devices + axis names, so equal-but-distinct meshes
# share one compiled step.
_cached_two_phase = functools.lru_cache(maxsize=32)(
    lambda mesh, axis, local_block, cross_block: build_two_phase(
        mesh, axis=axis, local_block=local_block, cross_block=cross_block
    )
)


def sharded_two_phase_skyline(
    mesh: Mesh,
    x,
    valid,
    *,
    axis: str = AXIS,
    local_block: int = 2048,
    cross_block: int = 8192,
):
    """Convenience wrapper: build (cached) + run the two-phase step."""
    step = _cached_two_phase(mesh, axis, local_block, cross_block)
    return step(x, valid)


def skyline_keep_np_sharded(
    mesh: Mesh,
    x: np.ndarray,
    *,
    axis: str | None = None,
    local_block: int = 2048,
    cross_block: int = 8192,
) -> np.ndarray:
    """Survivor mask of a host (n, d) array via the sharded two-phase step —
    the mesh counterpart of ``ops.dispatch.skyline_keep_np``. Pads rows to a
    power-of-two capacity (rounded to a mesh-size multiple), shards them
    across the mesh, and slices the exact mask back. This is the engine's
    global merge when it owns a mesh: the reference's single-reducer
    bottleneck (pdf §5.5) as a parallel collective.

    ``axis`` defaults to the mesh's first axis name, matching how
    ``stream.batched.PartitionSet`` shards partition state."""
    from skyline_tpu.utils.buckets import next_pow2

    n, d = x.shape
    if n == 0:
        return np.zeros((0,), dtype=bool)
    if axis is None:
        axis = mesh.axis_names[0]
    n_dev = int(mesh.shape[axis])
    cap = next_pow2(n, min_cap=1024)
    cap = -(-cap // n_dev) * n_dev  # no-op for power-of-two mesh sizes
    pad = np.full((cap, d), np.inf, dtype=np.float32)
    pad[:n] = x
    valid = np.arange(cap) < n
    xs, vs = shard_rows(mesh, pad, valid, axis=axis)
    _, global_keep = sharded_two_phase_skyline(
        mesh, xs, vs, axis=axis, local_block=local_block,
        cross_block=cross_block,
    )
    return np.asarray(global_keep)[:n]
