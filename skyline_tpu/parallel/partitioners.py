"""Vectorized spatial partitioners: MR-Dim, MR-Grid, MR-Angle.

Each maps a whole window ``(N, d) -> (N,) int32`` of partition ids in one
fused op — the reference computes the same keys tuple-at-a-time inside
Flink's ``keyBy`` (PartitioningLogic, FlinkSkyline.java:669-877). The key
formulas are preserved exactly, with one deliberate fix noted on MR-Grid.

Partition count convention follows the reference: ``numPartitions = 2 *
parallelism`` logical partitions over-partitioned onto workers for skew
tolerance (FlinkSkyline.java:74-76); here logical partitions round-robin onto
mesh devices (see ``skyline_tpu.parallel.mesh``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mr_dim(x: jax.Array, num_partitions: int, domain_max: float) -> jax.Array:
    """1-D range partitioning on dimension 0.

    Mirrors DimPartitioner.getKey (FlinkSkyline.java:686-713):
    ``p = floor(v0 / (domain_max / num_partitions))`` clamped to
    ``[0, num_partitions - 1]``.
    """
    width = domain_max / num_partitions
    p = jnp.floor(x[:, 0] / width).astype(jnp.int32)
    return jnp.clip(p, 0, num_partitions - 1)


def mr_grid(x: jax.Array, num_partitions: int, domain_max: float) -> jax.Array:
    """Hypercube-cell partitioning via the midpoint bitmask.

    Mirrors GridPartitioner.getKey (FlinkSkyline.java:746-790): bit ``i`` of
    the cell id is set iff ``values[i] >= domain_max / 2``, giving ``2^d``
    cells.

    Deliberate fix vs the reference: the reference uses the raw cell id as the
    partition key without reducing modulo ``num_partitions``
    (FlinkSkyline.java:786-788), so with ``d > log2(num_partitions)`` tuples
    land on partition ids that never receive a query trigger and are silently
    dropped from results (SURVEY.md §2.1 note on J4). Here the cell id is
    folded onto partitions with a modulo so every tuple reaches a queried
    partition; adjacent cells interleave across partitions.
    """
    return (mr_grid_cell(x, domain_max) % num_partitions).astype(jnp.int32)


def mr_grid_cell(x: jax.Array, domain_max: float) -> jax.Array:
    """Raw 2^d grid-cell ids (pre-modulo), exposed for parity tests vs the
    reference formula."""
    mid = domain_max / 2.0
    d = x.shape[1]
    bits = (x >= mid).astype(jnp.int32)
    weights = (1 << jnp.arange(d, dtype=jnp.int32))
    return jnp.sum(bits * weights, axis=1)


def mr_angle(x: jax.Array, num_partitions: int, domain_max: float) -> jax.Array:
    """Hyperspherical (angle-based) partitioning.

    Mirrors AnglePartitioner.getKey (FlinkSkyline.java:803-876): the d-1
    angles are ``phi_i = atan2(norm(v[i+1:]), v[i])`` (:839-851), each
    normalized by pi/2, averaged, scaled by the partition count, and clamped
    (:856-874). Angle partitioning is the documented best strategy for
    anti-correlated data — the north-star workload.

    The atan2 cascade vectorizes as a reversed cumulative sum of squares:
    ``tail_norm_i = sqrt(sum_{k>i} v_k^2)``.
    """
    d = x.shape[1]
    if d < 2:
        return jnp.zeros((x.shape[0],), dtype=jnp.int32)
    sq = x * x
    # tail_sq[:, i] = sum_{k > i} x[:, k]^2  for i in [0, d-2]
    rev_cumsum = jnp.cumsum(sq[:, ::-1], axis=1)[:, ::-1]
    tail_sq = rev_cumsum[:, 1:]  # (N, d-1)
    tail_norm = jnp.sqrt(tail_sq)
    phi = jnp.arctan2(tail_norm, x[:, : d - 1])  # (N, d-1), each in [0, pi/2]
    norm_phi = phi / (jnp.pi / 2.0)
    avg = jnp.mean(norm_phi, axis=1)
    p = jnp.floor(avg * num_partitions).astype(jnp.int32)
    return jnp.clip(p, 0, num_partitions - 1)


def partition_ids_np(
    x, algo: str, num_partitions: int, domain_max: float
):
    """Numpy twin of ``partition_ids`` for host-side stream routing (the
    engine assigns partitions while batches are still host buffers, avoiding
    a device round-trip per micro-batch). Kept formula-identical to the jnp
    versions; equivalence is asserted by tests."""
    import numpy as np

    x = np.asarray(x, dtype=np.float32)
    n, d = x.shape
    if algo == "mr-dim":
        width = domain_max / num_partitions
        p = np.floor(x[:, 0] / width).astype(np.int64)
        return np.clip(p, 0, num_partitions - 1).astype(np.int32)
    if algo == "mr-grid":
        bits = (x >= domain_max / 2.0).astype(np.int64)
        cell = bits @ (1 << np.arange(d, dtype=np.int64))
        return (cell % num_partitions).astype(np.int32)
    if algo == "mr-angle":
        if d < 2:
            return np.zeros((n,), dtype=np.int32)
        sq = (x * x).astype(np.float32)
        rev_cumsum = np.cumsum(sq[:, ::-1], axis=1)[:, ::-1]
        tail_norm = np.sqrt(rev_cumsum[:, 1:])
        phi = np.arctan2(tail_norm, x[:, : d - 1])
        avg = np.mean(phi / (np.pi / 2.0), axis=1, dtype=np.float32)
        p = np.floor(avg * np.float32(num_partitions)).astype(np.int64)
        return np.clip(p, 0, num_partitions - 1).astype(np.int32)
    raise ValueError(
        f"unknown partitioner {algo!r}; expected one of {sorted(PARTITIONERS)}"
    )


PARTITIONERS = {
    "mr-dim": mr_dim,
    "mr-grid": mr_grid,
    "mr-angle": mr_angle,
}

# Reference algo-id mapping (query_trigger.py:58-62): 1=mr-dim, 2=mr-grid, 3=mr-angle.
ALGO_IDS = {1: "mr-dim", 2: "mr-grid", 3: "mr-angle"}


def partition_ids(
    x: jax.Array, algo: str, num_partitions: int, domain_max: float
) -> jax.Array:
    """Dispatch to a partitioner by name ('mr-dim' | 'mr-grid' | 'mr-angle')."""
    try:
        fn = PARTITIONERS[algo]
    except KeyError:
        raise ValueError(
            f"unknown partitioner {algo!r}; expected one of {sorted(PARTITIONERS)}"
        ) from None
    return fn(x, num_partitions, domain_max)
