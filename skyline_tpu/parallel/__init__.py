"""Partitioning strategies + sharded two-phase skyline over a TPU mesh."""

from skyline_tpu.parallel.partitioners import (
    PARTITIONERS,
    mr_angle,
    mr_dim,
    mr_grid,
    partition_ids,
)
from skyline_tpu.parallel.mesh import (
    make_mesh,
    sharded_two_phase_skyline,
)

__all__ = [
    "PARTITIONERS",
    "mr_dim",
    "mr_grid",
    "mr_angle",
    "partition_ids",
    "make_mesh",
    "sharded_two_phase_skyline",
]
