"""Chip-group placement for the sharded streaming engine.

The ``distributed.ShardedEngine`` splits the partition set into
``chips`` contiguous groups and pins each group's device state to one
chip. This module owns the placement decision — which physical device
backs which chip index — and the one cross-chip "collective" the
two-level tournament needs: gathering the surviving chip-local skyline
buffers onto a single root device for the pairwise merge.

Everything here works identically on a CPU host forced to expose N
virtual devices (``XLA_FLAGS=--xla_force_host_platform_device_count=N``)
— that is how tier-1 exercises the real merge topology without a TPU.
"""

from __future__ import annotations

import jax


def chip_devices(chips: int) -> list:
    """The device backing each chip index, round-robined over the local
    device list.

    With at least ``chips`` devices each group gets its own chip; with
    fewer (a plain 1-CPU bench run, or more groups than hardware) the
    groups wrap — correctness never depends on the placement, only
    locality does, so oversubscription degrades bandwidth, not bytes.
    """
    if chips < 1:
        raise ValueError(f"chips must be >= 1, got {chips}")
    devs = jax.devices()
    return [devs[c % len(devs)] for c in range(chips)]


def chip_of(pid: int, group_size: int) -> int:
    """The chip owning global partition ``pid`` (contiguous blocks of
    ``group_size`` partitions per chip)."""
    return pid // group_size


def gather_to(device, arrays):
    """Move every array in ``arrays`` onto ``device`` — the cross-chip
    collective feeding the tournament root. On a forced-host-platform CPU
    mesh this is a (virtual) cross-device copy; on a real mesh it is the
    ICI transfer the chip-level witness prune exists to skip."""
    return [jax.device_put(a, device) for a in arrays]
