"""Multi-host (DCN) scale-out: hierarchical two-phase skyline over a 2-D mesh.

The reference scales out by adding Flink TaskManagers connected over the LAN
(docker-setup/docker-compose.yml:34-44; its shuffle and single-reducer merge
then cross machines, SURVEY.md §2.6). The TPU-native equivalent is a 2-D
``(host, chip)`` mesh: chips within a host merge over ICI (fast), hosts merge
over DCN (slow) — and the DCN stage moves only *compacted per-host survivor
buffers*, not raw windows, because on most distributions local+host pruning
removes the vast majority of points before they would cross the slow link.

Exactness: pruning against a host's *survivors* is exact by dominance
transitivity (a pruned point's dominator is itself in the survivor set). The
one approximation knob is ``host_cap`` — the static size of the per-host
survivor buffer shipped over DCN. Overflow drops *dominators*, which can only
make the result a SUPERSET of the true skyline (no true skyline point is ever
lost); the step reports an overflow flag so callers can detect and re-run
with a larger cap (or ``host_cap=rows_per_host``, which is always exact).

Single-process testing: with ``--xla_force_host_platform_device_count=8`` the
same code runs on a virtual 2x4 or 4x2 CPU mesh (SURVEY.md §4 item 5's
mini-cluster analogue); on a real pod slice, ``init_multihost`` wires
``jax.distributed`` and the host axis maps onto process boundaries so the
stage-2 all_gather rides DCN.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skyline_tpu.ops.block_skyline import dominated_by_blocked, skyline_mask_blocked
from skyline_tpu.ops.dominance import compact
from skyline_tpu.utils.jax_compat import shard_map

HOST_AXIS = "host"
CHIP_AXIS = "chip"


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize ``jax.distributed`` for a multi-host run (no-op when
    single-process). Arguments default to the ``SKYLINE_COORDINATOR``,
    ``SKYLINE_NUM_PROCESSES``, ``SKYLINE_PROCESS_ID`` env vars; on cloud TPU
    pods all three may be None (auto-detected by JAX)."""
    from skyline_tpu.analysis.registry import env_int, env_str

    coordinator_address = coordinator_address or env_str("SKYLINE_COORDINATOR")
    if num_processes is None:
        num_processes = env_int("SKYLINE_NUM_PROCESSES", None)
    if process_id is None:
        process_id = env_int("SKYLINE_PROCESS_ID", None)
    if num_processes is not None and num_processes <= 1:
        return
    if coordinator_address is None and num_processes is None and process_id is None:
        # nothing configured: single-process run (jax.distributed.initialize
        # with all-None args only works under managed cloud autodetection;
        # on a dev box it raises instead of no-opping)
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_host_chip_mesh(
    n_hosts: int | None = None, chips_per_host: int | None = None
) -> Mesh:
    """2-D ``(host, chip)`` mesh over all devices.

    On a real multi-process run the host axis follows ``process_index`` (so
    the chip-axis collectives stay intra-host on ICI and only the host axis
    crosses DCN). Single-process (virtual CPU devices, or one host's chips)
    falls back to an even reshape into the requested shape.
    """
    devices = jax.devices()
    n_proc = max(d.process_index for d in devices) + 1
    if n_proc > 1:
        by_proc: dict[int, list] = {}
        for d in devices:
            by_proc.setdefault(d.process_index, []).append(d)
        per = {p: sorted(ds, key=lambda d: d.id) for p, ds in by_proc.items()}
        counts = {len(ds) for ds in per.values()}
        if len(counts) != 1:
            raise ValueError(f"uneven devices per process: {per}")
        grid = np.array(
            [per[p] for p in sorted(per)], dtype=object
        )  # (n_hosts, chips_per_host)
    else:
        if n_hosts is None:
            n_hosts = 1
        if chips_per_host is None:
            if len(devices) % n_hosts:
                raise ValueError(
                    f"{len(devices)} devices not divisible into {n_hosts} hosts"
                )
            chips_per_host = len(devices) // n_hosts
        if n_hosts * chips_per_host > len(devices):
            raise ValueError(
                f"need {n_hosts}x{chips_per_host} devices, have {len(devices)}"
            )
        grid = np.asarray(devices[: n_hosts * chips_per_host]).reshape(
            n_hosts, chips_per_host
        )
    return Mesh(grid, (HOST_AXIS, CHIP_AXIS))


def build_hierarchical_two_phase(
    mesh: Mesh,
    *,
    rows_per_shard: int,
    host_cap: int | None = None,
    local_block: int = 2048,
    cross_block: int = 8192,
):
    """Jitted hierarchical two-phase skyline step for a ``(host, chip)`` mesh.

    Returns ``step(x, valid) -> (host_keep, global_keep, overflowed)`` for
    ``x: (N, d)`` row-sharded over both mesh axes (N = shards * rows_per_shard).

    - ``host_keep[j]``: row j survives its host's ICI-merged skyline.
    - ``global_keep[j]``: row j is in the global skyline (exact iff
      ``overflowed == 0``; otherwise a superset — see module docstring).
    - ``overflowed``: number of mesh participants whose host survivor count
      exceeded ``host_cap`` (0 on exact results).

    ``host_cap`` bounds the per-host survivor buffer all_gathered across the
    DCN host axis; default ``rows_per_host`` (always exact, full-size
    exchange). Set lower (e.g. ``rows_per_host // 8``) when local pruning is
    expected to be strong — the overflow flag guards correctness.
    """
    n_hosts, chips = (int(s) for s in mesh.devices.shape)
    rows_per_host = rows_per_shard * chips
    if host_cap is None:
        host_cap = rows_per_host
    if host_cap % 1024 and host_cap != rows_per_host:
        raise ValueError(f"host_cap {host_cap} must be a multiple of 1024")

    def per_device(x_shard, valid_shard):
        # Stage 0: per-chip local skyline.
        local_keep = skyline_mask_blocked(x_shard, valid_shard, block=local_block)
        # Stage 1 (ICI): host-level merge. Gather every chip-in-host's rows
        # and local survivor masks; prune own rows against them. Local
        # non-survivors are transitively covered as dominators.
        hx = lax.all_gather(x_shard, CHIP_AXIS, tiled=True)
        hlk = lax.all_gather(local_keep, CHIP_AXIS, tiled=True)
        dom_host = dominated_by_blocked(x_shard, hx, x_valid=hlk, block=cross_block)
        host_keep = local_keep & ~dom_host
        # Stage 2 (DCN): every chip of a host deterministically compacts the
        # SAME host-survivor set (hx is host-replicated after the gather; the
        # host_keep gather below makes the mask host-replicated too), so the
        # host buffer is identical host-wide and one all_gather over the host
        # axis exchanges exactly (n_hosts * host_cap) rows over DCN.
        hhk = lax.all_gather(host_keep, CHIP_AXIS, tiled=True)
        host_count = jnp.sum(hhk)
        buf, buf_valid, _ = compact(hx, hhk, host_cap)
        all_buf = lax.all_gather(buf, HOST_AXIS, tiled=True)
        all_valid = lax.all_gather(buf_valid, HOST_AXIS, tiled=True)
        dom_global = dominated_by_blocked(
            x_shard, all_buf, x_valid=all_valid, block=cross_block
        )
        global_keep = host_keep & ~dom_global
        overflow = (host_count > host_cap).astype(jnp.int32)
        overflowed = lax.psum(lax.psum(overflow, CHIP_AXIS), HOST_AXIS)
        return host_keep, global_keep, overflowed

    sharded = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P((HOST_AXIS, CHIP_AXIS)), P((HOST_AXIS, CHIP_AXIS))),
        out_specs=(
            P((HOST_AXIS, CHIP_AXIS)),
            P((HOST_AXIS, CHIP_AXIS)),
            P(),
        ),
        check_vma=False,
    )
    return jax.jit(sharded)


def shard_rows_2d(mesh: Mesh, x: np.ndarray, valid: np.ndarray):
    """Place (N, d) rows sharded over both mesh axes (N % mesh size == 0)."""
    sh = NamedSharding(mesh, P((HOST_AXIS, CHIP_AXIS)))
    return jax.device_put(x, sh), jax.device_put(valid, sh)
