"""tpu-skyline: a TPU-native framework for distributed streaming skyline queries.

Re-implements the capability surface of the Flink/Kafka reference system
(Asterinos1/Flink-Skyline-QoS — see SURVEY.md) as an idiomatic JAX/XLA/Pallas
design: windowed micro-batches become ``(N, d)`` tensors, per-partition
dominance testing runs as tiled dominance-bitmask kernels, and local skylines
are merged into the global skyline by on-chip collectives over a
``jax.sharding.Mesh``.

Subpackage map (reference parity noted per SURVEY.md §2):

- ``ops``       — dominance predicate + skyline kernels (replaces the JVM BNL
                  hot loop, FlinkSkyline.java:417-444 / ServiceTuple.java:67-77)
- ``parallel``  — MR-Dim / MR-Grid / MR-Angle partitioners (FlinkSkyline.java:669-877)
                  and the sharded two-phase local/global skyline over a TPU mesh
                  (replaces keyBy shuffle + GlobalSkylineAggregator)
- ``stream``    — windowing, record-id query barrier, streaming engine
                  (SkylineLocalProcessor semantics, FlinkSkyline.java:214-445)
- ``bridge``    — Kafka/in-memory transport plane + the skyline worker
                  (FlinkSkyline.java:84-97,177-183 Kafka I/O)
- ``workload``  — synthetic generators + producer/trigger CLIs
                  (python/unified_producer.py, kafka_producer.py, query_trigger.py)
- ``metrics``   — result-JSON → CSV collector + phase tracing
                  (python/metrics_collector.py; FlinkSkyline.java timing fields)
- ``plots``     — figure tools (python/graph_*.py)
- ``utils``     — config, padding/bucketing, checkpointing

Import-time side effect: if ``JAX_PLATFORMS`` is set in the environment and
the JAX backend is not yet initialized, importing this package re-applies the
env var to ``jax.config`` (see ``_honor_jax_platforms_env``). This restores
stock JAX semantics under TPU plugins that pin the platform at interpreter
startup; embedding applications that manage ``jax.config`` themselves should
unset ``JAX_PLATFORMS`` or initialize their backend before importing.
"""

__version__ = "0.1.0"


def _honor_jax_platforms_env() -> None:
    """Restore standard ``JAX_PLATFORMS`` semantics under plugin pinning.

    Some TPU plugins import jax at interpreter startup and pin the platform
    via ``jax.config``, which silently overrides a user's
    ``JAX_PLATFORMS=cpu`` — scripts then hang on an unreachable device
    instead of using the requested backend. If the env var is set, the
    backend is not yet initialized, and the pinned config disagrees,
    re-apply the env var (exactly what stock JAX would have done).
    """
    import sys

    # registry import stays inside the function: transport-only CLIs pay
    # nothing extra, and the accessor keeps the knob lint's single-reader
    # invariant airtight (JAX_PLATFORMS is declared external in KNOBS)
    from skyline_tpu.analysis.registry import env_str

    want = env_str("JAX_PLATFORMS")
    if not want:
        return
    # only repair when a plugin ALREADY imported jax at interpreter startup
    # (that's the pinning scenario); if jax isn't loaded, its own lazy init
    # honors the env var natively — and transport-only CLIs (producer,
    # broker, collector) skip the ~2 s jax import entirely
    jax = sys.modules.get("jax")
    if jax is None:
        return
    try:
        import jax._src.xla_bridge as _xb

        backend_live = bool(_xb._backends)
    except (ImportError, AttributeError):
        # a JAX-internal rename broke the probe: warn loudly instead of
        # silently disabling the workaround
        import warnings

        warnings.warn(
            "skyline_tpu: cannot probe JAX backend state "
            "(jax._src.xla_bridge._backends moved?); JAX_PLATFORMS may be "
            "ignored if a plugin pinned the platform",
            RuntimeWarning,
            stacklevel=2,
        )
        return
    if not backend_live and jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)


_honor_jax_platforms_env()
