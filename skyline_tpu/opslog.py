"""CLI alias: ``python -m skyline_tpu.opslog`` pretty-prints/diffs the
cluster ops journal (the implementation lives in
``skyline_tpu.telemetry.opslog``; this module exists so the CLI sits
beside ``python -m skyline_tpu.explain`` and ``python -m
skyline_tpu.audit`` in the operator's muscle memory — RUNBOOK §2s)."""

from skyline_tpu.telemetry.opslog import main

if __name__ == "__main__":
    raise SystemExit(main())
