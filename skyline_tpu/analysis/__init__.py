"""Static-analysis subsystem: knob registry + CI lint passes.

``registry`` (stdlib-only, import-light — the dispatch hot path and
``skyline_tpu/__init__.py`` import it) declares every runtime knob and owns
the sanctioned env accessors. The three analysis passes live in
``knob_lint`` / ``jaxpr_audit`` / ``lock_lint`` and run together via
``python -m skyline_tpu.analysis`` (see ``__main__.py``; wired into CI by
``scripts/lint.sh`` and ``scripts/obs_smoke.sh``).

Only the registry is re-exported here: importing the package must never
pull in jax (the jaxpr auditor imports it lazily inside ``run``).
"""

from skyline_tpu.analysis.registry import (  # noqa: F401
    KNOBS,
    Knob,
    env_bool,
    env_float,
    env_int,
    env_str,
    knob,
    knob_doc_markdown,
    knob_names,
    parse_bool,
)

__all__ = [
    "KNOBS",
    "Knob",
    "env_bool",
    "env_float",
    "env_int",
    "env_str",
    "knob",
    "knob_doc_markdown",
    "knob_names",
    "parse_bool",
]
