"""Pass 2 — jaxpr invariant auditor for the dispatch variants.

Traces the dominance hot ops the dispatcher can select — the backend-auto
skyline mask, the SFS append round, the incremental merge step, and the
flush-tail summary kernels — over a (d × op × knob-toggle) matrix via
``jax.make_jaxpr``, then statically asserts on each closed jaxpr
(recursively, through scan/cond/pjit sub-jaxprs):

- ``jaxpr-f64``: no float64/complex128 anywhere. The engine's byte-identity
  contracts are stated over f32 buffers; a stray f64 constant would both
  break them and double VMEM traffic.
- ``jaxpr-host-callback``: no host callback primitives inside jit — a
  callback in a flush kernel would serialize the overlapped pipeline.
- ``jaxpr-dynamic-shape``: every output aval has a static int shape (the
  executable-set-bounded-by-buckets invariant).
- ``jaxpr-bf16-gate``: bfloat16 appears in the traced kernel iff the
  mixed-precision flag is on for that trace — the §2g cascade must not
  leak bf16 into exact paths, and the mp=True executable must actually
  contain the margin pass.
- ``jaxpr-retrace-unstable``: tracing the identical config twice must give
  the identical jaxpr text, and re-calling an already-compiled jitted
  kernel with same-shape inputs must not grow its compilation cache —
  the silent-recompile class of perf bug (an env read inside a traced
  function, a non-hashable static arg, an unstable weak type).

CPU-safe: ``make_jaxpr`` only traces. The two cache-stability executions
use tiny shapes.
"""

from __future__ import annotations

import os

import numpy as np

from skyline_tpu.analysis.findings import Finding

# primitives that re-enter the host from inside a traced computation
CALLBACK_PRIMITIVES = frozenset((
    "pure_callback", "io_callback", "debug_callback", "host_callback",
    "outside_call", "callback",
))

DEFAULT_DIMS = (2, 4, 8)


def _iter_jaxprs(jaxpr):
    """Yield ``jaxpr`` and every sub-jaxpr reachable through eqn params
    (scan/while bodies, cond branches, pjit calls, custom_jvp, ...)."""
    import jax

    seen = []
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        if any(j is s for s in seen):
            continue
        seen.append(j)
        yield j
        for eqn in j.eqns:
            for v in eqn.params.values():
                for cand in v if isinstance(v, (tuple, list)) else (v,):
                    if isinstance(cand, jax.core.ClosedJaxpr):
                        stack.append(cand.jaxpr)
                    elif isinstance(cand, jax.core.Jaxpr):
                        stack.append(cand)


def _iter_avals(jaxpr):
    for j in _iter_jaxprs(jaxpr):
        for v in (*j.invars, *j.outvars, *j.constvars):
            aval = getattr(v, "aval", None)
            if aval is not None:
                yield j, v, aval
        for eqn in j.eqns:
            for v in (*eqn.invars, *eqn.outvars):
                aval = getattr(v, "aval", None)
                if aval is not None:
                    yield j, v, aval


def audit_closed_jaxpr(closed, label: str, expect_bf16=None) -> list[Finding]:
    """Invariant checks on one ``ClosedJaxpr``. ``expect_bf16``: None = no
    bf16 assertion; True/False = bfloat16 must/must-not appear. Findings
    anchor to the registry of traced configs (file = the audit module)."""
    import jax.numpy as jnp

    findings: list[Finding] = []
    here = "skyline_tpu/analysis/jaxpr_audit.py"

    def flag(rule, message):
        findings.append(Finding(here, 1, "error", rule, f"[{label}] {message}"))

    saw_bf16 = False
    bad_f64: set[str] = set()
    for j, v, aval in _iter_avals(closed.jaxpr):
        dtype = getattr(aval, "dtype", None)
        if dtype is not None:
            if dtype in (jnp.float64, np.dtype("complex128")):
                bad_f64.add(str(dtype))
            if dtype == jnp.bfloat16:
                saw_bf16 = True
        shape = getattr(aval, "shape", None)
        if shape is not None and not all(isinstance(d, int) for d in shape):
            flag(
                "jaxpr-dynamic-shape",
                f"non-static dimension in aval {aval} — executables must "
                "be keyed by concrete capacity buckets",
            )
    for dt in sorted(bad_f64):
        flag("jaxpr-f64", f"{dt} value traced — the engine is f32-only")
    for j in _iter_jaxprs(closed.jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name in CALLBACK_PRIMITIVES:
                flag(
                    "jaxpr-host-callback",
                    f"host callback primitive {eqn.primitive.name!r} "
                    "inside a traced hot op",
                )
    if expect_bf16 is True and not saw_bf16:
        flag(
            "jaxpr-bf16-gate",
            "mixed-precision trace contains no bfloat16 — the §2g margin "
            "pass is not actually in the executable",
        )
    if expect_bf16 is False and saw_bf16:
        flag(
            "jaxpr-bf16-gate",
            "bfloat16 leaked into an exact (mp=off) trace",
        )
    return findings


def _trace_twice(fn, args, label: str, expect_bf16=None) -> list[Finding]:
    """make_jaxpr twice: audit the first, compare text for retrace drift."""
    import jax

    closed1 = jax.make_jaxpr(fn)(*args)
    findings = audit_closed_jaxpr(closed1, label, expect_bf16=expect_bf16)
    closed2 = jax.make_jaxpr(fn)(*args)
    if str(closed1) != str(closed2):
        findings.append(
            Finding(
                "skyline_tpu/analysis/jaxpr_audit.py", 1, "error",
                "jaxpr-retrace-unstable",
                f"[{label}] re-tracing the identical config produced a "
                "different jaxpr — the jit cache key is unstable "
                "(env read or fresh closure inside the traced fn?)",
            )
        )
    return findings


def _cache_stability(jitted, make_args, label: str) -> list[Finding]:
    """Execute a jitted kernel twice with identically-shaped inputs and
    assert the second call added zero compile-cache entries."""
    findings: list[Finding] = []
    if not hasattr(jitted, "_cache_size"):
        return findings  # older/newer jax without the introspection hook
    jitted(*make_args())  # may compile: the baseline entry
    size1 = jitted._cache_size()
    jitted(*make_args())  # identical avals: MUST hit the cache
    size2 = jitted._cache_size()
    if size2 > size1:
        findings.append(
            Finding(
                "skyline_tpu/analysis/jaxpr_audit.py", 1, "error",
                "jaxpr-retrace-unstable",
                f"[{label}] second call with identical avals grew the jit "
                f"cache {size1} -> {size2}: silent recompile",
            )
        )
    return findings


def run(dims=DEFAULT_DIMS, n: int = 256) -> tuple[list[Finding], dict]:
    """The full pass-2 matrix. Returns ``(findings, summary)``; the summary
    (configs traced, backend, dims) is what bench.py stamps as the
    ``analysis`` block's audit provenance."""
    import jax
    import jax.numpy as jnp

    from skyline_tpu.ops.dispatch import skyline_mask_auto
    from skyline_tpu.ops.sfs import sfs_round_single
    from skyline_tpu.stream.window import (
        grid_summary_device,
        merge_step_active,
        partition_summaries_device,
    )

    findings: list[Finding] = []
    configs = 0
    rng = np.random.default_rng(0)

    # dispatch-level mask: the op the engine routes every self-skyline
    # through; d=2 exercises the sort-sweep variant, d>2 the scan/Pallas one
    for d in dims:
        x = jnp.asarray(rng.uniform(0, 1, (n, d)).astype(np.float32))
        valid = jnp.asarray(np.arange(n) < n - 3)
        findings += _trace_twice(
            lambda xx, vv: skyline_mask_auto(xx, vv), (x, valid),
            f"skyline_mask_auto d={d} n={n}", expect_bf16=False,
        )
        configs += 1

    # sorted-SFS containment (ISSUE 11): with the host cascade FORCED on,
    # a traced skyline_mask_auto must still lower to pure device ops —
    # under tracing the inputs are tracers, so the host path must step
    # aside (a leak would surface as a host callback or a concretization
    # error). One d>2 config; d<=2 never routes to the cascade.
    d_sorted = max(dims)
    if d_sorted > 2:
        prev = os.environ.get("SKYLINE_SORTED_SFS")  # lint: allow-raw-env
        os.environ["SKYLINE_SORTED_SFS"] = "on"
        try:
            x = jnp.asarray(
                rng.uniform(0, 1, (n, d_sorted)).astype(np.float32)
            )
            valid = jnp.asarray(np.arange(n) < n - 3)
            findings += _trace_twice(
                lambda xx, vv: skyline_mask_auto(xx, vv), (x, valid),
                f"skyline_mask_auto[sorted_sfs=on] d={d_sorted} n={n}",
                expect_bf16=False,
            )
        finally:
            if prev is None:
                os.environ.pop("SKYLINE_SORTED_SFS", None)
            else:
                os.environ["SKYLINE_SORTED_SFS"] = prev
        configs += 1

    # device cascade (ISSUE 18): the jit-safe sorted dominance cascade is
    # the one variant allowed to replace the quadratic kernels inside a
    # trace, so it gets the full invariant battery at both mp settings —
    # the f32 sum key must not smuggle in f64, the blocked scan must keep
    # static shapes, and bf16 must appear iff the margin pre-drop is on.
    from skyline_tpu.ops.device_cascade import _cascade_core

    d_casc = max(dims)
    if d_casc > 2:
        x = jnp.asarray(rng.uniform(0, 1, (n, d_casc)).astype(np.float32))
        valid = jnp.asarray(np.arange(n) < n - 3)
        for mp in (False, True):
            findings += _trace_twice(
                lambda xx, vv: _cascade_core(
                    xx, vv, block=64, mp=mp, use_pallas=False,
                    interpret=False,
                ),
                (x, valid),
                f"device_cascade_core d={d_casc} n={n} mp={int(mp)}",
                expect_bf16=mp,
            )
            configs += 1

        # forced-mode containment: with the cascade FORCED on, a traced
        # skyline_mask_auto must lower to the cascade's pure device ops
        # (same save/restore discipline as the sorted-SFS leg above)
        prev = os.environ.get("SKYLINE_DEVICE_CASCADE")  # lint: allow-raw-env
        os.environ["SKYLINE_DEVICE_CASCADE"] = "on"
        try:
            findings += _trace_twice(
                lambda xx, vv: skyline_mask_auto(xx, vv), (x, valid),
                f"skyline_mask_auto[device_cascade=on] d={d_casc} n={n}",
                expect_bf16=False,
            )
        finally:
            if prev is None:
                os.environ.pop("SKYLINE_DEVICE_CASCADE", None)
            else:
                os.environ["SKYLINE_DEVICE_CASCADE"] = prev
        configs += 1

    # SFS round + incremental merge step: the two flush hot ops, with the
    # mixed-precision knob toggled as the static arg the env gate threads
    for d in (min(dims), max(dims)):
        cap, b, p = 64, 32, 2
        sky1 = jnp.full((cap, d), jnp.inf, jnp.float32)
        cnt1 = jnp.zeros((), jnp.int32)
        block = jnp.asarray(rng.uniform(0, 1, (b, d)).astype(np.float32))
        bvalid = jnp.ones((b,), bool)
        skyP = jnp.full((p, cap, d), jnp.inf, jnp.float32)
        svalP = jnp.zeros((p, cap), bool)
        batchP = jnp.asarray(rng.uniform(0, 1, (p, b, d)).astype(np.float32))
        bvalP = jnp.ones((p, b), bool)
        for mp in (False, True):
            findings += _trace_twice(
                lambda s, c, bl, bv: sfs_round_single(s, c, bl, bv, cap, mp),
                (sky1, cnt1, block, bvalid),
                f"sfs_round_single d={d} mp={int(mp)}", expect_bf16=mp,
            )
            findings += _trace_twice(
                lambda s, sv, ba, bv: merge_step_active(
                    s, sv, ba, bv, cap, cap + b, mp
                ),
                (skyP, svalP, batchP, bvalP),
                f"merge_step_active d={d} mp={int(mp)}", expect_bf16=mp,
            )
            configs += 2

    # flush-tail summary kernels (PR 4/5): feed the host prefilters, so a
    # callback or f64 here would poison every flush
    for d in (min(dims), max(dims)):
        cap, p = 64, 2
        sky = jnp.asarray(rng.uniform(0, 1, (p, cap, d)).astype(np.float32))
        counts = jnp.asarray(np.array([cap // 2, cap // 4], np.int32))
        findings += _trace_twice(
            lambda s, c: partition_summaries_device(s, c, cap), (sky, counts),
            f"partition_summaries_device d={d}", expect_bf16=False,
        )
        findings += _trace_twice(
            lambda s, c: grid_summary_device(s, c, cap), (sky, counts),
            f"grid_summary_device d={d}", expect_bf16=False,
        )
        configs += 2

    # executed cache-stability legs (no donated args: grid/partition
    # summaries), catching recompiles make_jaxpr text equality can't see
    def mk():
        d = max(dims)
        sky = jnp.asarray(rng.uniform(0, 1, (2, 64, d)).astype(np.float32))
        counts = jnp.asarray(np.array([32, 16], np.int32))
        return (sky, counts, 64)

    findings += _cache_stability(grid_summary_device, mk, "grid_summary_device")
    findings += _cache_stability(
        partition_summaries_device, mk, "partition_summaries_device"
    )
    configs += 2

    def mk_cascade():
        d = max(dims)
        x = jnp.asarray(rng.uniform(0, 1, (128, d)).astype(np.float32))
        valid = jnp.ones((128,), bool)
        return (x, valid, 64, False, False, False)

    findings += _cache_stability(
        _cascade_core, mk_cascade, "device_cascade_core"
    )
    configs += 1

    summary = {
        "backend": jax.default_backend(),
        "configs_traced": configs,
        "dims": list(dims),
        "rules": sorted({
            "jaxpr-f64", "jaxpr-host-callback", "jaxpr-dynamic-shape",
            "jaxpr-bf16-gate", "jaxpr-retrace-unstable",
        }),
        "findings": len(findings),
    }
    return findings, summary
