"""Pass 3 — lock-discipline lint for shared mutable state.

Convention: an instance attribute assigned in ``__init__`` (or annotated at
class level) may carry a trailing ``# guarded-by: <lock expr>`` comment::

    self._ring = deque(maxlen=cap)  # guarded-by: self._lock

The lint then checks every *mutation* of that attribute in every other
method of the class — rebinding, augmented assignment, item assignment,
``del``, or a call of a known mutating method (``append``, ``popleft``,
``update``, ...) — and flags any that is not lexically inside a
``with <lock expr>:`` block (rule ``unguarded-mutation``, error). This is
exactly the bug class of the PR-2 collector header race: state documented
as lock-protected, mutated on a path that forgot the lock.

Reads are deliberately NOT checked — the serve plane's whole design is
lock-free reads over frozen snapshots plus locked writers, and that is
the discipline the annotation encodes.

Scope notes (lexical, conservative-but-honest):

- Nested functions/lambdas defined inside a ``with`` block do NOT inherit
  the held lock: their bodies run whenever they're called, not where
  they're defined, so the stack resets at each function boundary.
- ``__init__`` is exempt — the object is not yet shared while it is being
  constructed.
- A line containing ``# unguarded-ok`` (with a reason) suppresses the rule
  for deliberate lock-free mutations (e.g. a single-reference atomic swap).
- Lock matching is textual on the normalized expression (``ast.unparse``),
  so ``with self._lock :`` matches ``# guarded-by: self._lock``. Holding a
  *different* lock does not count.
"""

from __future__ import annotations

import ast
import os
import re

from skyline_tpu.analysis.findings import Finding
from skyline_tpu.analysis.knob_lint import SKIP_DIRS, iter_python_files

GUARD_RE = re.compile(r"#\s*guarded-by:\s*([^#]+?)\s*(?:#.*)?$")
SUPPRESS_RE = re.compile(r"#\s*unguarded-ok\b")

# method names that mutate their receiver (list/deque/dict/set/OrderedDict
# and numpy's in-place flag setter)
MUTATING_METHODS = frozenset((
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "add", "discard",
    "setdefault", "move_to_end", "sort", "reverse", "rotate", "setflags",
    "fill", "resize",
))


def _normalize_expr(expr: str) -> str:
    try:
        return ast.unparse(ast.parse(expr.strip(), mode="eval"))
    except SyntaxError:
        return expr.strip()


def _self_attr(node: ast.AST) -> str | None:
    """'x' when ``node`` is ``self.x``; None otherwise."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _collect_guards(cls: ast.ClassDef, lines: list[str]) -> dict[str, str]:
    """{attr: normalized lock expr} from guarded-by comments in the class."""
    guards: dict[str, str] = {}

    def note(attr: str | None, node: ast.AST):
        if attr is None:
            return
        end = getattr(node, "end_lineno", node.lineno)
        for ln in range(node.lineno, end + 1):
            if ln - 1 >= len(lines):
                break
            m = GUARD_RE.search(lines[ln - 1])
            if m:
                guards[attr] = _normalize_expr(m.group(1))
                return

    for stmt in ast.walk(cls):
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                note(_self_attr(tgt), stmt)
        elif isinstance(stmt, ast.AnnAssign):
            note(_self_attr(stmt.target), stmt)
    return guards


class _MethodCheck(ast.NodeVisitor):
    """Walk one method body tracking the lexically-held ``with`` locks."""

    def __init__(self, rel, cls_name, guards, lines):
        self.rel = rel
        self.cls_name = cls_name
        self.guards = guards
        self.lines = lines
        self.held: list[str] = []
        self.findings: list[Finding] = []

    def _suppressed(self, node) -> bool:
        ln = node.lineno - 1
        return ln < len(self.lines) and bool(SUPPRESS_RE.search(self.lines[ln]))

    def _check(self, node: ast.AST, attr: str | None, verb: str):
        if attr is None or attr not in self.guards:
            return
        lock = self.guards[attr]
        if lock in self.held or self._suppressed(node):
            return
        self.findings.append(
            Finding(
                self.rel, node.lineno, "error", "unguarded-mutation",
                f"{self.cls_name}.{attr} is guarded-by {lock} but {verb} "
                f"here outside `with {lock}`",
            )
        )

    def _target_attr(self, tgt: ast.AST) -> str | None:
        """The guarded self-attribute a store target touches, if any:
        ``self.x``, ``self.x[i]``, ``self.x.y``."""
        if isinstance(tgt, ast.Subscript):
            return self._target_attr(tgt.value)
        if isinstance(tgt, ast.Starred):
            return self._target_attr(tgt.value)
        return _self_attr(tgt)

    def visit_With(self, node: ast.With):
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith):
        self._visit_with(node)

    def _visit_with(self, node):
        exprs = [ast.unparse(item.context_expr) for item in node.items]
        self.held.extend(exprs)
        for child in node.body:
            self.visit(child)
        del self.held[-len(exprs):]
        # with-item expressions themselves (lock acquisition) need no check

    def _visit_nested(self, node):
        # a nested function does not inherit the definition site's locks
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    def visit_FunctionDef(self, node):
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node):
        self._visit_nested(node)

    def visit_Lambda(self, node):
        self._visit_nested(node)

    def visit_Assign(self, node: ast.Assign):
        for tgt in node.targets:
            for t in ast.walk(tgt) if isinstance(tgt, ast.Tuple) else (tgt,):
                self._check(node, self._target_attr(t), "assigned")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check(node, self._target_attr(node.target), "updated")
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._check(node, self._target_attr(node.target), "assigned")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for tgt in node.targets:
            self._check(node, self._target_attr(tgt), "deleted")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATING_METHODS:
            self._check(node, _self_attr(f.value), f"mutated (.{f.attr})")
        self.generic_visit(node)


def lint_file(path: str, rel: str) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rel, 1, "error", "parse-error", f"could not parse: {e}")]
    lines = source.splitlines()
    findings: list[Finding] = []
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        guards = _collect_guards(cls, lines)
        if not guards:
            continue
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name in ("__init__", "__new__"):
                continue  # not shared until construction completes
            checker = _MethodCheck(rel, cls.name, guards, lines)
            for stmt in item.body:
                checker.visit(stmt)
            findings.extend(checker.findings)
    return findings


def run(roots, base: str | None = None) -> list[Finding]:
    base = base or os.getcwd()
    findings: list[Finding] = []
    for path in iter_python_files(roots, SKIP_DIRS):
        findings.extend(lint_file(path, os.path.relpath(path, base)))
    return findings
