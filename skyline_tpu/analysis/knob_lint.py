"""Pass 1 — knob lint: every env read goes through the declared registry.

An ``ast`` walk over the product tree (package, bench harness, benchmarks,
scripts, driver entry — tests excluded) enforcing:

- ``raw-env-read`` (error): ``os.environ.get`` / ``os.environ[...]`` /
  ``os.getenv`` / ``"X" in os.environ`` / ``os.environ.setdefault``
  anywhere outside ``analysis/registry.py``. Writes
  (``os.environ[k] = v``, ``.pop``, ``del``) and whole-mapping passthrough
  (``dict(os.environ)``, ``.items()`` / ``.keys()`` / ``.values()`` /
  ``.copy()``, or passing ``os.environ`` itself along) stay legal — only
  *reads of individual knob values* must go through the accessor.
- ``undeclared-knob`` (error): an accessor call (``env_str`` / ``env_bool``
  / ``env_int`` / ``env_float``) naming a knob the registry doesn't
  declare.
- ``dynamic-knob-name`` (error): an accessor called with a non-literal
  name — the registry checks it at runtime, but the static dead-knob
  analysis can't see through it, so literal names are required.
- ``dead-knob`` (error): a declared, non-external knob no accessor call in
  the tree reads.
- ``bool-compare`` (error): comparing an env/accessor string against a
  truthiness literal (``env_str(...) != "0"``) — the pattern that gave
  different call sites different ideas of ``"false"``; use ``env_bool``.
- ``raw-applicability`` (error, ISSUE 20): a call to one of the dispatch
  gate helpers (``merge_tree_enabled``, ``chip_prune_enabled``, ...)
  outside ``ops/cascade.py`` / ``ops/dispatch.py``. The cascade table is
  the single source of truth for variant/path/gate applicability —
  engines must resolve through ``cascade.gate/applies/merge_*`` so tuner
  overrides and pins are honored everywhere; a raw gate call silently
  forks the decision.

Suppression: a line containing ``# lint: allow-raw-env`` is exempt from
``raw-env-read`` / ``dynamic-knob-name`` (used by the benchmark
save/flip/restore idiom that snapshots knob values by name); a line
containing ``# lint: allow-raw-gate`` is exempt from
``raw-applicability`` (A/B harnesses comparing a gate's legacy default
against the table-resolved value).
"""

from __future__ import annotations

import ast
import os

from skyline_tpu.analysis.findings import Finding
from skyline_tpu.analysis.registry import ACCESSORS, _BY_NAME

SUPPRESS = "# lint: allow-raw-env"
SUPPRESS_GATE = "# lint: allow-raw-gate"

# dispatch gate helpers whose calls must stay inside the cascade table
# (ops/cascade.py) or their defining module (ops/dispatch.py). Anything
# else resolving applicability from these raw gates bypasses the table's
# tuner overrides/pins and forks the dispatch decision.
GATE_HELPERS = frozenset((
    "merge_cache_enabled", "merge_tree_enabled", "merge_prune_enabled",
    "chip_prune_enabled", "host_prune_enabled", "flush_prefilter_enabled",
    "sorted_sfs_mode", "device_cascade_mode", "delta_dirty_cutoff",
    "rank_cascade",
))

# modules allowed to call the gate helpers directly: the table itself and
# the module that defines them
_TABLE_SUFFIXES = (
    os.path.join("ops", "cascade.py"),
    os.path.join("ops", "dispatch.py"),
)

# os.environ methods that only read single values (flagged) vs. passthrough
# or write methods (allowed)
_READ_METHODS = frozenset(("get", "setdefault", "__getitem__"))
_ALLOWED_METHODS = frozenset(("items", "keys", "values", "copy", "pop", "update"))

# string literals whose comparison against an env value implies ad-hoc
# truthiness parsing
_TRUTHINESS_LITERALS = frozenset(
    ("0", "1", "true", "false", "yes", "no", "on", "off")
)

# default directories/files skipped inside lint roots
SKIP_DIRS = frozenset(
    ("tests", "__pycache__", ".git", ".jax_cache", "artifacts",
     "bench_out_cpu", "bench_out_tpu", "docs", "node_modules")
)

# the one module allowed to touch os.environ for knob reads
_REGISTRY_SUFFIX = os.path.join("analysis", "registry.py")


def _is_os_environ(node: ast.AST) -> bool:
    """``os.environ`` or a bare ``environ`` imported from os."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return isinstance(node.value, ast.Name) and node.value.id == "os"
    return isinstance(node, ast.Name) and node.id == "environ"


def _is_env_read_call(node: ast.Call) -> str | None:
    """'raw' for flagged env reads, None otherwise."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if _is_os_environ(f.value) and f.attr in _READ_METHODS:
            return "raw"
        if (
            isinstance(f.value, ast.Name)
            and f.value.id == "os"
            and f.attr == "getenv"
        ):
            return "raw"
    return None


def _accessor_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Name) and f.id in ACCESSORS:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in ACCESSORS:
        return f.attr
    return None


def _gate_helper_name(node: ast.Call) -> str | None:
    """The called dispatch-gate helper's name, or None. Matches both the
    bare import (``merge_tree_enabled()``) and the attribute form
    (``dispatch.merge_tree_enabled()``)."""
    f = node.func
    if isinstance(f, ast.Name) and f.id in GATE_HELPERS:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in GATE_HELPERS:
        return f.attr
    return None


class _FileLint(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str, is_registry: bool,
                 is_table: bool = False):
        self.rel = rel
        self.lines = source.splitlines()
        self.is_registry = is_registry
        self.is_table = is_table
        self.findings: list[Finding] = []
        self.reads: set[str] = set()  # knob names read via accessor

    def _suppressed(self, node: ast.AST, marker: str = SUPPRESS) -> bool:
        for ln in range(node.lineno, getattr(node, "end_lineno", node.lineno) + 1):
            if ln - 1 < len(self.lines) and marker in self.lines[ln - 1]:
                return True
        return False

    def _flag(self, node: ast.AST, rule: str, message: str, severity="error"):
        self.findings.append(
            Finding(self.rel, node.lineno, severity, rule, message)
        )

    # -- raw reads ---------------------------------------------------------

    def visit_Subscript(self, node: ast.Subscript):
        if (
            _is_os_environ(node.value)
            and isinstance(node.ctx, ast.Load)
            and not self.is_registry
            and not self._suppressed(node)
        ):
            self._flag(
                node, "raw-env-read",
                "os.environ[...] read outside the registry accessor "
                "(use skyline_tpu.analysis.registry.env_*)",
            )
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        # "X" in os.environ — presence probe is still a read
        if (
            any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops)
            and any(_is_os_environ(c) for c in node.comparators)
            and not self.is_registry
            and not self._suppressed(node)
        ):
            self._flag(
                node, "raw-env-read",
                "`in os.environ` presence check outside the registry "
                "accessor (use env_* with default=None)",
            )
        self._check_bool_compare(node)
        self.generic_visit(node)

    def _check_bool_compare(self, node: ast.Compare):
        if not any(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
            return
        operands = [node.left, *node.comparators]
        calls = [
            n for n in operands
            if isinstance(n, ast.Call)
            and (_is_env_read_call(n) or _accessor_name(n) == "env_str")
        ]
        lits = [
            n.value for n in operands
            if isinstance(n, ast.Constant)
            and isinstance(n.value, str)
            and n.value.strip().lower() in _TRUTHINESS_LITERALS
        ]
        if calls and lits and not self.is_registry:
            self._flag(
                node, "bool-compare",
                f"ad-hoc truthiness comparison against {lits[0]!r} — "
                "use env_bool so '0'/'false'/unset parse identically",
            )

    def visit_Call(self, node: ast.Call):
        if (
            _is_env_read_call(node)
            and not self.is_registry
            and not self._suppressed(node)
        ):
            self._flag(
                node, "raw-env-read",
                "os.environ read outside the registry accessor "
                "(use skyline_tpu.analysis.registry.env_*)",
            )
        gate = _gate_helper_name(node)
        if (
            gate is not None
            and not self.is_table
            and not self._suppressed(node, SUPPRESS_GATE)
        ):
            self._flag(
                node, "raw-applicability",
                f"{gate}() called outside the cascade table — resolve "
                "through skyline_tpu.ops.cascade (gate/applies/merge_*/"
                "resolve_*) so tuner overrides and pins apply",
            )
        acc = _accessor_name(node)
        if acc is not None:
            if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
                node.args[0].value, str
            ):
                name = node.args[0].value
                self.reads.add(name)
                if name not in _BY_NAME:
                    self._flag(
                        node, "undeclared-knob",
                        f"{acc}({name!r}) reads a knob the registry does "
                        "not declare — add it to registry.KNOBS",
                    )
            elif not self._suppressed(node):
                self._flag(
                    node, "dynamic-knob-name",
                    f"{acc}(...) with a non-literal knob name defeats the "
                    "dead-knob analysis — pass the full name as a string "
                    "literal",
                )
        self.generic_visit(node)


def iter_python_files(roots, skip_dirs=SKIP_DIRS):
    for root in roots:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in skip_dirs]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_paths(roots, base: str | None = None):
    """Run the knob lint over ``roots`` (files or directories).

    Returns ``(findings, reads)`` where ``reads`` is the set of knob names
    seen at accessor call sites (the dead-knob input)."""
    findings: list[Finding] = []
    reads: set[str] = set()
    base = base or os.getcwd()
    for path in iter_python_files(roots):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            findings.append(
                Finding(os.path.relpath(path, base), 1, "error",
                        "parse-error", f"could not parse: {e}")
            )
            continue
        rel = os.path.relpath(path, base)
        apath = os.path.abspath(path)
        is_registry = apath.endswith(_REGISTRY_SUFFIX)
        is_table = any(apath.endswith(sfx) for sfx in _TABLE_SUFFIXES)
        lint = _FileLint(path, rel, source, is_registry, is_table=is_table)
        lint.visit(tree)
        findings.extend(lint.findings)
        reads |= lint.reads
    return findings, reads


def dead_knobs(reads: set[str]) -> list[Finding]:
    out = []
    for name, k in _BY_NAME.items():
        if not k.external and name not in reads:
            out.append(
                Finding("skyline_tpu/analysis/registry.py", 1, "error",
                        "dead-knob",
                        f"{name} is declared but no accessor call in the "
                        "tree reads it — delete the declaration or the "
                        "knob is silently inert")
            )
    return out


def run(roots, base: str | None = None) -> list[Finding]:
    """The full pass 1: per-file lint plus the global dead-knob check."""
    findings, reads = lint_paths(roots, base)
    findings.extend(dead_knobs(reads))
    return findings
