"""Shared finding record for the static-analysis passes.

Every pass (knob lint, jaxpr audit, lock lint) reports the same shape:
``file:line severity rule message`` — one line per finding, grep-able,
stable enough for CI to diff. ``severity`` is ``error`` (fails the gate)
or ``warn`` (printed, never fails).
"""

from __future__ import annotations

from dataclasses import dataclass

SEVERITIES = ("error", "warn")


@dataclass(frozen=True)
class Finding:
    file: str
    line: int
    severity: str
    rule: str
    message: str

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}")

    def format(self) -> str:
        return f"{self.file}:{self.line} {self.severity} {self.rule} {self.message}"


def errors(findings) -> list[Finding]:
    return [f for f in findings if f.severity == "error"]
