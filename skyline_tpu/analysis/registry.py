"""Declarative runtime-knob registry — the single source of truth.

Five perf PRs grew ~50 ``SKYLINE_*`` / ``BENCH_*`` environment knobs read
ad hoc via ``os.environ`` across the engine, dispatch, serve plane and
bench harness, each call site with its own parser and its own idea of what
``"false"`` means (``!= "0"`` at one site, ``in ("1", "true", ...)`` at
another). This module declares every knob ONCE — name, type, default,
applicability, RUNBOOK anchor — and owns the only sanctioned readers
(``env_str`` / ``env_bool`` / ``env_int`` / ``env_float``). The knob lint
(``skyline_tpu.analysis.knob_lint``) walks the tree and fails CI on any
``os.environ`` read outside this module, any accessor read of an
undeclared knob, and any declared knob nothing reads (dead).

Parsing contract (the PR-6 unification):

- bool: ``"0" / "false" / "no" / "off"`` (any case) are False,
  ``"1" / "true" / "yes" / "on"`` are True, unset/empty means the
  call-site default, anything else warns once and means the default.
  Every boolean knob in the tree goes through this one parser, so
  ``SKYLINE_MERGE_PRUNE=false`` can no longer silently mean *enabled*
  while ``SKYLINE_EMIT_PER_SLIDE=false`` means disabled.
- int / float: unset/empty means the default; an unparseable value warns
  once and means the default (a typo'd knob must not crash a worker that
  has been ingesting for an hour).

This module must stay stdlib-only and import-light: ``skyline_tpu/
__init__.py`` and the dispatch hot path import it.
"""

from __future__ import annotations

import os
import warnings
from dataclasses import dataclass, field

_TRUTHY = frozenset(("1", "true", "yes", "on"))
_FALSY = frozenset(("0", "false", "no", "off"))

# sentinel: "use the knob's declared default" is deliberately NOT the
# accessor default — call sites state their default explicitly (config.py's
# flag defaults live on JobConfig) and tests assert the two never drift
_UNSET = object()


@dataclass(frozen=True)
class Knob:
    """One declared runtime knob.

    ``default`` is the documented effective value when the variable is
    unset (None = unset-sensitive: the call site branches on presence,
    e.g. SKYLINE_MIXED_PRECISION's backend-dependent auto). ``job_field``
    names the JobConfig dataclass field a flag-backed knob defaults from —
    tests assert registry default == JobConfig field default so the table
    cannot drift. ``external`` marks variables owned by another system
    (JAX, XLA): read through the accessor for lint coverage, but exempt
    from the dead-knob and naming checks.
    """

    name: str
    type: str  # bool | int | float | str | enum
    default: object
    description: str
    applies_to: str
    runbook: str = ""
    choices: tuple = ()
    job_field: str = ""
    external: bool = False

    def __post_init__(self):
        if self.type not in ("bool", "int", "float", "str", "enum"):
            raise ValueError(f"{self.name}: bad type {self.type!r}")
        if self.type == "enum" and not self.choices:
            raise ValueError(f"{self.name}: enum knob needs choices")


def _k(name, type, default, description, applies_to, **kw) -> Knob:
    return Knob(name, type, default, description, applies_to, **kw)


KNOBS: tuple[Knob, ...] = (
    # -- dispatch / engine perf gates (ops/dispatch.py) --------------------
    _k("SKYLINE_RANK_CASCADE", "bool", False,
       "dense-rank dominance cascade for the self-skyline passes "
       "(default off until the hardware A/B lands)", "engine/tpu", runbook="§2"),
    _k("SKYLINE_MERGE_CACHE", "bool", True,
       "epoch-keyed global-merge result cache (repeated triggers launch "
       "zero kernels)", "engine", runbook="§2e"),
    _k("SKYLINE_DELTA_CUTOFF", "float", 0.75,
       "max dirty-partition fraction for the delta-merge path; above it "
       "the full union merge runs", "engine", runbook="§2e"),
    _k("SKYLINE_STAGE_DEPTH", "int", 1,
       "flush rounds staged ahead of the in-flight merge kernel "
       "(0 = no staging, 1 = double buffering)", "engine", runbook="§2e"),
    _k("SKYLINE_MERGE_TREE", "bool", True,
       "pruned tournament-tree global merge for d > 2 (0 = flat union "
       "merge, the A/B baseline)", "engine", runbook="§2f"),
    _k("SKYLINE_MERGE_PRUNE", "bool", True,
       "witness-dominance partition prefilter ahead of the tree merge",
       "engine", runbook="§2f"),
    _k("SKYLINE_FLUSH_PREFILTER", "bool", True,
       "quantized-grid host prefilter ahead of the flush merge kernels",
       "engine", runbook="§2g"),
    _k("SKYLINE_MIXED_PRECISION", "bool", None,
       "bf16 margin pass inside the flush dominance kernels; unset = auto "
       "(on for TPU, off elsewhere — XLA CPU emulates bf16)", "engine",
       runbook="§2g"),
    _k("SKYLINE_CHIP_PRUNE", "bool", True,
       "chip-level witness prefilter in the sharded engine's two-level "
       "merge (a dominated chip never crosses the interconnect)",
       "engine/sharded", runbook="§2n"),
    _k("SKYLINE_CHIP_BARRIER", "enum", "merge",
       "when the sharded engine writes chip-consistency barrier records: "
       "merge (every two-level merge), checkpoint (checkpoint time only), "
       "off (no chip WAL plane)", "engine/sharded",
       choices=("merge", "checkpoint", "off"), runbook="§2n"),
    _k("SKYLINE_CHIP_MERGE_DEADLINE_MS", "float", 0.0,
       "per-chip level-1 merge deadline in the sharded tournament; a chip "
       "that misses it is excluded and the answer publishes marked "
       "partial (0 = unbounded, the byte-identity default)",
       "engine/sharded", runbook="§2p"),
    _k("SKYLINE_CHIP_MERGE_RETRIES", "int", 1,
       "bounded retries per chip inside the merge deadline (transient "
       "faults get a second chance before exclusion)", "engine/sharded",
       runbook="§2p"),
    _k("SKYLINE_CHIP_MERGE_BACKOFF_MS", "float", 50.0,
       "base backoff between per-chip merge retries (doubles per "
       "attempt)", "engine/sharded", runbook="§2p"),
    _k("SKYLINE_CHIP_HEDGE_MS", "float", 0.0,
       "straggler hedge: launch a second attempt for a chip still "
       "running after this many ms (0 = no hedging)", "engine/sharded",
       runbook="§2p"),
    _k("SKYLINE_CHIP_FAILOVER", "bool", True,
       "online partition-group failover: a quarantined chip's group is "
       "re-owned by a healthy chip at the next merge launch",
       "engine/sharded", runbook="§2p"),
    _k("SKYLINE_CHIP_FAILOVER_LOCK_MS", "float", 5000.0,
       "bounded wait for a chip's merge lock before failover captures "
       "its group state (an in-flight merge attempt must drain first; "
       "past the bound failover defers to the next tick)",
       "engine/sharded", runbook="§2p"),
    _k("SKYLINE_QUERY_OVERLAP", "bool", True,
       "overlapped query sync: launch the global merge at trigger time, "
       "harvest at emission", "engine", runbook="§2f"),
    _k("SKYLINE_PALLAS_INTERPRET", "bool", False,
       "run the Pallas kernels in interpret mode on CPU (lowering "
       "validation without TPU hardware)", "kernels/test"),
    _k("SKYLINE_SORTED_SFS", "enum", "auto",
       "sorted-order SFS dominance cascade for d>2 on non-TPU backends: "
       "auto (per-(d,N,backend) choice from measured KernelProfiler wall "
       "data), on (force the sorted host path), off (device kernels only)",
       "engine", choices=("auto", "on", "off"), runbook="§2m"),
    _k("SKYLINE_SORTED_SFS_BLOCK", "int", 8192,
       "max scan-block width of the sorted SFS cascade (the exact "
       "in-block pairwise tile; blocks start at 1024 and double up to "
       "this)", "engine", runbook="§2m"),
    _k("SKYLINE_DEVICE_CASCADE", "enum", "auto",
       "device-side sorted dominance cascade (jit-safe, TPU + traced "
       "paths): auto (per-(d,N,backend,mp) choice from measured "
       "KernelProfiler wall data), on (force the cascade, including "
       "under trace), off (quadratic device kernels only)",
       "engine", choices=("auto", "on", "off"), runbook="§2t"),
    _k("SKYLINE_DEVICE_CASCADE_BLOCK", "int", 2048,
       "scan block size of the device cascade (buffer chunks, in-block "
       "pairwise tiles, and ambiguous-band tiles; rounded to a power of "
       "two, floored at 1024 on the Pallas path)", "engine",
       runbook="§2t"),
    # -- utils -------------------------------------------------------------
    _k("SKYLINE_COMPILE_CACHE", "str", None,
       "persistent XLA compilation cache directory (default: repo-local "
       ".jax_cache in a source checkout)", "utils"),
    _k("SKYLINE_PROBE_CACHE_TTL_S", "float", 3600.0,
       "TTL of the cross-process backend-probe verdict file under "
       "artifacts/ (0 disables)", "utils/probe"),
    _k("SKYLINE_PROBE_TIMEOUT_S", "float", 150.0,
       "backend-probe subprocess timeout", "utils/probe"),
    _k("BENCH_PROBE_TIMEOUT", "float", 150.0,
       "legacy alias of SKYLINE_PROBE_TIMEOUT_S (lower precedence)",
       "utils/probe"),
    # -- multihost ---------------------------------------------------------
    _k("SKYLINE_COORDINATOR", "str", None,
       "jax.distributed coordinator address for multi-host runs",
       "parallel/multihost"),
    _k("SKYLINE_NUM_PROCESSES", "int", None,
       "jax.distributed process count (None = auto-detect)",
       "parallel/multihost"),
    _k("SKYLINE_PROCESS_ID", "int", None,
       "jax.distributed process id (None = auto-detect)",
       "parallel/multihost"),
    # -- driver entry (__graft_entry__.py) ---------------------------------
    _k("SKYLINE_DRYRUN_FORCE_CPU", "bool", False,
       "skip the hardware probe in dryrun_multichip and emulate on CPU",
       "driver"),
    _k("SKYLINE_DRYRUN_PROBE_TIMEOUT", "float", 60.0,
       "backend-probe timeout inside dryrun_multichip", "driver"),
    # -- job flags (utils/config.py; SKYLINE_<FLAG> overrides the default,
    #    the CLI flag overrides both; defaults live on JobConfig) ----------
    _k("SKYLINE_PARALLELISM", "int", 4, "worker parallelism", "job flag",
       job_field="parallelism"),
    _k("SKYLINE_ALGO", "str", "mr-angle", "partitioner algorithm",
       "job flag", job_field="algo"),
    _k("SKYLINE_INPUT_TOPIC", "str", "input-tuples", "input topic",
       "job flag", job_field="input_topic"),
    _k("SKYLINE_QUERY_TOPIC", "str", "queries", "query topic", "job flag",
       job_field="query_topic"),
    _k("SKYLINE_OUTPUT_TOPIC", "str", "output-skyline", "output topic",
       "job flag", job_field="output_topic"),
    _k("SKYLINE_DOMAIN", "float", 1000.0, "domain max per dimension",
       "job flag", job_field="domain"),
    _k("SKYLINE_DIMS", "int", 2, "tuple dimensionality", "job flag",
       job_field="dims"),
    _k("SKYLINE_BOOTSTRAP", "str", "localhost:9092",
       "Kafka bootstrap address", "job flag", job_field="bootstrap"),
    _k("SKYLINE_BUFFER_SIZE", "int", 4096, "per-partition buffer size",
       "job flag", job_field="buffer_size"),
    _k("SKYLINE_EMIT_SKYLINE_POINTS", "bool", False,
       "include skyline points in result JSON", "job flag",
       job_field="emit_skyline_points"),
    _k("SKYLINE_QUERY_TIMEOUT_MS", "float", 0.0,
       "finalize overdue queries as partial results (0 = wait forever)",
       "job flag", job_field="query_timeout_ms"),
    _k("SKYLINE_GRID_PREFILTER", "bool", False,
       "domain-midpoint dominance prefilter (the reference's disabled "
       "GridDominanceFilter, barrier-safe)", "job flag",
       job_field="grid_prefilter"),
    _k("SKYLINE_INITIAL_CAPACITY", "int", 0,
       "pre-size per-partition skyline buffers", "job flag",
       job_field="initial_capacity"),
    _k("SKYLINE_FLUSH_POLICY", "enum", "incremental", "flush policy",
       "job flag", choices=("incremental", "lazy", "overlap"),
       job_field="flush_policy"),
    _k("SKYLINE_OVERLAP_ROWS", "int", 262144,
       "rows between automatic flushes under flush-policy overlap",
       "job flag", job_field="overlap_rows"),
    _k("SKYLINE_INGEST", "enum", "auto",
       "where routing/sort/block assembly runs", "job flag",
       choices=("auto", "host", "device"), job_field="ingest"),
    _k("SKYLINE_MESH", "int", 0,
       "shard partitions over this many devices (0 = single device)",
       "job flag", job_field="mesh"),
    _k("SKYLINE_MESH_CHIPS", "int", 0,
       "sharded streaming engine: split partitions into this many per-chip "
       "groups with a two-level tournament merge (0 = single device)",
       "job flag", runbook="§2n", job_field="mesh_chips"),
    _k("SKYLINE_STATS_PORT", "int", 0,
       "serve live /stats JSON on this port (0 = off)", "job flag",
       runbook="§2b", job_field="stats_port"),
    _k("SKYLINE_WINDOW", "int", 0,
       "sliding-window size in tuples (0 = unbounded)", "job flag",
       runbook="§2c", job_field="window_size"),
    _k("SKYLINE_SLIDE", "int", 0, "slide in tuples (with SKYLINE_WINDOW)",
       "job flag", runbook="§2c", job_field="slide"),
    _k("SKYLINE_EMIT_PER_SLIDE", "bool", False,
       "emit one result JSON per completed slide", "job flag",
       runbook="§2c", job_field="emit_per_slide"),
    _k("SKYLINE_MAX_DRAIN_POLLS", "int", 256,
       "cap on trigger-pending data re-polls per worker step", "job flag",
       job_field="max_drain_polls"),
    _k("SKYLINE_SERVE", "int", -1,
       "query-serving plane port (-1 = off, 0 = pick a free port)",
       "job flag", runbook="§2d", job_field="serve_port"),
    _k("SKYLINE_SERVE_READ_RATE", "float", 0.0,
       "snapshot-read token rate per second (0 = unlimited)", "job flag",
       runbook="§2d", job_field="serve_read_rate"),
    _k("SKYLINE_SERVE_READ_BURST", "int", 256,
       "snapshot-read token bucket capacity", "job flag", runbook="§2d",
       job_field="serve_read_burst"),
    _k("SKYLINE_SERVE_MAX_QUERIES", "int", 2,
       "concurrent forced merges (POST /query)", "job flag",
       runbook="§2d", job_field="serve_max_queries"),
    _k("SKYLINE_SERVE_QUERY_QUEUE", "int", 8,
       "queued forced merges beyond the concurrent cap", "job flag",
       runbook="§2d", job_field="serve_query_queue"),
    _k("SKYLINE_SERVE_QUERY_DEADLINE_MS", "float", 10_000.0,
       "deadline for an admitted forced merge", "job flag", runbook="§2d",
       job_field="serve_query_deadline_ms"),
    _k("SKYLINE_SERVE_DELTA_RING", "int", 128,
       "snapshot transitions kept for /deltas catch-up", "job flag",
       runbook="§2d", job_field="serve_delta_ring"),
    _k("SKYLINE_SERVE_HISTORY", "int", 64,
       "snapshot versions retained in the store", "job flag",
       runbook="§2d", job_field="serve_history"),
    _k("SKYLINE_SERVE_READ_CACHE", "int", 64,
       "serialized-response LRU entries (0 disables)", "job flag",
       runbook="§2e", job_field="serve_read_cache"),
    _k("SKYLINE_SERVE_READY_TIMEOUT_S", "float", 10.0,
       "startup wait for the serving loop to bind its socket", "serve",
       runbook="§2d"),
    _k("SKYLINE_SERVE_SHUTDOWN_TIMEOUT_S", "float", 10.0,
       "close() wait for the serving loop thread to drain", "serve",
       runbook="§2d"),
    _k("SKYLINE_SERVE_HEADER_TIMEOUT_S", "float", 10.0,
       "per-connection wait for a complete request header block", "serve",
       runbook="§2d"),
    _k("SKYLINE_SERVE_SSE_QUEUE", "int", 64,
       "per-subscriber event queue for GET /subscribe; a subscriber that "
       "falls further behind is drained and sent a resync event", "serve",
       runbook="§2q"),
    _k("SKYLINE_SERVE_TENANT_RATE", "float", 0.0,
       "per-tenant snapshot-read token rate per second, keyed on the "
       "X-Tenant header (0 = no per-tenant limit)", "job flag",
       runbook="§2q", job_field="serve_tenant_rate"),
    _k("SKYLINE_SERVE_TENANT_BURST", "int", 64,
       "per-tenant snapshot-read token bucket capacity", "job flag",
       runbook="§2q", job_field="serve_tenant_burst"),
    _k("SKYLINE_REPLICAS", "int", 0,
       "WAL-tailing read replicas spawned in-process by the worker "
       "(requires --checkpoint-dir and --serve)", "job flag",
       runbook="§2q", job_field="replicas"),
    _k("SKYLINE_REPLICA_OF", "str", "",
       "run as a standalone read replica tailing this WAL directory "
       "instead of a worker (mutually exclusive with --replicas)",
       "job flag", runbook="§2q", job_field="replica_of"),
    _k("SKYLINE_CLUSTER_HOSTS", "int", 0,
       "multi-host cluster ingest: partition the stream across this many "
       "host-level partition groups with a third (host) tournament merge "
       "level (0 = single host)", "job flag", runbook="§2r",
       job_field="cluster_hosts"),
    _k("SKYLINE_CLUSTER_LEASE_TTL_MS", "float", 3000.0,
       "write-lease time-to-live: the primary must renew within this "
       "window or the ClusterSupervisor fences its epoch and promotes "
       "the most-caught-up replica", "cluster", runbook="§2r"),
    _k("SKYLINE_CLUSTER_LEASE_RENEW_MS", "float", 0.0,
       "primary lease renew cadence (0 = TTL/3); must be well under the "
       "TTL or the primary deposes itself", "cluster", runbook="§2r"),
    _k("SKYLINE_CLUSTER_HOST_PRUNE", "bool", True,
       "host-level witness prefilter in the cluster merge: a host whose "
       "summary is witness-dominated ships zero rows to the coordinator "
       "(byte-identical either way)", "cluster", runbook="§2r"),
    _k("SKYLINE_CLUSTER_MIGRATION_BUDGET", "int", 8,
       "max live partition-group migrations between hosts per coordinator "
       "lifetime (drain/checkpoint-slice/restore/fence cycles); guards "
       "against health-signal flapping thrashing state", "cluster",
       runbook="§2r"),
    _k("SKYLINE_REPLICA_MAX_STALE_MS", "float", 30_000.0,
       "replica staleness fence: reads whose snapshot is older than this "
       "are refused with 503 + Retry-After instead of served silently "
       "stale", "serve", runbook="§2q"),
    _k("SKYLINE_REPLICA_POLL_MS", "float", 25.0,
       "replica WAL tail poll interval when no new frames are available",
       "serve", runbook="§2q"),
    _k("SKYLINE_BODYSTORE", "bool", True,
       "zero-copy body store: serialize wire bodies once at publish time "
       "and serve them via fence-checked buffer handoffs (primary retained "
       "bytes; replicas map the primary's bodystore.dat)", "serve",
       runbook="§2u"),
    _k("SKYLINE_BODYSTORE_BYTES", "int", 8 << 20,
       "body-store data ring capacity in bytes; bodies larger than this "
       "skip the mmap (in-process retained bytes still serve them)",
       "serve", runbook="§2u"),
    _k("SKYLINE_BODYSTORE_SLOTS", "int", 512,
       "body-store directory slots ((version, format) keys live at "
       "(version*5+fmt) mod slots)", "serve", runbook="§2u"),
    _k("SKYLINE_BODYSTORE_RETRIES", "int", 4,
       "bounded seqlock retries per body-store read before declaring a "
       "miss and falling back to Python serialization", "serve",
       runbook="§2u"),
    _k("SKYLINE_BODYSTORE_KEEP", "int", 4,
       "snapshot versions whose wire bodies the primary retains in-process "
       "(zero-copy dict hits; older versions fall through to the mmap "
       "ring)", "serve", runbook="§2u"),
    _k("SKYLINE_BODYSTORE_NATIVE", "bool", True,
       "use the native sky_format_rows row serializer for body encoding "
       "(0 forces the byte-identical pure-Python encoders)", "serve",
       runbook="§2u"),
    _k("SKYLINE_BODYSTORE_VERIFY", "bool", False,
       "verify EVERY native-encoded body against the Python encoder "
       "(default verifies only the first per process); mismatch disables "
       "the native path", "serve", runbook="§2u"),
    _k("SKYLINE_TRACE_OUT", "str", "",
       "write the span ring as Chrome trace-event JSON on shutdown",
       "job flag", runbook="§2b", job_field="trace_out"),
    _k("SKYLINE_TRACE_RING", "int", 4096, "span ring capacity",
       "job flag", runbook="§2b", job_field="trace_ring"),
    _k("SKYLINE_JAX_PROFILE_DIR", "str", "",
       "wrap each forced-query injection in jax.profiler.trace",
       "job flag", runbook="§2b", job_field="jax_profile_dir"),
    _k("SKYLINE_CHECKPOINT_DIR", "str", "",
       "enable crash safety: WAL + periodic checkpoints under this "
       "directory (empty = off)", "job flag", runbook="§2i",
       job_field="checkpoint_dir"),
    _k("SKYLINE_CHECKPOINT_INTERVAL_S", "float", 30.0,
       "seconds between automatic checkpoints (0 = only on clean "
       "shutdown / manual)", "job flag", runbook="§2i",
       job_field="checkpoint_interval_s"),
    _k("SKYLINE_CHECKPOINT_RETAIN", "int", 3,
       "checkpoints kept on disk (older ones pruned)", "job flag",
       runbook="§2i", job_field="checkpoint_retain"),
    _k("SKYLINE_WAL_FSYNC", "enum", "batch",
       "WAL durability: always (per append), batch (per worker step), "
       "off (OS page cache only)", "job flag",
       choices=("always", "batch", "off"), runbook="§2i",
       job_field="wal_fsync"),
    _k("SKYLINE_WAL_SEGMENT_BYTES", "int", 4_194_304,
       "WAL segment rotation size", "job flag", runbook="§2i",
       job_field="wal_segment_bytes"),
    _k("SKYLINE_WAL_TAILER_TTL_S", "float", 600.0,
       "staleness TTL on replica tail acks: barrier() keeps segments a "
       "live tailer hasn't consumed, but an ack older than this stops "
       "pinning retention (dead replica protection)", "resilience",
       runbook="§2q"),
    # -- resilience runtime (skyline_tpu/resilience) -----------------------
    _k("SKYLINE_FAULT_PLAN", "str", None,
       "deterministic fault-injection plan, e.g. crash@flush.pre_merge:3 "
       "(comma-separated action@point:nth clauses; actions: crash, exit, "
       "corrupt, slow, hang; chip-scopable as point#chip; test/chaos use "
       "only)", "resilience", runbook="§2i"),
    _k("SKYLINE_FAULT_SLOW_MS", "float", 250.0,
       "injected delay of a slow@ fault clause", "resilience",
       runbook="§2p"),
    _k("SKYLINE_FAULT_HANG_S", "float", 3600.0,
       "cap on a hang@ fault clause (the hung thread parks on an event "
       "released by faults.clear())", "resilience", runbook="§2p"),
    _k("SKYLINE_CHIP_FAIL_THRESHOLD", "int", 1,
       "consecutive per-chip merge failures/timeouts before quarantine",
       "resilience", runbook="§2p"),
    _k("SKYLINE_CHIP_QUARANTINE_SCORE", "float", 0.5,
       "health score below which a chip quarantines (scores decay on "
       "failure/straggle, recover on clean merges)", "resilience",
       runbook="§2p"),
    _k("SKYLINE_CHIP_STRAGGLER_FACTOR", "float", 4.0,
       "a chip's level-1 wall beyond this multiple of the peer-EMA "
       "median counts as a straggle (after a warmup of clean merges)",
       "resilience", runbook="§2p"),
    _k("SKYLINE_CHIP_HEARTBEAT_MS", "float", 30000.0,
       "per-chip heartbeat staleness limit for the health tick "
       "(relative: the whole fleet idling does not quarantine anyone)",
       "resilience", runbook="§2p"),
    _k("SKYLINE_SUPERVISOR_MAX_RESTARTS", "int", 5,
       "supervised-restart budget before giving up", "resilience",
       runbook="§2i"),
    _k("SKYLINE_SUPERVISOR_BACKOFF_S", "float", 0.5,
       "base restart backoff (doubles per crash, plus jitter)",
       "resilience", runbook="§2i"),
    _k("SKYLINE_SUPERVISOR_BACKOFF_CAP_S", "float", 30.0,
       "restart backoff ceiling", "resilience", runbook="§2i"),
    _k("SKYLINE_KAFKA_RETRIES", "int", 5,
       "kafkalite transport reconnect attempts per request", "bridge",
       runbook="§2i"),
    _k("SKYLINE_KAFKA_BACKOFF_S", "float", 0.05,
       "base kafkalite reconnect backoff (doubles per attempt)", "bridge",
       runbook="§2i"),
    # -- observability (skyline_tpu/telemetry) -----------------------------
    _k("SKYLINE_FRESHNESS", "bool", True,
       "event-time freshness lineage: per-stage lag histograms "
       "(ingest/flush/merge/publish/read) and staleness_ms on /skyline",
       "telemetry", runbook="§2j"),
    _k("SKYLINE_KERNEL_PROFILE", "bool", True,
       "per-dispatch-signature kernel profiler behind GET /profile",
       "telemetry", runbook="§2j"),
    _k("SKYLINE_PROFILE_COST", "bool", False,
       "capture XLA cost_analysis() FLOPs/bytes once per signature via an "
       "AOT lower+compile (expensive; profiling sessions only)",
       "telemetry", runbook="§2j"),
    _k("SKYLINE_FLIGHT_RING", "int", 256,
       "flight-recorder ring capacity (last N engine decisions, "
       "/debug/flight and the crash dump)", "telemetry", runbook="§2j"),
    _k("SKYLINE_EXPLAIN", "bool", True,
       "per-query EXPLAIN plane: a causal QueryPlan per trigger (merge "
       "path, prune witnesses, cascade + kernel attribution, publish "
       "watermark) behind GET /explain and /skyline?explain=1",
       "telemetry", runbook="§2k"),
    _k("SKYLINE_EXPLAIN_RING", "int", 256,
       "EXPLAIN plan ring capacity (last N finalized query plans)",
       "telemetry", runbook="§2k"),
    _k("SKYLINE_SLO_FAST_WINDOW_S", "float", 300.0,
       "fast burn-rate window for the /slo evaluation", "telemetry/slo",
       runbook="§2j"),
    _k("SKYLINE_SLO_SLOW_WINDOW_S", "float", 3600.0,
       "slow burn-rate window for the /slo evaluation", "telemetry/slo",
       runbook="§2j"),
    _k("SKYLINE_SLO_READ_P99_MS", "float", 50.0,
       "SLO target: serve read p99 latency threshold", "telemetry/slo",
       runbook="§2j"),
    _k("SKYLINE_SLO_FRESH_P99_MS", "float", 5000.0,
       "SLO target: read-stage freshness lag p99 threshold",
       "telemetry/slo", runbook="§2j"),
    _k("SKYLINE_SLO_SHED_FRACTION", "float", 0.05,
       "SLO target: max fraction of snapshot reads shed by admission",
       "telemetry/slo", runbook="§2j"),
    _k("SKYLINE_SLO_RESTARTS_PER_HOUR", "float", 6.0,
       "SLO target: supervised-restart rate ceiling", "telemetry/slo",
       runbook="§2j"),
    _k("SKYLINE_AUDIT", "bool", True,
       "online audit plane: sampled shadow verification of published "
       "snapshots against the host oracle, divergence repro bundles, and "
       "correctness canaries behind GET /audit", "audit", runbook="§2l"),
    _k("SKYLINE_AUDIT_SAMPLE", "float", 1.0,
       "fraction of published snapshots shadow-verified (deterministic "
       "accumulator, not random; 0 disables organic checks)", "audit",
       runbook="§2l"),
    _k("SKYLINE_AUDIT_RING", "int", 256,
       "audit check-record ring capacity (last N verdicts on /audit)",
       "audit", runbook="§2l"),
    _k("SKYLINE_AUDIT_DIR", "str", "artifacts/audit",
       "divergence repro-bundle directory (checkpoint + WAL slice + "
       "EXPLAIN plan + knob snapshot + both skylines)", "audit",
       runbook="§2l"),
    _k("SKYLINE_AUDIT_CANARY_S", "float", 300.0,
       "seconds between synthetic known-answer canary sweeps over every "
       "merge path while the worker is idle (0 = off)", "audit",
       runbook="§2l"),
    _k("SKYLINE_AUDIT_ORACLE", "enum", "sorted",
       "host oracle the auditor verifies answers against: sorted "
       "(dedup + sum-sorted scan, full-rate affordable) or quadratic "
       "(the O(n²d) oracle-of-the-oracle kept for tests)", "audit",
       choices=("sorted", "quadratic"), runbook="§2l"),
    _k("SKYLINE_SLO_AUDIT_DIVERGENCE", "float", 0.0001,
       "SLO target: max fraction of audited snapshots diverging from the "
       "host oracle", "telemetry/slo", runbook="§2l"),
    _k("SKYLINE_SLO_DEGRADED_ANSWERS", "float", 0.01,
       "SLO target: max fraction of answered queries published "
       "chip-degraded (marked partial)", "telemetry/slo", runbook="§2p"),
    _k("SKYLINE_SLO_TENANT_SHED", "float", 0.05,
       "SLO target: max fraction of tenant-attributed read attempts shed "
       "by the per-tenant buckets", "telemetry/slo", runbook="§2q"),
    _k("SKYLINE_SLO_REPLICATION_LAG_P99_MS", "float", 2000.0,
       "SLO target: 99% of replica WAL-fold applications land within this "
       "many ms of the frame's publish time (the staleness a failover "
       "would inherit)", "telemetry/slo", runbook="§2s"),
    _k("SKYLINE_SLO_PROMOTE_P99_MS", "float", 1000.0,
       "SLO target: 99% of supervisor promotions (fence raise to replica "
       "serving) complete within this many ms", "telemetry/slo",
       runbook="§2s"),
    _k("SKYLINE_OPSLOG", "bool", True,
       "durable cross-process ops journal beside the WAL: control-plane "
       "transitions (lease/fence/promote/demote/quarantine/migrate/"
       "degraded publish) as CRC-framed records, GET /ops on both HTTP "
       "surfaces", "telemetry/ops", runbook="§2s"),
    _k("SKYLINE_OPSLOG_FSYNC", "enum", "off",
       "ops-journal durability policy: 'off' relies on one unbuffered "
       "write per record (survives process death), 'always' fsyncs every "
       "record (power-loss durable, ~ms each), 'batch' fsyncs on flush()",
       "telemetry/ops", choices=("always", "batch", "off"), runbook="§2s"),
    _k("SKYLINE_OPSLOG_MAX_BYTES", "int", 8_388_608,
       "per-incarnation ops-journal size cap; past it records are dropped "
       "and counted (ops.dropped), never raised", "telemetry/ops",
       runbook="§2s"),
    _k("SKYLINE_CLUSTERVIEW_MEMBERS", "str", None,
       "comma-separated member base URLs the fleet-wide aggregation view "
       "scrapes for GET /cluster/overview (and the clusterview CLI "
       "default)", "telemetry/ops", runbook="§2s"),
    _k("SKYLINE_CLUSTERVIEW_TIMEOUT_S", "float", 2.0,
       "per-request timeout when the clusterview scraper polls a member's "
       "/metrics, /cluster, /healthz, /ops", "telemetry/ops",
       runbook="§2s"),
    _k("SKYLINE_CLUSTERVIEW_OPS_TAIL", "int", 64,
       "ops-journal records the clusterview scraper pulls per member "
       "(?limit= on each member's /ops)", "telemetry/ops", runbook="§2s"),
    _k("SKYLINE_FLEET", "bool", True,
       "per-chip fleet plane on the sharded engine: skyline_chip_* "
       "labeled metric families, imbalance index + skew ring, per-chip "
       "tournament spans, and GET /fleet", "telemetry", runbook="§2o"),
    _k("SKYLINE_FLEET_IMBALANCE_THRESHOLD", "float", 2.0,
       "imbalance index (max/mean chip ingest load) above which a "
       "fleet.imbalance flight-recorder entry fires (edge-triggered per "
       "excursion)", "telemetry", runbook="§2o"),
    _k("SKYLINE_FLEET_RING", "int", 64,
       "rolling skew ring capacity (per-merge imbalance samples behind "
       "the skew score)", "telemetry", runbook="§2o"),
    _k("SKYLINE_WORKLOAD", "bool", True,
       "streaming workload characterizer: per-dim quantile sketches, "
       "correlation estimate, uniform/correlated/anti_correlated "
       "classification, drift detection; the regime tag on every EXPLAIN "
       "plan", "telemetry", runbook="§2o"),
    _k("SKYLINE_WORKLOAD_EPOCH_ROWS", "int", 4096,
       "sampled rows per characterizer epoch (classification + drift "
       "check cadence)", "telemetry", runbook="§2o"),
    _k("SKYLINE_WORKLOAD_SAMPLE_CAP", "int", 512,
       "max rows sampled per ingest batch (deterministic stride, no RNG)",
       "telemetry", runbook="§2o"),
    _k("SKYLINE_WORKLOAD_RING", "int", 64,
       "epoch-summary and query-trajectory ring capacity", "telemetry",
       runbook="§2o"),
    _k("SKYLINE_WORKLOAD_SUM_RATIO", "float", 0.5,
       "row-sum variance ratio below which the stream classifies "
       "anti_correlated (constant-sum band; 1.0 = independent dims)",
       "telemetry", runbook="§2o"),
    _k("SKYLINE_WORKLOAD_CORR_THRESHOLD", "float", 0.25,
       "mean pairwise correlation above which the stream classifies "
       "correlated (subject to the dispersion tiebreak)", "telemetry",
       runbook="§2o"),
    _k("SKYLINE_WORKLOAD_DISP_THRESHOLD", "float", 0.27,
       "within-row coefficient-of-variation above which a positively "
       "correlated stream reclassifies as wide-band anti_correlated "
       "(shared per-row scale)", "telemetry", runbook="§2o"),
    _k("SKYLINE_WORKLOAD_DRIFT_THRESHOLD", "float", 0.2,
       "per-dim p50 shift (normalized by the frozen sketch range) beyond "
       "which consecutive epochs count as drift", "telemetry",
       runbook="§2o"),
    # -- closed-loop dispatch tuner (telemetry/tuner.py, ops/cascade.py) ---
    _k("SKYLINE_TUNER", "bool", True,
       "closed-loop dispatch tuner over the cascade table: pins measured "
       "EMA winners per signature and retunes table-scoped knobs per "
       "workload regime (0 = static dispatch, the A/B baseline)",
       "engine", runbook="§2v"),
    _k("SKYLINE_TUNER_EPOCH_S", "float", 5.0,
       "min seconds between controller epochs (the tuner is also passive "
       "until the first workload epoch closes)", "engine", runbook="§2v"),
    _k("SKYLINE_TUNER_HYSTERESIS", "int", 2,
       "consecutive controller epochs a new workload regime must persist "
       "before the tuner switches context (drift-flip damping)",
       "engine", runbook="§2v"),
    _k("SKYLINE_TUNER_MAX_MOVES", "int", 2,
       "max pin/knob moves per controller epoch (bounded-move rule)",
       "engine", runbook="§2v"),
    _k("SKYLINE_TUNER_CUTOFF_STEP", "float", 0.1,
       "max delta-cutoff movement per controller epoch when steering "
       "toward the observed dirty-fraction quantile", "engine",
       runbook="§2v"),
    _k("SKYLINE_TUNER_EXPLORE_ON_DRIFT", "bool", True,
       "on a confirmed regime switch with no banked state, reset the "
       "mask/flush profiler signatures so the variant race re-runs under "
       "the new distribution", "engine", runbook="§2v"),
    _k("SKYLINE_SENTINEL_WINDOW", "int", 4,
       "perf-trajectory sentinel: rolling-baseline window (newest "
       "artifact compared against the median of up to N prior comparable "
       "rounds)", "telemetry", runbook="§2o"),
    _k("SKYLINE_SENTINEL_THRESHOLD", "float", 0.3,
       "perf-trajectory sentinel: default max fractional regression vs "
       "the rolling baseline (per-metric rules can override)",
       "telemetry", runbook="§2o"),
    # -- bench harness (bench.py) ------------------------------------------
    _k("BENCH_N", "int", None,
       "window rows (default 1M on TPU, BENCH_CPU_N on the fallback)",
       "bench"),
    _k("BENCH_CPU_N", "int", 131072, "window rows for the CPU fallback",
       "bench"),
    _k("BENCH_D", "int", 8, "tuple dimensionality", "bench"),
    _k("BENCH_WINDOWS", "int", None,
       "measured windows (default 5 on TPU, 1 on the CPU fallback)",
       "bench"),
    _k("BENCH_PARALLELISM", "int", 4, "engine parallelism", "bench"),
    _k("BENCH_ALGO", "str", "mr-angle", "partitioner for the bench run",
       "bench"),
    _k("BENCH_BUFFER", "int", 8192, "per-partition buffer size", "bench"),
    _k("BENCH_INITIAL_CAP", "int", 65536,
       "pre-sized per-partition skyline capacity", "bench"),
    _k("BENCH_FLUSH_POLICY", "str", "lazy", "flush policy for the bench run",
       "bench"),
    _k("BENCH_SERVE", "bool", True, "run the serving-plane bench leg",
       "bench"),
    _k("BENCH_SERVE_N", "int", 65536, "serve-leg window rows", "bench"),
    _k("BENCH_SERVE_READERS", "int", 32, "serve-leg reader threads",
       "bench"),
    _k("BENCH_SERVE_READS", "int", 25, "serve-leg reads per reader",
       "bench"),
    _k("BENCH_REPLICA", "bool", True, "run the replica-plane bench leg",
       "bench", runbook="§2q"),
    _k("BENCH_REPLICA_PUBLISHES", "int", 40,
       "replica-leg publish transitions tailed", "bench"),
    _k("BENCH_REPLICA_ROWS", "int", 2048,
       "replica-leg rows per published snapshot", "bench"),
    _k("BENCH_LOAD", "bool", True,
       "run the serve_load leg (benchmarks/loadgen.py multi-tenant A/B "
       "harness)", "bench", runbook="§2u"),
    _k("BENCH_LOAD_TENANTS", "int", 10_000,
       "synthetic tenants in the load harness (zipf-skewed)", "bench",
       runbook="§2u"),
    _k("BENCH_LOAD_SECONDS", "float", 3.0,
       "measured wall seconds per load-harness arm", "bench",
       runbook="§2u"),
    _k("BENCH_LOAD_WORKERS", "int", 8,
       "concurrent client worker threads in the load harness", "bench",
       runbook="§2u"),
    _k("BENCH_LOAD_ZIPF", "float", 1.1,
       "zipf exponent for tenant skew (higher = hotter head tenants)",
       "bench", runbook="§2u"),
    _k("BENCH_LOAD_BURST", "float", 0.05,
       "burst-storm fraction: slice of request slots fired as "
       "simultaneous storms against the head tenants", "bench",
       runbook="§2u"),
    _k("BENCH_LOAD_SSE", "int", 4,
       "long-lived SSE subscriber connections held open during the load "
       "run", "bench", runbook="§2u"),
    _k("BENCH_CLUSTER", "bool", True,
       "run the cluster-plane bench leg (host-prune probe + promotion "
       "drill)", "bench", runbook="§2r"),
    _k("BENCH_TUNER", "bool", True,
       "run the dispatch-tuner A/B leg (benchmarks/tuner.py static-best "
       "vs controller under drift, byte-identity asserted before timing)",
       "bench", runbook="§2v"),
    _k("BENCH_OPS", "bool", True,
       "run the ops-plane bench leg (journal append cost + clusterview "
       "scrape wall)", "bench", runbook="§2s"),
    _k("BENCH_OPS_APPENDS", "int", 2000,
       "ops-leg journal appends timed for the per-record cost", "bench"),
    _k("BENCH_SERVE_POINTS", "bool", False,
       "serve-leg full-payload reads instead of metadata-only", "bench"),
    _k("BENCH_COMPILE_CACHE", "str", None,
       "persistent compile-cache dir override for bench children", "bench"),
    _k("BENCH_PROBE_ATTEMPTS", "int", 2, "backend-probe attempts", "bench"),
    _k("BENCH_PROBE_BACKOFF", "float", 20.0,
       "seconds between probe attempts", "bench"),
    _k("BENCH_CHILD_TIMEOUT", "float", 3000.0,
       "bounded child-run timeout in seconds", "bench"),
    _k("BENCH_TPU_ATTEMPTS", "int", 2, "TPU child-run attempts", "bench"),
    _k("BENCH_FORCE_CPU", "bool", False, "skip the TPU leg entirely",
       "bench"),
    # -- external (owned by JAX/XLA; declared for lint coverage) -----------
    _k("JAX_PLATFORMS", "str", None, "JAX backend selection (external)",
       "external", external=True),
    _k("XLA_FLAGS", "str", None, "XLA runtime flags (external)",
       "external", external=True),
)

_BY_NAME: dict[str, Knob] = {k.name: k for k in KNOBS}
if len(_BY_NAME) != len(KNOBS):  # duplicate declaration is a bug, not data
    raise RuntimeError("duplicate knob declaration in KNOBS")

_warned: set[str] = set()


def knob(name: str) -> Knob:
    """The declaration behind ``name`` (raises LookupError if undeclared —
    the runtime mirror of the knob lint's undeclared-knob rule)."""
    try:
        return _BY_NAME[name]
    except KeyError:
        raise LookupError(
            f"env knob {name!r} is not declared in "
            "skyline_tpu.analysis.registry.KNOBS"
        ) from None


def knob_names() -> tuple[str, ...]:
    return tuple(_BY_NAME)


def _warn_once(name: str, raw: str, why: str) -> None:
    if name not in _warned:
        _warned.add(name)
        warnings.warn(
            f"ignoring {name}={raw!r}: {why}; using the default",
            RuntimeWarning,
            stacklevel=3,
        )


def _raw(name: str) -> str | None:
    knob(name)  # undeclared reads fail fast, even at runtime
    return os.environ.get(name)


def env_str(name: str, default=None):
    """String knob: unset or empty means ``default``."""
    v = _raw(name)
    if v is None or v == "":
        return default
    return v


def parse_bool(raw: str | None, default=False):
    """THE boolean parse. ``"0"/"false"/"no"/"off"`` (any case) are False;
    ``"1"/"true"/"yes"/"on"`` are True; unset/empty/unrecognized mean
    ``default`` (which may be None for unset-sensitive tri-state knobs)."""
    if raw is None:
        return default
    s = raw.strip().lower()
    if s == "" or (s not in _FALSY and s not in _TRUTHY):
        return default
    return s in _TRUTHY


def env_bool(name: str, default=False):
    v = _raw(name)
    if v is not None and v.strip() != "":
        s = v.strip().lower()
        if s not in _FALSY and s not in _TRUTHY:
            _warn_once(name, v, "not a recognized boolean")
    return parse_bool(v, default)


def env_int(name: str, default=0):
    v = _raw(name)
    if v is None or v.strip() == "":
        return default
    try:
        return int(v)
    except ValueError:
        _warn_once(name, v, "not an integer")
        return default


def env_float(name: str, default=0.0):
    v = _raw(name)
    if v is None or v.strip() == "":
        return default
    try:
        return float(v)
    except ValueError:
        _warn_once(name, v, "not a number")
        return default


# accessor names the knob lint recognizes as sanctioned read sites
ACCESSORS = ("env_str", "env_bool", "env_int", "env_float")


def _fmt_default(k: Knob) -> str:
    if k.default is None:
        return "unset"
    if k.type == "bool":
        return "on" if k.default else "off"
    return repr(k.default) if isinstance(k.default, str) else str(k.default)


def knob_doc_markdown() -> str:
    """The autogenerated knob table (``--knob-doc`` writes it to
    docs/KNOBS.md; ``--check-doc`` fails CI on drift)."""
    lines = [
        "# Runtime knobs",
        "",
        "Autogenerated by `python -m skyline_tpu.analysis --knob-doc` from",
        "`skyline_tpu/analysis/registry.py` — edit the registry, not this",
        "file (`--check-doc` fails CI on drift).",
        "",
        "Boolean knobs share one parser: `0/false/no/off` disable,",
        "`1/true/yes/on` enable, unset/empty/unrecognized mean the default.",
        "",
        "| Knob | Type | Default | Applies to | RUNBOOK | Description |",
        "|---|---|---|---|---|---|",
    ]
    for k in KNOBS:
        typ = k.type if not k.choices else "enum(" + "\\|".join(k.choices) + ")"
        lines.append(
            f"| `{k.name}` | {typ} | {_fmt_default(k)} | {k.applies_to} "
            f"| {k.runbook or '—'} | {k.description} |"
        )
    lines.append("")
    lines.append(f"{len(KNOBS)} knobs declared.")
    lines.append("")
    return "\n".join(lines)
