"""Vectorized synthetic stream generators — the three reference distributions.

Formula parity with python/unified_producer.py:50-123, vectorized over whole
batches with numpy instead of per-tuple faker calls:

- ``uniform``: independent integers in [d_min, d_max] (:50-51)
- ``correlated``: per-point base in [d_min, d_max] plus per-dimension noise in
  ±(1-rho)(d_max-d_min), truncated to int and clamped (:58-73); points hug the
  diagonal, easiest to prune
- ``anti_correlated``: a random positive direction vector scaled so its sum
  lands in a band around the hypercube-center sum, with the reference's
  dimension-dependent band thickness heuristic (2D: 0.0005, 3D: 0.05,
  4D: 0.9, else d*0.5 — :92-102), truncated and clamped; points hug the
  anti-diagonal, the documented worst case (pdf §5.6)

All generators return int-valued float32 arrays (the reference streams
integers as CSV; values stay exactly representable).
"""

from __future__ import annotations

import numpy as np

# Trigger injection interval (unified_producer.py:25)
QUERY_THRESHOLD = 1_000_000


def _epsilon(dimensions: int) -> float:
    # unified_producer.py:92-102
    if dimensions == 2:
        return 0.0005
    if dimensions == 3:
        return 0.05
    if dimensions == 4:
        return 0.9
    return dimensions * 0.005 * 100


def uniform(rng: np.random.Generator, n: int, dims: int, d_min: float, d_max: float):
    vals = rng.integers(int(d_min), int(d_max) + 1, size=(n, dims))
    return vals.astype(np.float32)


def correlated(
    rng: np.random.Generator,
    n: int,
    dims: int,
    d_min: float,
    d_max: float,
    rho: float = 0.9,
):
    base = rng.uniform(d_min, d_max, size=(n, 1))
    spread = (1.0 - rho) * (d_max - d_min)
    noise = rng.uniform(-spread, spread, size=(n, dims))
    vals = np.trunc(base + noise)  # int(val) truncates toward zero for v >= 0
    return np.clip(vals, d_min, d_max).astype(np.float32)


def anti_correlated(
    rng: np.random.Generator, n: int, dims: int, d_min: float, d_max: float
):
    eps = _epsilon(dims)
    vals = rng.random(size=(n, dims))
    total = vals.sum(axis=1, keepdims=True)
    total = np.where(total == 0, 1.0, total)
    mean = (d_min + d_max) / 2.0 * dims
    slack = eps * (d_max - d_min) * dims
    target = rng.uniform(mean - slack, mean + slack, size=(n, 1))
    scaled = vals * (target / total)
    return np.clip(np.trunc(scaled), d_min, d_max).astype(np.float32)


def qos(rng: np.random.Generator, n: int, dims: int, d_min: float, d_max: float):
    """QoS web-service workload (BASELINE.json config #5): latency,
    throughput, availability, price — the reference repo's titular use case
    (Flink-Skyline-**QoS**), though its producers only ship the three
    synthetic distributions.

    Skyline semantics are minimization in ALL dimensions, so
    higher-is-better attributes (throughput, availability) are flipped into
    the minimization space as ``d_max - value`` before emission. Shapes:
    latency is log-normal-ish (many fast services, a long slow tail);
    throughput anti-correlates with latency; availability is skewed toward
    the top of the range; price weakly correlates with quality. ``dims`` > 4
    appends uniform auxiliary attributes; ``dims`` < 4 truncates.
    """
    span = d_max - d_min
    # latency: lognormal scaled into the domain, clipped
    lat = d_min + np.clip(rng.lognormal(mean=0.0, sigma=0.8, size=n) / 6.0, 0, 1) * span
    # throughput: anti-correlated with latency + noise (maximize)
    thr = d_min + np.clip(1.0 - (lat - d_min) / span + rng.normal(0, 0.15, n), 0, 1) * span
    # availability: skewed high (maximize)
    avail = d_min + np.clip(rng.beta(8, 1.5, size=n), 0, 1) * span
    # price: weakly correlated with quality (minimize)
    qual = ((thr - d_min) + (avail - d_min)) / (2 * span)
    price = d_min + np.clip(0.3 * qual + 0.7 * rng.random(n), 0, 1) * span
    cols = [lat, d_max - (thr - d_min), d_max - (avail - d_min), price]
    if dims < 4:
        cols = cols[:dims]
    elif dims > 4:
        cols += [rng.uniform(d_min, d_max, size=n) for _ in range(dims - 4)]
    return np.clip(np.trunc(np.stack(cols, axis=1)), d_min, d_max).astype(np.float32)


def simple_correlated(
    rng: np.random.Generator, n: int, dims: int, d_min: float, d_max: float
):
    """P2's distinct correlated math (kafka_producer.py:58-64): INTEGER base
    in [d_min, d_max], per-dimension INTEGER offsets in ±10% of the domain,
    clamped — vs the unified producer's float base ± (1-rho)-scaled float
    noise. The offset window happens to coincide at rho=0.9, but the integer
    lattice and inclusive-bound sampling are P2's own."""
    offset = int((d_max - d_min) * 0.1)
    base = rng.integers(int(d_min), int(d_max) + 1, size=(n, 1))
    noise = rng.integers(-offset, offset + 1, size=(n, dims))
    return np.clip(base + noise, d_min, d_max).astype(np.float32)


def simple_anti_correlated(
    rng: np.random.Generator, n: int, dims: int, d_min: float, d_max: float
):
    """P2's anti-correlated (kafka_producer.py:77-88): every point scaled so
    its coordinate sum lands EXACTLY on the hypercube-center plane (no
    epsilon thickness band, unlike unified_producer.py:92-102) — a strictly
    harder skyline workload at d >= 4, where the unified band (eps 0.9) is
    wide enough to dilute the anti-correlation."""
    vals = rng.random(size=(n, dims))
    total = vals.sum(axis=1, keepdims=True)
    total = np.where(total == 0, 1.0, total)
    target = (d_min + d_max) / 2.0 * dims
    return np.clip(np.trunc(vals * (target / total)), d_min, d_max).astype(
        np.float32
    )


GENERATORS = {
    "uniform": uniform,
    "correlated": correlated,
    "anti_correlated": anti_correlated,
    "qos": qos,
    "simple_correlated": simple_correlated,
    "simple_anti_correlated": simple_anti_correlated,
}

# P2 (kafka_producer.py) shares P1's uniform math but has its own
# correlated / anti-correlated formulas; ``--variant simple`` maps the
# common CLI names onto them.
SIMPLE_VARIANT = {
    "uniform": "uniform",
    "correlated": "simple_correlated",
    "anti_correlated": "simple_anti_correlated",
}


def generate(
    method: str,
    rng: np.random.Generator,
    n: int,
    dims: int,
    d_min: float,
    d_max: float,
):
    """Dispatch by distribution name (the GenMethod enum, unified_producer.py:31-42)."""
    try:
        fn = GENERATORS[method.lower().replace("-", "_")]
    except KeyError:
        raise ValueError(
            f"unknown distribution {method!r}; expected one of {sorted(GENERATORS)}"
        ) from None
    return fn(rng, n, dims, d_min, d_max)
