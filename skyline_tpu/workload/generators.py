"""Vectorized synthetic stream generators — the three reference distributions.

Formula parity with python/unified_producer.py:50-123, vectorized over whole
batches with numpy instead of per-tuple faker calls:

- ``uniform``: independent integers in [d_min, d_max] (:50-51)
- ``correlated``: per-point base in [d_min, d_max] plus per-dimension noise in
  ±(1-rho)(d_max-d_min), truncated to int and clamped (:58-73); points hug the
  diagonal, easiest to prune
- ``anti_correlated``: a random positive direction vector scaled so its sum
  lands in a band around the hypercube-center sum, with the reference's
  dimension-dependent band thickness heuristic (2D: 0.0005, 3D: 0.05,
  4D: 0.9, else d*0.5 — :92-102), truncated and clamped; points hug the
  anti-diagonal, the documented worst case (pdf §5.6)

All generators return int-valued float32 arrays (the reference streams
integers as CSV; values stay exactly representable).
"""

from __future__ import annotations

import numpy as np

# Trigger injection interval (unified_producer.py:25)
QUERY_THRESHOLD = 1_000_000


def _epsilon(dimensions: int) -> float:
    # unified_producer.py:92-102
    if dimensions == 2:
        return 0.0005
    if dimensions == 3:
        return 0.05
    if dimensions == 4:
        return 0.9
    return dimensions * 0.005 * 100


def uniform(rng: np.random.Generator, n: int, dims: int, d_min: float, d_max: float):
    vals = rng.integers(int(d_min), int(d_max) + 1, size=(n, dims))
    return vals.astype(np.float32)


def correlated(
    rng: np.random.Generator,
    n: int,
    dims: int,
    d_min: float,
    d_max: float,
    rho: float = 0.9,
):
    base = rng.uniform(d_min, d_max, size=(n, 1))
    spread = (1.0 - rho) * (d_max - d_min)
    noise = rng.uniform(-spread, spread, size=(n, dims))
    vals = np.trunc(base + noise)  # int(val) truncates toward zero for v >= 0
    return np.clip(vals, d_min, d_max).astype(np.float32)


def anti_correlated(
    rng: np.random.Generator, n: int, dims: int, d_min: float, d_max: float
):
    eps = _epsilon(dims)
    vals = rng.random(size=(n, dims))
    total = vals.sum(axis=1, keepdims=True)
    total = np.where(total == 0, 1.0, total)
    mean = (d_min + d_max) / 2.0 * dims
    slack = eps * (d_max - d_min) * dims
    target = rng.uniform(mean - slack, mean + slack, size=(n, 1))
    scaled = vals * (target / total)
    return np.clip(np.trunc(scaled), d_min, d_max).astype(np.float32)


GENERATORS = {
    "uniform": uniform,
    "correlated": correlated,
    "anti_correlated": anti_correlated,
}


def generate(
    method: str,
    rng: np.random.Generator,
    n: int,
    dims: int,
    d_min: float,
    d_max: float,
):
    """Dispatch by distribution name (the GenMethod enum, unified_producer.py:31-42)."""
    try:
        fn = GENERATORS[method.lower().replace("-", "_")]
    except KeyError:
        raise ValueError(
            f"unknown distribution {method!r}; expected one of {sorted(GENERATORS)}"
        ) from None
    return fn(rng, n, dims, d_min, d_max)
