"""Manual query-trigger CLI.

Parity with python/query_trigger.py: sends a single trigger to the query
topic. The reference's payload is the bare algo id (1=mr-dim, 2=mr-grid,
3=mr-angle, :58-62) — a count-less payload, which parses to required=0 and
executes immediately (:21-26, 78-82). ``--required`` optionally adds a real
record-id barrier.
"""

from __future__ import annotations

import argparse
import sys

from skyline_tpu.bridge.wire import format_trigger


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("query_id", nargs="?", default="1")
    ap.add_argument("--required", type=int, default=None,
                    help="record-id barrier; omitted = immediate execution")
    ap.add_argument("--topic", default="queries")
    ap.add_argument("--sink", choices=["kafka", "stdout"], default="kafka")
    ap.add_argument("--bootstrap", default="localhost:9092")
    args = ap.parse_args(argv)

    payload = (
        args.query_id
        if args.required is None
        else format_trigger(args.query_id, args.required)
    )
    if args.sink == "stdout":
        sys.stdout.write(f"{args.topic}\t{payload}\n")
    else:
        from skyline_tpu.bridge.kafka import KafkaBus

        KafkaBus(args.bootstrap).produce_many(args.topic, [payload])
    print(f"sent trigger {payload!r} to {args.topic}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
