"""Synthetic workload generation (the reference's Python producer harness)."""

from skyline_tpu.workload.generators import (
    QUERY_THRESHOLD,
    anti_correlated,
    correlated,
    generate,
    uniform,
)

__all__ = [
    "QUERY_THRESHOLD",
    "uniform",
    "correlated",
    "anti_correlated",
    "generate",
]
