"""Unified producer CLI — data stream + periodic query triggers.

Parity with python/unified_producer.py: positional args
``<topic> <distribution> <dims> <d_min> <d_max> [query-topic]``
(:137-142), CSV tuple lines ``"id,v1,...,vd"`` (:174), a trigger
``"queryId,recordId"`` every QUERY_THRESHOLD records (:180-188), and a
progress print every 100k (:191-192). Differences: batched generation
(vectorized numpy instead of per-tuple faker), an optional ``--count`` bound
instead of only an infinite loop, and ``--sink stdout`` for broker-less runs
(kafka-python is optional in this environment).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from skyline_tpu.bridge.wire import format_trigger
from skyline_tpu.native import format_tuples_native
from skyline_tpu.workload.generators import (
    QUERY_THRESHOLD,
    SIMPLE_VARIANT,
    generate,
)


def _build_sink(args):
    """Returns (send(topic, lines), send_blob(topic, blob, offsets) | None)."""
    if args.sink == "stdout":
        def send(topic, lines):
            out = sys.stdout
            for ln in lines:
                if isinstance(ln, bytes):
                    ln = ln.decode("utf-8")
                out.write(f"{topic}\t{ln}\n")
        return send, None
    from skyline_tpu.bridge.kafka import KafkaBus

    bus = KafkaBus(args.bootstrap)

    def send(topic, lines):
        bus.produce_many(topic, lines)

    return send, bus.produce_blob


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("topic", nargs="?", default="input-tuples")
    ap.add_argument("distribution", nargs="?", default="uniform")
    ap.add_argument("dims", nargs="?", type=int, default=2)
    ap.add_argument("d_min", nargs="?", type=float, default=0.0)
    ap.add_argument("d_max", nargs="?", type=float, default=1000.0)
    ap.add_argument("query_topic", nargs="?", default="queries")
    ap.add_argument("--count", type=int, default=0, help="stop after N records (0 = infinite)")
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--query-threshold", type=int, default=QUERY_THRESHOLD,
                    help="records per injected trigger; <= 0 disables triggers "
                         "(the reference's data-only kafka_producer.py variant)")
    ap.add_argument("--variant", choices=["unified", "simple"], default="unified",
                    help="generator math: 'unified' = unified_producer.py:50-123; "
                         "'simple' = kafka_producer.py:58-88's distinct "
                         "correlated/anti-correlated formulas (P2 parity)")
    ap.add_argument("--sink", choices=["kafka", "stdout"], default="kafka")
    ap.add_argument("--bootstrap", default="localhost:9092")
    ap.add_argument("--start-id", type=int, default=0,
                    help="first record id — resume a stream where a previous "
                         "producer stopped (the reference always restarts at "
                         "0, unified_producer.py:160, breaking barrier "
                         "monotonicity on resume)")
    ap.add_argument("--start-query-id", type=int, default=0)
    ap.add_argument("--final-trigger", action="store_true",
                    help="after a finite stream (--count > 0), send one "
                         "IMMEDIATE trigger (P3 parity: count-less payload "
                         "-> required=0, query_trigger.py:21-26). The "
                         "id-barrier form ('qid,N') can defer forever on a "
                         "finite stream when a sparse partition's few "
                         "records all predate N (the reference's heuristic "
                         "barrier, SURVEY.md §3.3 — its own producer is an "
                         "infinite loop, so it never faces stream end)")
    args = ap.parse_args(argv)

    send, send_blob = _build_sink(args)
    distribution = args.distribution
    if args.variant == "simple":
        key = distribution.lower().replace("-", "_")
        distribution = SIMPLE_VARIANT.get(key, distribution)
    rng = np.random.default_rng(args.seed)
    record_id = args.start_id
    query_id = args.start_query_id
    qt = args.query_threshold
    # next trigger fires at the next threshold multiple past start-id, so a
    # resumed stream keeps the reference's every-QUERY_THRESHOLD cadence
    next_trigger = (record_id // qt + 1) * qt if qt > 0 else 0
    next_progress = (record_id // 100_000 + 1) * 100_000

    end_id = args.start_id + args.count
    while args.count == 0 or record_id < end_id:
        n = args.batch if args.count == 0 else min(args.batch, end_id - record_id)
        vals = generate(distribution, rng, n, args.dims, args.d_min, args.d_max)
        ids = np.arange(record_id, record_id + n, dtype=np.int64)
        # integer-valued floats print without trailing .0 via int cast; the
        # C++ formatter (native/fastcsv.cpp sky_format_tuples) emits the
        # whole batch into one buffer — formatting was the producer's
        # dominant cost (np.char chain: ~69 s/1M x 8D on the dev box vs
        # ~0.1 s native)
        iv = vals.astype(np.int64)
        fmt = format_tuples_native(ids, iv)
        if fmt is not None and send_blob is not None:
            # zero-copy plane: blob + offsets go straight into RecordBatch
            # assembly (kafkalite send_blob) — no per-record bytes objects
            send_blob(args.topic, *fmt)
        elif fmt is not None:
            blob, offs = fmt
            ot = offs.tolist()
            send(args.topic, [blob[ot[i] : ot[i + 1]] for i in range(n)])
        else:
            send(
                args.topic,
                [
                    ",".join(map(str, (i, *row)))
                    for i, row in zip(ids.tolist(), iv.tolist())
                ],
            )
        record_id += n
        while args.query_threshold > 0 and record_id >= next_trigger:
            # barrier = the threshold-crossing id, NOT the batch-end id: the
            # reference fires per-record at the threshold
            # (unified_producer.py:180-188); stamping the batch tail would
            # set a barrier no partition can clear until ids pass it
            send(args.query_topic, [format_trigger(query_id, next_trigger - 1)])
            query_id += 1
            next_trigger += args.query_threshold
        if record_id >= next_progress:
            print(f"produced {record_id} records", file=sys.stderr)
            next_progress += 100_000
    if args.final_trigger and args.count > 0:
        # data is acked before this produce, so the worker's trigger-pending
        # drain ingests the whole stream before the immediate query runs
        send(args.query_topic, [str(query_id)])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
