"""Count-based sliding-window continuous skyline.

BASELINE.json config #4 ("sliding-window continuous skyline, count-based,
high window overlap"). The reference has no eviction at all — its skyline is
over the whole unbounded stream — so this is a capability extension built on
the same kernels.

Skyline under deletion is handled with the standard bucket decomposition: a
window of W tuples sliding by S is K = W/S buckets; each bucket keeps the
skyline of ITS OWN tuples (computed once, when the bucket closes), and the
window skyline is the skyline of the union of the K bucket skylines — exact
by the merge law (SURVEY.md §4). Eviction is then O(1): drop the oldest
bucket, no re-examination of "resurrected" points is ever needed because
bucket skylines never pruned across buckets.

Per-slide cost: one bucket skyline (S points) + one union merge
(sum of K bucket skyline sizes), both on-device.
"""

from __future__ import annotations

import time
from collections import deque


import numpy as np

from skyline_tpu.ops.dispatch import skyline_of_np as _device_skyline


class SlidingSkyline:
    """Continuous skyline over the last ``window_size`` tuples, emitting one
    result every ``slide`` tuples. ``window_size % slide == 0``."""

    def __init__(self, window_size: int, slide: int, dims: int):
        if window_size % slide != 0:
            raise ValueError(
                f"window_size {window_size} must be a multiple of slide {slide}"
            )
        self.window_size = window_size
        self.slide = slide
        self.dims = dims
        self.k = window_size // slide
        self._buckets: deque[np.ndarray] = deque()  # per-bucket skylines
        self._pending: list[np.ndarray] = []
        self._pending_rows = 0
        self._tuples_seen = 0
        self.device_ns = 0

    def push(self, values: np.ndarray) -> list[dict]:
        """Feed a micro-batch; returns one result dict per completed slide:
        ``{"window_end": id, "skyline": (k, d) array, "window_filled": bool}``
        (window_filled is False while fewer than window_size tuples exist —
        the result then covers the partial window, like any warmup period)."""
        out = []
        n = values.shape[0]
        pos = 0
        while pos < n:
            take = min(self.slide - self._pending_rows, n - pos)
            # copy: pending rows outlive this call and the caller may reuse
            # its batch buffer
            self._pending.append(np.array(values[pos : pos + take]))
            self._pending_rows += take
            pos += take
            self._tuples_seen += take
            if self._pending_rows == self.slide:
                out.append(self._close_bucket())
        return out

    def _close_bucket(self) -> dict:
        t0 = time.perf_counter_ns()
        rows = (
            self._pending[0]
            if len(self._pending) == 1
            else np.concatenate(self._pending, axis=0)
        )
        self._pending = []
        self._pending_rows = 0
        self._buckets.append(_device_skyline(rows, self.dims))
        if len(self._buckets) > self.k:
            self._buckets.popleft()  # O(1) eviction of the oldest bucket
        union = np.concatenate(list(self._buckets), axis=0)
        sky = _device_skyline(union, self.dims)
        self.device_ns += time.perf_counter_ns() - t0
        return {
            "window_end": self._tuples_seen - 1,
            "skyline": sky,
            "window_filled": len(self._buckets) == self.k,
        }

    @property
    def current_skyline(self) -> np.ndarray:
        """Skyline over the current (possibly partial) window, including
        pending rows not yet forming a full slide."""
        parts = list(self._buckets)
        if self._pending_rows:
            parts.append(np.concatenate(self._pending, axis=0))
        if not parts:
            return np.empty((0, self.dims), dtype=np.float32)
        return _device_skyline(np.concatenate(parts, axis=0), self.dims)
