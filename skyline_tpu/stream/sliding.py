"""Count-based sliding-window continuous skyline, device-resident.

BASELINE.json config #4 ("sliding-window continuous skyline, count-based,
high window overlap"). The reference has no eviction at all — its skyline is
over the whole unbounded stream — so this is a capability extension built on
the same kernels.

Skyline under deletion is handled with the standard bucket decomposition: a
window of W tuples sliding by S is K = W/S buckets; each bucket keeps the
skyline of ITS OWN tuples (computed once, when the bucket closes), and the
window skyline is the skyline of the union of the K bucket skylines — exact
by the merge law (SURVEY.md §4). Eviction is then O(1): overwrite the oldest
ring slot, no re-examination of "resurrected" points is ever needed because
bucket skylines never pruned across buckets.

TPU shape: the K bucket skylines live on device as a ``(K, S_cap, d)`` ring
(S_cap = the slide's power-of-two bucket — a bucket skyline can never exceed
its bucket's row count, so the ring never grows). Each completed slide is ONE
jitted launch: bucket-skyline the new rows, write the ring slot, window-
skyline the masked union, and compact survivors — only the survivor rows and
a count cross back to the host.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from skyline_tpu.ops.block_skyline import skyline_mask_scan
from skyline_tpu.ops.dominance import compact
from skyline_tpu.ops.dispatch import skyline_of_np
from skyline_tpu.utils.buckets import next_pow2


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _slide_step(ring, ring_valid, slot, rows, rows_valid):
    """One slide: close the new bucket into ``slot`` and window-merge.

    ring (K, C, d), ring_valid (K, C), slot scalar int, rows (C, d) padded,
    rows_valid (C,). Returns (ring, ring_valid, sky (K*C, d), sky_valid,
    sky_count) with the window skyline compacted to the front of ``sky``.
    """
    k, c, d = ring.shape
    bucket_keep = skyline_mask_scan(rows, rows_valid)
    bvals, bvalid, _ = compact(rows, bucket_keep, c)
    ring = ring.at[slot].set(bvals)
    ring_valid = ring_valid.at[slot].set(bvalid)
    flat = ring.reshape(k * c, d)
    fvalid = ring_valid.reshape(k * c)
    wkeep = skyline_mask_scan(flat, fvalid)
    sky, sky_valid, count = compact(flat, wkeep, k * c)
    return ring, ring_valid, sky, sky_valid, count


class SlidingSkyline:
    """Continuous skyline over the last ``window_size`` tuples, emitting one
    result every ``slide`` tuples. ``window_size % slide == 0``."""

    def __init__(self, window_size: int, slide: int, dims: int):
        if window_size % slide != 0:
            raise ValueError(
                f"window_size {window_size} must be a multiple of slide {slide}"
            )
        self.window_size = window_size
        self.slide = slide
        self.dims = dims
        self.k = window_size // slide
        self._cap = next_pow2(slide, min_cap=128)
        self._ring = jnp.full(
            (self.k, self._cap, dims), jnp.inf, dtype=jnp.float32
        )
        self._ring_valid = jnp.zeros((self.k, self._cap), dtype=bool)
        self._slot = 0
        self._buckets_closed = 0
        self._pending: list[np.ndarray] = []
        self._pending_rows = 0
        self._tuples_seen = 0
        self._last_sky: np.ndarray | None = None
        self.device_ns = 0

    def push(self, values: np.ndarray) -> list[dict]:
        """Feed a micro-batch; returns one result dict per completed slide:
        ``{"window_end": id, "skyline": (k, d) array, "window_filled": bool}``
        (window_filled is False while fewer than window_size tuples exist —
        the result then covers the partial window, like any warmup period)."""
        out = []
        n = values.shape[0]
        pos = 0
        while pos < n:
            take = min(self.slide - self._pending_rows, n - pos)
            # copy: pending rows outlive this call and the caller may reuse
            # its batch buffer
            self._pending.append(np.array(values[pos : pos + take]))
            self._pending_rows += take
            pos += take
            self._tuples_seen += take
            if self._pending_rows == self.slide:
                out.append(self._close_bucket())
        return out

    def _close_bucket(self) -> dict:
        t0 = time.perf_counter_ns()
        rows = (
            self._pending[0]
            if len(self._pending) == 1
            else np.concatenate(self._pending, axis=0)
        )
        self._pending = []
        self._pending_rows = 0
        padded = np.full((self._cap, self.dims), np.inf, dtype=np.float32)
        padded[: rows.shape[0]] = rows
        rvalid = np.arange(self._cap) < rows.shape[0]
        self._ring, self._ring_valid, sky, sky_valid, count = _slide_step(
            self._ring,
            self._ring_valid,
            jnp.asarray(self._slot),  # traced: one executable for all slots
            jnp.asarray(padded),
            jnp.asarray(rvalid),
        )
        self._slot = (self._slot + 1) % self.k
        self._buckets_closed += 1
        c = int(count)  # one sync; transfer only the survivors below
        result_sky = np.asarray(sky[:c])
        # private copy: the caller owns result_sky and may mutate it; the
        # cache must stay pristine for current_skyline reads
        self._last_sky = result_sky.copy()
        self.device_ns += time.perf_counter_ns() - t0
        return {
            "window_end": self._tuples_seen - 1,
            "skyline": result_sky,
            "window_filled": self._buckets_closed >= self.k,
        }

    @property
    def current_skyline(self) -> np.ndarray:
        """Skyline over the current (possibly partial) window, including
        pending rows not yet forming a full slide."""
        if not self._pending_rows and self._last_sky is not None:
            # nothing changed since the last slide closed: its compacted
            # window skyline is exactly current (no ring transfer needed);
            # copy so callers can't corrupt the cache (PartitionSet.snapshot
            # makes the same promise)
            return self._last_sky.copy()
        ring = np.asarray(self._ring)
        ring_valid = np.asarray(self._ring_valid)
        parts = [
            ring[s][ring_valid[s]]
            for s in range(min(self._buckets_closed, self.k))
        ]
        if self._pending_rows:
            parts.append(np.concatenate(self._pending, axis=0))
        if not parts:
            return np.empty((0, self.dims), dtype=np.float32)
        return skyline_of_np(np.concatenate(parts, axis=0), self.dims)
