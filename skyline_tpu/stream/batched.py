"""Batched partition-set state: all logical partitions' skylines in one
stacked device buffer, merged in one launch.

A per-partition state model dispatches 3 dominance kernels + a compact per
partition per flush — ~P*4 launches per micro-batch.
Through a dispatch-latency-bound link (the remote-TPU tunnel adds ~10s of ms
per launch) that overhead dominates the actual VPU work by an order of
magnitude. ``PartitionSet`` keeps the SAME semantics (per-partition
incremental skylines, barriers, timing — SkylineLocalProcessor's state model,
FlinkSkyline.java:214-445) but stores all P partitions as ``(P, cap, d)`` /
``(P, cap)`` stacked buffers and merges every partition's pending rows in ONE
vmapped kernel launch per flush.

Semantic deltas vs per-partition flushing, both documented here on purpose:

- flush granularity: a flush happens when the LARGEST partition's pending
  rows reach ``buffer_size`` (or on demand), and it flushes ALL partitions'
  pending rows at once. Results are identical — the incremental merge is
  order- and batching-invariant (the merge law, SURVEY.md §4) — only the
  points at which device work happens differ.
- per-partition CPU attribution: flush wall time is accounted to the set,
  and every partition reports the same ``processing_ms`` (the set total).
  The reference's per-query ``local_processing_time_ms`` is the MAX over
  partitions (FlinkSkyline.java:579-588), which under shared attribution is
  exactly the set total — the number the dashboard stacks local bars from.
"""

from __future__ import annotations

import time
from contextlib import nullcontext

import jax.numpy as jnp
import numpy as np

from skyline_tpu.metrics.tracing import NULL_TRACER
from skyline_tpu.resilience.faults import fault_point
from skyline_tpu.ops import cascade
from skyline_tpu.ops.dispatch import (
    flush_stage_depth,
    mixed_precision_enabled,
    on_tpu,
    profile_cost_enabled,
)
from skyline_tpu.stream.window import (
    DEFAULT_BUFFER_SIZE,
    GRID_BINS,
    _MIN_CAP,
    _active_bucket,
    _next_pow2,
    extract_cached_leaf,
    extract_sky_leaf,
    global_merge_delta_device,
    global_merge_stats_device,
    global_points_device,
    grid_summary_device,
    merge_step_active,
    meshed_merge_step,
    meshed_sfs_cleanup,
    meshed_sfs_round,
    partition_summaries_device,
    prune_witness_mask,
    sfs_cleanup,
    sfs_round,
    sfs_round_single,
    tree_pair_merge,
    tree_points_device,
    tree_stats_device,
)


from skyline_tpu.stream import device_window as dw

# Sequential-SFS probe block: rounds start at this size so a small-skyline
# partition never pays big-block dominance work; the loop escalates to the
# row-scaled block once a round's surviving count exceeds half a block
# (a probe round keeps at most B survivors, so half-a-block survival is
# strong evidence of a large skyline).
_PROBE_B = 8192

# Device-ingest chunks are split/padded to power-of-two buckets capped here,
# bounding the set of ingest executables.
_CHUNK_BUCKET_MAX = 65536

# Host chunk for the grid-prefilter cell coding: the (chunk, GRID_REPS, d)
# comparison broadcast stays ~10 MB at 8D instead of scaling with the
# whole pending window.
_PREFILTER_CHUNK = 16384


class _MergeHandle:
    """An in-flight global merge: every kernel launched, nothing synced.

    Produced by ``PartitionSet.global_merge_launch``; consumed (once) by
    ``PartitionSet.global_merge_harvest``. Between the two the caller is free
    to keep ingesting — the handle pins the launch-time epoch vector, so
    harvest-time bookkeeping knows whether the set moved underneath it.
    """

    __slots__ = (
        "key",
        "epoch",
        "emit_points",
        "use_cache",
        "cached",
        "result",
        "stats",
        "union",
        "keep",
        "root_vals",
        "dirty",
        "clean_total",
        "explain",
    )

    def __init__(self):
        self.cached = False
        self.result = None
        self.stats = None
        self.union = None
        self.keep = None
        self.root_vals = None
        self.dirty = None
        self.clean_total = 0
        # the EXPLAIN QueryPlan riding this merge (telemetry/explain.py);
        # annotated host-side at launch/tree/harvest, None when the plane
        # is off — the handle carries it so overlapped merges attribute to
        # the query that launched them, not whatever is current at harvest
        self.explain = None

    def ready(self) -> bool:
        """True once harvest would not block (best-effort: backends without
        ``is_ready`` report False, so callers fall back to a later blocking
        harvest rather than an early one)."""
        if self.cached:
            return True
        try:
            return bool(self.stats.is_ready())
        except AttributeError:
            return False


class PartitionSet:
    """Device-stacked state for ``num_partitions`` logical partitions.

    With a ``mesh``, the stacked partition axis is sharded across the mesh
    devices (``num_partitions`` divisible by mesh size — the reference's
    ``2×parallelism`` logical keys round-robined onto ``parallelism``
    workers, FlinkSkyline.java:74-76, with workers = chips). The batched
    merge has no cross-partition data flow, so each flush runs fully SPMD:
    one launch, every chip merging its resident partitions over ICI-free
    local compute. Without a mesh, the same code runs single-device.
    """

    def __init__(
        self,
        num_partitions: int,
        dims: int,
        buffer_size: int = DEFAULT_BUFFER_SIZE,
        mesh=None,
        initial_capacity: int = 0,
        tracer=None,
        flush_policy: str = "incremental",
        route: tuple[str, float] | None = None,
        overlap_rows: int = 262144,
        window_capacity: int = 0,
        counters=None,
    ):
        """``initial_capacity``: pre-size the per-partition skyline buffers
        (rounded up to the power-of-two bucket). Capacity normally grows on
        demand with one count sync per doubling; a workload that knows its
        steady-state skyline size (e.g. repeated same-shape windows) can
        pre-size to skip every growth step and its sync.

        ``flush_policy``:

        - ``"incremental"`` (default): merge pending rows into the running
          skylines whenever the largest partition's pending buffer reaches
          ``buffer_size`` — the reference's processBuffer cadence
          (FlinkSkyline.java:232). Work is spread across ingest; memory for
          pending rows is bounded by the threshold.
        - ``"lazy"``: accumulate pending rows (host RAM ~ window size) and
          compute at query time via sum-sorted append-only SFS rounds — no
          buffer re-pruning, no full-buffer compaction. For
          tumbling-window-then-query streams this does a fraction of the
          incremental policy's dominance work (see stream/window.py SFS
          notes). Results are identical (the merge law). Under a ``mesh``
          the rounds run SPMD via ``shard_map`` over the partition axis
          (one launch, each chip appending to its resident partitions; the
          skew-sequential path and the device-side global merge are
          single-device specializations, so the meshed flush always uses
          the vmapped rounds and the engine's host-side global merge).
        - ``"overlap"``: the lazy machinery with automatic chunked flushes
          every ``overlap_rows`` accumulated rows, so the append rounds of
          an earlier chunk run on device WHILE the host parses / uploads
          the next one (JAX async dispatch). A mid-window flush on
          non-empty state pays the old-vs-new SFS cleanup pass per chunk —
          a fraction of the append work — in exchange for hiding device
          time behind the transport-bound ingest (the concurrent
          source/operator dataflow Flink gets by construction,
          FlinkSkyline.java:84-104). Results identical (merge law).

        ``route``: ``(algo, domain_max)`` enables DEVICE ingest for the
        lazy/overlap policies: raw chunks are uploaded as they arrive and
        partition routing, the flush-time (pid, sum) sort, and SFS block
        slicing all run on device (see stream/device_window.py). ``None``
        keeps the host routing path (the engine routes and calls
        ``add_batch``). Single-device only.

        ``counters``: optional ``metrics.collector.Counters``-like sink
        (``inc(name, n)``) mirroring the merge-cache counters into the
        telemetry plane (``merge.cache_hit`` / ``merge.cache_miss`` /
        ``merge.delta_merge`` / ``merge.delta_rows`` → Prometheus
        ``skyline_merge_*_total`` on GET /metrics).
        """
        self.num_partitions = num_partitions
        self.dims = dims
        self.buffer_size = buffer_size
        self.initial_capacity = initial_capacity
        self.overlap_rows = overlap_rows
        self.tracer = tracer if tracer is not None else NULL_TRACER
        if flush_policy not in ("incremental", "lazy", "overlap"):
            raise ValueError(f"unknown flush_policy {flush_policy!r}")
        if route is not None and (
            mesh is not None or flush_policy == "incremental"
        ):
            raise ValueError(
                "device ingest (route=...) requires a single-device "
                "lazy/overlap PartitionSet"
            )
        self.flush_policy = flush_policy
        self._route = route
        self.window_capacity = window_capacity
        # device-ingest accumulation state (route is not None):
        self._dev_window = None  # (dev_cap, d) +inf-padded row buffer
        self._dev_pids = None  # (dev_cap,) int32, sentinel num_partitions
        self._dev_cap = 0
        self._dev_rows = 0  # valid rows currently accumulated
        # per-chunk (stats_dev (2, P), now_ms) awaiting a host bookkeeping
        # sync (lazy: only a query barrier or a flush needs them)
        self._chunk_stats: list[tuple] = []
        self.mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            # shard over the mesh's FIRST axis (multi-axis meshes keep the
            # remaining axes replicated), so divisibility is against that
            # axis's extent, not the total device count
            axis = mesh.axis_names[0]
            n_axis = int(mesh.shape[axis])
            if num_partitions % n_axis:
                raise ValueError(
                    f"num_partitions {num_partitions} must be divisible by "
                    f"mesh axis {axis!r} size {n_axis}"
                )
            self._sharding = NamedSharding(mesh, PartitionSpec(axis))
        else:
            self._sharding = None
        p = num_partitions
        # pending micro-batch rows awaiting a flush, per partition
        self._pending: list[list[np.ndarray]] = [[] for _ in range(p)]
        self._pending_rows = np.zeros(p, dtype=np.int64)
        # stacked running skylines: (P, cap, d) values + (P, cap) validity
        self._cap = _next_pow2(max(initial_capacity, _MIN_CAP))
        self.sky = self._put(
            np.full((p, self._cap, dims), np.inf, dtype=np.float32)
        )
        self.sky_valid = self._put(np.zeros((p, self._cap), dtype=bool))
        # survivor counts: device vector (exact, read lazily) + host upper
        # bounds (drive capacity growth WITHOUT per-flush syncs)
        self._count_dev = self._put(np.zeros((p,), dtype=np.int32))
        self._count_ub = np.zeros(p, dtype=np.int64)
        # barrier + metrics bookkeeping (FlinkSkyline.java:243-248, 267)
        self.max_seen_id = np.full(p, -1, dtype=np.int64)
        self.start_time_ms: list[float | None] = [None] * p
        self.records_seen = np.zeros(p, dtype=np.int64)
        self.processing_ns: int = 0  # set-wide (see module docstring)
        # host-side caches of device state, invalidated by flush/restore:
        # repeated per-partition snapshots (e.g. a trigger answering all P
        # partitions) then cost ONE count sync + ONE buffer transfer total
        self._counts_cache: np.ndarray | None = None
        self._host_cache: np.ndarray | None = None
        # flushed-state versioning: a monotone per-partition epoch, bumped
        # by every flush path that merges rows into that partition (and by
        # restore). The epoch vector is the identity of the device state —
        # the global-merge cache keys on it, and the serving plane dedupes
        # snapshot publishes against it (epoch_key).
        self._epoch = np.zeros(p, dtype=np.int64)
        # epoch-keyed global-merge result cache (see global_merge_stats):
        # {key, epoch, counts, surv, g, pts_dev, pts_host}
        self._gm_cache: dict | None = None
        self._counters = counters
        # kernel profiler + decision flight recorder (telemetry/profiler.py),
        # attached by the engine when observability is enabled; None keeps
        # every dispatch site on the bare tracer-phase path
        self._profiler = None
        self._flight = None
        # one-shot EXPLAIN plan sink: the engine parks the current query's
        # QueryPlan here before launching its merge; global_merge_launch
        # claims it onto the handle (and clears it) so annotation follows
        # the merge, not the PartitionSet
        self._explain = None
        # flush-path chooser profiler: whole-flush wall per variant
        # (flush_sorted_sfs vs flush_sfs_sequential/vmapped) under the
        # (d, N, backend) signature — kept SEPARATE from self._profiler,
        # whose per-round records these flush-level aggregates would
        # double-count. Lazily created: the TPU/mesh paths never pay it.
        self._flush_prof = None
        self.merge_cache_hits = 0
        self.merge_cache_misses = 0
        self.merge_delta_merges = 0
        self.merge_delta_rows = 0
        self.last_dirty_fraction: float | None = None
        # per-partition prune summaries for the tournament-tree merge:
        # (P, 2d+2) device array [min_corner | witness | min_sum | max_sum],
        # launched async at flush tails and stamped with the epoch vector it
        # describes (a stale stamp means a tiny re-launch at merge time)
        self._summary_dev = None
        self._summary_epoch: np.ndarray | None = None
        self.merge_tree_merges = 0
        self.merge_partitions_pruned = 0
        self.last_tree_info: dict | None = None
        # witness_of vector from the last prune pass (window.py
        # prune_witness_mask) — the per-partition prune REASONS the
        # EXPLAIN plane folds into a QueryPlan's tree block
        self.last_prune_witness: np.ndarray | None = None
        # quantized-grid flush prefilter (ISSUE 5 stage 1): the device
        # handle pair (bounds, rep cell codes) launched async at flush
        # tails; the validated host copy is harvested lazily at the next
        # flush. Stale summaries are sound (a removed skyline row always
        # leaves a transitive strict dominator behind — _prefilter_rows),
        # but restore replaces the world and must invalidate.
        self._grid_dev = None
        self._grid_host = None
        self._grid_epoch: np.ndarray | None = None
        self.prefilter_dropped = 0
        self.prefilter_seen = 0
        # mixed-precision stage 2: running device scalar of bf16-resolved
        # pair counts (one tiny add per flush round, synced only on the
        # stats path) + the high-water mark already fed to the telemetry
        # counters (flush_cascade_stats delta-feeds them)
        self._mp_resolved_dev = None
        self._bf16_resolved_reported = 0
        self.bf16_resolved = 0
        # a deferred (async-started) count-bound tighten from the last lazy
        # flush, consumed by the next sky_counts()/global merge
        self._tighten_pending = False

    def _put(self, arr: np.ndarray):
        """Place a (P, ...) array on device, partition-sharded if meshed."""
        if self._sharding is not None:
            import jax

            return jax.device_put(arr, self._sharding)
        return jnp.asarray(arr)

    # -- state versioning --------------------------------------------------

    @property
    def epoch(self) -> np.ndarray:
        """Per-partition flush epochs (monotone; read-only view)."""
        return self._epoch

    @property
    def epoch_key(self) -> bytes:
        """Opaque identity of the flushed device state: equal keys mean no
        flush touched any partition in between. The merge cache keys on it
        and the serving plane uses it as the snapshot-dedupe source key."""
        return self._epoch.tobytes()

    def _bump_epoch(self, which) -> None:
        """Advance the epoch of every partition in ``which`` (index list or
        boolean mask) — called by each flush path for the partitions whose
        merged state is about to change."""
        self._epoch[which] += 1

    def _inc(self, name: str, n: int = 1) -> None:
        if self._counters is not None:
            self._counters.inc(name, n)

    # -- observability hooks ------------------------------------------------

    def attach_observability(self, profiler=None, flight=None) -> None:
        """Attach a ``telemetry.profiler.KernelProfiler`` and/or
        ``FlightRecorder``. The profiler sub-attributes every
        ``flush/merge_kernel`` tracer phase to its dispatch signature
        (variant, d, N-bucket, backend, mp — see stream/window.py
        ``KERNEL_VARIANTS``); the flight recorder keeps the last N
        dispatch/cascade/prune/cache decisions. Both are host-side wrappers
        around already-timed regions — skyline bytes are unchanged."""
        self._profiler = profiler
        self._flight = flight
        if profiler is not None:
            # share with the dispatch-level chooser so host-path mask
            # dispatches (sorted_sfs_mask vs mask_scan) land in /profile
            # and the EXPLAIN kernel deltas too
            from skyline_tpu.ops.dispatch import register_profiler

            register_profiler(profiler)

    def set_explain(self, plan) -> None:
        """Park the current query's ``QueryPlan`` for the next
        ``global_merge_launch`` to claim (None clears). Host-side
        annotation only — nothing the plan records enters a kernel."""
        self._explain = plan

    def _kernel(self, variant: str, n: int, mp: bool = False, cost_thunk=None):
        """Profiling context for one merge-kernel dispatch (nullcontext
        when no profiler is attached)."""
        if self._profiler is None:
            return nullcontext()
        return self._profiler.record(
            variant, self.dims, n, mp=mp, cost_thunk=cost_thunk
        )

    def _merge_cost_thunk(self, batch_dev, bvalid_dev, active, out_active, mp):
        """AOT ``cost_analysis()`` thunk for the incremental merge step's
        current dispatch signature (``SKYLINE_PROFILE_COST``). Shapes are
        captured eagerly — the live buffers are donated by the dispatch —
        and the lower+compile runs only once per signature, inside the
        profiler's first-call path."""
        import jax

        shapes = tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype)
            for a in (self.sky, self.sky_valid, batch_dev, bvalid_dev)
        )

        def thunk():
            return (
                merge_step_active.lower(*shapes, active, out_active, mp)
                .compile()
                .cost_analysis()
            )

        return thunk

    def _fnote(self, kind: str, **fields) -> None:
        if self._flight is not None:
            self._flight.note(kind, **fields)

    # -- ingest -----------------------------------------------------------

    def add_batch(
        self, p: int, values: np.ndarray, max_id: int, now_ms: float
    ) -> None:
        """Buffer a routed micro-batch for partition ``p``; the caller
        decides when to ``flush_all`` (usually via ``maybe_flush``)."""
        n = values.shape[0]
        if n == 0:
            return
        if self.start_time_ms[p] is None:
            self.start_time_ms[p] = now_ms
        self.max_seen_id[p] = max(self.max_seen_id[p], int(max_id))
        self.records_seen[p] += n
        self._pending[p].append(values)
        self._pending_rows[p] += n

    @property
    def device_ingest(self) -> bool:
        return self._route is not None

    @property
    def has_unsynced_ingest(self) -> bool:
        return bool(self._chunk_stats)

    @property
    def pending_rows_total(self) -> int:
        """Un-flushed rows across both ingest paths (host pending lists +
        the device accumulation window)."""
        return int(self._pending_rows.sum()) + self._dev_rows

    def ingest_chunk(self, ids, values, now_ms: float) -> None:
        """Device-ingest twin of route-then-``add_batch``: upload one raw
        micro-batch, compute its partition ids and per-partition barrier
        stats on device (stream/device_window.py), and append it to the
        accumulated window. Host-side barrier/metrics bookkeeping is synced
        lazily (``sync_ingest_bookkeeping``) — the hot no-pending-queries
        path never waits on the device."""
        n = values.shape[0]
        if n == 0:
            return
        algo, domain_max = self._route
        if int(ids.max()) >= 2**31:
            raise ValueError(
                "device ingest tracks record ids as int32; ids >= 2^31 "
                "need the host ingest path"
            )
        for s in range(0, n, _CHUNK_BUCKET_MAX):
            chunk = np.asarray(values[s : s + _CHUNK_BUCKET_MAX], np.float32)
            cids = ids[s : s + _CHUNK_BUCKET_MAX]
            m = chunk.shape[0]
            bucket = _next_pow2(m)
            vp = np.full((bucket, self.dims), np.inf, dtype=np.float32)
            vp[:m] = chunk
            ip = np.full((bucket,), -1, dtype=np.int32)
            ip[:m] = cids
            self._ensure_dev_capacity(self._dev_rows + bucket)
            self._dev_window, self._dev_pids, stats = dw.ingest_chunk(
                self._dev_window,
                self._dev_pids,
                jnp.asarray(vp),
                jnp.asarray(ip),
                m,
                self._dev_rows,
                algo=algo,
                num_partitions=self.num_partitions,
                domain_max=domain_max,
            )
            self._dev_rows += m
            self._chunk_stats.append((stats, now_ms))

    def _ensure_dev_capacity(self, need: int) -> None:
        """Allocate or double the device accumulation buffers. The write
        offset is row-granular while ``need`` includes the incoming chunk's
        padded bucket, so the dynamic_update_slice never clamps."""
        if self._dev_window is None:
            # window_capacity hint: pre-size so a full expected window
            # (plus the final chunk's padded bucket) never reallocates
            hint = (
                _next_pow2(self.window_capacity + _CHUNK_BUCKET_MAX)
                if self.window_capacity
                else 0
            )
            cap = max(_next_pow2(need), hint, 131072)
            self._dev_window = jnp.full(
                (cap, self.dims), jnp.inf, dtype=jnp.float32
            )
            self._dev_pids = jnp.full(
                (cap,), self.num_partitions, dtype=jnp.int32
            )
            self._dev_cap = cap
            return
        while self._dev_cap < need:
            new_cap = self._dev_cap * 2
            self._dev_window = jnp.concatenate(
                [
                    self._dev_window,
                    jnp.full(
                        (new_cap - self._dev_cap, self.dims),
                        jnp.inf,
                        dtype=jnp.float32,
                    ),
                ],
                axis=0,
            )
            self._dev_pids = jnp.concatenate(
                [
                    self._dev_pids,
                    jnp.full(
                        (new_cap - self._dev_cap,),
                        self.num_partitions,
                        dtype=jnp.int32,
                    ),
                ]
            )
            self._dev_cap = new_cap

    def sync_ingest_bookkeeping(self) -> None:
        """Fold queued per-chunk device stats into the host barrier/metrics
        state (max_seen_id, records_seen, start_time_ms). One small
        transfer per queued chunk; called before any barrier check or
        flush, never on the pure-ingest hot path."""
        if not self._chunk_stats:
            return
        with self.tracer.phase("ingest/bookkeeping_sync"):
            for stats_dev, now_ms in self._chunk_stats:
                s = np.asarray(stats_dev, dtype=np.int64)
                counts, maxids = s[0], s[1]
                got = counts > 0
                self.records_seen[got] += counts[got]
                np.maximum(
                    self.max_seen_id,
                    np.where(got, maxids, -1),
                    out=self.max_seen_id,
                )
                for p in np.nonzero(got)[0]:
                    if self.start_time_ms[p] is None:
                        self.start_time_ms[p] = now_ms
        self._chunk_stats = []

    def maybe_flush(self) -> bool:
        """Flush all partitions once the largest pending buffer reaches
        ``buffer_size`` (the processBuffer threshold, FlinkSkyline.java:232,
        applied set-wide). Returns True if a flush happened. Under the lazy
        policy this never fires — all work happens at query time. Under the
        overlap policy it fires whenever ``overlap_rows`` rows have
        accumulated across both ingest paths."""
        if self.flush_policy == "lazy":
            return False
        if self.flush_policy == "overlap":
            if self.pending_rows_total >= self.overlap_rows:
                self.flush_all(tighten=False)
                return True
            return False
        if int(self._pending_rows.max()) >= self.buffer_size:
            self.flush_all()
            return True
        return False


    def _drain_pending(self) -> list[np.ndarray]:
        """Move every partition's pending micro-batches out as one (m, d)
        array per partition (empty partitions get (0, d)), clearing the
        pending state. Shared by both flush policies."""
        rows = [
            (
                self._pending[p][0]
                if len(self._pending[p]) == 1
                else np.concatenate(self._pending[p], axis=0)
            )
            if self._pending[p]
            else np.empty((0, self.dims), dtype=np.float32)
            for p in range(self.num_partitions)
        ]
        self._pending = [[] for _ in range(self.num_partitions)]
        self._pending_rows[:] = 0
        return rows

    def _pad_block(self, part_rows: np.ndarray, B: int):
        """Pad one partition's (w, d) rows to a (B, d) +inf block +
        validity mask — the single padding convention both SFS paths and
        the batched assembly share."""
        w = part_rows.shape[0]
        block = np.full((B, self.dims), np.inf, dtype=np.float32)
        block[:w] = part_rows
        return block, np.arange(B) < w, w

    def _round_batch(self, rows: list[np.ndarray], rnd: int, B: int):
        """Assemble round ``rnd``'s (P, B, d) padded batch + validity +
        per-partition widths from the drained ``rows``."""
        batch = np.full(
            (self.num_partitions, B, self.dims), np.inf, dtype=np.float32
        )
        bvalid = np.zeros((self.num_partitions, B), dtype=bool)
        widths = np.zeros(self.num_partitions, dtype=np.int64)
        for p, r in enumerate(rows):
            part_rows = r[rnd * B : (rnd + 1) * B]
            w = part_rows.shape[0]
            if w:
                batch[p], bvalid[p], widths[p] = self._pad_block(part_rows, B)
        return batch, bvalid, widths

    def flush_all(self, tighten: bool = True) -> None:
        """Merge every partition's pending rows into its running skyline:
        one batched device launch per round (incremental policy), or
        append-only SFS rounds over the sum-sorted pending windows
        (lazy/overlap policies — host pending lists first, then the device
        accumulation window; a restored checkpoint can leave host pendings
        on a device-ingest set).

        ``tighten=False`` (overlap auto-flushes) runs the device flush
        SYNC-FREE: had-old detection and buckets come from the host upper
        bounds, the cleanup's exact old counts stay a device array, and the
        trailing bound-tightening sync is skipped — the host never blocks
        on the device mid-stream, which is the point of the overlap policy.
        Query-time flushes keep the default (exact buckets for the global
        merge)."""
        fault_point("flush.pre_merge")
        total = int(self._pending_rows.sum())
        if self.dims <= 2 and self.mesh is None:
            # d <= 2: the whole flush (host pendings + device window + old
            # skylines, every policy) collapses to one sort-and-sweep pass —
            # no SFS rounds, no pairwise work (ops/sweep2d.py)
            if total or self._dev_rows:
                self._flush_sweep()
            return
        if self.flush_policy in ("lazy", "overlap"):
            if total:
                self._flush_lazy()
            if self._dev_rows:
                self._flush_lazy_device(tighten)
            return
        if total == 0:
            return
        t0 = time.perf_counter_ns()
        mp = mixed_precision_enabled()
        self._bump_epoch(self._pending_rows > 0)
        with self.tracer.phase("flush/assemble"):
            rows = self._drain_pending()
        rows = self._prefilter_rows(rows)

        max_rows = max(r.shape[0] for r in rows)
        # one common power-of-two batch bucket B; partitions with more than B
        # pending rows (heavy skew) take extra rounds
        B = _next_pow2(min(max_rows, max(self.buffer_size, _MIN_CAP)))
        n_rounds = -(-max_rows // B)
        self._fnote(
            "flush.dispatch", policy="incremental", rows=total,
            rounds=n_rounds, block=B,
        )
        # staged pipeline: round r+1..r+depth are assembled and device_put
        # AFTER round r's merge kernel is dispatched (async), so host-side
        # assembly and the upload overlap the in-flight kernel — and a
        # growth sync at round r+1 waits behind an upload that's already
        # moving instead of serializing in front of it
        depth = flush_stage_depth()
        staged: dict[int, tuple] = {}

        def _stage(r: int):
            with self.tracer.phase("flush/assemble"):
                batch, bvalid, widths = self._round_batch(rows, r, B)
            with self.tracer.phase("flush/device_put"):
                return self._put(batch), self._put(bvalid), widths

        for rnd in range(n_rounds):
            if rnd not in staged:
                staged[rnd] = _stage(rnd)
            batch_dev, bvalid_dev, widths = staged.pop(rnd)

            def _grow_bucket():
                return _next_pow2(max(int((self._count_ub + widths).max()), 1))

            grow = _grow_bucket()
            if grow > self._cap:
                # about to grow: tighten the bounds with ONE real count sync
                # (growth events are log-bounded, so steady-state flushes
                # stay fully async)
                self._count_ub = np.asarray(self._count_dev, dtype=np.int64)
                grow = _grow_bucket()
            out_cap = max(self._cap, grow)
            variant = (
                "meshed_merge_step" if self.mesh is not None else "merge_step"
            )
            active = cost_thunk = None
            if self.mesh is None:
                # active-prefix merge: dominance passes + compact run
                # over the live-count bucket, not the storage capacity.
                active = min(
                    self._cap,
                    _active_bucket(max(int(self._count_ub.max()), 1)),
                )
                if self._profiler is not None and profile_cost_enabled():
                    cost_thunk = self._merge_cost_thunk(
                        batch_dev, bvalid_dev, active, grow, mp
                    )
            with self.tracer.phase("flush/merge_kernel"), self._kernel(
                variant, out_cap, mp, cost_thunk=cost_thunk
            ):
                if self.mesh is not None:
                    # explicit SPMD: pallas_call has no GSPMD partitioning
                    # rule, so the meshed flush must shard_map over the
                    # partition axis (each device merges only its resident
                    # partitions)
                    merge = meshed_merge_step(
                        self.mesh, self.mesh.axis_names[0], on_tpu(), out_cap,
                        mp,
                    )
                    self.sky, self.sky_valid, self._count_dev, res = merge(
                        self.sky, self.sky_valid, batch_dev, bvalid_dev
                    )
                else:
                    # out_active is the SAME bucket out_cap grew from, so
                    # merge_step_active's max(cap, out_active) == out_cap
                    # structurally.
                    self.sky, self.sky_valid, self._count_dev, res = (
                        merge_step_active(
                            self.sky,
                            self.sky_valid,
                            batch_dev,
                            bvalid_dev,
                            active,
                            grow,
                            mp,
                        )
                    )
                if mp:
                    self._accum_resolved(res)
                if self.tracer.sync_device:
                    # profiling mode: attribute the async kernel here instead
                    # of at whichever later phase forces the sync. A host
                    # read, not block_until_ready — the latter can return
                    # early on the axon remote-TPU platform.
                    np.asarray(self._count_dev)
            self._cap = out_cap
            self._count_ub = np.minimum(out_cap, self._count_ub + widths)
            for s in range(rnd + 1, min(rnd + 1 + depth, n_rounds)):
                if s not in staged:
                    staged[s] = _stage(s)
        self._counts_cache = None
        self._host_cache = None
        self._maybe_launch_summaries()
        self._maybe_launch_grid()
        self.processing_ns += time.perf_counter_ns() - t0

    def _sfs_vmapped(self, rows: list[np.ndarray], max_rows: int):
        """Balanced-load SFS: one vmapped launch per round for all
        partitions. Returns the device counts vector."""
        # bigger blocks than the incremental threshold pay off here: the
        # cross-prune work is block-count invariant, so fewer rounds just
        # save dispatches (at B^2/2 self-prune cost per round)
        B = _next_pow2(min(max_rows, max(self.buffer_size, 8192)))
        n_rounds = -(-max_rows // B)
        mp = mixed_precision_enabled()
        counts = self._count_dev
        # lag-2 tightening: the rows-streamed bound on _count_ub grows
        # linearly, but the true skyline may stay tiny (uniform/correlated
        # streams); reading the count vector from two rounds back — work
        # the device already drained while later rounds queued — keeps the
        # active bucket near the true size without stalling the pipeline
        prev: list[tuple] = []  # (counts_dev_after_round, widths_of_round)
        # same staged assemble/upload pipeline as the incremental rounds
        # (see flush_all): the next rounds' host work overlaps this round's
        # kernel, and a capacity-growth sync waits behind uploads that are
        # already in flight instead of serializing ahead of them
        depth = flush_stage_depth()
        staged: dict[int, tuple] = {}

        def _stage(r: int):
            with self.tracer.phase("flush/assemble"):
                batch, bvalid, widths = self._round_batch(rows, r, B)
            with self.tracer.phase("flush/device_put"):
                return self._put(batch), self._put(bvalid), widths

        for rnd in range(n_rounds):
            if rnd not in staged:
                staged[rnd] = _stage(rnd)
            batch_dev, bvalid_dev, widths = staged.pop(rnd)
            if len(prev) >= 2:
                c2, w1 = prev[-2][0], prev[-1][1]
                self._count_ub = np.minimum(
                    self._count_ub,
                    np.asarray(c2, dtype=np.int64) + w1,
                )
            # the SFS append writes a full B-row block at offset count, so
            # capacity must cover count + B for every partition
            need = int(self._count_ub.max()) + B
            if need > self._cap:
                self._count_ub = np.asarray(counts, dtype=np.int64)
                need = int(self._count_ub.max()) + B
                if need > self._cap:
                    self._grow_cap(_next_pow2(need))
            active = min(
                self._cap, _active_bucket(max(int(self._count_ub.max()), 1))
            )
            variant = (
                "meshed_sfs_round" if self.mesh is not None else "sfs_vmapped"
            )
            with self.tracer.phase("flush/merge_kernel"), self._kernel(
                variant, active, mp
            ):
                if self.mesh is not None:
                    rnd_fn = meshed_sfs_round(
                        self.mesh, self.mesh.axis_names[0], on_tpu(), active,
                        mp,
                    )
                    self.sky, counts, res = rnd_fn(
                        self.sky, counts, batch_dev, bvalid_dev
                    )
                else:
                    self.sky, counts, res = sfs_round(
                        self.sky, counts, batch_dev, bvalid_dev, active, mp
                    )
                if mp:
                    self._accum_resolved(res)
                if self.tracer.sync_device:
                    np.asarray(counts)
            prev.append((counts, widths))
            self._count_ub = np.minimum(self._cap, self._count_ub + widths)
            for s in range(rnd + 1, min(rnd + 1 + depth, n_rounds)):
                if s not in staged:
                    staged[s] = _stage(s)
        self._count_dev = counts
        return counts

    def _seq_block_size(self, rows_p: int) -> int:
        """The large-skyline sequential block: a ~500k-row heavy partition
        runs 8 rounds at B=64k instead of 30 at 16k (the self-prune cost
        grows only linearly in B, dispatch latency through the tunnel per
        round is the real price). Only used once the running count has
        PROVEN large — per-round work is B x bucket(S + B), so big blocks
        on a small-skyline stream multiply total work for nothing (uniform
        4D: S ~ 500 of 500k rows)."""
        return _next_pow2(
            min(
                max(rows_p, 1),
                max(self.buffer_size, 16384, min(rows_p // 8, 65536)),
            )
        )

    def _pad_sky_rows(self, s, new_cap: int):
        add = jnp.full(
            (new_cap - s.shape[0], self.dims), jnp.inf, dtype=jnp.float32
        )
        return jnp.concatenate([s, add], axis=0)

    def _restack_skies(self, new_skies: list, new_counts: list):
        """One stacked reassembly after a sequential pass (device-side; no
        host transfer), padded to the largest per-partition capacity
        reached."""
        final_cap = max(s.shape[0] for s in new_skies)
        new_skies = [
            s if s.shape[0] == final_cap else self._pad_sky_rows(s, final_cap)
            for s in new_skies
        ]
        self.sky = jnp.stack(new_skies)
        self._cap = final_cap
        counts = jnp.stack(new_counts).astype(jnp.int32)
        self._count_dev = counts
        return counts

    def _sfs_sequential(self, rows: list[np.ndarray]):
        """Skew-path SFS: heavy partitions processed one at a time with
        per-partition block and active buckets — total work tracks each
        partition's own rows instead of P x the heaviest. Returns the
        device counts vector."""
        # exact starting counts make the per-partition active buckets
        # tight; a fresh set (all upper bounds zero) provably has zero
        # counts, skipping the sync — through the remote-TPU tunnel each
        # host<->device round trip costs real wall time
        if not int(self._count_ub.max()):
            counts_host = np.zeros(self.num_partitions, dtype=np.int64)
        else:
            counts_host = self.sky_counts().astype(np.int64)
        mp = mixed_precision_enabled()
        row_counts = np.array([r.shape[0] for r in rows], dtype=np.int64)

        # capacity grows ON DEMAND as survivor counts actually grow (one
        # exact count sync per doubling, like the vmapped path) — the old
        # worst-case pre-grow (prior counts + ALL streamed rows) allocated
        # a 16M-row bucket for a 10M-row skewed stream, and executables at
        # that shape are what crashed the remote-compile helper on the QoS
        # config. Start with room for existing survivors + one big block.
        B_max = self._seq_block_size(int(row_counts.max()))
        need0 = int(counts_host.max()) + B_max
        if need0 > self._cap:
            self._grow_cap(_next_pow2(need0))

        new_skies = []
        new_counts = []
        for p in range(self.num_partitions):
            rp = rows[p]
            sky_p = self.sky[p]
            cap_p = sky_p.shape[0]
            cnt_p = self._count_dev[p]
            ub_p = int(counts_host[p])
            if rp.shape[0]:
                # start at the probe block; escalate to the big block only
                # once the running count proves the skyline is large (a
                # known-large prior skyline escalates immediately)
                B_big = self._seq_block_size(rp.shape[0])
                B = B_big if ub_p > _PROBE_B // 2 else min(_PROBE_B, B_big)
                # lag-2 tightening (see _sfs_vmapped): low-skyline heavy
                # partitions would otherwise pay active buckets that track
                # rows streamed instead of survivors
                prev: list[tuple] = []
                off = 0
                while off < rp.shape[0]:
                    if len(prev) >= 2:
                        c2, w1 = prev[-2][0], prev[-1][1]
                        ub_p = min(ub_p, int(c2) + w1)
                        # escalate once survival proves high: a probe
                        # round keeps <= B survivors, so compare against
                        # half a block (uniform keeps ~1% and never trips)
                        if B < B_big and int(c2) > B // 2:
                            B = B_big
                    if ub_p + B > cap_p:
                        # tighten with one exact count sync (a blocking
                        # read of the previous round), then grow with a
                        # full block of slack past the trip band — growing
                        # to exactly ub+B would leave cap in a band this
                        # check re-enters every round, paying a pipeline
                        # stall per round instead of one per doubling
                        ub_p = min(ub_p, int(cnt_p))
                        if ub_p + 2 * B > cap_p:
                            cap_p = _next_pow2(ub_p + 2 * B)
                            sky_p = self._pad_sky_rows(sky_p, cap_p)
                    with self.tracer.phase("flush/assemble"):
                        block, bvalid, w = self._pad_block(
                            rp[off : off + B], B
                        )
                    active = min(
                        cap_p, _active_bucket(max(ub_p, 1))
                    )
                    with self.tracer.phase("flush/device_put"):
                        block_dev = jnp.asarray(block)
                        bvalid_dev = jnp.asarray(bvalid)
                    with self.tracer.phase("flush/merge_kernel"), (
                        self._kernel("sfs_sequential", active, mp)
                    ):
                        sky_p, cnt_p, res = sfs_round_single(
                            sky_p, cnt_p, block_dev, bvalid_dev, active, mp
                        )
                        if mp:
                            self._accum_resolved(res)
                        if self.tracer.sync_device:
                            np.asarray(cnt_p)
                    prev.append((cnt_p, w))
                    ub_p = min(cap_p, ub_p + w)
                    off += w
            new_skies.append(sky_p)
            new_counts.append(cnt_p)
            self._count_ub[p] = ub_p
        return self._restack_skies(new_skies, new_counts)

    def _sfs_sequential_dev(
        self, ws, bounds: np.ndarray, rank=None, tighten=True
    ):
        """Device-window twin of ``_sfs_sequential``: blocks are sliced out
        of the sorted window ``ws`` at host-tracked offsets instead of
        assembled from host rows — same probe/escalation, lag-2 tightening,
        and on-demand capacity growth. ``rank``: (ws_ranks, sorted_dims)
        switches the rounds to the rank cascade. ``tighten=False`` seeds
        the per-partition bounds from the host upper bounds instead of a
        count sync (sync-free overlap flushes; bounds-only use). Returns
        the device counts vector."""
        # fresh set: counts are provably zero, skip the sync (see
        # _sfs_sequential)
        if not int(self._count_ub.max()):
            counts_host = np.zeros(self.num_partitions, dtype=np.int64)
        elif not tighten:
            counts_host = self._count_ub.copy()
        else:
            counts_host = self.sky_counts().astype(np.int64)
        mp = mixed_precision_enabled()
        widths = np.diff(bounds)
        # blocks sliced from the sorted window must fit its SORT_TAIL pad
        # (a dynamic_slice past the buffer clamps backward and desyncs the
        # block from its validity mask) — cap every device block there
        B_max = min(self._seq_block_size(int(widths.max())), dw.SORT_TAIL)
        need0 = int(counts_host.max()) + B_max
        if need0 > self._cap and not tighten:
            # growth pressure under loose bounds: tighten with one exact
            # sync ONLY now (the bounds otherwise ratchet up with rows
            # streamed and capacity would track the stream, not the
            # skyline — the same on-demand fallback _sfs_vmapped_dev uses)
            counts_host = self.sky_counts().astype(np.int64)
            need0 = int(counts_host.max()) + B_max
        if need0 > self._cap:
            self._grow_cap(_next_pow2(need0))

        new_skies = []
        new_counts = []
        for p in range(self.num_partitions):
            lo, hi = int(bounds[p]), int(bounds[p + 1])
            sky_p = self.sky[p]
            cap_p = sky_p.shape[0]
            cnt_p = self._count_dev[p]
            ub_p = int(counts_host[p])
            if hi > lo:
                B_big = min(self._seq_block_size(hi - lo), dw.SORT_TAIL)
                B = B_big if ub_p > _PROBE_B // 2 else min(_PROBE_B, B_big)
                prev: list[tuple] = []
                off = lo
                while off < hi:
                    if len(prev) >= 2:
                        c2, w1 = prev[-2][0], prev[-1][1]
                        ub_p = min(ub_p, int(c2) + w1)
                        if B < B_big and int(c2) > B // 2:
                            B = B_big
                    if ub_p + B > cap_p:
                        ub_p = min(ub_p, int(cnt_p))
                        if ub_p + 2 * B > cap_p:
                            cap_p = _next_pow2(ub_p + 2 * B)
                            sky_p = self._pad_sky_rows(sky_p, cap_p)
                    w = min(B, hi - off)
                    active = min(cap_p, _active_bucket(max(ub_p, 1)))
                    variant = (
                        "sfs_rank" if rank is not None else "sfs_sequential"
                    )
                    with self.tracer.phase("flush/merge_kernel"), (
                        self._kernel(variant, active, mp)
                    ):
                        if rank is not None:
                            sky_p, cnt_p = dw.sfs_round_at_rank(
                                sky_p, cnt_p, ws, rank[0], rank[1],
                                off, w, B=B, active=active,
                            )
                        else:
                            sky_p, cnt_p, res = dw.sfs_round_at(
                                sky_p, cnt_p, ws, off, w,
                                B=B, active=active, mp=mp,
                            )
                            if mp:
                                self._accum_resolved(res)
                        if self.tracer.sync_device:
                            np.asarray(cnt_p)
                    prev.append((cnt_p, w))
                    ub_p = min(cap_p, ub_p + w)
                    off += w
            new_skies.append(sky_p)
            new_counts.append(cnt_p)
            self._count_ub[p] = ub_p
        return self._restack_skies(new_skies, new_counts)

    def _grow_cap(self, new_cap: int) -> None:
        """Grow the stacked skyline storage to ``new_cap`` rows (padding
        with +inf, which both flush policies treat as invalid)."""
        pad = jnp.full(
            (self.num_partitions, new_cap - self._cap, self.dims),
            jnp.inf,
            dtype=jnp.float32,
        )
        self.sky = self._put(jnp.concatenate([self.sky, pad], axis=1))
        self._cap = new_cap

    # -- flush dominance cascade (grid prefilter + mixed precision) ---------

    def _accum_resolved(self, res) -> None:
        """Fold one round's bf16-resolved counts into the running device
        scalar — a tiny async add, synced only by ``flush_cascade_stats``
        (never on the flush hot path)."""
        s = jnp.sum(res, dtype=jnp.int32)
        self._mp_resolved_dev = (
            s if self._mp_resolved_dev is None else self._mp_resolved_dev + s
        )

    def _prefilter_on(self) -> bool:
        """Grid prefilter liveness for this set: single device, ``dims >
        2`` (the d <= 2 sweep flush has no merge kernels to save), gate
        resolved through the cascade table per flush."""
        return cascade.applies(
            "flush_prefilter", d=self.dims, meshed=self.mesh is not None
        )

    def _maybe_launch_grid(self) -> None:
        """Flush-tail hook (both host-row flush paths): start the grid
        summary compute for the state just flushed, async, so the NEXT
        flush's prefilter reads landed bytes instead of syncing cold."""
        if not self._prefilter_on():
            return
        active = min(
            self._cap, _active_bucket(max(int(self._count_ub.max()), 1))
        )
        self._grid_dev = grid_summary_device(
            self.sky, self._count_dev, active
        )
        for a in self._grid_dev:
            try:
                a.copy_to_host_async()
            except AttributeError:
                pass
        self._grid_host = None
        self._grid_epoch = self._epoch.copy()

    def _grid_summaries(self):
        """Validated host copy of the launched grid summary, or ``None``
        when no summary exists yet. Host-side validation disables (per
        partition x dim) any boundary ladder f32 rounding failed to keep
        strictly increasing — codes against a non-monotone ladder could
        certify false dominance; a disabled dim never certifies, which
        disables drops for its whole partition (the certificate needs
        every dim). Empty partitions produce NaN ladders and disable
        everything — zero drops, conservative."""
        if self._grid_dev is None:
            return None
        if self._grid_host is None:
            bounds = np.asarray(self._grid_dev[0])
            ux = np.asarray(self._grid_dev[1]).copy()
            with np.errstate(invalid="ignore"):  # NaN ladder = empty part.
                bad = ~np.all(np.diff(bounds, axis=1) > 0, axis=1)  # (P, d)
            if bad.any():
                ux[np.broadcast_to(bad[:, None, :], ux.shape)] = GRID_BINS + 1
            self._grid_host = (bounds, ux)
        return self._grid_host

    def _prefilter_rows(self, rows: list[np.ndarray]) -> list[np.ndarray]:
        """Stage 1 of the flush cascade: drop pending rows whose grid cell
        is strictly dominated by a representative cell of their partition's
        resident skyline — an O(B·C) integer-compare pass before any merge
        kernel launches (C = GRID_REPS ≪ S resident rows).

        Soundness: a row y coded ``vy`` and a representative x coded ``ux``
        with ``ux < vy`` in EVERY dim satisfy
        ``x <= bounds[ux] < bounds[vy] <= y`` per-dim (the ladder is
        validated strictly increasing), i.e. x strictly dominates y. x was
        a LIVE skyline row when the summary launched; if a later flush
        removed it, its remover chain ends at a current row that still
        strictly dominates y (each removal step only tightens every
        coordinate), so the exact merge drops y anyway — and any pending
        row y itself would have pruned is strictly dominated by the same
        chain (transitivity). Survivor set AND compaction/append order are
        therefore byte-identical with the prefilter on or off
        (tests/test_flush_cascade.py asserts this). NaN rows code to -1
        and are never dropped; +inf rows code to GRID_BINS and may drop
        (legitimately — a finite representative strictly dominates +inf).
        """
        if not self._prefilter_on():
            return rows
        grid = self._grid_summaries()
        seen = int(sum(r.shape[0] for r in rows))
        dropped = 0
        if grid is not None and seen:
            bounds, ux = grid
            with self.tracer.phase("flush/prefilter"):
                for p, r in enumerate(rows):
                    n = r.shape[0]
                    if n == 0:
                        continue
                    b = bounds[p]  # (GRID_BINS+1, d) boundary ladder
                    u = ux[p]  # (R, d) representative cell codes
                    if not (u <= GRID_BINS).all(axis=1).any():
                        continue  # no representative can certify here
                    keep = np.ones(n, dtype=bool)
                    any_drop = False
                    for s in range(0, n, _PREFILTER_CHUNK):
                        c = np.asarray(
                            r[s : s + _PREFILTER_CHUNK], np.float32
                        )
                        # vy = largest ladder index with bounds[vy] <= y
                        # (NaN compares false everywhere -> vy = -1)
                        vy = (
                            b[None, :, :] <= c[:, None, :]
                        ).sum(axis=1, dtype=np.int32) - 1  # (m, d)
                        drop = np.any(
                            np.all(u[None, :, :] < vy[:, None, :], axis=2),
                            axis=1,
                        )
                        if drop.any():
                            keep[s : s + c.shape[0]] = ~drop
                            any_drop = True
                    if any_drop:
                        dropped += int(n - keep.sum())
                        rows[p] = r[keep]
        self.prefilter_seen += seen
        self.prefilter_dropped += dropped
        if seen:
            self._fnote("flush.prefilter", seen=seen, dropped=dropped)
        # inc 0 too: the Prometheus series must register at the first
        # prefiltered flush, not the first nonzero drop (obs_smoke asserts
        # presence right after one flush+stats round trip)
        self._inc("flush.prefilter_dropped", dropped)
        # register unconditionally: the series must exist even where mixed
        # precision defaults off (CPU-fallback), so scrapers see a stable
        # schema and obs_smoke can assert both series on any backend
        self._inc("flush.bf16_resolved", 0)
        return rows

    def flush_cascade_stats(self) -> dict:
        """Flush-cascade observability block (stage-1 grid-prefilter
        counters, host-exact, plus the stage-2 bf16-resolved device
        accumulator). The device scalar is synced HERE — stats/bench
        paths only, the flush hot path never blocks on it — and the total
        is delta-fed to the telemetry counters so /metrics and this dict
        always agree."""
        if self._mp_resolved_dev is not None:
            total = int(np.asarray(self._mp_resolved_dev))
            self.bf16_resolved = total
            delta = total - self._bf16_resolved_reported
            if delta:
                self._inc("flush.bf16_resolved", delta)
                self._bf16_resolved_reported = total
        seen = self.prefilter_seen
        return {
            "prefilter_enabled": self._prefilter_on(),
            "mixed_precision": mixed_precision_enabled(),
            "prefilter_seen": seen,
            "prefilter_dropped": self.prefilter_dropped,
            "prefilter_drop_fraction": (
                self.prefilter_dropped / seen if seen else 0.0
            ),
            "bf16_resolved": self.bf16_resolved,
        }

    def _flush_lazy(self) -> None:
        """Lazy-policy flush: sum-sort each partition's accumulated window
        and stream it through append-only SFS rounds — one vmapped launch
        per round for balanced loads, per-partition rounds under routing
        skew. See stream/window.py's SFS notes for the invariant."""
        t0 = time.perf_counter_ns()
        self._bump_epoch(self._pending_rows > 0)
        with self.tracer.phase("flush/assemble"):
            rows = self._drain_pending()
        # prefilter BEFORE the sum sort: dropped rows skip the sort too,
        # and a stable sort of the surviving subset keeps the same relative
        # order the post-sort drop would (byte-identical SFS appends)
        rows = self._prefilter_rows(rows)
        with self.tracer.phase("flush/assemble"):
            for p, r in enumerate(rows):
                if r.shape[0] > 1:
                    order = np.argsort(r.sum(axis=1), kind="stable")
                    rows[p] = r[order]
        had_old, old_counts = self._check_had_old()

        max_rows = max(r.shape[0] for r in rows)
        total_rows = int(sum(r.shape[0] for r in rows))
        # path choice: the vmapped round costs P lanes of (B x active) work
        # per round regardless of how many lanes carry real rows, i.e.
        # ~P * max_rows lane-rows total; the per-partition sequential path
        # costs ~total_rows. Under routing skew (mr-angle at 8D sends ~96%
        # of rows to 2 of 8 partitions) sequential wins by ~P/2; balanced
        # streams keep the one-launch-per-round batching.
        sequential = self.mesh is None and (
            self.num_partitions * max_rows > 2 * total_rows
        )
        device_variant = "sequential" if sequential else "vmapped"
        path = self._choose_lazy_path(device_variant, total_rows)
        self._fnote(
            "flush.dispatch", policy=self.flush_policy, rows=total_rows,
            max_rows=max_rows, sequential=sequential, path=path,
        )
        if path == "sorted_sfs":
            self._inc("flush.sorted_sfs")
            with self._flush_prof.record(
                "flush_sorted_sfs", self.dims, total_rows
            ):
                counts = self._sfs_sorted_host(rows)
        elif path == "device_cascade":
            self._inc("flush.device_cascade")
            with self._flush_prof.record(
                "flush_device_cascade", self.dims, total_rows
            ):
                counts = self._sfs_device_cascade(rows)
        elif self._flush_prof is not None:
            # chooser active: time the device flush end to end (counts
            # sync included) so the EMA compare is honest
            with self._flush_prof.record(
                "flush_sfs_" + device_variant, self.dims, total_rows
            ):
                counts = (
                    self._sfs_sequential(rows)
                    if sequential
                    else self._sfs_vmapped(rows, max_rows)
                )
                np.asarray(counts)
        elif sequential:
            counts = self._sfs_sequential(rows)
        else:
            counts = self._sfs_vmapped(rows, max_rows)
        self._finish_lazy_flush(
            counts,
            had_old,
            old_counts,
            int(old_counts.max()) if had_old else 0,
            t0,
        )

    def _choose_lazy_path(self, device_variant: str, total_rows: int) -> str:
        """Pick the lazy-flush merge path: ``sorted_sfs`` (host cascade,
        ops/sorted_sfs.py) or the device SFS variant. Per ISSUE 11 this is
        a profiler-driven choice, not an env gate: under ``auto`` each
        candidate's WHOLE-FLUSH wall is recorded once per (d, N-bucket,
        backend) signature and the measured EMA decides thereafter
        (``dispatch.choose_variant``; the sorted path explores first). The
        host path needs concrete host rows, so meshes and TPU backends
        never list it; the DEVICE cascade (``ops/device_cascade.py``,
        ISSUE 18) is jit-safe and joins the candidate row whenever the
        host cascade is OUT of play (TPU, or ``SKYLINE_SORTED_SFS=off``)
        — on host backends with the sorted cascade available, the device
        cascade loses to it at every measured signature, so listing it
        would make every fresh engine pay a losing exploration flush for
        nothing (``SKYLINE_DEVICE_CASCADE=on`` still forces it anywhere
        for A/B). Meshed flushes stay on the shard_map SFS rounds. The
        candidate set and race now resolve through the declarative
        cascade table (``ops/cascade.py resolve_flush``), which also
        honors tuner-pinned winners for this (d, N-bucket) signature."""
        meshed = self.mesh is not None
        if meshed:
            return device_variant
        if cascade.flush_chooser_active(meshed) and self._flush_prof is None:
            from skyline_tpu.telemetry.profiler import KernelProfiler

            self._flush_prof = KernelProfiler()
        return cascade.resolve_flush(
            device_variant, self.dims, total_rows, meshed, self._flush_prof
        )

    def _sfs_sorted_host(self, rows: list[np.ndarray]):
        """Host sorted-order SFS flush: per partition, take the exact
        survivor mask of old ∪ new on the host (ops/sorted_sfs.py dedup +
        sum-sorted scan) and append the surviving new rows after the old
        prefix — the same rows in the same order the device SFS rounds
        append (rows arrive pre-sorted by row sum from ``_flush_lazy``,
        and the cascade only selects, never reorders), so every
        downstream consumer sees byte-identical state; the shared
        ``_finish_lazy_flush`` old-vs-new cleanup then runs unchanged.
        Returns the device counts vector like its device siblings."""
        from skyline_tpu.ops.sorted_sfs import sorted_sfs_keep

        if not int(self._count_ub.max()):
            counts_host = np.zeros(self.num_partitions, dtype=np.int64)
        else:
            counts_host = self.sky_counts().astype(np.int64)
        new_skies = []
        new_counts = []
        for p in range(self.num_partitions):
            rp = rows[p]
            sky_p = self.sky[p]
            cnt_p = self._count_dev[p]
            old_n = int(counts_host[p])
            if rp.shape[0]:
                with self.tracer.phase("flush/assemble"):
                    old = np.asarray(sky_p[:old_n]) if old_n else None
                with self.tracer.phase("flush/merge_kernel"), self._kernel(
                    "sorted_sfs", old_n + rp.shape[0]
                ):
                    keep = sorted_sfs_keep(rp, old)
                surv = rp[keep]
                need = old_n + surv.shape[0]
                cap_p = max(sky_p.shape[0], _next_pow2(max(need, 1)))
                with self.tracer.phase("flush/assemble"):
                    buf = np.full(
                        (cap_p, self.dims), np.inf, dtype=np.float32
                    )
                    if old_n:
                        buf[:old_n] = old
                    buf[old_n:need] = surv
                with self.tracer.phase("flush/device_put"):
                    sky_p = jnp.asarray(buf)
                    cnt_p = jnp.asarray(np.int32(need))
                self._count_ub[p] = need
            new_skies.append(sky_p)
            new_counts.append(cnt_p)
        return self._restack_skies(new_skies, new_counts)

    def _sfs_device_cascade(self, rows: list[np.ndarray]):
        """Device-cascade flush: same per-partition shape as
        ``_sfs_sorted_host`` — exact survivor mask of old ∪ new, new
        survivors appended after the old prefix in arrival order — but
        the mask comes from the jit-compiled sorted dominance cascade
        (``ops/device_cascade.py``), so the merge kernel runs on the
        accelerator instead of a host numpy scan. Byte-identical state
        by the same argument: the cascade only selects, never reorders,
        and the old prefix always survives the union (old rows are
        mutually non-dominated and new rows arrive pre-screened)."""
        from skyline_tpu.ops.device_cascade import device_cascade_keep

        if not int(self._count_ub.max()):
            counts_host = np.zeros(self.num_partitions, dtype=np.int64)
        else:
            counts_host = self.sky_counts().astype(np.int64)
        new_skies = []
        new_counts = []
        for p in range(self.num_partitions):
            rp = rows[p]
            sky_p = self.sky[p]
            cnt_p = self._count_dev[p]
            old_n = int(counts_host[p])
            if rp.shape[0]:
                with self.tracer.phase("flush/assemble"):
                    old = (
                        np.asarray(sky_p[:old_n])
                        if old_n
                        else np.empty((0, self.dims), dtype=np.float32)
                    )
                with self.tracer.phase("flush/merge_kernel"), self._kernel(
                    "device_cascade", old_n + rp.shape[0]
                ):
                    keep = device_cascade_keep(rp, old)
                surv = rp[keep]
                need = old_n + surv.shape[0]
                cap_p = max(sky_p.shape[0], _next_pow2(max(need, 1)))
                with self.tracer.phase("flush/assemble"):
                    buf = np.full(
                        (cap_p, self.dims), np.inf, dtype=np.float32
                    )
                    if old_n:
                        buf[:old_n] = old
                    buf[old_n:need] = surv
                with self.tracer.phase("flush/device_put"):
                    sky_p = jnp.asarray(buf)
                    cnt_p = jnp.asarray(np.int32(need))
                self._count_ub[p] = need
            new_skies.append(sky_p)
            new_counts.append(cnt_p)
        return self._restack_skies(new_skies, new_counts)

    def _check_had_old(self):
        """Non-empty initial state needs exact old counts for the final
        old-vs-new cleanup pass (one sync; fresh windows skip it)."""
        had_old = bool((self._count_ub > 0).any())
        old_counts = self.sky_counts().astype(np.int32) if had_old else None
        if had_old and not int(old_counts.max()):
            had_old = False
        return had_old, old_counts

    def _finish_lazy_flush(
        self, counts, had_old, old_counts, old_max, t0, rank=None,
        tighten=True,
    ) -> None:
        """Shared tail of the lazy flush paths: old-vs-new cleanup,
        validity/caches, bound tightening. ``old_counts``: exact
        per-partition pre-flush counts, host or device array (host required
        under a mesh for sharding); ``old_max``: a host bound on their max
        (buckets only). ``rank``: (ws_ranks, sorted_dims) from the
        rank-cascade device flush — the cleanup then compares in rank space
        (old prefixes are universe members). ``tighten=False`` skips the
        trailing count sync (overlap auto-flushes; the next flush's buckets
        then run off the lag-2 upper bounds)."""
        if had_old:
            old_active = min(self._cap, _active_bucket(max(old_max, 1)))
            active = min(
                self._cap, _active_bucket(max(int(self._count_ub.max()), 1))
            )
            with self.tracer.phase("flush/merge_kernel"), self._kernel(
                "sfs_cleanup", active
            ):
                if rank is not None:
                    self.sky, counts = dw.sfs_cleanup_rank(
                        self.sky,
                        counts,
                        jnp.asarray(old_counts),
                        rank[1],
                        old_active,
                        active,
                    )
                elif self.mesh is not None:
                    cl = meshed_sfs_cleanup(
                        self.mesh, self.mesh.axis_names[0], on_tpu(),
                        old_active, active,
                    )
                    self.sky, counts = cl(
                        self.sky, counts, self._put(np.asarray(old_counts))
                    )
                else:
                    self.sky, counts = sfs_cleanup(
                        self.sky, counts, jnp.asarray(old_counts),
                        old_active, active,
                    )
                if self.tracer.sync_device:
                    np.asarray(counts)
        self._count_dev = counts
        # validity is a pure function of counts under append-only state
        self.sky_valid = jnp.arange(self._cap)[None, :] < counts[:, None]
        self._counts_cache = None
        self._host_cache = None
        if tighten:
            # start the count transfer now but don't block on it: the
            # caller's next step is almost always the global merge, whose
            # active bucket comes from _count_ub — the first consumer
            # (sky_counts / global_merge_stats) absorbs the already-landed
            # bytes instead of stalling ingest here on a cold sync
            try:
                counts.copy_to_host_async()
            except AttributeError:
                pass
            self._tighten_pending = True
        self._maybe_launch_summaries()
        self._maybe_launch_grid()
        self.processing_ns += time.perf_counter_ns() - t0

    def _flush_sweep(self) -> None:
        """d <= 2 flush, every policy: union the old skylines, the host
        pending rows, and the device accumulation window into ONE buffer
        and take per-partition skylines by sort + segmented prefix-min
        sweep (ops/sweep2d.py) — O(N log N), no pairwise dominance, no SFS
        rounds, exact by the merge law (skyline(union) per partition).

        Two launches + one count sync: the core launch yields exact
        survivor counts, the host sizes storage to their max, the scatter
        launch packs the stacked (P, cap, d) layout. The sync costs ~ms
        where the SFS rounds it replaces cost seconds, so the overlap
        policy's sync-free property is deliberately traded away here.
        d == 1 rides as (x, 0) pairs: constant second dim makes 2D
        dominance degenerate to 1D (strictness must come from x)."""
        t0 = time.perf_counter_ns()
        # dirty set without a sync: host pending rows are known per
        # partition; a non-empty device window could touch any partition,
        # so it conservatively dirties all (over-bumping only costs cache
        # reuse, never correctness)
        if self._dev_rows > 0:
            self._bump_epoch(slice(None))
        else:
            self._bump_epoch(self._pending_rows > 0)
        from skyline_tpu.ops.sweep2d import (
            partitioned_sweep2_core,
            scatter_sweep2,
        )

        P = self.num_partitions
        with self.tracer.phase("flush/assemble"):
            rows = self._drain_pending()
            host_vals = np.concatenate(
                [r for r in rows if r.shape[0]] or
                [np.empty((0, self.dims), np.float32)]
            )
            host_pids = np.repeat(
                np.arange(P, dtype=np.int32),
                [r.shape[0] for r in rows],
            )
        n_host = host_vals.shape[0]
        # valid prefixes only (the conventions the SFS paths use): the dev
        # window is allocated in doubling buckets that never shrink, and
        # sky rows past the active bucket are invalid by the count bounds —
        # sorting either's full allocation would inflate every flush and
        # churn n_bucket recompiles
        dev_bucket = (
            min(self._dev_cap, _next_pow2(self._dev_rows))
            if self._dev_rows
            else 0
        )
        sky_active = min(
            self._cap, _active_bucket(max(int(self._count_ub.max()), 1))
        )
        n_in = P * sky_active + n_host + dev_bucket
        n_bucket = _next_pow2(n_in)
        pad = n_bucket - n_in
        with self.tracer.phase("flush/device_put"):
            host_vals_d = jnp.asarray(host_vals)
            host_pids_d = jnp.asarray(host_pids)
        sky_flat = self.sky[:, :sky_active].reshape(
            P * sky_active, self.dims
        )
        sky_pids = jnp.repeat(jnp.arange(P, dtype=jnp.int32), sky_active)
        sky_ok = self.sky_valid[:, :sky_active].reshape(-1)
        parts_v = [sky_flat, host_vals_d]
        parts_p = [sky_pids, host_pids_d]
        parts_ok = [sky_ok, jnp.ones((n_host,), bool)]
        if dev_bucket:
            parts_v.append(self._dev_window[:dev_bucket])
            parts_p.append(self._dev_pids[:dev_bucket])
            parts_ok.append(jnp.arange(dev_bucket) < self._dev_rows)
        if pad:
            parts_v.append(jnp.full((pad, self.dims), jnp.inf, jnp.float32))
            parts_p.append(jnp.zeros((pad,), jnp.int32))
            parts_ok.append(jnp.zeros((pad,), bool))
        values = jnp.concatenate(parts_v)
        pids = jnp.concatenate(parts_p)
        valid = jnp.concatenate(parts_ok)
        if self.dims == 1:
            values = jnp.concatenate(
                [values, jnp.zeros((n_bucket, 1), jnp.float32)], axis=1
            )
        with self.tracer.phase("flush/sweep"):
            srows, sp, keep, rank, counts = partitioned_sweep2_core(
                values, pids, valid, P
            )
            counts_host = np.asarray(counts, dtype=np.int64)  # the one sync
        new_cap = max(
            self._cap, _next_pow2(max(int(counts_host.max()), _MIN_CAP))
        )
        with self.tracer.phase("flush/sweep"):
            sky2, counts_dev = scatter_sweep2(
                srows, sp, keep, rank, counts, P, new_cap
            )
            if self.dims == 1:
                sky2 = sky2[:, :, :1]
        self.sky = sky2
        self._cap = new_cap
        self._count_dev = counts_dev
        self.sky_valid = (
            jnp.arange(new_cap)[None, :] < counts_dev[:, None]
        )
        self._count_ub = counts_host.copy()
        self._counts_cache = None
        self._host_cache = None
        self._dev_rows = 0
        self.processing_ns += time.perf_counter_ns() - t0

    def _flush_lazy_device(self, tighten: bool = True) -> None:
        """Lazy/overlap flush over the device accumulation window: one
        (pid, sum) sort + segment-bounds launch, then SFS rounds slicing
        blocks straight from the sorted buffer (stream/device_window.py) —
        no host routing, assembly, or per-block upload. Barrier/metrics
        bookkeeping is NOT synced here (flushing doesn't need it; triggers
        sync it on demand).

        ``tighten=False``: no count syncs — had-old detection and every
        bucket come from the host upper bounds (conservative is correct:
        rows past the true counts are +inf/invalid), the cleanup's exact
        per-partition old counts stay the pre-flush DEVICE count vector,
        and the trailing tighten sync is skipped. Only the segment-bounds
        transfer (host loop control) touches the device."""
        t0 = time.perf_counter_ns()
        n = self._dev_rows
        n_bucket = _next_pow2(n)
        with self.tracer.phase("flush/sort"):
            ws, bounds_dev = dw.sort_window(
                self._dev_window,
                self._dev_pids,
                n,
                n_bucket,
                self.num_partitions,
                dw.SORT_TAIL,
            )
            bounds = np.asarray(bounds_dev, dtype=np.int64)
        self._bump_epoch(np.diff(bounds) > 0)
        self._dev_rows = 0
        if tighten:
            had_old, old_counts = self._check_had_old()
            old_max = int(old_counts.max()) if had_old else 0
        else:
            had_old = bool((self._count_ub > 0).any())
            # exact per-partition old counts WITHOUT a sync: the pre-flush
            # device count vector (cleanup classifies old-vs-new rows by
            # these, so exactness matters; buckets below only need bounds)
            old_counts = self._count_dev if had_old else None
            old_max = int(self._count_ub.max()) if had_old else 0
        # rank-cascade mode: rank the window (+ live sky prefixes, which
        # must share the rank universe) once per flush; the rounds then
        # compare dense ranks instead of values (2 VPU ops/dim + one
        # rank-sum compare vs 3/dim — see ops/pallas_dominance.py)
        rank = None
        if dw.rank_flush_enabled():
            active_old = (
                min(self._cap, _active_bucket(max(old_max, 1)))
                if had_old
                else 0
            )
            univ_bucket = _next_pow2(
                n_bucket + self.num_partitions * active_old
            )
            with self.tracer.phase("flush/rank"):
                sorted_dims, wr = dw.rank_window(
                    ws,
                    self.sky,
                    self._count_dev,
                    n_bucket,
                    active_old,
                    univ_bucket,
                )
            rank = (wr, sorted_dims)
        widths = np.diff(bounds)
        max_rows = int(widths.max())
        total_rows = int(widths.sum())
        # same skew heuristic as the host path (see _flush_lazy)
        sequential = self.num_partitions * max_rows > 2 * total_rows
        self._fnote(
            "flush.dispatch", policy=self.flush_policy, device_window=True,
            rows=total_rows, max_rows=max_rows, sequential=sequential,
        )
        if sequential:
            counts = self._sfs_sequential_dev(ws, bounds, rank, tighten)
        else:
            counts = self._sfs_vmapped_dev(ws, bounds, max_rows, rank)
        self._finish_lazy_flush(
            counts, had_old, old_counts, old_max, t0, rank, tighten
        )

    def _sfs_vmapped_dev(
        self, ws, bounds: np.ndarray, max_rows: int, rank=None
    ):
        """Device-window twin of ``_sfs_vmapped``: one vmapped launch per
        round, every lane slicing its block from the shared sorted window.
        ``rank``: (ws_ranks, sorted_dims) switches to the rank cascade.
        Returns the device counts vector."""
        # cap at SORT_TAIL: see _sfs_sequential_dev's B_max note
        B = min(
            _next_pow2(min(max_rows, max(self.buffer_size, 8192))),
            dw.SORT_TAIL,
        )
        n_rounds = -(-max_rows // B)
        mp = mixed_precision_enabled()
        counts = self._count_dev
        lo = bounds[:-1]
        hi = bounds[1:]
        prev: list[tuple] = []  # lag-2 tightening, see _sfs_vmapped
        for rnd in range(n_rounds):
            offs = np.minimum(lo + rnd * B, hi)
            w = np.clip(hi - offs, 0, B)
            if len(prev) >= 2:
                c2, w1 = prev[-2][0], prev[-1][1]
                self._count_ub = np.minimum(
                    self._count_ub,
                    np.asarray(c2, dtype=np.int64) + w1,
                )
            need = int(self._count_ub.max()) + B
            if need > self._cap:
                self._count_ub = np.asarray(counts, dtype=np.int64)
                need = int(self._count_ub.max()) + B
                if need > self._cap:
                    self._grow_cap(_next_pow2(need))
            active = min(
                self._cap, _active_bucket(max(int(self._count_ub.max()), 1))
            )
            variant = "sfs_rank" if rank is not None else "sfs_vmapped"
            with self.tracer.phase("flush/merge_kernel"), self._kernel(
                variant, active, mp
            ):
                offs_d = jnp.asarray(offs.astype(np.int32))
                w_d = jnp.asarray(w.astype(np.int32))
                if rank is not None:
                    self.sky, counts = dw.sfs_round_at_rank_vmapped(
                        self.sky, counts, ws, rank[0], rank[1],
                        offs_d, w_d, B=B, active=active,
                    )
                else:
                    self.sky, counts, res = dw.sfs_round_at_vmapped(
                        self.sky, counts, ws, offs_d, w_d,
                        B=B, active=active, mp=mp,
                    )
                    if mp:
                        self._accum_resolved(res)
                if self.tracer.sync_device:
                    np.asarray(counts)
            prev.append((counts, w))
            self._count_ub = np.minimum(self._cap, self._count_ub + w)
        self._count_dev = counts
        return counts

    # -- query ------------------------------------------------------------

    def global_merge_stats(self, emit_points: bool = False):
        """Device-side global merge over the (flushed) stacked state.

        Returns ``(counts (P,), survivors_per_partition (P,), global_count,
        points | None)`` with ONE small device->host transfer for the stats
        (plus one bounded transfer when ``emit_points``) — replacing the
        full-buffer snapshot pull + host merge + re-upload. Single-device
        only (the engine falls back to the host path under a mesh).

        Incremental reuse (``SKYLINE_MERGE_CACHE``, default on): the result
        is cached keyed by the partition epoch vector. An identical key
        means no flush touched any partition since the cached merge, so the
        cached stats (and lazily-transferred points) come back with ZERO
        kernel launches; when only a dirty subset changed (fraction <=
        ``SKYLINE_DELTA_CUTOFF``) the merge runs over ``cached_global ∪
        dirty skylines`` instead of the full union
        (``global_merge_delta_device`` documents the correctness argument).
        Either way the result is byte-identical to the from-scratch
        recompute — tests/test_merge_cache.py property-checks this against
        random flush/query interleavings.

        With ``SKYLINE_MERGE_TREE`` (default on, ``dims > 2``, single
        device) the union pass is replaced by a pruned tournament tree:
        whole partitions whose min-corner is strictly dominated by another
        partition's witness point are dropped before any kernel launches
        (``SKYLINE_MERGE_PRUNE``), and the survivors merge pairwise up a
        log-depth binary tree so each level halves the candidate set.
        Byte-identical to the flat recompute — tests/test_merge_tree.py
        property-checks the grid.

        This method is ``global_merge_launch`` + ``global_merge_harvest``
        back to back; callers that want to overlap the merge with further
        ingest use the two halves directly (stream/engine.py's overlapped
        query sync).
        """
        return self.global_merge_harvest(self.global_merge_launch(emit_points))

    def global_merge_launch(self, emit_points: bool = False) -> _MergeHandle:
        """Launch the global merge without blocking on the result.

        Every kernel (tree or flat, full or delta) and the stats
        device->host copy are dispatched here; the returned handle carries
        the in-flight arrays plus the launch-time epoch identity. The
        caller may keep flushing new rows before harvesting — harvest
        detects the moved epoch and skips the count-bound refresh (the
        cached result itself stays valid under its own key).
        """
        if self._tighten_pending:
            # absorb the flush's async count transfer before sizing any
            # bucket below: the bytes are already in flight, so this sync
            # is cheap and the bounds it tightens halve the pairwise work
            self.sky_counts()
        h = _MergeHandle()
        h.emit_points = emit_points
        h.key = self.epoch_key
        h.epoch = self._epoch.copy()
        # claim the parked EXPLAIN plan (one-shot): it rides the handle so
        # an overlapped merge annotates the query that launched it
        h.explain, self._explain = self._explain, None
        use_cache = cascade.merge_cache_on(self.mesh is not None)
        h.use_cache = use_cache
        cache = self._gm_cache if use_cache else None
        if cache is not None and cache["key"] == h.key:
            # exact hit: no flush touched any partition since this result
            # was computed — materialize it now (zero kernel launches) so
            # harvest can't be skewed by a later cache replacement
            self.merge_cache_hits += 1
            self._inc("merge.cache_hit")
            self._fnote("merge.cache_hit", key=h.key)
            self._counts_cache = cache["counts"].copy()
            self._count_ub = cache["counts"].copy()
            h.cached = True
            h.result = (
                cache["counts"].copy(),
                cache["surv"].copy(),
                cache["g"],
                self._cached_points() if emit_points else None,
            )
            if h.explain is not None:
                h.explain.merge = {
                    "path": "cache_hit",
                    "cached": True,
                    "epoch_key": h.key.hex(),
                    "dirty_fraction": 0.0,
                    "dirty": [],
                    "clean": np.flatnonzero(
                        cache["counts"] > 0
                    ).tolist(),
                    "skyline_size": int(cache["g"]),
                }
            return h
        self.merge_cache_misses += 1
        self._inc("merge.cache_miss")
        P = self.num_partitions
        dirty = None
        dirty_mask = None
        if cache is not None:
            dirty_mask = self._epoch != cache["epoch"]
            self.last_dirty_fraction = float(dirty_mask.sum()) / P
            if cascade.delta_applies(self.last_dirty_fraction):
                dirty = dirty_mask
        elif use_cache:
            self.last_dirty_fraction = 1.0  # cold miss == everything dirty
        use_tree = cascade.merge_tree_on(self.mesh is not None, self.dims)
        path = cascade.merge_path(use_tree, dirty is not None)
        self._fnote(
            "merge.launch", path=path, dirty_fraction=self.last_dirty_fraction,
        )
        if h.explain is not None:
            if dirty_mask is not None:
                dirty_set = np.flatnonzero(dirty_mask).tolist()
                clean_set = np.flatnonzero(~dirty_mask).tolist()
            else:
                # no cached epoch to diff against: everything recomputes
                dirty_set = list(range(P))
                clean_set = []
            h.explain.merge = {
                "path": path,
                "cached": False,
                "epoch_key": h.key.hex(),
                # only meaningful when the cache plane computed it this
                # launch; otherwise it's a stale carry-over
                "dirty_fraction": (
                    self.last_dirty_fraction if use_cache else None
                ),
                "dirty": dirty_set,
                "clean": clean_set,
            }
        stats = None
        if dirty is not None:
            h.dirty = dirty
            if use_tree:
                stats = self._merge_tree_delta(cache, dirty, h)
            if stats is None:
                union, keep, stats, _, clean_total = self._merge_delta(
                    cache, dirty
                )
                h.union, h.keep = union, keep
                h.clean_total = clean_total
        else:
            if use_tree:
                stats = self._merge_tree_full(h)
            if stats is None:
                # the count upper bounds are maintained without syncs, so
                # these buckets cost no round trip (pessimistic is safe:
                # rows between count and active are invalid by the mask;
                # union_cap from the SUMMED bounds keeps the pass
                # union-sized under routing skew)
                active = min(
                    self._cap,
                    _active_bucket(max(int(self._count_ub.max()), 1)),
                )
                # quarter-pow2 ladder on the union too: the triangular pass
                # costs O(union_cap^2), so the ladder's ~1.14x tighter
                # bucket is ~1.3x less pairwise work at the north-star
                # union (~437k rows)
                union_cap = _active_bucket(max(int(self._count_ub.sum()), 1))
                union, keep, stats = global_merge_stats_device(
                    self.sky, self._count_dev, active, union_cap
                )
                h.union, h.keep = union, keep
        h.stats = stats
        # start the stats transfer before any host-side bookkeeping so the
        # copy overlaps it instead of starting cold inside np.asarray
        try:
            stats.copy_to_host_async()
        except AttributeError:
            pass
        return h

    def global_merge_harvest(self, handle: _MergeHandle):
        """Block on an in-flight merge and return ``(counts, surv, g,
        points | None)`` — the second half of ``global_merge_stats``. The
        sync cost lands under the ``query/global_stats_sync`` phase; when
        the caller overlapped enough ingest, the bytes already arrived and
        the phase records only the harvest."""
        h = handle
        if h.cached:
            return h.result
        P = self.num_partitions
        with self.tracer.phase("query/global_stats_sync"):
            svec = np.asarray(h.stats, dtype=np.int64)
        counts, surv, g = svec[:P].copy(), svec[P : 2 * P].copy(), int(svec[2 * P])
        if h.dirty is not None:
            self.merge_delta_merges += 1
            drows = h.clean_total + int(counts[h.dirty].sum())
            self.merge_delta_rows += drows
            self._inc("merge.delta_rows", drows)
            if h.explain is not None:
                h.explain.merge["delta_rows"] = drows
                h.explain.merge["clean_rows"] = int(h.clean_total)
        if h.explain is not None and h.explain.merge is not None:
            h.explain.merge["skyline_size"] = g
        pts = None
        if h.use_cache:
            # compact the survivors into the cache buffer even when the
            # caller skipped points: the next delta merge reads them, and a
            # later emit_points hit transfers lazily. Capacity 2*pow2(g)
            # keeps the delta kernel's clean dynamic_slice from ever
            # clamping (lo <= g, clean_active <= pow2(g)). Stored under the
            # handle's LAUNCH-time key: even if flushes landed since, the
            # result correctly describes that epoch's state.
            gcap = 2 * _next_pow2(max(g, 1))
            if h.root_vals is not None:
                pts_dev = tree_points_device(h.root_vals, gcap)
            else:
                pts_dev = global_points_device(h.union, h.keep, gcap)
            self._gm_cache = {
                "key": h.key,
                "epoch": h.epoch.copy(),
                "counts": counts.copy(),
                "surv": surv.copy(),
                "g": g,
                "pts_dev": pts_dev,
                "pts_host": None,
            }
            if h.emit_points:
                pts = self._cached_points()
        elif h.emit_points:
            out_cap = _next_pow2(max(g, 1))
            with self.tracer.phase("query/points_transfer"):
                if h.root_vals is not None:
                    pts_dev = tree_points_device(h.root_vals, out_cap)
                else:
                    pts_dev = global_points_device(h.union, h.keep, out_cap)
                pts = np.asarray(pts_dev)[:g].copy()
        if self.epoch_key == h.key:
            # only refresh the live count bookkeeping when no flush landed
            # between launch and harvest — stale counts would corrupt the
            # capacity upper bounds that size every later bucket
            self._counts_cache = counts.copy()
            self._count_ub = counts.copy()
        return counts, surv, g, pts

    def _merge_delta(self, cache, dirty: np.ndarray):
        """Launch the dirty-subset merge (``global_merge_delta_device``)
        against the cached global points. Returns ``(union, keep, stats,
        union_cap, clean_total)`` — stats packs the CURRENT per-partition
        counts, so the caller's sync/points path is shared with the full
        merge. ``clean_bounds`` rides as a DEVICE array: survivor-layout
        changes between merges then never recompile; only the (recurring)
        dirty pattern and the size buckets are executable keys."""
        surv = cache["surv"]
        bounds = np.concatenate(([0], np.cumsum(surv))).astype(np.int32)
        seg = np.where(dirty, 0, surv)
        clean_total = int(seg.sum())
        clean_active = _active_bucket(max(int(seg.max()), 1))
        active = min(
            self._cap,
            _active_bucket(max(int(self._count_ub[dirty].max()), 1)),
        )
        union_cap = _active_bucket(
            max(clean_total + int(self._count_ub[dirty].sum()), 1)
        )
        union, keep, stats = global_merge_delta_device(
            self.sky,
            self._count_dev,
            cache["pts_dev"],
            jnp.asarray(bounds),
            active,
            clean_active,
            union_cap,
            tuple(bool(b) for b in dirty),
        )
        return union, keep, stats, union_cap, clean_total

    # -- pruned tournament-tree merge --------------------------------------

    def _maybe_launch_summaries(self) -> None:
        """Flush-tail hook: start the per-partition prune summary compute
        (async, tiny) so the next merge's prefilter reads landed bytes
        instead of launching cold. Only when the tree + prefilter are both
        live for this set (``dims > 2``, single device)."""
        if cascade.merge_tree_on(
            self.mesh is not None, self.dims
        ) and cascade.gate("partition_prune"):
            self._launch_summaries()

    def _launch_summaries(self) -> None:
        active = min(
            self._cap, _active_bucket(max(int(self._count_ub.max()), 1))
        )
        self._summary_dev = partition_summaries_device(
            self.sky, self._count_dev, active
        )
        try:
            self._summary_dev.copy_to_host_async()
        except AttributeError:
            pass
        self._summary_epoch = self._epoch.copy()

    def _tree_summaries(self) -> np.ndarray:
        """Host copy of the (P, 2d+2) prune summaries for the CURRENT
        epoch — usually already in flight from the flush-tail launch;
        re-launched (one tiny kernel) when the stamp is stale."""
        if self._summary_epoch is None or not np.array_equal(
            self._summary_epoch, self._epoch
        ):
            self._launch_summaries()
        return np.asarray(self._summary_dev)

    def _prune_mask(self, alive: np.ndarray) -> np.ndarray:
        """The O(P²·d) bound-dominance prefilter (core now in
        ``stream.window.prune_witness_mask`` — see its docstring for the
        soundness argument). Keeps the per-partition witness reasons on
        ``self.last_prune_witness`` for the EXPLAIN plane; the mask itself
        is byte-for-byte the historical one."""
        pruned, self.last_prune_witness = prune_witness_mask(
            self._tree_summaries(), alive, self.dims
        )
        return pruned

    def _merge_tree_full(self, h: _MergeHandle):
        """Assemble + launch the pruned tournament tree over the current
        partition skylines. Returns the packed stats device vector, or
        ``None`` to fall back to the flat union pass (no live leaves)."""
        alive = self._count_ub > 0
        considered = int(alive.sum())
        npruned = 0
        if cascade.gate("partition_prune") and considered > 1:
            pruned = self._prune_mask(alive)
            npruned = int(pruned.sum())
            leaf_mask = alive & ~pruned
            if h.explain is not None:
                wit = self.last_prune_witness
                h.explain.tree = {
                    "pruned": [
                        {"partition": int(b), "witness": int(wit[b])}
                        for b in np.flatnonzero(pruned)
                    ],
                }
        else:
            leaf_mask = alive
        pids = np.flatnonzero(leaf_mask)
        if pids.size == 0:
            return None  # empty set: the flat pass handles the zero state
        leaves = []
        for p in pids:
            w = min(
                self._cap,
                _active_bucket(max(int(self._count_ub[p]), 1)),
            )
            vals, lpids, cnt = extract_sky_leaf(
                self.sky, self._count_dev, int(p), w
            )
            leaves.append((vals, lpids, cnt, int(self._count_ub[p])))
        return self._run_tree(leaves, h, npruned, considered)

    def _merge_tree_delta(self, cache, dirty: np.ndarray, h: _MergeHandle):
        """Delta merge routed through the tree: dirty partitions feed
        their skylines as leaves, clean partitions feed their cached
        global-survivor segments (masked past the true width — the tail
        rows belong to the NEXT partitions' survivors). Leaves stay in
        ascending pid order, so the root is byte-identical to the flat
        delta's compaction. No prefilter here: the witness summaries
        describe partition skylines, not cached survivor segments."""
        surv = cache["surv"]
        bounds = np.concatenate(([0], np.cumsum(surv))).astype(np.int64)
        gpts = cache["pts_dev"]
        leaves = []
        clean_total = 0
        for p in range(self.num_partitions):
            if dirty[p]:
                if self._count_ub[p] <= 0:
                    continue
                w = min(
                    self._cap,
                    _active_bucket(max(int(self._count_ub[p]), 1)),
                )
                vals, lpids, cnt = extract_sky_leaf(
                    self.sky, self._count_dev, int(p), w
                )
                leaves.append((vals, lpids, cnt, int(self._count_ub[p])))
            else:
                sw = int(surv[p])
                if sw <= 0:
                    continue
                clean_total += sw
                w = _active_bucket(sw)
                vals, lpids, cnt = extract_cached_leaf(
                    gpts,
                    jnp.asarray(np.int32(bounds[p])),
                    jnp.asarray(np.int32(sw)),
                    int(p),
                    w,
                )
                leaves.append((vals, lpids, cnt, sw))
        h.clean_total = clean_total
        if not leaves:
            return None
        return self._run_tree(leaves, h, 0, len(leaves))

    def _run_tree(self, leaves, h: _MergeHandle, npruned: int, considered: int):
        """Pair the leaves up a binary tree (adjacent pairs, odd tail
        passes through) so pid order — and therefore byte identity with the
        flat pass's stable compaction — is preserved at every level. Each
        node's capacity covers the sum of its children's count upper
        bounds, so ``compact`` never silently clips."""
        levels = 0
        cand = [len(leaves)]
        nodes = leaves
        while len(nodes) > 1:
            levels += 1
            nxt = []
            for i in range(0, len(nodes) - 1, 2):
                av, ap, ac, aub = nodes[i]
                bv, bp, bc, bub = nodes[i + 1]
                out_cap = _active_bucket(max(aub + bub, 1))
                vals, pids_out, cnt = tree_pair_merge(
                    av, ap, ac, bv, bp, bc, out_cap
                )
                nxt.append((vals, pids_out, cnt, min(aub + bub, out_cap)))
            if len(nodes) % 2:
                nxt.append(nodes[-1])
            nodes = nxt
            cand.append(len(nodes))
        root_vals, root_pids, root_cnt, _ = nodes[0]
        h.root_vals = root_vals
        self.merge_tree_merges += 1
        self.merge_partitions_pruned += npruned
        # inc even when zero so the Prometheus series registers on the
        # first tree merge, not the first nonzero prune
        self._inc("merge.tree_levels", levels)
        self._inc("merge.partitions_pruned", npruned)
        self._fnote(
            "merge.tree", levels=levels, pruned=npruned, considered=considered,
        )
        self.last_tree_info = {
            "levels": levels,
            "partitions_pruned": npruned,
            "candidates_per_level": cand,
            "pruned_fraction": (npruned / considered) if considered else 0.0,
        }
        if h.explain is not None:
            # the prune hook (full path only) may already have stashed the
            # witness rows; fold the tree shape in beside them
            tree = h.explain.tree or {"pruned": []}
            tree.update(self.last_tree_info)
            tree["considered"] = considered
            h.explain.tree = tree
        return tree_stats_device(
            self._count_dev, root_pids, root_cnt, self.num_partitions
        )

    def merge_points_device(self, handle: _MergeHandle, out_cap: int):
        """Device buffer of a HARVESTED merge's global skyline points,
        ``(out_cap, d)`` with rows past the true count +inf-padded — no
        host transfer. The sharded engine's cross-chip tournament feeds
        each chip-local root straight into ``tree_pair_merge`` through
        this, so chip results never round-trip through host memory.

        Valid only between a harvest and the next flush (the caller holds
        the chip's epoch fixed across the two-level merge). Prefers the
        cache-plane buffer when it describes the handle's epoch; otherwise
        compacts the handle's in-flight tree/flat result."""
        h = handle
        cache = self._gm_cache
        if cache is not None and cache["key"] == h.key:
            pts = cache["pts_dev"]
            if pts.shape[0] >= out_cap:
                return pts[:out_cap]
            return jnp.pad(
                pts,
                ((0, out_cap - pts.shape[0]), (0, 0)),
                constant_values=jnp.inf,
            )
        if h.root_vals is not None:
            return tree_points_device(h.root_vals, out_cap)
        return global_points_device(h.union, h.keep, out_cap)

    def _cached_points(self) -> np.ndarray:
        """Host copy of the cached global skyline points, transferred at
        most once per cached merge (later hits reuse the host array)."""
        c = self._gm_cache
        if c["pts_host"] is None:
            with self.tracer.phase("query/points_transfer"):
                c["pts_host"] = np.asarray(c["pts_dev"])[: c["g"]].copy()
        return c["pts_host"].copy()

    def sky_counts(self) -> np.ndarray:
        """Exact survivor counts (P,) — one device sync (cached until the
        next flush)."""
        if self._counts_cache is None:
            with self.tracer.phase("query/count_sync"):
                self._counts_cache = np.asarray(self._count_dev, dtype=np.int64)
            self._count_ub = self._counts_cache.copy()
        self._tighten_pending = False
        return self._counts_cache

    def _host_sky(self) -> np.ndarray:
        if self._host_cache is None:
            with self.tracer.phase("query/snapshot_transfer"):
                self._host_cache = np.asarray(self.sky)
        return self._host_cache

    def snapshot(self, p: int) -> np.ndarray:
        """Flush pending rows and return partition ``p``'s local skyline
        (k, d) on host — the processQuery path (FlinkSkyline.java:367-403)."""
        self.flush_all()  # times itself; t0 after it avoids double-counting
        t0 = time.perf_counter_ns()
        count = int(self.sky_counts()[p])
        out = self._host_sky()[p, :count].copy()
        self.processing_ns += time.perf_counter_ns() - t0
        return out

    def skyline_host(self, p: int) -> np.ndarray:
        """Partition ``p``'s device skyline pulled to host WITHOUT flushing
        pending rows (checkpointing reads state as-is)."""
        count = int(self.sky_counts()[p])
        return self._host_sky()[p, :count].copy()

    def pending_rows_of(self, p: int) -> np.ndarray:
        """Partition ``p``'s un-flushed pending rows as one (m, d) array."""
        if not self._pending[p]:
            return np.empty((0, self.dims), dtype=np.float32)
        if len(self._pending[p]) == 1:
            return self._pending[p][0]
        return np.concatenate(self._pending[p], axis=0)

    def audit_state(self) -> tuple[list[np.ndarray], list[np.ndarray]]:
        """Off-hot-path state capture for the audit plane: every
        partition's device skyline plus its un-flushed pending rows, as
        host arrays. One bulk device→host transfer (the ``_host_sky``
        cache) — no flush, no merge, no epoch advance, so capturing for a
        shadow check never perturbs the state being checked."""
        skies = [self.skyline_host(p) for p in range(self.num_partitions)]
        pendings = [
            self.pending_rows_of(p) for p in range(self.num_partitions)
        ]
        return skies, pendings

    def restore_all(
        self, skies: list[np.ndarray], pendings: list[np.ndarray]
    ) -> None:
        """Checkpoint-restore every partition's skyline + pending buffer in
        one host pass and one device upload.

        ``skies[p]`` rows are assumed mutually non-dominated (they came from
        ``skyline_host``). Replaces ALL existing state, including barrier and
        metrics bookkeeping (reset to fresh; the caller re-applies saved
        values, as ``utils.checkpoint.load_engine`` does).
        """
        assert len(skies) == len(pendings) == self.num_partitions
        # discard any un-flushed device-ingest window (checkpoint saves
        # flush it first, so a restore over live state starts clean)
        self._dev_rows = 0
        self._chunk_stats = []
        self.max_seen_id[:] = -1
        self.start_time_ms = [None] * self.num_partitions
        self.records_seen[:] = 0
        self.processing_ns = 0
        counts = np.array([s.shape[0] for s in skies], dtype=np.int64)
        # honor the configured pre-sizing across restore, so a resumed
        # engine keeps the growth-sync-free capacity the knob promises
        cap = max(
            _next_pow2(max(int(counts.max()), 1)),
            _next_pow2(max(self.initial_capacity, _MIN_CAP)),
        )
        svals = np.full(
            (self.num_partitions, cap, self.dims), np.inf, dtype=np.float32
        )
        svalid = np.zeros((self.num_partitions, cap), dtype=bool)
        for p, sky in enumerate(skies):
            k = sky.shape[0]
            svals[p, :k] = sky
            svalid[p, :k] = True
        self.sky = self._put(svals)
        self.sky_valid = self._put(svalid)
        self._count_dev = self._put(counts.astype(np.int32))
        self._count_ub = counts.copy()
        self._cap = cap
        self._counts_cache = None
        self._host_cache = None
        # restored state is a different world: advance every epoch so any
        # merge cached against the pre-restore state can never be reused
        self._epoch += 1
        self._gm_cache = None
        # the grid prefilter summary described the replaced skylines; the
        # staleness argument (_prefilter_rows) covers EVOLVED state, not a
        # swapped world, so it must go
        self._grid_dev = None
        self._grid_host = None
        self._grid_epoch = None
        self._tighten_pending = False
        for p, pending in enumerate(pendings):
            if pending.shape[0]:
                self._pending[p] = [pending]
                self._pending_rows[p] = pending.shape[0]
            else:
                self._pending[p] = []
                self._pending_rows[p] = 0

    @property
    def processing_ms(self) -> float:
        return self.processing_ns / 1e6


class PartitionView:
    """Per-partition facade over a ``PartitionSet`` — the engine and
    checkpointing address partitions individually while storage stays
    stacked.

    Contract note: ``add_batch`` does NOT auto-flush at the buffer
    threshold. Flush policy belongs to the set (one batched launch for all
    partitions) — the owner must call ``PartitionSet.maybe_flush()`` after
    routing a micro-batch, as ``SkylineEngine.process_records`` does.
    ``snapshot`` still flushes, so query results never miss pending rows
    either way."""

    __slots__ = ("_set", "partition_id")

    def __init__(self, pset: PartitionSet, p: int):
        self._set = pset
        self.partition_id = p

    # bookkeeping fields (read/write, used by the engine's barrier +
    # grid-prefilter paths)
    @property
    def max_seen_id(self) -> int:
        return int(self._set.max_seen_id[self.partition_id])

    @max_seen_id.setter
    def max_seen_id(self, v: int) -> None:
        self._set.max_seen_id[self.partition_id] = v

    @property
    def start_time_ms(self):
        return self._set.start_time_ms[self.partition_id]

    @start_time_ms.setter
    def start_time_ms(self, v) -> None:
        self._set.start_time_ms[self.partition_id] = v

    @property
    def records_seen(self) -> int:
        return int(self._set.records_seen[self.partition_id])

    @records_seen.setter
    def records_seen(self, v: int) -> None:
        self._set.records_seen[self.partition_id] = v

    @property
    def processing_ns(self) -> int:
        return self._set.processing_ns

    @property
    def processing_ms(self) -> float:
        return self._set.processing_ms

    def add_batch(self, values: np.ndarray, max_id: int, now_ms: float) -> None:
        self._set.add_batch(self.partition_id, values, max_id, now_ms)

    def flush(self) -> None:
        self._set.flush_all()

    def snapshot(self) -> np.ndarray:
        return self._set.snapshot(self.partition_id)

    def skyline_host(self) -> np.ndarray:
        return self._set.skyline_host(self.partition_id)

    @property
    def sky_count(self) -> int:
        return int(self._set.sky_counts()[self.partition_id])
