"""Incremental windowed-merge kernels for streaming skyline maintenance.

The merge step is the flush-time replacement for the reference's BNL
buffer-vs-skyline loop (``SkylineLocalProcessor.processBuffer``,
FlinkSkyline.java:417-444): one jitted masked dominance pass folds a new
micro-batch into a running skyline buffer. The stateful owner of these
kernels is ``skyline_tpu.stream.batched.PartitionSet``, which stacks all
logical partitions and calls the *batched* variants — one device launch per
flush for the whole set.

TPU residency: running skylines live on device as padded
power-of-two-capacity buffers; each flush ships only the new micro-batch up
and survivor counts back, so steady-state streaming never transfers the
skyline itself. Capacities are bucketed so XLA compiles a bounded number of
executables.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from skyline_tpu.ops.dominance import compact, dominated_by, skyline_mask
from skyline_tpu.ops.sfs import (  # noqa: F401  (re-exported: the SFS
    pallas_interpret as _pallas_interpret,  # kernels moved to the ops layer)
    sfs_cleanup,
    sfs_round,
    sfs_round_single,
)
from skyline_tpu.utils.buckets import next_pow2

# Reference flushes its input buffer at 5000 tuples (BUFFER_SIZE,
# FlinkSkyline.java:232); we default to the nearest power of two.
DEFAULT_BUFFER_SIZE = 4096

# Minimum buffer capacity. Power-of-two buckets >= this always divide the
# Pallas tile sizes after the kernels' min(tile, n) clamp
# (ops/pallas_dominance.py), which is what keeps sub-COL_TILE buffers legal.
_MIN_CAP = 1024


def _next_pow2(n: int) -> int:
    return next_pow2(n, min_cap=_MIN_CAP)


# the ladder only engages when its step (p//8) is a whole number of Pallas
# victim tiles: derived from the kernel's tile constants so a future tile
# sweep can't silently strand victims past a truncated grid division
# (dominated_by_pallas computes grid = n // tile with no remainder handling)
@functools.cache
def _ladder_min() -> int:
    import math

    from skyline_tpu.ops.pallas_dominance import COL_TILE, ROW_TILE

    return 8 * math.lcm(ROW_TILE, COL_TILE)


def _active_bucket(n: int) -> int:
    """Quarter-pow2 ladder for ACTIVE (compute-prefix) buckets:
    {1, 1.25, 1.5, 1.75} x 2^k. ``active`` sets the dominator-prefix width
    of every SFS/merge dominance pass, so the power-of-two bucket's average
    ~1.33x overshoot of the true survivor count is directly wasted pairwise
    work; the finer ladder cuts the overshoot to ~1.11x for at most 3 extra
    executables per octave (cached across windows, persistent via the
    compile cache). Storage capacities stay power-of-two (`_next_pow2`) —
    only compute prefixes use this ladder.

    The ladder only runs when the pow2 bucket ``p`` is >= ``_ladder_min()``
    (8 * lcm(ROW_TILE, COL_TILE) = 16384 at the current tiles, so p//8 is
    a whole number of victim tiles): the Pallas grids divide the victim
    extent by the column tile with no remainder handling
    (ops/pallas_dominance.py), and this guard makes every returned value
    either a power of two (below the guard) or a tile multiple (at or
    above it). Note the guard is on ``p``, not the returned value —
    n=9000 returns 10240, a non-pow2 value below 16384 (still a
    tile-multiple). Returned values are always >= n and <=
    _next_pow2(n), so callers' capacity invariants are unaffected."""
    p = _next_pow2(n)
    if p < _ladder_min():
        return p
    # p is the true next pow2 here (the guard keeps n above the _MIN_CAP
    # floor), so p/2 < n and the 1.0x(p/2) rung can never be selected
    step = p // 8
    for num in (5, 6, 7):
        if step * num >= n:
            return step * num
    return p


def _merge_step_core(sky, sky_valid, batch, batch_valid, out_cap: int):
    """One windowed-BNL step: merge a new batch into a running skyline and
    compact survivors into a fresh ``out_cap`` buffer.

    sky is assumed to already be a skyline (mutually non-dominated):

    - a batch point survives iff it is not dominated within its batch nor by
      the running skyline (dominated dominators prune correctly by
      transitivity, so the full sky buffer is a valid dominator set);
    - a sky point survives iff no *surviving* batch point dominates it
      (a dropped batch dominator's own dominator chain ends at a kept point
      that also dominates the victim, so kept batch points suffice).

    Returns (values (out_cap, d), valid (out_cap,), count). ``out_cap`` must
    be >= current survivor count + batch rows, so overflow cannot occur.
    """
    batch_local = skyline_mask(batch, batch_valid)
    keep_batch = batch_local & ~dominated_by(batch, sky, x_valid=sky_valid)
    keep_sky = sky_valid & ~dominated_by(sky, batch, x_valid=keep_batch)
    x = jnp.concatenate([sky, batch], axis=0)
    keep = jnp.concatenate([keep_sky, keep_batch], axis=0)
    return compact(x, keep, out_cap)


def _merge_step_pallas_core(sky, sky_valid, batch, batch_valid, out_cap: int):
    """TPU fast path of ``_merge_step_core``: the three dominance passes run
    in the Pallas VMEM-tiled kernel (same mask logic, same transitivity
    arguments). Requires sky/batch extents to be tile multiples — the
    _MIN_CAP floor plus pow2 capacities / pow2-or-tile-multiple active
    prefixes (``_active_bucket``) guarantee that."""
    from skyline_tpu.ops.pallas_dominance import dominated_by_pallas

    interp = _pallas_interpret()
    sky_t = sky.T
    batch_t = batch.T
    batch_local = batch_valid & ~dominated_by_pallas(
        batch_t, batch_valid, batch_t, interpret=interp
    )
    keep_batch = batch_local & ~dominated_by_pallas(
        sky_t, sky_valid, batch_t, interpret=interp
    )
    keep_sky = sky_valid & ~dominated_by_pallas(
        batch_t, keep_batch, sky_t, interpret=interp
    )
    x = jnp.concatenate([sky, batch], axis=0)
    keep = jnp.concatenate([keep_sky, keep_batch], axis=0)
    return compact(x, keep, out_cap)


# Batched merge: P partitions' flushes in ONE device launch
# (sky (P, cap, d), batch (P, B, d) -> (P, out_cap, d)). Streaming through a
# dispatch-latency-bound link (the remote-TPU tunnel) is launch-count-bound,
# so collapsing P per-partition merges into one vmapped executable is the
# difference between ~P*3 launches per micro-batch and ~1.
_merge_step_batched = jax.jit(
    jax.vmap(_merge_step_core, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("out_cap",),
)
_merge_step_pallas_batched = jax.jit(
    jax.vmap(_merge_step_pallas_core, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("out_cap",),
)


@functools.partial(
    jax.jit,
    static_argnames=("active", "out_active"),
    donate_argnums=(0, 1),
)
def merge_step_active(sky, sky_valid, batch, bvalid, active: int, out_active: int):
    """Incremental flush step over the ACTIVE capacity prefix only.

    A pre-sized or previously-grown buffer makes the plain batched merge pay
    full-capacity dominance passes and a full-buffer compact argsort on
    every flush, even when the live skylines are a fraction of capacity.
    This variant slices the dominator/compact work to ``active`` (the
    capacity bucket of the current max count; rows past it are guaranteed
    invalid) and compacts into ``out_active`` (the bucket covering counts +
    this batch), then pads back out to the storage capacity — one fused
    launch, same storage shape out. Requires out_active >= active and
    out_active >= per-partition count + batch rows (the caller's capacity
    bookkeeping guarantees both). Single-device only (the meshed path keeps
    ``meshed_merge_step``).

    The stacked sky/valid buffers are donated (the ops/sfs.py idiom): the
    steady-state same-shape flush updates in place instead of allocating a
    fresh (P, cap, d) buffer per round, which is what lets the staged
    pipeline keep two rounds in flight without doubling residency. Growth
    rounds (out_cap > cap) can't reuse the buffer and fall back to a fresh
    allocation with jax's "donated buffers not usable" warning (filtered in
    tests/conftest.py, log-bounded in production by the doubling schedule).
    """
    from skyline_tpu.ops.dispatch import on_tpu

    P, cap, d = sky.shape
    core = _merge_step_pallas_core if on_tpu() else _merge_step_core
    sky_a = lax.slice(sky, (0, 0, 0), (P, active, d))
    val_a = lax.slice(sky_valid, (0, 0), (P, active))
    vals, valid, cnt = jax.vmap(
        lambda s, sv, b, bv: core(s, sv, b, bv, out_active)
    )(sky_a, val_a, batch, bvalid)
    out_cap = max(cap, out_active)
    if out_active < out_cap:
        vals = jnp.concatenate(
            [
                vals,
                jnp.full((P, out_cap - out_active, d), jnp.inf, vals.dtype),
            ],
            axis=1,
        )
        valid = jnp.concatenate(
            [valid, jnp.zeros((P, out_cap - out_active), dtype=bool)], axis=1
        )
    return vals, valid, cnt.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("active", "union_cap"))
def global_merge_stats_device(sky, counts, active: int, union_cap: int):
    """Device-side two-phase finish over the stacked state: gather every
    partition's live prefix into ONE contiguous union buffer, then a single
    triangular pass — instead of pulling buffers to host, merging there,
    and re-uploading (GlobalSkylineAggregator's role,
    FlinkSkyline.java:547-608, minus the host round-trip).

    ``active`` (static) bounds each partition's copied prefix (the bucket
    of the max count); ``union_cap`` (static) is the bucket of the summed
    counts — the dominance pass runs over the union's size, NOT P x active.
    Under routing skew (mr-angle at 8D sends ~96% of rows to 2 of 8
    partitions) the flattened-padded formulation pays (P*active)^2 while
    the union is barely bigger than one partition — a 16x difference at the
    north-star window.

    The sequential gather writes each partition's full ``active`` slice at
    the running count offset: rows >= count are +inf padding under BOTH
    flush policies (compact/SFS-append invariants), each write's garbage
    tail is overwritten by the next partition's rows, and the buffer keeps
    an ``active``-row scratch tail so no write ever clamps.

    Returns (union (union_cap, d) — still on device for the points path —
    keep (union_cap,) bool, and a packed stats vector [counts (P,),
    survivors_per_partition (P,), global_count] so the caller syncs ONE
    small transfer)."""
    from skyline_tpu.ops.dispatch import skyline_mask_auto

    P, cap, d = sky.shape
    scratch = union_cap + active
    u = jnp.full((scratch, d), jnp.inf, dtype=sky.dtype)
    uo = jnp.zeros((scratch,), dtype=jnp.int32)
    off = jnp.zeros((), jnp.int32)
    for p in range(P):  # static unroll; P is small
        sl = lax.slice(sky, (p, 0, 0), (p + 1, active, d)).reshape(active, d)
        u = lax.dynamic_update_slice(u, sl, (off, jnp.zeros((), jnp.int32)))
        uo = lax.dynamic_update_slice(
            uo, jnp.full((active,), p, jnp.int32), (off,)
        )
        off = off + counts[p].astype(jnp.int32)
    u = lax.slice(u, (0, 0), (union_cap, d))
    uo = lax.slice(uo, (0,), (union_cap,))
    uv = jnp.arange(union_cap) < off
    keep = skyline_mask_auto(u, uv)
    surv = jax.ops.segment_sum(
        keep.astype(jnp.int32), uo, num_segments=P
    )
    g = keep.sum(dtype=jnp.int32)
    stats = jnp.concatenate([counts.astype(jnp.int32), surv, g[None]])
    return u, keep, stats


@functools.partial(jax.jit, static_argnames=("out_cap",))
def global_points_device(union, keep, out_cap: int):
    """Compact the global survivors (union + keep from
    ``global_merge_stats_device``) to the front of an (out_cap, d) buffer
    for a single bounded transfer — only paid when a query asks for
    skyline_points."""
    return compact(union, keep, out_cap)[0]


@functools.partial(
    jax.jit,
    static_argnames=("active", "clean_active", "union_cap", "dirty"),
)
def global_merge_delta_device(
    sky,
    counts,
    gpts,
    clean_bounds,
    active: int,
    clean_active: int,
    union_cap: int,
    dirty: tuple,
):
    """Dirty-subset variant of ``global_merge_stats_device``: the union is
    ``cached_global ∪ dirty partitions' current skylines`` instead of every
    partition's full prefix, shrinking the triangular pass from
    O((Σ all counts)²) to O((g + Σ dirty)²).

    Correctness (the merge law + transitivity): a CLEAN partition's
    contribution is its cached global survivors — any of its points culled
    at cache time had a dominator in some partition's then-skyline, and
    partition skylines only lose points to strict dominance by current
    members, so a current dominator always exists transitively; a DIRTY
    partition contributes its full current skyline (its cached survivors
    may be stale, so they are excluded — also what prevents a stale
    duplicate from double-counting against the current copy). Survivor
    order is byte-identical to the full merge: partitions are written in
    ascending id, clean segments keep the cached (storage-order) layout,
    and ``compact``'s stable sort preserves write order.

    ``dirty``: static per-partition bool tuple (executable count is bounded
    by the recurring dirty patterns; the caller's dirty-fraction cutoff
    keeps the tail from compiling). ``clean_bounds``: (P+1,) int32 row
    offsets of each partition's segment inside ``gpts`` (cumsum of the
    cached per-partition survivor counts — dirty partitions' segments are
    simply skipped). ``active`` bounds the dirty slices (bucket of the max
    dirty count); ``clean_active`` bounds the clean slices (bucket of the
    max clean segment width) — both slices write their full static width at
    the running offset and advance by the true width, each garbage tail
    overwritten by the next write (the gather trick
    ``global_merge_stats_device`` documents). ``gpts`` capacity must be >=
    g + clean_active so the clean ``dynamic_slice`` never clamps backward
    (the caller pads the cached points buffer to 2*next_pow2(g)).

    Returns (union, keep, stats) with the same shapes/semantics as the full
    merge so the caller's sync/points paths are shared."""
    from skyline_tpu.ops.dispatch import skyline_mask_auto

    P, cap, d = sky.shape
    scratch = union_cap + max(active, clean_active)
    u = jnp.full((scratch, d), jnp.inf, dtype=sky.dtype)
    uo = jnp.zeros((scratch,), dtype=jnp.int32)
    off = jnp.zeros((), jnp.int32)
    zero = jnp.zeros((), jnp.int32)
    for p in range(P):  # static unroll; P is small
        if dirty[p]:
            sl = lax.slice(sky, (p, 0, 0), (p + 1, active, d)).reshape(
                active, d
            )
            u = lax.dynamic_update_slice(u, sl, (off, zero))
            uo = lax.dynamic_update_slice(
                uo, jnp.full((active,), p, jnp.int32), (off,)
            )
            off = off + counts[p].astype(jnp.int32)
        else:
            lo = clean_bounds[p]
            w = clean_bounds[p + 1] - lo
            sl = lax.dynamic_slice(gpts, (lo, zero), (clean_active, d))
            # unlike ``sky`` prefixes, rows past this segment are NOT +inf
            # padding — they are the NEXT partitions' cached survivors — so
            # the static-width tail must be masked out before the write (a
            # shorter next write would otherwise leave live duplicates)
            sl = jnp.where(
                jnp.arange(clean_active)[:, None] < w, sl, jnp.inf
            )
            u = lax.dynamic_update_slice(u, sl, (off, zero))
            uo = lax.dynamic_update_slice(
                uo, jnp.full((clean_active,), p, jnp.int32), (off,)
            )
            off = off + w
    u = lax.slice(u, (0, 0), (union_cap, d))
    uo = lax.slice(uo, (0,), (union_cap,))
    uv = jnp.arange(union_cap) < off
    keep = skyline_mask_auto(u, uv)
    surv = jax.ops.segment_sum(keep.astype(jnp.int32), uo, num_segments=P)
    g = keep.sum(dtype=jnp.int32)
    stats = jnp.concatenate([counts.astype(jnp.int32), surv, g[None]])
    return u, keep, stats


def _shard_map_vmapped(mesh, axis, fn, n_in: int, n_out: int, donate=()):
    """``jit(shard_map(vmap(fn)))`` over the partition axis — the one shared
    wrapper for every meshed per-partition kernel. All inputs and outputs
    are partition-sharded; the per-partition kernels have no cross-partition
    data flow, so no collectives appear and each device runs its resident
    partitions only. Needed explicitly (vs GSPMD) because ``pallas_call``
    has no auto-partitioning rule."""
    from jax.sharding import PartitionSpec

    spec = PartitionSpec(axis)
    sharded = jax.shard_map(
        jax.vmap(fn),
        mesh=mesh,
        in_specs=(spec,) * n_in,
        out_specs=(spec,) * n_out,
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=donate)


@functools.lru_cache(maxsize=None)
def meshed_merge_step(mesh, axis: str, use_pallas: bool, out_cap: int):
    """Batched merge wrapped in ``shard_map`` over the partition axis
    (see ``_shard_map_vmapped``). Cached per (mesh, axis, kernel, capacity
    bucket) so steady-state flushes reuse one executable."""
    core = _merge_step_pallas_core if use_pallas else _merge_step_core
    return _shard_map_vmapped(
        mesh, axis, lambda s, sv, b, bv: core(s, sv, b, bv, out_cap), 4, 3
    )


@functools.lru_cache(maxsize=None)
def meshed_sfs_round(mesh, axis: str, use_pallas: bool, active: int):
    """``sfs_round`` wrapped in ``shard_map`` over the partition axis (see
    ``_shard_map_vmapped``) — the lazy policy's meshed flush. Cached per
    (mesh, axis, kernel, active bucket); donates the sky buffer like the
    single-device jit."""
    from skyline_tpu.ops.sfs import pallas_interpret, sfs_round_core

    interp = pallas_interpret()
    return _shard_map_vmapped(
        mesh,
        axis,
        lambda s, c, b, bv: sfs_round_core(
            s, c, b, bv, active, use_pallas, interp
        ),
        4,
        2,
        donate=(0,),
    )


@functools.lru_cache(maxsize=None)
def meshed_sfs_cleanup(mesh, axis: str, use_pallas: bool, old_active: int, active: int):
    """``sfs_cleanup`` wrapped in ``shard_map`` over the partition axis —
    the old-vs-new prune after SFS rounds on non-empty initial state, per
    resident partition (no collectives)."""
    from skyline_tpu.ops.sfs import pallas_interpret, sfs_cleanup_core

    interp = pallas_interpret()
    return _shard_map_vmapped(
        mesh,
        axis,
        lambda s, c, oc: sfs_cleanup_core(
            s, c, oc, old_active, active, use_pallas, interp
        ),
        3,
        2,
        donate=(0,),
    )
