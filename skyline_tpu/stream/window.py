"""Incremental windowed-merge kernels for streaming skyline maintenance.

The merge step is the flush-time replacement for the reference's BNL
buffer-vs-skyline loop (``SkylineLocalProcessor.processBuffer``,
FlinkSkyline.java:417-444): one jitted masked dominance pass folds a new
micro-batch into a running skyline buffer. The stateful owner of these
kernels is ``skyline_tpu.stream.batched.PartitionSet``, which stacks all
logical partitions and calls the *batched* variants — one device launch per
flush for the whole set.

TPU residency: running skylines live on device as padded
power-of-two-capacity buffers; each flush ships only the new micro-batch up
and survivor counts back, so steady-state streaming never transfers the
skyline itself. Capacities are bucketed so XLA compiles a bounded number of
executables.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from skyline_tpu.ops.dominance import compact, dominated_by, skyline_mask
from skyline_tpu.utils.buckets import next_pow2

# Reference flushes its input buffer at 5000 tuples (BUFFER_SIZE,
# FlinkSkyline.java:232); we default to the nearest power of two.
DEFAULT_BUFFER_SIZE = 4096

# Minimum buffer capacity. Power-of-two buckets >= this always divide the
# Pallas tile sizes after the kernels' min(tile, n) clamp
# (ops/pallas_dominance.py), which is what keeps sub-COL_TILE buffers legal.
_MIN_CAP = 1024


def _next_pow2(n: int) -> int:
    return next_pow2(n, min_cap=_MIN_CAP)


def _merge_step_core(sky, sky_valid, batch, batch_valid, out_cap: int):
    """One windowed-BNL step: merge a new batch into a running skyline and
    compact survivors into a fresh ``out_cap`` buffer.

    sky is assumed to already be a skyline (mutually non-dominated):

    - a batch point survives iff it is not dominated within its batch nor by
      the running skyline (dominated dominators prune correctly by
      transitivity, so the full sky buffer is a valid dominator set);
    - a sky point survives iff no *surviving* batch point dominates it
      (a dropped batch dominator's own dominator chain ends at a kept point
      that also dominates the victim, so kept batch points suffice).

    Returns (values (out_cap, d), valid (out_cap,), count). ``out_cap`` must
    be >= current survivor count + batch rows, so overflow cannot occur.
    """
    batch_local = skyline_mask(batch, batch_valid)
    keep_batch = batch_local & ~dominated_by(batch, sky, x_valid=sky_valid)
    keep_sky = sky_valid & ~dominated_by(sky, batch, x_valid=keep_batch)
    x = jnp.concatenate([sky, batch], axis=0)
    keep = jnp.concatenate([keep_sky, keep_batch], axis=0)
    return compact(x, keep, out_cap)


def _pallas_interpret() -> bool:
    """Read lazily (at trace time, not import time): set
    ``SKYLINE_PALLAS_INTERPRET=1`` to run the Pallas merge in interpret mode
    on CPU — how ``dryrun_multichip`` validates the shard_map-of-pallas_call
    lowering without TPU hardware. Evaluated when a merge step first traces;
    already-compiled executables are unaffected by later env changes."""
    return os.environ.get("SKYLINE_PALLAS_INTERPRET", "") == "1"


def _merge_step_pallas_core(sky, sky_valid, batch, batch_valid, out_cap: int):
    """TPU fast path of ``_merge_step_core``: the three dominance passes run
    in the Pallas VMEM-tiled kernel (same mask logic, same transitivity
    arguments). Requires sky/batch capacities to be tile multiples — the
    _MIN_CAP floor and power-of-two bucketing guarantee that."""
    from skyline_tpu.ops.pallas_dominance import dominated_by_pallas

    interp = _pallas_interpret()
    sky_t = sky.T
    batch_t = batch.T
    batch_local = batch_valid & ~dominated_by_pallas(
        batch_t, batch_valid, batch_t, interpret=interp
    )
    keep_batch = batch_local & ~dominated_by_pallas(
        sky_t, sky_valid, batch_t, interpret=interp
    )
    keep_sky = sky_valid & ~dominated_by_pallas(
        batch_t, keep_batch, sky_t, interpret=interp
    )
    x = jnp.concatenate([sky, batch], axis=0)
    keep = jnp.concatenate([keep_sky, keep_batch], axis=0)
    return compact(x, keep, out_cap)


# Batched merge: P partitions' flushes in ONE device launch
# (sky (P, cap, d), batch (P, B, d) -> (P, out_cap, d)). Streaming through a
# dispatch-latency-bound link (the remote-TPU tunnel) is launch-count-bound,
# so collapsing P per-partition merges into one vmapped executable is the
# difference between ~P*3 launches per micro-batch and ~1.
_merge_step_batched = jax.jit(
    jax.vmap(_merge_step_core, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("out_cap",),
)
_merge_step_pallas_batched = jax.jit(
    jax.vmap(_merge_step_pallas_core, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("out_cap",),
)


@functools.partial(jax.jit, static_argnames=("active", "out_active"))
def merge_step_active(sky, sky_valid, batch, bvalid, active: int, out_active: int):
    """Incremental flush step over the ACTIVE capacity prefix only.

    A pre-sized or previously-grown buffer makes the plain batched merge pay
    full-capacity dominance passes and a full-buffer compact argsort on
    every flush, even when the live skylines are a fraction of capacity.
    This variant slices the dominator/compact work to ``active`` (the
    capacity bucket of the current max count; rows past it are guaranteed
    invalid) and compacts into ``out_active`` (the bucket covering counts +
    this batch), then pads back out to the storage capacity — one fused
    launch, same storage shape out. Requires out_active >= active and
    out_active >= per-partition count + batch rows (the caller's capacity
    bookkeeping guarantees both). Single-device only (the meshed path keeps
    ``meshed_merge_step``).
    """
    from skyline_tpu.ops.dispatch import on_tpu

    P, cap, d = sky.shape
    core = _merge_step_pallas_core if on_tpu() else _merge_step_core
    sky_a = lax.slice(sky, (0, 0, 0), (P, active, d))
    val_a = lax.slice(sky_valid, (0, 0), (P, active))
    vals, valid, cnt = jax.vmap(
        lambda s, sv, b, bv: core(s, sv, b, bv, out_active)
    )(sky_a, val_a, batch, bvalid)
    out_cap = max(cap, out_active)
    if out_active < out_cap:
        vals = jnp.concatenate(
            [
                vals,
                jnp.full((P, out_cap - out_active, d), jnp.inf, vals.dtype),
            ],
            axis=1,
        )
        valid = jnp.concatenate(
            [valid, jnp.zeros((P, out_cap - out_active), dtype=bool)], axis=1
        )
    return vals, valid, cnt.astype(jnp.int32)


# --------------------------------------------------------------------------
# SFS (sort-filter-skyline) rounds: the lazy flush policy's kernel.
#
# For a tumbling window queried once, incremental maintenance is wasted
# work: every flush re-prunes the running skyline against the new batch
# both ways and re-compacts the full buffer. When ALL rows are available at
# trigger time, sum-sorting each partition's window and streaming blocks in
# ascending-sum order makes the skyline buffer APPEND-ONLY (a dominator
# always has a strictly smaller coordinate sum, so nothing already appended
# can be dominated by a later block): one forward pass, one small compact
# per block, no buffer re-pruning. This is `ops.block_skyline.skyline_large`
# generalized to all partitions at once (one vmapped launch per round) and
# to non-empty initial state.
# --------------------------------------------------------------------------


def _sfs_round_core(sky, count, block, bvalid, active, use_pallas, interp):
    """One SFS append round for one partition.

    sky: (cap, d) buffer whose first ``count`` rows are a skyline; block:
    (B, d) sum-sorted ascending (invalid rows padded +inf at the end), with
    all sums >= any previously appended block's in this SFS pass. Appends
    the block's survivors at ``count``. ``active`` (static) bounds the
    dominator prefix actually compared against — the capacity bucket of the
    current max count, so early rounds don't pay full-capacity passes.

    Caller guarantees count + B <= cap (the compacted block writes B slots;
    rows past the survivor count are +inf padding landing on virgin rows).
    """
    cap, d = sky.shape
    sky_act = lax.slice(sky, (0, 0), (active, d))
    sky_ok = jnp.arange(active) < count
    if use_pallas:
        from skyline_tpu.ops.pallas_dominance import (
            dominated_by_any_pallas,
            dominated_by_pallas,
        )

        block_t = block.T
        keep = bvalid & ~dominated_by_any_pallas(
            block_t, bvalid, triangular=True, interpret=interp
        )
        keep = keep & ~dominated_by_pallas(
            sky_act.T, sky_ok, block_t, interpret=interp
        )
    else:
        keep = skyline_mask(block, bvalid)
        keep = keep & ~dominated_by(block, sky_act, x_valid=sky_ok)
    vals, _, m = compact(block, keep, block.shape[0])
    sky = lax.dynamic_update_slice(sky, vals, (count, 0))
    return sky, count + m


@functools.partial(jax.jit, static_argnames=("active",))
def sfs_round(sky, counts, blocks, bvalids, active: int):
    """Vmapped SFS round over all partitions: sky (P, cap, d), counts (P,)
    int32, blocks (P, B, d), bvalids (P, B) -> (sky', counts'). One device
    launch for the whole set — right when partitions carry comparable row
    counts (every vmap lane computes the full (B x active) passes whether
    its block is real or padding; see ``sfs_round_single`` for the skewed
    case)."""
    from skyline_tpu.ops.dispatch import on_tpu

    use_pallas = on_tpu()
    interp = _pallas_interpret()

    def core(s, c, b, bv):
        return _sfs_round_core(s, c, b, bv, active, use_pallas, interp)

    return jax.vmap(core)(sky, counts, blocks, bvalids)


@functools.partial(jax.jit, static_argnames=("active",))
def sfs_round_single(sky_p, count, block, bvalid, active: int):
    """One partition's SFS round without the vmap lane dimension: sky_p
    (cap, d), count () int32, block (B, d), bvalid (B,). Under routing skew
    (one or two partitions holding most of the stream — mr-angle at 8D
    anti-correlated routes ~96%% of rows to 2 of 8 partitions) the vmapped
    round pays P lanes of (B x active) work for one real lane; processing
    the heavy partitions individually costs exactly their own rows."""
    from skyline_tpu.ops.dispatch import on_tpu

    return _sfs_round_core(
        sky_p, count, block, bvalid, active, on_tpu(), _pallas_interpret()
    )


@functools.partial(jax.jit, static_argnames=("old_active", "active"))
def sfs_cleanup(sky, counts, old_counts, old_active: int, active: int):
    """After SFS rounds on a buffer that started non-empty: rows of the OLD
    region (per-partition prefix of ``old_counts``) may be dominated by newly
    appended rows (which were only guaranteed non-dominated among themselves
    and not dominated BY the old rows). Prune old-vs-new and re-compact each
    partition's buffer. ``old_active``/``active`` (static) are the capacity
    buckets of the old and final max counts — dominator and victim sets are
    sliced to them so a shrunken skyline in a grown buffer never pays
    full-capacity passes. Returns (sky', counts')."""
    from skyline_tpu.ops.dispatch import on_tpu

    use_pallas = on_tpu()
    interp = _pallas_interpret()
    P, cap, d = sky.shape

    def core(s, c, old_c):
        act = lax.slice(s, (0, 0), (active, d))
        new_ok = (jnp.arange(active) >= old_c) & (jnp.arange(active) < c)
        old = lax.slice(s, (0, 0), (old_active, d))
        if use_pallas:
            from skyline_tpu.ops.pallas_dominance import dominated_by_pallas

            old_dom = dominated_by_pallas(
                act.T, new_ok, old.T, interpret=interp
            )
        else:
            old_dom = dominated_by(old, act, x_valid=new_ok)
        old_keep = (jnp.arange(old_active) < old_c) & ~old_dom
        keep = jnp.zeros((cap,), dtype=bool)
        keep = keep.at[:active].set(new_ok)
        keep = keep.at[:old_active].set(old_keep | new_ok[:old_active])
        return compact(s, keep, cap)

    vals, valid, cnt = jax.vmap(core)(sky, counts, old_counts)
    return vals, cnt.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("active", "union_cap"))
def global_merge_stats_device(sky, counts, active: int, union_cap: int):
    """Device-side two-phase finish over the stacked state: gather every
    partition's live prefix into ONE contiguous union buffer, then a single
    triangular pass — instead of pulling buffers to host, merging there,
    and re-uploading (GlobalSkylineAggregator's role,
    FlinkSkyline.java:547-608, minus the host round-trip).

    ``active`` (static) bounds each partition's copied prefix (the bucket
    of the max count); ``union_cap`` (static) is the bucket of the summed
    counts — the dominance pass runs over the union's size, NOT P x active.
    Under routing skew (mr-angle at 8D sends ~96%% of rows to 2 of 8
    partitions) the flattened-padded formulation pays (P*active)^2 while
    the union is barely bigger than one partition — a 16x difference at the
    north-star window.

    The sequential gather writes each partition's full ``active`` slice at
    the running count offset: rows >= count are +inf padding under BOTH
    flush policies (compact/SFS-append invariants), each write's garbage
    tail is overwritten by the next partition's rows, and the buffer keeps
    an ``active``-row scratch tail so no write ever clamps.

    Returns (union (union_cap, d) — still on device for the points path —
    keep (union_cap,) bool, and a packed stats vector [counts (P,),
    survivors_per_partition (P,), global_count] so the caller syncs ONE
    small transfer)."""
    from skyline_tpu.ops.dispatch import skyline_mask_auto

    P, cap, d = sky.shape
    scratch = union_cap + active
    u = jnp.full((scratch, d), jnp.inf, dtype=sky.dtype)
    uo = jnp.zeros((scratch,), dtype=jnp.int32)
    off = jnp.zeros((), jnp.int32)
    for p in range(P):  # static unroll; P is small
        sl = lax.slice(sky, (p, 0, 0), (p + 1, active, d)).reshape(active, d)
        u = lax.dynamic_update_slice(u, sl, (off, jnp.zeros((), jnp.int32)))
        uo = lax.dynamic_update_slice(
            uo, jnp.full((active,), p, jnp.int32), (off,)
        )
        off = off + counts[p].astype(jnp.int32)
    u = lax.slice(u, (0, 0), (union_cap, d))
    uo = lax.slice(uo, (0,), (union_cap,))
    uv = jnp.arange(union_cap) < off
    keep = skyline_mask_auto(u, uv)
    surv = jax.ops.segment_sum(
        keep.astype(jnp.int32), uo, num_segments=P
    )
    g = keep.sum(dtype=jnp.int32)
    stats = jnp.concatenate([counts.astype(jnp.int32), surv, g[None]])
    return u, keep, stats


@functools.partial(jax.jit, static_argnames=("out_cap",))
def global_points_device(union, keep, out_cap: int):
    """Compact the global survivors (union + keep from
    ``global_merge_stats_device``) to the front of an (out_cap, d) buffer
    for a single bounded transfer — only paid when a query asks for
    skyline_points."""
    return compact(union, keep, out_cap)[0]


@functools.lru_cache(maxsize=None)
def meshed_merge_step(mesh, axis: str, use_pallas: bool, out_cap: int):
    """Batched merge wrapped in ``shard_map`` over the partition axis.

    With partition state sharded ``(P, cap, d)`` across a mesh, the plain
    jitted vmap relies on GSPMD auto-partitioning — fine for the XLA merge,
    but ``pallas_call`` has no partitioning rule, so the Pallas variant must
    be explicitly SPMD: each device runs the vmapped merge on its resident
    partitions (the merge has no cross-partition data flow, so no
    collectives are needed). Cached per (mesh, axis, kernel, capacity
    bucket) so steady-state flushes reuse one executable.
    """
    from jax.sharding import PartitionSpec

    core = _merge_step_pallas_core if use_pallas else _merge_step_core
    vm = jax.vmap(lambda s, sv, b, bv: core(s, sv, b, bv, out_cap))
    spec = PartitionSpec(axis)
    sharded = jax.shard_map(
        vm,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(spec, spec, spec),
        check_vma=False,
    )
    return jax.jit(sharded)
