"""Incremental windowed-merge kernels for streaming skyline maintenance.

The merge step is the flush-time replacement for the reference's BNL
buffer-vs-skyline loop (``SkylineLocalProcessor.processBuffer``,
FlinkSkyline.java:417-444): one jitted masked dominance pass folds a new
micro-batch into a running skyline buffer. The stateful owner of these
kernels is ``skyline_tpu.stream.batched.PartitionSet``, which stacks all
logical partitions and calls the *batched* variants — one device launch per
flush for the whole set.

TPU residency: running skylines live on device as padded
power-of-two-capacity buffers; each flush ships only the new micro-batch up
and survivor counts back, so steady-state streaming never transfers the
skyline itself. Capacities are bucketed so XLA compiles a bounded number of
executables.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from skyline_tpu.ops.dominance import (
    compact,
    dominated_by,
    skyline_mask,
    strictly_dominated_bf16,
)
from skyline_tpu.ops.sfs import (  # noqa: F401  (re-exported: the SFS
    _MP_PREFIX,  # kernels moved to the ops layer)
    pallas_interpret as _pallas_interpret,
    sfs_cleanup,
    sfs_round,
    sfs_round_single,
)
from skyline_tpu.utils.buckets import next_pow2
from skyline_tpu.utils.jax_compat import shard_map

# Reference flushes its input buffer at 5000 tuples (BUFFER_SIZE,
# FlinkSkyline.java:232); we default to the nearest power of two.
DEFAULT_BUFFER_SIZE = 4096

# Dispatch-signature variant names for the kernel profiler
# (telemetry/profiler.py): every ``flush/merge_kernel`` tracer site in
# stream/batched.py attributes its wall time to one of these. The mapping
# is documentation + a closed vocabulary for /profile consumers; the
# profiler itself accepts any string.
KERNEL_VARIANTS = {
    "merge_step": "batched merge of one micro-batch into all partitions",
    "meshed_merge_step": "shard_map merge across a device mesh",
    "sfs_vmapped": "vmapped sort-filter-skyline flush round",
    "meshed_sfs_round": "shard_map SFS flush round",
    "sfs_sequential": "single-partition SFS flush round",
    "sfs_rank": "device-resident SFS round (per-rank / vmapped dw paths)",
    "sfs_cleanup": "lazy-flush cleanup pass",
    "sorted_sfs": "host sorted-order SFS cascade, one partition's flush "
                  "(ops/sorted_sfs.py: dedup + f64 sum-sort + blocked scan)",
    "device_cascade": "device sorted dominance cascade, one partition's "
                      "flush (ops/device_cascade.py: on-device dedup + f32 "
                      "sum-key sort + blocked prefix/band scan, jit-safe)",
    # dispatch-chooser signatures (recorded into PartitionSet._flush_prof
    # and dispatch._MASK_PROFILER, not the engine profiler — whole-path
    # aggregates that would double-count the per-round rows above)
    "flush_sorted_sfs": "whole lazy flush via the host sorted cascade",
    "flush_sfs_sequential": "whole lazy flush via per-partition SFS rounds",
    "flush_sfs_vmapped": "whole lazy flush via vmapped SFS rounds",
    "flush_device_cascade": "whole lazy flush via the device sorted "
                            "dominance cascade",
    "sorted_sfs_mask": "skyline_mask_auto host path (concrete non-TPU d>2)",
    "mask_scan": "skyline_mask_auto device scan kernel (concrete arrays)",
    "mask_device_cascade": "skyline_mask_auto device sorted dominance "
                           "cascade (jit-safe, all backends)",
    "mask_pallas": "skyline_mask_auto Pallas sum-sorted tiles (TPU)",
    "mask_rank_pallas": "skyline_mask_auto Pallas rank-cascade tiles (TPU)",
}

# Minimum buffer capacity. Power-of-two buckets >= this always divide the
# Pallas tile sizes after the kernels' min(tile, n) clamp
# (ops/pallas_dominance.py), which is what keeps sub-COL_TILE buffers legal.
_MIN_CAP = 1024


def _next_pow2(n: int) -> int:
    return next_pow2(n, min_cap=_MIN_CAP)


# the ladder only engages when its step (p//8) is a whole number of Pallas
# victim tiles: derived from the kernel's tile constants so a future tile
# sweep can't silently strand victims past a truncated grid division
# (dominated_by_pallas computes grid = n // tile with no remainder handling)
@functools.cache
def _ladder_min() -> int:
    import math

    from skyline_tpu.ops.pallas_dominance import COL_TILE, ROW_TILE

    return 8 * math.lcm(ROW_TILE, COL_TILE)


def _active_bucket(n: int) -> int:
    """Quarter-pow2 ladder for ACTIVE (compute-prefix) buckets:
    {1, 1.25, 1.5, 1.75} x 2^k. ``active`` sets the dominator-prefix width
    of every SFS/merge dominance pass, so the power-of-two bucket's average
    ~1.33x overshoot of the true survivor count is directly wasted pairwise
    work; the finer ladder cuts the overshoot to ~1.11x for at most 3 extra
    executables per octave (cached across windows, persistent via the
    compile cache). Storage capacities stay power-of-two (`_next_pow2`) —
    only compute prefixes use this ladder.

    The ladder only runs when the pow2 bucket ``p`` is >= ``_ladder_min()``
    (8 * lcm(ROW_TILE, COL_TILE) = 16384 at the current tiles, so p//8 is
    a whole number of victim tiles): the Pallas grids divide the victim
    extent by the column tile with no remainder handling
    (ops/pallas_dominance.py), and this guard makes every returned value
    either a power of two (below the guard) or a tile multiple (at or
    above it). Note the guard is on ``p``, not the returned value —
    n=9000 returns 10240, a non-pow2 value below 16384 (still a
    tile-multiple). Returned values are always >= n and <=
    _next_pow2(n), so callers' capacity invariants are unaffected."""
    p = _next_pow2(n)
    if p < _ladder_min():
        return p
    # p is the true next pow2 here (the guard keeps n above the _MIN_CAP
    # floor), so p/2 < n and the 1.0x(p/2) rung can never be selected
    step = p // 8
    for num in (5, 6, 7):
        if step * num >= n:
            return step * num
    return p


def _mp_predrop(sky, sky_valid, batch, batch_valid):
    """bf16-margin pre-drop of batch rows certainly strictly-dominated by a
    skyline prefix row (mixed-precision stage 2, shared by both merge cores).

    Bit-exact vs skipping it: a certified row y has a valid sky dominator x
    with x < y strictly in every dim, so the exact sky-vs-batch pass drops y
    anyway, and any batch row q that y would have pruned from the
    batch-local pass satisfies x < y <= q per-dim — x strictly dominates q
    too (transitivity), so q is dropped by the sky pass either way. Masking
    y to +inf only moves its coordinate sum UP, so sum-sorted invariants of
    callers are preserved. Returns (batch', batch_valid', resolved)."""
    limit = min(sky.shape[0], _MP_PREFIX)
    d = sky.shape[1]
    pre = strictly_dominated_bf16(
        batch, lax.slice(sky, (0, 0), (limit, d)), sky_valid[:limit]
    )
    pre = pre & batch_valid
    resolved = jnp.sum(pre, dtype=jnp.int32)
    batch_valid = batch_valid & ~pre
    batch = jnp.where(batch_valid[:, None], batch, jnp.inf)
    return batch, batch_valid, resolved


def _merge_step_core(sky, sky_valid, batch, batch_valid, out_cap: int, mp: bool = False):
    """One windowed-BNL step: merge a new batch into a running skyline and
    compact survivors into a fresh ``out_cap`` buffer.

    sky is assumed to already be a skyline (mutually non-dominated):

    - a batch point survives iff it is not dominated within its batch nor by
      the running skyline (dominated dominators prune correctly by
      transitivity, so the full sky buffer is a valid dominator set);
    - a sky point survives iff no *surviving* batch point dominates it
      (a dropped batch dominator's own dominator chain ends at a kept point
      that also dominates the victim, so kept batch points suffice).

    ``mp`` (static) enables the bf16 margin pre-drop (``_mp_predrop``) —
    bit-exact either way. Returns (values (out_cap, d), valid (out_cap,),
    count, resolved); ``resolved`` is the int32 count of bf16-certified
    drops (0 when ``mp=False``). ``out_cap`` must be >= current survivor
    count + batch rows, so overflow cannot occur.
    """
    resolved = jnp.zeros((), dtype=jnp.int32)
    if mp:
        batch, batch_valid, resolved = _mp_predrop(
            sky, sky_valid, batch, batch_valid
        )
    batch_local = skyline_mask(batch, batch_valid)
    keep_batch = batch_local & ~dominated_by(batch, sky, x_valid=sky_valid)
    keep_sky = sky_valid & ~dominated_by(sky, batch, x_valid=keep_batch)
    x = jnp.concatenate([sky, batch], axis=0)
    keep = jnp.concatenate([keep_sky, keep_batch], axis=0)
    vals, valid, cnt = compact(x, keep, out_cap)
    return vals, valid, cnt, resolved


def _merge_step_pallas_core(sky, sky_valid, batch, batch_valid, out_cap: int, mp: bool = False):
    """TPU fast path of ``_merge_step_core``: the three dominance passes run
    in the Pallas VMEM-tiled kernel (same mask logic, same transitivity
    arguments; ``mp`` additionally threads the in-kernel bf16 first pass).
    Requires sky/batch extents to be tile multiples — the
    _MIN_CAP floor plus pow2 capacities / pow2-or-tile-multiple active
    prefixes (``_active_bucket``) guarantee that."""
    from skyline_tpu.ops.pallas_dominance import dominated_by_pallas

    interp = _pallas_interpret()
    resolved = jnp.zeros((), dtype=jnp.int32)
    if mp:
        batch, batch_valid, resolved = _mp_predrop(
            sky, sky_valid, batch, batch_valid
        )
    sky_t = sky.T
    batch_t = batch.T
    batch_local = batch_valid & ~dominated_by_pallas(
        batch_t, batch_valid, batch_t, interpret=interp, mp=mp
    )
    keep_batch = batch_local & ~dominated_by_pallas(
        sky_t, sky_valid, batch_t, interpret=interp, mp=mp
    )
    keep_sky = sky_valid & ~dominated_by_pallas(
        batch_t, keep_batch, sky_t, interpret=interp, mp=mp
    )
    x = jnp.concatenate([sky, batch], axis=0)
    keep = jnp.concatenate([keep_sky, keep_batch], axis=0)
    vals, valid, cnt = compact(x, keep, out_cap)
    return vals, valid, cnt, resolved


# Batched merge: P partitions' flushes in ONE device launch
# (sky (P, cap, d), batch (P, B, d) -> (P, out_cap, d)). Streaming through a
# dispatch-latency-bound link (the remote-TPU tunnel) is launch-count-bound,
# so collapsing P per-partition merges into one vmapped executable is the
# difference between ~P*3 launches per micro-batch and ~1.
_merge_step_batched = jax.jit(
    jax.vmap(_merge_step_core, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("out_cap",),
)
_merge_step_pallas_batched = jax.jit(
    jax.vmap(_merge_step_pallas_core, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("out_cap",),
)


@functools.partial(
    jax.jit,
    static_argnames=("active", "out_active", "mp"),
    donate_argnums=(0, 1),
)
def merge_step_active(
    sky, sky_valid, batch, bvalid, active: int, out_active: int, mp: bool = False
):
    """Incremental flush step over the ACTIVE capacity prefix only.

    A pre-sized or previously-grown buffer makes the plain batched merge pay
    full-capacity dominance passes and a full-buffer compact argsort on
    every flush, even when the live skylines are a fraction of capacity.
    This variant slices the dominator/compact work to ``active`` (the
    capacity bucket of the current max count; rows past it are guaranteed
    invalid) and compacts into ``out_active`` (the bucket covering counts +
    this batch), then pads back out to the storage capacity — one fused
    launch, same storage shape out. Requires out_active >= active and
    out_active >= per-partition count + batch rows (the caller's capacity
    bookkeeping guarantees both). Single-device only (the meshed path keeps
    ``meshed_merge_step``).

    The stacked sky/valid buffers are donated (the ops/sfs.py idiom): the
    steady-state same-shape flush updates in place instead of allocating a
    fresh (P, cap, d) buffer per round, which is what lets the staged
    pipeline keep two rounds in flight without doubling residency. Growth
    rounds (out_cap > cap) can't reuse the buffer and fall back to a fresh
    allocation with jax's "donated buffers not usable" warning (filtered in
    tests/conftest.py, log-bounded in production by the doubling schedule).

    ``mp`` (static, a jit cache key) threads the bf16 margin pass; the
    fourth return is the per-partition bf16-resolved count (P,) int32.
    """
    from skyline_tpu.ops.dispatch import on_tpu

    P, cap, d = sky.shape
    core = _merge_step_pallas_core if on_tpu() else _merge_step_core
    sky_a = lax.slice(sky, (0, 0, 0), (P, active, d))
    val_a = lax.slice(sky_valid, (0, 0), (P, active))
    vals, valid, cnt, res = jax.vmap(
        lambda s, sv, b, bv: core(s, sv, b, bv, out_active, mp)
    )(sky_a, val_a, batch, bvalid)
    out_cap = max(cap, out_active)
    if out_active < out_cap:
        vals = jnp.concatenate(
            [
                vals,
                jnp.full((P, out_cap - out_active, d), jnp.inf, vals.dtype),
            ],
            axis=1,
        )
        valid = jnp.concatenate(
            [valid, jnp.zeros((P, out_cap - out_active), dtype=bool)], axis=1
        )
    return vals, valid, cnt.astype(jnp.int32), res


@functools.partial(jax.jit, static_argnames=("active", "union_cap"))
def global_merge_stats_device(sky, counts, active: int, union_cap: int):
    """Device-side two-phase finish over the stacked state: gather every
    partition's live prefix into ONE contiguous union buffer, then a single
    triangular pass — instead of pulling buffers to host, merging there,
    and re-uploading (GlobalSkylineAggregator's role,
    FlinkSkyline.java:547-608, minus the host round-trip).

    ``active`` (static) bounds each partition's copied prefix (the bucket
    of the max count); ``union_cap`` (static) is the bucket of the summed
    counts — the dominance pass runs over the union's size, NOT P x active.
    Under routing skew (mr-angle at 8D sends ~96% of rows to 2 of 8
    partitions) the flattened-padded formulation pays (P*active)^2 while
    the union is barely bigger than one partition — a 16x difference at the
    north-star window.

    The sequential gather writes each partition's full ``active`` slice at
    the running count offset: rows >= count are +inf padding under BOTH
    flush policies (compact/SFS-append invariants), each write's garbage
    tail is overwritten by the next partition's rows, and the buffer keeps
    an ``active``-row scratch tail so no write ever clamps.

    Returns (union (union_cap, d) — still on device for the points path —
    keep (union_cap,) bool, and a packed stats vector [counts (P,),
    survivors_per_partition (P,), global_count] so the caller syncs ONE
    small transfer)."""
    from skyline_tpu.ops.dispatch import skyline_mask_auto

    P, cap, d = sky.shape
    scratch = union_cap + active
    u = jnp.full((scratch, d), jnp.inf, dtype=sky.dtype)
    uo = jnp.zeros((scratch,), dtype=jnp.int32)
    off = jnp.zeros((), jnp.int32)
    for p in range(P):  # static unroll; P is small
        sl = lax.slice(sky, (p, 0, 0), (p + 1, active, d)).reshape(active, d)
        u = lax.dynamic_update_slice(u, sl, (off, jnp.zeros((), jnp.int32)))
        uo = lax.dynamic_update_slice(
            uo, jnp.full((active,), p, jnp.int32), (off,)
        )
        off = off + counts[p].astype(jnp.int32)
    u = lax.slice(u, (0, 0), (union_cap, d))
    uo = lax.slice(uo, (0,), (union_cap,))
    uv = jnp.arange(union_cap) < off
    keep = skyline_mask_auto(u, uv)
    surv = jax.ops.segment_sum(
        keep.astype(jnp.int32), uo, num_segments=P
    )
    g = keep.sum(dtype=jnp.int32)
    stats = jnp.concatenate([counts.astype(jnp.int32), surv, g[None]])
    return u, keep, stats


@functools.partial(jax.jit, static_argnames=("out_cap",))
def global_points_device(union, keep, out_cap: int):
    """Compact the global survivors (union + keep from
    ``global_merge_stats_device``) to the front of an (out_cap, d) buffer
    for a single bounded transfer — only paid when a query asks for
    skyline_points."""
    return compact(union, keep, out_cap)[0]


@functools.partial(
    jax.jit,
    static_argnames=("active", "clean_active", "union_cap", "dirty"),
)
def global_merge_delta_device(
    sky,
    counts,
    gpts,
    clean_bounds,
    active: int,
    clean_active: int,
    union_cap: int,
    dirty: tuple,
):
    """Dirty-subset variant of ``global_merge_stats_device``: the union is
    ``cached_global ∪ dirty partitions' current skylines`` instead of every
    partition's full prefix, shrinking the triangular pass from
    O((Σ all counts)²) to O((g + Σ dirty)²).

    Correctness (the merge law + transitivity): a CLEAN partition's
    contribution is its cached global survivors — any of its points culled
    at cache time had a dominator in some partition's then-skyline, and
    partition skylines only lose points to strict dominance by current
    members, so a current dominator always exists transitively; a DIRTY
    partition contributes its full current skyline (its cached survivors
    may be stale, so they are excluded — also what prevents a stale
    duplicate from double-counting against the current copy). Survivor
    order is byte-identical to the full merge: partitions are written in
    ascending id, clean segments keep the cached (storage-order) layout,
    and ``compact``'s stable sort preserves write order.

    ``dirty``: static per-partition bool tuple (executable count is bounded
    by the recurring dirty patterns; the caller's dirty-fraction cutoff
    keeps the tail from compiling). ``clean_bounds``: (P+1,) int32 row
    offsets of each partition's segment inside ``gpts`` (cumsum of the
    cached per-partition survivor counts — dirty partitions' segments are
    simply skipped). ``active`` bounds the dirty slices (bucket of the max
    dirty count); ``clean_active`` bounds the clean slices (bucket of the
    max clean segment width) — both slices write their full static width at
    the running offset and advance by the true width, each garbage tail
    overwritten by the next write (the gather trick
    ``global_merge_stats_device`` documents). ``gpts`` capacity must be >=
    g + clean_active so the clean ``dynamic_slice`` never clamps backward
    (the caller pads the cached points buffer to 2*next_pow2(g)).

    Returns (union, keep, stats) with the same shapes/semantics as the full
    merge so the caller's sync/points paths are shared."""
    from skyline_tpu.ops.dispatch import skyline_mask_auto

    P, cap, d = sky.shape
    scratch = union_cap + max(active, clean_active)
    u = jnp.full((scratch, d), jnp.inf, dtype=sky.dtype)
    uo = jnp.zeros((scratch,), dtype=jnp.int32)
    off = jnp.zeros((), jnp.int32)
    zero = jnp.zeros((), jnp.int32)
    for p in range(P):  # static unroll; P is small
        if dirty[p]:
            sl = lax.slice(sky, (p, 0, 0), (p + 1, active, d)).reshape(
                active, d
            )
            u = lax.dynamic_update_slice(u, sl, (off, zero))
            uo = lax.dynamic_update_slice(
                uo, jnp.full((active,), p, jnp.int32), (off,)
            )
            off = off + counts[p].astype(jnp.int32)
        else:
            lo = clean_bounds[p]
            w = clean_bounds[p + 1] - lo
            sl = lax.dynamic_slice(gpts, (lo, zero), (clean_active, d))
            # unlike ``sky`` prefixes, rows past this segment are NOT +inf
            # padding — they are the NEXT partitions' cached survivors — so
            # the static-width tail must be masked out before the write (a
            # shorter next write would otherwise leave live duplicates)
            sl = jnp.where(
                jnp.arange(clean_active)[:, None] < w, sl, jnp.inf
            )
            u = lax.dynamic_update_slice(u, sl, (off, zero))
            uo = lax.dynamic_update_slice(
                uo, jnp.full((clean_active,), p, jnp.int32), (off,)
            )
            off = off + w
    u = lax.slice(u, (0, 0), (union_cap, d))
    uo = lax.slice(uo, (0,), (union_cap,))
    uv = jnp.arange(union_cap) < off
    keep = skyline_mask_auto(u, uv)
    surv = jax.ops.segment_sum(keep.astype(jnp.int32), uo, num_segments=P)
    g = keep.sum(dtype=jnp.int32)
    stats = jnp.concatenate([counts.astype(jnp.int32), surv, g[None]])
    return u, keep, stats


# --- Pruned tournament-tree global merge -----------------------------------
#
# The flat ``global_merge_stats_device`` pays one O(U²) dominance pass over
# the full union. The tree path instead (1) drops whole partitions via a
# host-side witness prefilter over tiny device summaries, then (2) merges the
# survivors pairwise up a binary tree — each level's pair merge prunes both
# sides, so the next level's quadratic kernel runs on a halved, already-
# thinned candidate set. Every primitive below preserves the flat path's
# survivor ORDER (ascending partition id, storage row within a partition —
# the order the flat gather writes and ``compact``'s stable sort keeps), so
# the tree's output bytes are identical to the flat recompute's.
# Orchestration lives in ``stream.batched.PartitionSet``.


@functools.partial(jax.jit, static_argnames=("active",))
def partition_summaries_device(sky, counts, active: int):
    """Per-partition prune summaries, (P, 2d + 2) packed as
    ``[min_corner (d) | witness (d) | min_sum | max_sum]``.

    ``witness`` is an ACTUAL live point of the partition — the row with the
    smallest coordinate sum (the best single-dominator candidate under
    minimization). The host prefilter prunes partition B when some other
    partition's witness dominates B's min-corner: the witness is then <=
    every B point in all dims and strictly below in the witnessing dim
    (witness_k < min_corner_k <= b_k), i.e. it strictly dominates ALL of B.
    Empty partitions report +inf everywhere and can neither prune nor
    survive. Launched asynchronously at flush time (a (P, 2d+2) transfer);
    the merge path re-launches only if the epoch moved since."""
    P, cap, d = sky.shape
    s = lax.slice(sky, (0, 0, 0), (P, active, d))
    valid = jnp.arange(active)[None, :] < counts[:, None]
    sm = jnp.where(valid[:, :, None], s, jnp.inf)
    min_corner = jnp.min(sm, axis=1)
    sums = jnp.where(valid, jnp.sum(s, axis=2), jnp.inf)
    wi = jnp.argmin(sums, axis=1)
    witness = jnp.take_along_axis(
        s, jnp.broadcast_to(wi[:, None, None], (P, 1, d)), axis=1
    ).reshape(P, d)
    witness = jnp.where((counts > 0)[:, None], witness, jnp.inf)
    min_sum = jnp.min(sums, axis=1)
    max_sum = jnp.max(jnp.where(valid, jnp.sum(s, axis=2), -jnp.inf), axis=1)
    return jnp.concatenate(
        [min_corner, witness, min_sum[:, None], max_sum[:, None]], axis=1
    )


def prune_witness_mask(summaries: np.ndarray, alive: np.ndarray, d: int):
    """Host-side O(P²·d) witness prefilter over the
    ``partition_summaries_device`` output: partition B is pruned when some
    alive partition A's witness (a REAL live point, not a bound) strictly
    dominates B's min-corner — the witness is then <= every B point in all
    dims and strictly below in the witnessing dim
    (``witness_k < min_corner_B_k <= b_k``), i.e. it strictly dominates ALL
    of B. Strict dominance is a strict partial order, so simultaneous
    pruning is acyclic: every pruned partition's dominator chain ends at a
    surviving partition's witness, and at least one alive partition always
    survives — dropping pruned partitions leaves the skyline byte-identical.

    Returns ``(pruned (P,) bool, witness_of (P,) int64)`` where
    ``witness_of[b]`` is the lowest-pid alive partition whose witness first
    certified b's prune (-1 when unpruned) — the per-partition witness
    REASON the EXPLAIN plane records. The mask is exactly the one
    ``PartitionSet._prune_mask`` historically computed inline; the reasons
    are free (one extra vector write per witnessing partition).
    """
    P = summaries.shape[0]
    mins = summaries[:, :d]
    wit = summaries[:, d : 2 * d]
    pruned = np.zeros(P, dtype=bool)
    witness_of = np.full(P, -1, dtype=np.int64)
    for a in np.flatnonzero(alive):
        w = wit[a]
        if not np.all(np.isfinite(w)):
            continue  # empty partition: +inf witness prunes nothing
        dom = np.all(w[None, :] <= mins, axis=1) & np.any(
            w[None, :] < mins, axis=1
        )
        dom[a] = False  # a witness never beats its own min-corner
        dom &= alive
        witness_of[dom & ~pruned] = a
        pruned |= dom
    return pruned, witness_of


# Quantized-grid flush prefilter (ISSUE 5 stage 1). GRID_BINS boundary
# steps per dimension; GRID_REPS representative skyline rows per partition.
# The summary is tiny — (P, BINS+1, d) f32 boundaries + (P, REPS, d) int32
# cell codes — so the flush-tail transfer is a few KB against the multi-MB
# skylines it summarizes.
GRID_BINS = 32
GRID_REPS = 64


@functools.partial(jax.jit, static_argnames=("active",))
def grid_summary_device(sky, counts, active: int):
    """Per-partition quantized grid summary for the flush prefilter:
    ``(bounds (P, GRID_BINS+1, d) f32, ux (P, R, d) int32)`` with
    R = min(active, GRID_REPS).

    ``bounds[p, :, k]`` is an explicit ascending boundary ladder
    ``lo + i*step`` over dimension ``k``'s finite live range — shipped to
    the host verbatim, so host and device quantize against the SAME f32
    values (no cross-platform arithmetic-identity assumptions). ``ux`` are
    the representatives' cell codes: ``ux = #(bounds < x)``, the smallest
    index with ``x <= bounds[ux]``. The host codes an incoming row y as
    ``vy = #(bounds <= y) - 1`` (largest index with ``bounds[vy] <= y``)
    and drops y iff ``ux < vy`` in EVERY dim: then
    ``x <= bounds[ux] < bounds[vy] <= y`` strictly per-dim (the host
    validates the ladder is strictly increasing and disables dims where
    f32 rounding collapsed it), i.e. the representative — an actual live
    skyline row — strictly dominates y, so the exact merge would drop y
    too (stage-1 soundness, RUNBOOK §2g).

    Representatives are the first R rows of the live prefix (sum-sorted
    under the lazy/SFS policies, insertion-ordered under incremental —
    soundness never depends on which rows are picked). Non-finite or
    out-of-count representative rows are masked to code GRID_BINS+1, which
    can never certify (vy <= GRID_BINS). Empty partitions produce NaN
    ladders that fail host validation — zero drops, conservative."""
    P, cap, d = sky.shape
    s = lax.slice(sky, (0, 0, 0), (P, active, d))
    valid = jnp.arange(active)[None, :] < counts[:, None]
    finite = jnp.isfinite(s) & valid[:, :, None]
    lo = jnp.min(jnp.where(finite, s, jnp.inf), axis=1)  # (P, d)
    hi = jnp.max(jnp.where(finite, s, -jnp.inf), axis=1)
    # step > 0 even for degenerate (single-value) dims, so the ladder is
    # strictly increasing whenever lo is finite and the step survives f32
    # addition (the host re-checks that)
    step = jnp.maximum(
        (hi - lo) / GRID_BINS, jnp.maximum(jnp.abs(lo), 1.0) * 1e-6
    )
    ladder = jnp.arange(GRID_BINS + 1, dtype=s.dtype)
    bounds = lo[:, None, :] + ladder[None, :, None] * step[:, None, :]
    r = min(active, GRID_REPS)
    reps = lax.slice(s, (0, 0, 0), (P, r, d))
    rep_ok = (jnp.arange(r)[None, :] < counts[:, None]) & jnp.all(
        jnp.isfinite(reps), axis=2
    )
    ux = jnp.sum(
        bounds[:, None, :, :] < reps[:, :, None, :], axis=2
    ).astype(jnp.int32)
    ux = jnp.where(rep_ok[:, :, None], ux, GRID_BINS + 1)
    return bounds, ux


@functools.partial(jax.jit, static_argnames=("p", "width"))
def extract_sky_leaf(sky, counts, p: int, width: int):
    """One partition's live prefix as a tree leaf: (vals (width, d),
    pids (width,), count). ``width`` must cover the partition's count (the
    caller buckets its count upper bound); rows >= count are +inf padding by
    the storage invariant. Static (p, width) keeps the executable set
    bounded by P x capacity buckets."""
    P, cap, d = sky.shape
    vals = lax.slice(sky, (p, 0, 0), (p + 1, width, d)).reshape(width, d)
    pids = jnp.full((width,), p, jnp.int32)
    return vals, pids, counts[p].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("p", "width"))
def extract_cached_leaf(gpts, lo, w, p: int, width: int):
    """A CLEAN partition's cached global-survivor segment as a tree leaf for
    the delta merge: rows [lo, lo+w) of the cached points buffer. The static
    ``width`` slice is masked past the true width ``w`` — rows beyond the
    segment are the NEXT partitions' cached survivors, not padding (the same
    hazard ``global_merge_delta_device`` documents). ``gpts`` capacity must
    be >= lo + width so the dynamic_slice never clamps backward (the cache
    pads to 2*next_pow2(g); width <= next_pow2(g) and lo <= g)."""
    d = gpts.shape[1]
    zero = jnp.zeros((), jnp.int32)
    sl = lax.dynamic_slice(gpts, (lo, zero), (width, d))
    sl = jnp.where(jnp.arange(width)[:, None] < w, sl, jnp.inf)
    pids = jnp.full((width,), p, jnp.int32)
    return sl, pids, w.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("out_cap",))
def tree_pair_merge(a, apids, acnt, b, bpids, bcnt, out_cap: int):
    """Merge two tree nodes — each already a skyline (mutually
    non-dominated) — and compact survivors in [a-order, b-order].

    Exactness without a self-prune pass: if a b-point y dominated an
    a-point x while y itself were dominated by some a-point w, transitivity
    would give w dominates x — impossible inside a skyline. So any b-point
    that dominates an a-point necessarily survives pass one, and checking a
    against only SURVIVING b-points (pass two) is exact; symmetrically the
    full valid a set is a correct dominator set for b. Two rectangular
    passes instead of ``_merge_step_core``'s three.

    Order: stable compaction of [a | b]. With leaves fed in ascending
    partition id, every level preserves (pid, storage-row) order, so the
    root's bytes equal the flat merge's compacted output. ``out_cap`` must
    be >= acnt + bcnt (callers bucket the summed upper bounds). Partition
    ids ride along for the root's per-partition survivor stats."""
    from skyline_tpu.ops.block_skyline import dominated_by_blocked
    from skyline_tpu.ops.dispatch import on_tpu
    from skyline_tpu.ops.dominance import compact_tagged

    wa, d = a.shape
    wb = b.shape[0]
    av = jnp.arange(wa) < acnt
    bv = jnp.arange(wb) < bcnt
    if on_tpu():
        from skyline_tpu.ops.pallas_dominance import dominated_by_pallas

        interp = _pallas_interpret()
        at, bt = a.T, b.T
        keep_b = bv & ~dominated_by_pallas(at, av, bt, interpret=interp)
        keep_a = av & ~dominated_by_pallas(bt, keep_b, at, interpret=interp)
    else:
        # chunk the dominator set so the dense tile stays ~256 MB; victim
        # validity tightens the sum-bound chunk skip (invalid victims may
        # then read undominated — masked by av/bv below)
        blk = max(256, min(8192, (1 << 28) // max(wb, 1)))
        keep_b = bv & ~dominated_by_blocked(
            b, a, x_valid=av, block=blk, y_valid=bv
        )
        blk = max(256, min(8192, (1 << 28) // max(wa, 1)))
        keep_a = av & ~dominated_by_blocked(
            a, b, x_valid=keep_b, block=blk, y_valid=av
        )
    x = jnp.concatenate([a, b], axis=0)
    t = jnp.concatenate([apids, bpids], axis=0)
    keep = jnp.concatenate([keep_a, keep_b], axis=0)
    vals, pids, _, cnt = compact_tagged(x, t, keep, out_cap)
    return vals, pids, cnt.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_partitions",))
def tree_stats_device(counts, root_pids, root_cnt, num_partitions: int):
    """Pack the tree root into the flat merge's stats layout
    ``[counts (P,) | survivors_per_partition (P,) | global_count]`` so the
    caller's sync / cache paths are shared. Per-partition survivors fall out
    of a segment-sum over the partition ids the pair merges threaded
    through; pruned and empty partitions report 0."""
    w = root_pids.shape[0]
    valid = jnp.arange(w) < root_cnt
    surv = jax.ops.segment_sum(
        valid.astype(jnp.int32),
        jnp.where(valid, root_pids, 0),
        num_segments=num_partitions,
    )
    return jnp.concatenate(
        [counts.astype(jnp.int32), surv, root_cnt.astype(jnp.int32)[None]]
    )


@functools.partial(jax.jit, static_argnames=("out_cap",))
def tree_points_device(vals, out_cap: int):
    """Resize the tree root's value buffer to the points transfer / cache
    capacity. Rows past the survivor count are already +inf (compact
    invariant, or the sky storage invariant for a single-leaf root), so a
    plain slice / pad reproduces ``global_points_device``'s bytes."""
    w, d = vals.shape
    if out_cap <= w:
        return lax.slice(vals, (0, 0), (out_cap, d))
    return jnp.concatenate(
        [vals, jnp.full((out_cap - w, d), jnp.inf, vals.dtype)], axis=0
    )


def _shard_map_vmapped(mesh, axis, fn, n_in: int, n_out: int, donate=()):
    """``jit(shard_map(vmap(fn)))`` over the partition axis — the one shared
    wrapper for every meshed per-partition kernel. All inputs and outputs
    are partition-sharded; the per-partition kernels have no cross-partition
    data flow, so no collectives appear and each device runs its resident
    partitions only. Needed explicitly (vs GSPMD) because ``pallas_call``
    has no auto-partitioning rule."""
    from jax.sharding import PartitionSpec

    spec = PartitionSpec(axis)
    sharded = shard_map(
        jax.vmap(fn),
        mesh=mesh,
        in_specs=(spec,) * n_in,
        out_specs=(spec,) * n_out,
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=donate)


@functools.lru_cache(maxsize=None)
def meshed_merge_step(mesh, axis: str, use_pallas: bool, out_cap: int, mp: bool = False):
    """Batched merge wrapped in ``shard_map`` over the partition axis
    (see ``_shard_map_vmapped``). Cached per (mesh, axis, kernel, capacity
    bucket, mixed-precision flag) so steady-state flushes reuse one
    executable. Returns 4 outputs — the per-partition bf16-resolved counts
    ride along (all-zero when ``mp=False``)."""
    core = _merge_step_pallas_core if use_pallas else _merge_step_core
    return _shard_map_vmapped(
        mesh, axis, lambda s, sv, b, bv: core(s, sv, b, bv, out_cap, mp), 4, 4
    )


@functools.lru_cache(maxsize=None)
def meshed_sfs_round(mesh, axis: str, use_pallas: bool, active: int, mp: bool = False):
    """``sfs_round`` wrapped in ``shard_map`` over the partition axis (see
    ``_shard_map_vmapped``) — the lazy policy's meshed flush. Cached per
    (mesh, axis, kernel, active bucket, mixed-precision flag); donates the
    sky buffer like the single-device jit. Returns 3 outputs — per-partition
    bf16-resolved counts third (all-zero when ``mp=False``)."""
    from skyline_tpu.ops.sfs import pallas_interpret, sfs_round_core

    interp = pallas_interpret()
    return _shard_map_vmapped(
        mesh,
        axis,
        lambda s, c, b, bv: sfs_round_core(
            s, c, b, bv, active, use_pallas, interp, mp
        ),
        4,
        3,
        donate=(0,),
    )


@functools.lru_cache(maxsize=None)
def meshed_sfs_cleanup(mesh, axis: str, use_pallas: bool, old_active: int, active: int):
    """``sfs_cleanup`` wrapped in ``shard_map`` over the partition axis —
    the old-vs-new prune after SFS rounds on non-empty initial state, per
    resident partition (no collectives)."""
    from skyline_tpu.ops.sfs import pallas_interpret, sfs_cleanup_core

    interp = pallas_interpret()
    return _shard_map_vmapped(
        mesh,
        axis,
        lambda s, c, oc: sfs_cleanup_core(
            s, c, oc, old_active, active, use_pallas, interp
        ),
        3,
        2,
        donate=(0,),
    )
