"""Per-partition streaming window state with incremental skyline maintenance.

Mirrors the state model of the reference's ``SkylineLocalProcessor``
(FlinkSkyline.java:214-445): a bounded input buffer that flushes into an
incrementally-maintained local skyline, a max-seen record id for the query
barrier, a first-arrival timestamp, and accumulated processing time. The BNL
buffer-vs-skyline loop (:417-444) becomes one jitted masked dominance pass
per flush.

TPU residency: the running skyline lives on device as a padded
power-of-two-capacity buffer; each flush ships only the new micro-batch up
and one scalar (the survivor count) back, so steady-state streaming never
transfers the skyline itself. Capacities are bucketed so XLA compiles a
bounded number of executables.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from skyline_tpu.ops.dominance import compact, dominated_by, skyline_mask
from skyline_tpu.ops.dispatch import on_tpu
from skyline_tpu.utils.buckets import next_pow2

# Reference flushes its input buffer at 5000 tuples (BUFFER_SIZE,
# FlinkSkyline.java:232); we default to the nearest power of two.
DEFAULT_BUFFER_SIZE = 4096

# Minimum buffer capacity: one full Pallas victim tile (COL_TILE), so every
# capacity bucket satisfies the kernel's tile-multiple constraints.
_MIN_CAP = 1024


def _next_pow2(n: int) -> int:
    return next_pow2(n, min_cap=_MIN_CAP)


def _merge_step_core(sky, sky_valid, batch, batch_valid, out_cap: int):
    """One windowed-BNL step: merge a new batch into a running skyline and
    compact survivors into a fresh ``out_cap`` buffer.

    sky is assumed to already be a skyline (mutually non-dominated):

    - a batch point survives iff it is not dominated within its batch nor by
      the running skyline (dominated dominators prune correctly by
      transitivity, so the full sky buffer is a valid dominator set);
    - a sky point survives iff no *surviving* batch point dominates it
      (a dropped batch dominator's own dominator chain ends at a kept point
      that also dominates the victim, so kept batch points suffice).

    Returns (values (out_cap, d), valid (out_cap,), count). ``out_cap`` must
    be >= current survivor count + batch rows, so overflow cannot occur.
    """
    batch_local = skyline_mask(batch, batch_valid)
    keep_batch = batch_local & ~dominated_by(batch, sky, x_valid=sky_valid)
    keep_sky = sky_valid & ~dominated_by(sky, batch, x_valid=keep_batch)
    x = jnp.concatenate([sky, batch], axis=0)
    keep = jnp.concatenate([keep_sky, keep_batch], axis=0)
    return compact(x, keep, out_cap)


def _merge_step_pallas_core(sky, sky_valid, batch, batch_valid, out_cap: int):
    """TPU fast path of ``_merge_step_core``: the three dominance passes run
    in the Pallas VMEM-tiled kernel (same mask logic, same transitivity
    arguments). Requires sky/batch capacities to be tile multiples — the
    _MIN_CAP floor and power-of-two bucketing guarantee that."""
    from skyline_tpu.ops.pallas_dominance import dominated_by_pallas

    sky_t = sky.T
    batch_t = batch.T
    batch_local = batch_valid & ~dominated_by_pallas(batch_t, batch_valid, batch_t)
    keep_batch = batch_local & ~dominated_by_pallas(sky_t, sky_valid, batch_t)
    keep_sky = sky_valid & ~dominated_by_pallas(batch_t, keep_batch, sky_t)
    x = jnp.concatenate([sky, batch], axis=0)
    keep = jnp.concatenate([keep_sky, keep_batch], axis=0)
    return compact(x, keep, out_cap)


_merge_step = jax.jit(_merge_step_core, static_argnames=("out_cap",))
_merge_step_pallas = jax.jit(_merge_step_pallas_core, static_argnames=("out_cap",))

# Batched variants: merge P partitions' flushes in ONE device launch
# (sky (P, cap, d), batch (P, B, d) -> (P, out_cap, d)). Streaming through a
# dispatch-latency-bound link (the remote-TPU tunnel) is launch-count-bound,
# so collapsing P per-partition merges into one vmapped executable is the
# difference between ~P*3 launches per micro-batch and ~1.
_merge_step_batched = jax.jit(
    jax.vmap(_merge_step_core, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("out_cap",),
)
_merge_step_pallas_batched = jax.jit(
    jax.vmap(_merge_step_pallas_core, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("out_cap",),
)


class PartitionState:
    """Host-side handle for one logical partition (of ``2 x parallelism``);
    the skyline buffer itself is device-resident."""

    def __init__(self, partition_id: int, dims: int, buffer_size: int = DEFAULT_BUFFER_SIZE):
        self.partition_id = partition_id
        self.dims = dims
        self.buffer_size = buffer_size
        # pending micro-batch rows awaiting a flush (list of (k, d) arrays)
        self._pending: list[np.ndarray] = []
        self._pending_rows = 0
        # running local skyline: device buffer padded to a power-of-two cap
        self._cap = _MIN_CAP
        self.sky = jnp.full((self._cap, dims), jnp.inf, dtype=jnp.float32)
        self.sky_valid = jnp.zeros((self._cap,), dtype=bool)
        # survivor count: device scalar (exact, read lazily) + host upper
        # bound (drives capacity growth WITHOUT a per-flush sync, so flushes
        # dispatch asynchronously and partitions pipeline on the device)
        self._count_dev = jnp.zeros((), dtype=jnp.int32)
        self._count_ub = 0
        # barrier + metrics bookkeeping (FlinkSkyline.java:243-248, 267)
        self.max_seen_id: int = -1
        self.start_time_ms: float | None = None
        self.processing_ns: int = 0
        self.records_seen: int = 0

    # -- ingest -----------------------------------------------------------

    def add_batch(self, values: np.ndarray, max_id: int, now_ms: float) -> None:
        """Buffer a routed micro-batch; flush once the buffer threshold is hit."""
        n = values.shape[0]
        if n == 0:
            return
        if self.start_time_ms is None:
            self.start_time_ms = now_ms
        self.max_seen_id = max(self.max_seen_id, int(max_id))
        self.records_seen += n
        self._pending.append(values)
        self._pending_rows += n
        if self._pending_rows >= self.buffer_size:
            self.flush()

    def flush(self) -> None:
        """Merge all pending rows into the running skyline (the processBuffer
        equivalent, FlinkSkyline.java:417-444).

        Batches are always padded to exactly ``buffer_size`` rows and the
        output capacity only changes on power-of-two growth, so XLA compiles
        at most two executables per capacity bucket over the engine's
        lifetime (shape-bucketing discipline — dynamic sizes live on host).
        """
        if self._pending_rows == 0:
            return
        t0 = time.perf_counter_ns()
        rows = (
            self._pending[0]
            if len(self._pending) == 1
            else np.concatenate(self._pending, axis=0)
        )
        self._pending = []
        self._pending_rows = 0

        # round the flush batch up to a whole Pallas victim tile so the TPU
        # fast path stays available for ANY buffer_size (e.g. the reference's
        # 5000); the pad rows are synthesized below either way
        B = -(-max(self.buffer_size, _MIN_CAP) // _MIN_CAP) * _MIN_CAP
        for lo in range(0, rows.shape[0], B):
            batch = rows[lo : lo + B]
            bpad = np.full((B, self.dims), np.inf, dtype=np.float32)
            bpad[: batch.shape[0]] = batch
            bvalid = np.arange(B) < batch.shape[0]
            # capacity growth from the host-side upper bound: may grow a
            # bucket early when pruning was strong, never too late
            out_cap = max(
                self._cap, _next_pow2(self._count_ub + batch.shape[0])
            )
            if out_cap > self._cap:
                # about to grow: tighten the bound with ONE real count sync
                # (growth events are log-bounded, so steady-state flushes
                # stay fully async; without this the bound accumulates every
                # ingested row and capacity tracks stream size, not skyline
                # size)
                self._count_ub = self.sky_count
                out_cap = max(
                    self._cap, _next_pow2(self._count_ub + batch.shape[0])
                )
            # B is a _MIN_CAP multiple by construction and capacities are
            # powers of two >= _MIN_CAP, so tile constraints always hold
            merge = _merge_step_pallas if on_tpu() else _merge_step
            self.sky, self.sky_valid, self._count_dev = merge(
                self.sky,
                self.sky_valid,
                jnp.asarray(bpad),
                jnp.asarray(bvalid),
                out_cap,
            )
            self._cap = out_cap
            self._count_ub = min(out_cap, self._count_ub + batch.shape[0])
        self.processing_ns += time.perf_counter_ns() - t0

    # -- query ------------------------------------------------------------

    @property
    def sky_count(self) -> int:
        """Exact survivor count (forces one device sync; prefer at query /
        checkpoint boundaries only)."""
        count = int(self._count_dev)
        self._count_ub = count
        return count

    def snapshot(self) -> np.ndarray:
        """Flush pending rows and return the local skyline (k, d) on host —
        the processQuery path (FlinkSkyline.java:367-403)."""
        t0 = time.perf_counter_ns()
        self.flush()
        count = self.sky_count  # sync first, then transfer only count rows
        out = np.asarray(self.sky[:count])
        # the sync here absorbs all of this partition's in-flight flush work
        self.processing_ns += time.perf_counter_ns() - t0
        return out

    def skyline_host(self) -> np.ndarray:
        """Current device skyline pulled to host WITHOUT flushing pending
        rows (checkpointing reads state as-is)."""
        count = self.sky_count
        return np.asarray(self.sky[:count])

    @property
    def processing_ms(self) -> float:
        return self.processing_ns / 1e6
