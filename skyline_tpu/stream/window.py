"""Incremental windowed-merge kernels for streaming skyline maintenance.

The merge step is the flush-time replacement for the reference's BNL
buffer-vs-skyline loop (``SkylineLocalProcessor.processBuffer``,
FlinkSkyline.java:417-444): one jitted masked dominance pass folds a new
micro-batch into a running skyline buffer. The stateful owner of these
kernels is ``skyline_tpu.stream.batched.PartitionSet``, which stacks all
logical partitions and calls the *batched* variants — one device launch per
flush for the whole set.

TPU residency: running skylines live on device as padded
power-of-two-capacity buffers; each flush ships only the new micro-batch up
and survivor counts back, so steady-state streaming never transfers the
skyline itself. Capacities are bucketed so XLA compiles a bounded number of
executables.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from skyline_tpu.ops.dominance import compact, dominated_by, skyline_mask
from skyline_tpu.utils.buckets import next_pow2

# Reference flushes its input buffer at 5000 tuples (BUFFER_SIZE,
# FlinkSkyline.java:232); we default to the nearest power of two.
DEFAULT_BUFFER_SIZE = 4096

# Minimum buffer capacity: one full Pallas victim tile (COL_TILE), so every
# capacity bucket satisfies the kernel's tile-multiple constraints.
_MIN_CAP = 1024


def _next_pow2(n: int) -> int:
    return next_pow2(n, min_cap=_MIN_CAP)


def _merge_step_core(sky, sky_valid, batch, batch_valid, out_cap: int):
    """One windowed-BNL step: merge a new batch into a running skyline and
    compact survivors into a fresh ``out_cap`` buffer.

    sky is assumed to already be a skyline (mutually non-dominated):

    - a batch point survives iff it is not dominated within its batch nor by
      the running skyline (dominated dominators prune correctly by
      transitivity, so the full sky buffer is a valid dominator set);
    - a sky point survives iff no *surviving* batch point dominates it
      (a dropped batch dominator's own dominator chain ends at a kept point
      that also dominates the victim, so kept batch points suffice).

    Returns (values (out_cap, d), valid (out_cap,), count). ``out_cap`` must
    be >= current survivor count + batch rows, so overflow cannot occur.
    """
    batch_local = skyline_mask(batch, batch_valid)
    keep_batch = batch_local & ~dominated_by(batch, sky, x_valid=sky_valid)
    keep_sky = sky_valid & ~dominated_by(sky, batch, x_valid=keep_batch)
    x = jnp.concatenate([sky, batch], axis=0)
    keep = jnp.concatenate([keep_sky, keep_batch], axis=0)
    return compact(x, keep, out_cap)


def _pallas_interpret() -> bool:
    """Read lazily (at trace time, not import time): set
    ``SKYLINE_PALLAS_INTERPRET=1`` to run the Pallas merge in interpret mode
    on CPU — how ``dryrun_multichip`` validates the shard_map-of-pallas_call
    lowering without TPU hardware. Evaluated when a merge step first traces;
    already-compiled executables are unaffected by later env changes."""
    return os.environ.get("SKYLINE_PALLAS_INTERPRET", "") == "1"


def _merge_step_pallas_core(sky, sky_valid, batch, batch_valid, out_cap: int):
    """TPU fast path of ``_merge_step_core``: the three dominance passes run
    in the Pallas VMEM-tiled kernel (same mask logic, same transitivity
    arguments). Requires sky/batch capacities to be tile multiples — the
    _MIN_CAP floor and power-of-two bucketing guarantee that."""
    from skyline_tpu.ops.pallas_dominance import dominated_by_pallas

    interp = _pallas_interpret()
    sky_t = sky.T
    batch_t = batch.T
    batch_local = batch_valid & ~dominated_by_pallas(
        batch_t, batch_valid, batch_t, interpret=interp
    )
    keep_batch = batch_local & ~dominated_by_pallas(
        sky_t, sky_valid, batch_t, interpret=interp
    )
    keep_sky = sky_valid & ~dominated_by_pallas(
        batch_t, keep_batch, sky_t, interpret=interp
    )
    x = jnp.concatenate([sky, batch], axis=0)
    keep = jnp.concatenate([keep_sky, keep_batch], axis=0)
    return compact(x, keep, out_cap)


# Batched merge: P partitions' flushes in ONE device launch
# (sky (P, cap, d), batch (P, B, d) -> (P, out_cap, d)). Streaming through a
# dispatch-latency-bound link (the remote-TPU tunnel) is launch-count-bound,
# so collapsing P per-partition merges into one vmapped executable is the
# difference between ~P*3 launches per micro-batch and ~1.
_merge_step_batched = jax.jit(
    jax.vmap(_merge_step_core, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("out_cap",),
)
_merge_step_pallas_batched = jax.jit(
    jax.vmap(_merge_step_pallas_core, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("out_cap",),
)


@functools.lru_cache(maxsize=None)
def meshed_merge_step(mesh, axis: str, use_pallas: bool, out_cap: int):
    """Batched merge wrapped in ``shard_map`` over the partition axis.

    With partition state sharded ``(P, cap, d)`` across a mesh, the plain
    jitted vmap relies on GSPMD auto-partitioning — fine for the XLA merge,
    but ``pallas_call`` has no partitioning rule, so the Pallas variant must
    be explicitly SPMD: each device runs the vmapped merge on its resident
    partitions (the merge has no cross-partition data flow, so no
    collectives are needed). Cached per (mesh, axis, kernel, capacity
    bucket) so steady-state flushes reuse one executable.
    """
    from jax.sharding import PartitionSpec

    core = _merge_step_pallas_core if use_pallas else _merge_step_core
    vm = jax.vmap(lambda s, sv, b, bv: core(s, sv, b, bv, out_cap))
    spec = PartitionSpec(axis)
    sharded = jax.shard_map(
        vm,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(spec, spec, spec),
        check_vma=False,
    )
    return jax.jit(sharded)
