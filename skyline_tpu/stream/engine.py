"""The streaming skyline engine: partition routing, query barrier, global merge.

One object replaces the reference's whole Flink job graph
(FlinkSkyline.java:61-186): the ``keyBy`` shuffle becomes vectorized
host-side partition-id routing; ``SkylineLocalProcessor`` becomes
``PartitionSet`` (all logical partitions stacked on device, one batched
merge launch per flush) addressed through per-partition ``PartitionView``
facades; the query broadcast flatMap (:145-157) becomes a loop over
partitions; and ``GlobalSkylineAggregator`` (:460-660) becomes a device-side
union skyline with the same countdown-latch semantics, timing decomposition
and optimality metric.

Record-id barrier semantics (SURVEY.md §3.3): a trigger ``"qid,N"`` executes
on a partition only once that partition has seen a record id >= N — or
immediately if the partition has never seen data (``max_seen_id == -1``,
FlinkSkyline.java:351). Pending triggers are re-evaluated whenever new data
reaches the partition (:298-315).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


import numpy as np

from skyline_tpu.ops.dispatch import query_overlap_enabled, skyline_keep_np
from skyline_tpu.parallel.partitioners import partition_ids_np
from skyline_tpu.bridge.wire import parse_trigger
from skyline_tpu.stream.batched import PartitionSet, PartitionView
from skyline_tpu.stream.window import DEFAULT_BUFFER_SIZE


@dataclass
class EngineConfig:
    """Engine flags, defaults matching the reference job's
    (FlinkSkyline.java:62-76): parallelism=4 → numPartitions=8, algo
    mr-angle, domain 1000, dims 2."""

    parallelism: int = 4
    algo: str = "mr-angle"
    domain_max: float = 1000.0
    dims: int = 2
    buffer_size: int = DEFAULT_BUFFER_SIZE
    emit_skyline_points: bool = False
    # failure detection: a query whose barrier never clears on some partition
    # finalizes as a PARTIAL result after this long (0 = wait forever, the
    # reference's behavior — its countdown latch hangs if a partition never
    # reports, SURVEY.md §5)
    query_timeout_ms: float = 0.0
    # the reference's GridDominanceFilter (J10) — commented out there "for
    # safety" over barrier-deadlock fears (FlinkSkyline.java:120-124,
    # 717-734) — implemented here SAFELY: a tuple with every coordinate
    # >= domain/2 (and one >) is dropped pre-routing, but only once a
    # witness tuple with every coordinate <= domain/2 has been seen (the
    # witness dominates-or-equals the midpoint, which by transitivity
    # dominates the dropped tuple). Barriers are unaffected: max-seen-id
    # advances before filtering.
    grid_prefilter: bool = False
    # pre-size per-partition skyline buffers (0 = grow on demand); see
    # PartitionSet.initial_capacity
    initial_capacity: int = 0
    # "incremental": merge pending rows at the buffer_size cadence (the
    # reference's processBuffer model); "lazy": accumulate and compute at
    # query time via append-only SFS rounds — far less total work for
    # tumbling-window-then-query streams (see stream/batched.py); "overlap":
    # the lazy machinery flushed every ``overlap_rows`` so device append
    # rounds run concurrently with transport/parse of the next chunk (the
    # Flink-style source/operator overlap). Identical results all three
    # ways; under a mesh the lazy rounds run shard_map SPMD.
    flush_policy: str = "incremental"
    # rows accumulated between automatic flushes under flush_policy="overlap"
    overlap_rows: int = 262144
    # expected rows per window (0 = unknown): pre-sizes the device-ingest
    # accumulation buffer so steady-state windows never grow it (each
    # growth is a reallocation + a fresh ingest executable per capacity)
    window_capacity: int = 0
    # "auto": route + sort + SFS block slicing on device when single-device
    # lazy/overlap without grid_prefilter (stream/device_window.py); "host":
    # numpy routing in process_records; "device": force the device path
    # (errors if unsupported by the configuration)
    ingest: str = "auto"

    @property
    def num_partitions(self) -> int:
        # 2x over-partitioning for skew tolerance (FlinkSkyline.java:74-76)
        return 2 * self.parallelism


def echo_record_count(payload: str):
    """The reference echoes the payload's second field as record_count
    (FlinkSkyline.java:640-642) — emitting the literal string, which for a
    count-less payload would produce invalid JSON (unquoted `unknown`); we
    quote it instead. Shared by both engine modes."""
    parts = payload.split(",")
    if len(parts) > 1 and parts[1].strip().lstrip("-").isdigit():
        return int(parts[1])
    return "unknown"


def optimality_mean(survivors, sizes, num_partitions: int) -> float:
    """Mean over ALL partitions of survivors_i / localSize_i, empty
    partitions contributing 0 (FlinkSkyline.java:592-608)."""
    ratios = 0.0
    for surv, size in zip(survivors, sizes):
        if size > 0:
            ratios += surv / size
    return ratios / num_partitions


@dataclass
class _QueryState:
    """Aggregator state for one in-flight query (FlinkSkyline.java:490-495)."""

    qid: str
    payload: str
    required: int
    dispatch_ms: float
    partials: dict = field(default_factory=dict)  # pid -> (k, d) local skyline
    local_sizes: dict = field(default_factory=dict)
    start_times: dict = field(default_factory=dict)
    cpu_ms: dict = field(default_factory=dict)
    last_arrival_ms: float = 0.0
    # telemetry plane: trace id minted at trigger ingestion + the perf
    # clock at dispatch, so the end-to-end "query" span is recordable
    trace_id: str | None = None
    span_t0_ns: int = 0
    # EXPLAIN plane (telemetry/explain.py): the QueryPlan minted beside the
    # trace id; annotated along the merge path, finalized at result emission
    plan: object | None = None


class SkylineEngine:
    """Single-host streaming engine over ``num_partitions`` logical partitions.

    Usage: ``process_records`` / ``process_trigger`` as data and control
    planes; completed query results accumulate and are drained with
    ``poll_results`` (each result is a dict with the reference's JSON fields).
    """

    def __init__(self, config: EngineConfig, mesh=None, tracer=None, telemetry=None):
        """``mesh``: optional ``jax.sharding.Mesh`` — logical partitions are
        then sharded across its devices (local flushes run SPMD, one launch
        for the whole set) and the global merge runs as the sharded
        two-phase collective instead of a single-device kernel. ``None``
        (default) runs everything on one chip. The mesh is a runtime
        placement choice, not part of the query semantics, so it lives
        outside ``EngineConfig`` (results are device-count invariant —
        tests/test_mesh.py pins this).

        ``tracer``: optional ``metrics.tracing.Tracer`` — wires the
        per-phase breakdown (route / flush kernels / snapshot transfer /
        global merge) the reference surfaces as a product feature
        (SURVEY.md §5); ``None`` costs nothing.

        ``telemetry``: optional ``telemetry.Telemetry`` hub — adds latency
        histograms (ingest batch / global merge / query latency), a
        ``trace_id`` per query, and per-phase spans into the hub's bounded
        ring (exported via ``GET /trace`` / ``--trace-out``); ``None``
        (default) records nothing."""
        from skyline_tpu.metrics.tracing import NULL_TRACER

        self.config = config
        self.mesh = mesh
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.telemetry = telemetry
        # resolve the ingest path: device ingest moves routing/sort/block
        # slicing onto the accelerator (stream/device_window.py); it
        # requires single-device lazy/overlap and no grid prefilter (the
        # prefilter inspects raw values host-side)
        device_ok = (
            mesh is None
            and config.flush_policy in ("lazy", "overlap")
            and not config.grid_prefilter
        )
        if config.ingest == "device":
            if not device_ok:
                raise ValueError(
                    "ingest='device' requires single-device lazy/overlap "
                    "without grid_prefilter"
                )
            use_device = True
        elif config.ingest == "auto":
            from skyline_tpu.ops.dispatch import on_tpu

            use_device = device_ok and on_tpu()
        elif config.ingest == "host":
            use_device = False
        else:
            raise ValueError(f"unknown ingest mode {config.ingest!r}")
        # stacked device state: all partitions' skylines merge in ONE launch
        # per flush (see stream/batched.py); `partitions` are per-partition
        # facades over it
        self.pset = PartitionSet(
            config.num_partitions,
            config.dims,
            config.buffer_size,
            mesh=mesh,
            initial_capacity=config.initial_capacity,
            tracer=self.tracer,
            flush_policy=config.flush_policy,
            route=(config.algo, config.domain_max) if use_device else None,
            overlap_rows=config.overlap_rows,
            window_capacity=config.window_capacity,
            counters=telemetry.counters if telemetry is not None else None,
        )
        self.partitions = [
            PartitionView(self.pset, i) for i in range(config.num_partitions)
        ]
        self._pending_queries: dict[int, list[_QueryState]] = {
            i: [] for i in range(config.num_partitions)
        }
        self._inflight: dict[str, _QueryState] = {}
        self._results: list[dict] = []
        self.records_in = 0
        self.dropped = 0
        self.prefiltered = 0
        self._midpoint_witness = False  # grid_prefilter safety latch
        # overlapped query sync (SKYLINE_QUERY_OVERLAP): at most one global
        # merge in flight as (query, handle, now_ms, flush_wall_ms,
        # launch_ms) — launched at trigger time, harvested at the next
        # result drain / trigger / stats poll, or opportunistically while
        # ingesting once the stats bytes have landed
        self._inflight_merge: tuple | None = None
        # serving plane (serve/snapshot.py): when attached, every completed
        # global skyline publishes as an immutable versioned snapshot and
        # every ingest micro-batch advances its staleness counter
        self.snapshots = None
        # observability plane (ISSUE 8): freshness lineage + the per-kernel
        # dispatch profiler / flight recorder. All host-side — nothing here
        # touches the jitted byte-identity path. Without a hub the engine
        # still owns private instances so bench legs get the stats blocks.
        from skyline_tpu.ops.dispatch import (
            audit_enabled,
            explain_enabled,
            freshness_enabled,
            kernel_profile_enabled,
            workload_enabled,
        )
        from skyline_tpu.telemetry import (
            FreshnessTracker,
            KernelProfiler,
            WorkloadCharacterizer,
        )

        self.freshness = (
            FreshnessTracker(telemetry) if freshness_enabled() else None
        )
        if kernel_profile_enabled():
            self.profiler = (
                telemetry.profiler
                if telemetry is not None
                else KernelProfiler()
            )
        else:
            self.profiler = None
        self.pset.attach_observability(
            profiler=self.profiler,
            flight=telemetry.flight if telemetry is not None else None,
        )
        # EXPLAIN plane (ISSUE 9): one QueryPlan per trigger, landed in the
        # hub's bounded ring. The marks anchor the per-query attribution
        # windows — cascade counters and kernel dispatch counts since the
        # PREVIOUS plan finalized belong to the next query's window.
        self._explain_on = explain_enabled() and telemetry is not None
        self._explain_cascade_mark: dict = {}
        self._explain_kernel_mark: dict = {}
        if self._explain_on:
            # inc even when zero so the Prometheus series registers before
            # the first query, not after it
            telemetry.inc("explain.records", 0)
        # audit plane (ISSUE 10): sampled shadow verification of published
        # snapshots against the host oracle, plus the canary driver the
        # worker ticks from its idle loop. Post-publish and host-side only.
        self.auditor = None
        if telemetry is not None and audit_enabled():
            from skyline_tpu.audit import Auditor

            self.auditor = Auditor(self, telemetry)
            telemetry.inc("audit.checks", 0)
            telemetry.inc("audit.divergence", 0)
        # workload plane (ISSUE 13): streaming regime characterization fed
        # from the ingest path — per-dim quantile sketches, a correlation
        # estimate, and drift detection between consecutive epochs. All
        # host-side on a bounded deterministic sample; published skyline
        # bytes are untouched on/off (benchmarks/fleet.py pins this). Hung
        # off the hub so both HTTP surfaces serve the ``workload`` block.
        self.workload = None
        if workload_enabled():
            self.workload = WorkloadCharacterizer(
                config.dims,
                counters=telemetry.counters if telemetry is not None else None,
                flight=telemetry.flight if telemetry is not None else None,
            )
            if telemetry is not None:
                telemetry.workload = self.workload
        # dispatch-tuner plane (ISSUE 20): the closed-loop controller
        # over the declarative cascade table — consumes the workload
        # regime, profiler EMAs, and SLO burn; retunes table pins/knobs
        # with bounded per-epoch moves. Ticked from the query path (cheap
        # cadence check) and the worker idle loop; passive until a
        # workload epoch closes, so bytes and unit-scale behavior are
        # untouched by default.
        self.tuner = None
        from skyline_tpu.ops.dispatch import tuner_enabled

        if telemetry is not None and tuner_enabled():
            from skyline_tpu.telemetry.tuner import DispatchTuner

            self.tuner = DispatchTuner(
                telemetry=telemetry,
                workload=self.workload,
                profiler=self.profiler,
                flush_profiler=lambda: getattr(
                    self.pset, "_flush_prof", None
                ),
            )
            telemetry.tuner = self.tuner

    def attach_snapshots(self, store) -> None:
        """Publish completed global skylines to ``store`` (a
        ``serve.snapshot.SnapshotStore``). Costs nothing until attached;
        once attached, query answers materialize their points even when
        ``emit_skyline_points`` is off (the snapshot IS the read path)."""
        self.snapshots = store

    # -- data plane -------------------------------------------------------

    def process_records(
        self,
        ids: np.ndarray,
        values: np.ndarray,
        now_ms: float | None = None,
        event_ms=None,
    ) -> None:
        """Route a micro-batch of records to partitions and advance barriers.

        ids: (N,) int64 global record ids; values: (N, d) float32.
        ``event_ms`` (optional): producer event-time of this batch for the
        freshness lineage — a scalar or a (min, max) pair in epoch ms. The
        wire format carries no timestamps, so callers typically stamp the
        poll wall time (a processing-time proxy; RUNBOOK §2j).
        """
        tel = self.telemetry
        if tel is None:
            return self._process_records(ids, values, now_ms, event_ms)
        t0 = time.perf_counter_ns()
        try:
            return self._process_records(ids, values, now_ms, event_ms)
        finally:
            end = time.perf_counter_ns()
            tel.histogram("ingest_batch_ms").observe((end - t0) / 1e6)
            tel.spans.record(
                "ingest", t0, end, args={"rows": int(values.shape[0])}
            )

    def _process_records(
        self,
        ids: np.ndarray,
        values: np.ndarray,
        now_ms: float | None = None,
        event_ms=None,
    ) -> None:
        if values.shape[0] == 0:
            return
        if now_ms is None:
            now_ms = time.time() * 1000.0
        cfg = self.config
        self.records_in += values.shape[0]
        if self.workload is not None:
            # characterize BEFORE the ingest path forks (device routing vs
            # host routing vs grid prefilter) so every regime sees the same
            # raw stream; bounded stride-sample inside, never the full batch
            self.workload.observe(values)
        ev_hi = None
        if self.freshness is not None:
            # stamp the batch's event-time window; absent stamps fall back
            # to the wall clock (NOT the caller's now_ms — tests inject
            # synthetic clocks that would poison the lag histograms)
            if event_ms is None:
                ev_lo = ev_hi = time.time() * 1000.0
            elif isinstance(event_ms, (tuple, list)):
                ev_lo, ev_hi = float(event_ms[0]), float(event_ms[1])
            else:
                ev_lo = ev_hi = float(event_ms)
            self.freshness.on_ingest(ev_lo, ev_hi)
        if self.snapshots is not None:
            # the latest snapshot is now one ingest advance behind
            self.snapshots.note_ingest(int(ids.max()), event_ms=ev_hi)
        if self.pset.device_ingest:
            # routing + barrier stats on device; host bookkeeping syncs only
            # when a pending query needs its barrier re-evaluated
            with self.tracer.phase("ingest/devroute"):
                self.pset.ingest_chunk(ids, values, now_ms)
            if any(self._pending_queries.values()):
                self.pset.sync_ingest_bookkeeping()
                for p in range(cfg.num_partitions):
                    now_ms = self._recheck_pending(p, now_ms)
            self.pset.maybe_flush()
            self._note_flush()
            self._harvest_inflight(block=False)
            return
        with self.tracer.phase("partition_ids"):
            pids = partition_ids_np(
                values, cfg.algo, cfg.num_partitions, cfg.domain_max
            )
        doomed_pids: np.ndarray | None = None
        if cfg.grid_prefilter:
            mid = cfg.domain_max / 2.0
            if not self._midpoint_witness and bool((values <= mid).all(axis=1).any()):
                self._midpoint_witness = True
            if self._midpoint_witness:
                # advance each partition's barrier with the dropped rows'
                # ids BEFORE filtering — the reference feared exactly this
                # deadlock (a dropped tuple's id never reaching the barrier)
                doomed = (values >= mid).all(axis=1) & (values > mid).any(axis=1)
                if doomed.any():
                    doomed_pids = np.unique(pids[doomed])
                    for p in doomed_pids:
                        part = self.partitions[p]
                        mx = int(ids[doomed & (pids == p)].max())
                        if part.start_time_ms is None:
                            part.start_time_ms = now_ms
                        part.max_seen_id = max(part.max_seen_id, mx)
                    self.prefiltered += int(doomed.sum())
                    keep = ~doomed
                    values = values[keep]
                    ids = ids[keep]
                    pids = pids[keep]
        # group rows by partition with one argsort (the keyBy shuffle).
        # now_ms advances through the loop: an answer's snapshot flush can
        # take seconds (first-query compile), and later answers in the SAME
        # call must see a clock past it or the timing decomposition goes
        # impossible (local > total) — the round-2 deploy-artifact bug.
        with self.tracer.phase("route"):
            order = np.argsort(pids, kind="stable")
            sorted_pids = pids[order]
            sorted_vals = values[order]
            sorted_ids = ids[order]
            bounds = np.searchsorted(
                sorted_pids, np.arange(cfg.num_partitions + 1)
            )
            for p in range(cfg.num_partitions):
                lo, hi = bounds[p], bounds[p + 1]
                if lo == hi:
                    continue
                part = self.partitions[p]
                part.add_batch(
                    sorted_vals[lo:hi], int(sorted_ids[lo:hi].max()), now_ms
                )
                now_ms = self._recheck_pending(p, now_ms)
        # one batched launch merges every partition's pending rows at once
        self.pset.maybe_flush()
        self._note_flush()
        if doomed_pids is not None:
            # partitions whose barrier advanced only via dropped rows still
            # need their pending queries rechecked (after the kept rows of
            # this batch have routed, so answers reflect the full batch)
            for p in doomed_pids:
                now_ms = self._recheck_pending(int(p), now_ms)
        # an overlapped merge whose bytes already landed costs ~nothing to
        # harvest here; one that hasn't stays in flight (never block ingest)
        self._harvest_inflight(block=False)

    def _note_flush(self) -> None:
        """Advance the freshness flush stage once NO ingested rows remain
        host-pending — lazy/overlap policies may leave rows buffered past a
        ``maybe_flush``, and those batches must keep aging in the ingest
        stage until a flush actually absorbs them."""
        if self.freshness is not None and self.pset.pending_rows_total == 0:
            self.freshness.on_flush()

    # -- control plane ----------------------------------------------------

    def process_trigger(self, payload: str, now_ms: float | None = None) -> None:
        """Broadcast a query trigger to every partition (the flatMap fan-out,
        FlinkSkyline.java:145-157).

        Fast path: when every partition's barrier passes at dispatch (the
        dominant case — a trigger after its window is ingested) and the
        engine is single-device, the local snapshots and the global merge
        all run on device with only per-partition counts coming back to
        host; the full local-skyline buffers are never transferred."""
        if now_ms is None:
            now_ms = time.time() * 1000.0
        # a previous overlapped merge lands before a new query dispatches:
        # results stay in trigger order and the engine keeps at most one
        # merge in flight
        self._harvest_inflight()
        if self.pset.has_unsynced_ingest:
            # barrier checks below read per-partition max ids
            self.pset.sync_ingest_bookkeeping()
        qid, required = parse_trigger(payload)
        q = _QueryState(qid=qid, payload=payload, required=required, dispatch_ms=now_ms)
        flight = None
        if self.telemetry is not None:
            q.trace_id = self.telemetry.mint_trace_id()
            q.span_t0_ns = time.perf_counter_ns()
            # stamp this trigger's flush/launch decisions in the flight
            # ring with its trace id so /debug/flight joins /trace and
            # /explain instead of being time-correlated by eye
            flight = self.telemetry.flight
            flight.set_trace(q.trace_id)
        if self._explain_on:
            from skyline_tpu.telemetry.explain import QueryPlan

            q.plan = QueryPlan(q.trace_id, qid)
            # park it for global_merge_launch to claim onto its handle
            self.pset.set_explain(q.plan)
        self._inflight[payload] = q
        try:
            all_ready = all(
                part.max_seen_id >= required or part.max_seen_id == -1
                for part in self.partitions
            )
            if all_ready and self.mesh is None:
                self._answer_all_device(q, now_ms)
                return
            for p in range(self.config.num_partitions):
                part = self.partitions[p]
                if part.max_seen_id >= required or part.max_seen_id == -1:
                    now_ms = self._answer(p, q, now_ms)
                else:
                    self._pending_queries[p].append(q)
        finally:
            if flight is not None:
                flight.set_trace(None)
            # a plan the merge never claimed (host path, pending barrier)
            # must not leak onto a later query's merge
            self.pset.set_explain(None)

    def _recheck_pending(self, p: int, now_ms: float) -> float:
        """Returns the advanced clock (answers add their snapshot wall so
        the caller's subsequent answers don't time-travel before them)."""
        part = self.partitions[p]
        still = []
        for q in self._pending_queries[p]:
            if part.max_seen_id >= q.required:
                now_ms = self._answer(p, q, now_ms)
            else:
                still.append(q)
        self._pending_queries[p] = still
        return now_ms

    # -- local answer + global aggregation --------------------------------

    def _answer(self, p: int, q: _QueryState, now_ms: float) -> float:
        """Partition p finalizes its local skyline for query q
        (processQuery, FlinkSkyline.java:367-403).

        Clock discipline: ``snapshot()`` runs ``flush_all`` whose wall time
        (possibly seconds, incl. first-query jit compile) accrues to
        ``processing_ms`` → local_ms. The arrival timestamp must advance
        past that work — the reference stamps arrival when the partial
        reaches the aggregator, i.e. AFTER processQuery's flush
        (FlinkSkyline.java:524-539) — or the decomposition goes impossible
        (local > total, ingestion clamped). So the snapshot's own wall is
        added to the caller's clock before recording the arrival."""
        part = self.partitions[p]
        t0 = time.perf_counter_ns()
        local = part.snapshot()
        self._note_flush()
        t1 = time.perf_counter_ns()
        if self.telemetry is not None:
            self.telemetry.spans.record(
                "local", t0, t1, trace_id=q.trace_id, tid=p,
                args={"rows": int(local.shape[0])},
            )
        arrival_ms = now_ms + (t1 - t0) / 1e6
        start = part.start_time_ms if part.start_time_ms is not None else now_ms
        q.partials[p] = local
        q.local_sizes[p] = local.shape[0]
        q.start_times[p] = start
        q.cpu_ms[p] = part.processing_ms
        q.last_arrival_ms = max(q.last_arrival_ms, arrival_ms)
        if len(q.partials) >= self.config.num_partitions:
            self._finalize(q, max(arrival_ms, q.last_arrival_ms))
        return arrival_ms

    def _finalize(
        self, q: _QueryState, now_ms: float, partial_missing: list[int] | None = None
    ) -> None:
        """All partitions reported: global merge + metrics + result emission
        (GlobalSkylineAggregator final block, FlinkSkyline.java:573-657).

        ``now_ms`` continues the caller's clock; the merge's own device time
        is added on top so global_processing_time_ms stays real even under an
        injected clock."""
        merge_t0 = time.perf_counter_ns()
        with self.tracer.phase("global_merge"):
            pids_order = sorted(q.partials)
            stacked = [q.partials[p] for p in pids_order]
            origins = np.concatenate(
                [
                    np.full(q.partials[p].shape[0], p, dtype=np.int32)
                    for p in pids_order
                ]
            )
            union = (
                np.concatenate(stacked, axis=0)
                if origins.size
                else np.empty((0, self.config.dims), dtype=np.float32)
            )

            if self.mesh is not None:
                from skyline_tpu.parallel.mesh import skyline_keep_np_sharded

                keep = skyline_keep_np_sharded(self.mesh, union)
            else:
                keep = skyline_keep_np(union)
            global_sky = union[keep]
        survivors_per_pid = np.bincount(
            origins[keep], minlength=self.config.num_partitions
        )

        if self.freshness is not None:
            self.freshness.on_merge()
        merge_end_ns = time.perf_counter_ns()
        merge_ms = (merge_end_ns - merge_t0) / 1e6
        if self.telemetry is not None:
            self.telemetry.spans.record(
                "merge", merge_t0, merge_end_ns, trace_id=q.trace_id,
                args={"union_rows": int(union.shape[0]),
                      "skyline_size": int(global_sky.shape[0])},
            )
            self.telemetry.histogram("global_merge_ms").observe(merge_ms)
        now = now_ms + merge_ms
        job_start = min(q.start_times.values()) if q.start_times else now
        # a pure-timeout finalize may have zero arrivals; anchor to now
        # (test q.partials, not the timestamp — an injected clock at 0.0 is a
        # legitimate arrival time)
        map_finish = q.last_arrival_ms if q.partials else now
        local_ms = max(q.cpu_ms.values()) if q.cpu_ms else 0.0
        map_wall = max(0.0, map_finish - job_start)
        ingestion = max(0.0, map_wall - local_ms)
        global_ms = now - map_finish
        total_ms = now - job_start
        latency_ms = now - q.dispatch_ms

        optimality = optimality_mean(
            [survivors_per_pid[p] for p in pids_order],
            [q.local_sizes[p] for p in pids_order],
            self.config.num_partitions,
        )

        if self.snapshots is not None:
            self._publish_snapshot(global_sky, q)
        self._emit_result(
            q,
            skyline_size=int(global_sky.shape[0]),
            optimality=float(optimality),
            ingestion=ingestion,
            local_ms=local_ms,
            global_ms=global_ms,
            total_ms=total_ms,
            latency_ms=latency_ms,
            points=global_sky if self.config.emit_skyline_points else None,
            partial_missing=partial_missing,
        )

    def _publish_snapshot(
        self, points, q: _QueryState, source_key=None, degraded=None
    ) -> None:
        """Publish a completed global skyline, stamped with the query's
        trace id and wrapped in a "publish" span when telemetry is on.
        ``source_key``: opaque identity of the engine state the points came
        from (the partition-epoch key) — the store dedupes repeat publishes
        of an unchanged state instead of minting a new version.
        ``degraded``: the sharded facade's partial marker — the snapshot
        carries honest incompleteness fields (``partial``,
        ``excluded_chips``, ``completeness_bound``) all the way to
        ``/skyline`` (RUNBOOK §2p). Callers pass ``source_key=None`` with
        it: a degraded snapshot must never dedupe against — or be served
        in place of — a full snapshot of the same engine state."""
        meta = {"query_id": q.qid, "source_key": source_key}
        if degraded is not None:
            meta["partial"] = True
            meta["excluded_chips"] = degraded["excluded_chips"]
            meta["completeness_bound"] = degraded["completeness_bound"]
        if q.trace_id is not None:
            meta["trace_id"] = q.trace_id
        if self.freshness is not None:
            # the merged window's newest event time becomes the snapshot's
            # published watermark (monotone; None until any event stamped)
            meta["event_wm_ms"] = self.freshness.on_publish()
        if self.telemetry is None:
            self.snapshots.publish(points, **meta)
            return
        t0 = time.perf_counter_ns()
        snap = self.snapshots.publish(points, **meta)
        self.telemetry.spans.record(
            "publish", t0, time.perf_counter_ns(), trace_id=q.trace_id
        )
        if q.plan is not None and snap is not None:
            # a deduped publish returns the EXISTING snapshot — the plan
            # still records which version its answer's bytes live under
            q.plan.publish = {
                "version": int(snap.version),
                "deduped": bool(self.snapshots.last_publish_deduped),
                "event_wm_ms": meta.get("event_wm_ms"),
            }

    def _emit_result(
        self,
        q: _QueryState,
        *,
        skyline_size: int,
        optimality: float,
        ingestion: float,
        local_ms: float,
        global_ms: float,
        total_ms: float,
        latency_ms: float,
        points=None,
        partial_missing=None,
        degraded=None,
    ) -> None:
        result = {
            "query_id": q.qid,
            "record_count": echo_record_count(q.payload),
            "skyline_size": skyline_size,
            "optimality": optimality,
            "ingestion_time_ms": int(ingestion),
            "local_processing_time_ms": int(local_ms),
            "global_processing_time_ms": int(global_ms),
            "total_processing_time_ms": int(total_ms),
            "query_latency_ms": int(latency_ms),
        }
        if partial_missing is not None:
            result["partial"] = True
            result["missing_partitions"] = partial_missing
        if degraded is not None:
            # chip-level degradation (RUNBOOK §2p): the answer is the
            # EXACT skyline of the surviving chips' records (NOT a
            # subset of the truth — a point dominated only by
            # excluded-chip data legitimately appears), marked with who
            # is missing and how much record mass the bound guarantees
            result["partial"] = True
            result["excluded_chips"] = degraded["excluded_chips"]
            result["completeness_bound"] = degraded["completeness_bound"]
        if points is not None:
            result["skyline_points"] = (
                points.tolist() if hasattr(points, "tolist") else points
            )
        if self.telemetry is not None:
            if q.trace_id is not None:
                # optional wire extension field: format_result appends it
                # after the reference's fields, so parity consumers are
                # unaffected (bridge/wire.py)
                result["trace_id"] = q.trace_id
            # SLO denominator/numerator pair: every emitted answer counts,
            # chip-degraded ones additionally burn the degraded budget
            # (skyline_degraded_answers_total, telemetry/slo.py)
            self.telemetry.inc("queries.answered")
            if degraded is not None:
                self.telemetry.inc("degraded_answers")
                # degraded publishes are control-plane transitions: the
                # fleet's honest-availability story must survive the
                # process, so they join the durable ops journal
                ops = getattr(self.telemetry, "opslog", None)
                if ops is not None:
                    ops.record(
                        "degraded_publish",
                        trace_id=q.trace_id,
                        excluded_chips=degraded["excluded_chips"],
                        completeness_bound=degraded["completeness_bound"],
                    )
            self.telemetry.histogram("query_latency_ms").observe(latency_ms)
            if q.span_t0_ns:
                self.telemetry.spans.record(
                    "query", q.span_t0_ns, time.perf_counter_ns(),
                    trace_id=q.trace_id,
                    args={"query_id": q.qid, "skyline_size": skyline_size},
                )
        if self.workload is not None and partial_missing is None and degraded is None:
            # one trajectory point per complete answer (partials would
            # poison the dominance-rate series with truncated skylines)
            self.workload.note_query(skyline_size, self.records_in)
        if q.plan is not None:
            self._finalize_plan(
                q,
                skyline_size=skyline_size,
                local_ms=local_ms,
                global_ms=global_ms,
                total_ms=total_ms,
                latency_ms=latency_ms,
            )
        if (
            self.auditor is not None
            and partial_missing is None
            and degraded is None
            and self.snapshots is not None
        ):
            # shadow-verify AFTER the answer is out the door (plan already
            # finalized, snapshot already published); partial answers
            # intentionally exclude state, so they are never audited.
            # Observability must never take the answer down.
            try:
                self.auditor.maybe_check(q)
            except Exception:
                if self.telemetry is not None:
                    self.telemetry.inc("audit.errors")
        self._results.append(result)
        self._inflight.pop(q.payload, None)

    def _finalize_plan(
        self, q, *, skyline_size, local_ms, global_ms, total_ms, latency_ms
    ) -> None:
        """Close out a query's EXPLAIN plan: attribute the window's
        flush-cascade and kernel-dispatch deltas, stamp the timing
        decomposition, land the record in the hub ring, and nest an
        ``explain/<path>`` child span under the query span. Observability
        must never take the answer down, so the whole tail is defensive."""
        try:
            from skyline_tpu.telemetry.explain import (
                cascade_delta,
                kernel_delta,
            )

            plan = q.plan
            if plan.merge is None:
                # per-partition host merge (mesh, pending barriers,
                # timeouts): no device merge claimed the plan
                plan.merge = {"path": "host", "cached": False,
                              "skyline_size": int(skyline_size)}
            cascade_now = self.pset.flush_cascade_stats()
            plan.cascade = cascade_delta(
                self._explain_cascade_mark, cascade_now
            )
            self._explain_cascade_mark = cascade_now
            if self.profiler is not None:
                kernels_now = self.profiler.snapshot_counts()
                plan.kernels = kernel_delta(
                    self._explain_kernel_mark, kernels_now
                )
                self._explain_kernel_mark = kernels_now
            plan.timing = {
                "local_ms": round(float(local_ms), 3),
                "global_ms": round(float(global_ms), 3),
                "total_ms": round(float(total_ms), 3),
                "latency_ms": round(float(latency_ms), 3),
            }
            if self.workload is not None:
                # the regime this answer was computed under — joins the
                # drift trajectory to individual answers in /explain
                plan.workload = self.workload.regime()
            if self.tuner is not None:
                # one cadence-gated controller epoch per query window,
                # then the dispatch context this answer ran under
                self.tuner.maybe_tune()
                plan.tuner = self.tuner.explain_block()
            self.telemetry.explain.add(plan.to_doc())
            self.telemetry.inc("explain.records")
            if q.span_t0_ns:
                self.telemetry.spans.record(
                    f"explain/{plan.merge.get('path')}",
                    q.span_t0_ns,
                    time.perf_counter_ns(),
                    trace_id=q.trace_id,
                    tid=3,
                    args={
                        "path": plan.merge.get("path"),
                        "pruned": (plan.tree or {}).get(
                            "partitions_pruned", 0
                        ),
                        "kernels": len(plan.kernels),
                        "version": (plan.publish or {}).get("version"),
                    },
                )
        except Exception:
            pass

    def _answer_all_device(self, q: _QueryState, now_ms: float) -> None:
        """All barriers passed at dispatch: answer every partition and run
        the global merge on device. Equivalent to _answer x P followed by
        _finalize, but local skylines never leave the device — only the
        packed (counts, survivors, global_count) stats vector (plus the
        compacted points buffer when requested) transfers.

        Timing decomposition follows the same clock discipline as
        _answer/_finalize: the flush wall advances the arrival clock (local
        phase); the merge wall rides on top (global phase)."""
        tel = self.telemetry
        t0 = time.perf_counter_ns()
        self.pset.flush_all()
        self._note_flush()
        flush_end_ns = time.perf_counter_ns()
        flush_wall_ms = (flush_end_ns - t0) / 1e6
        if tel is not None:
            # one stacked launch covers every partition's local skyline
            tel.spans.record(
                "local", t0, flush_end_ns, trace_id=q.trace_id,
                args={"partitions": self.config.num_partitions},
            )
        t1 = time.perf_counter_ns()
        # an attached snapshot store needs the materialized points even when
        # the result JSON omits them — the snapshot IS the serving read path
        want_points = (
            self.config.emit_skyline_points or self.snapshots is not None
        )
        if query_overlap_enabled() and self.mesh is None:
            # overlapped sync: launch every merge kernel now, keep the
            # handle, and return — ingest continues while the device works.
            # The result emits at the next harvest point (poll_results /
            # next trigger / stats / timeout check, or opportunistically in
            # process_records once the stats bytes land), where the phase
            # records only the residual harvest time instead of the full
            # merge wall.
            handle = self.pset.global_merge_launch(emit_points=want_points)
            launch_ms = (time.perf_counter_ns() - t1) / 1e6
            self._inflight_merge = (q, handle, now_ms, flush_wall_ms, launch_ms)
            return
        counts, surv, g, pts = self.pset.global_merge_stats(
            emit_points=want_points
        )
        if self.freshness is not None:
            self.freshness.on_merge()
        merge_end_ns = time.perf_counter_ns()
        merge_ms = (merge_end_ns - t1) / 1e6
        if tel is not None:
            tel.spans.record(
                "merge", t1, merge_end_ns, trace_id=q.trace_id,
                args={"skyline_size": int(g)},
            )
            tel.histogram("global_merge_ms").observe(merge_ms)
        self._emit_device_result(
            q, now_ms, flush_wall_ms, merge_ms, counts, surv, g, pts,
            source_key=self.pset.epoch_key,
        )

    def _harvest_inflight(self, block: bool = True) -> bool:
        """Land the overlapped merge, if one is in flight. ``block=False``
        harvests only when the stats transfer already completed (an
        effectively-free sync) — the ingest path uses it so a still-running
        merge never stalls new data. Returns True when a result emitted."""
        if self._inflight_merge is None:
            return False
        q, handle, now_ms, flush_wall_ms, launch_ms = self._inflight_merge
        if not block and not handle.ready():
            return False
        self._inflight_merge = None
        h0 = time.perf_counter_ns()
        counts, surv, g, pts = self.pset.global_merge_harvest(handle)
        if self.freshness is not None:
            self.freshness.on_merge()
        h1 = time.perf_counter_ns()
        # the query's merge cost = launch dispatch + harvest sync; the
        # in-flight span in between ran under ingest, so charging it here
        # would double-count the overlap the split exists to buy
        merge_ms = launch_ms + (h1 - h0) / 1e6
        if self.telemetry is not None:
            self.telemetry.spans.record(
                "merge", h0, h1, trace_id=q.trace_id,
                args={"skyline_size": int(g), "overlapped": True},
            )
            self.telemetry.histogram("global_merge_ms").observe(merge_ms)
        self._emit_device_result(
            q, now_ms, flush_wall_ms, merge_ms, counts, surv, g, pts,
            source_key=handle.key,
        )
        return True

    def _emit_device_result(
        self, q, now_ms, flush_wall_ms, merge_ms, counts, surv, g, pts,
        source_key,
    ) -> None:
        """Shared tail of the device answer paths (blocking + overlapped):
        snapshot publish, timing decomposition, result emission."""
        # chip-level degradation marker from the sharded facade's harvest
        # (None on flat engines and on full sharded answers)
        degraded = getattr(self.pset, "last_partial", None)
        if self.snapshots is not None:
            # the epoch key identifies the flushed state the merge saw, so
            # repeated triggers over unchanged state dedupe in the store
            # (the host _finalize path publishes un-keyed: its unions mix
            # per-partition arrival times, so no single key describes
            # them). A DEGRADED answer publishes un-keyed too: it must
            # never dedupe against — nor be deduped by — a full snapshot
            # of the same epoch.
            if degraded is not None:
                self._publish_snapshot(pts, q, source_key=None,
                                       degraded=degraded)
            else:
                self._publish_snapshot(pts, q, source_key=source_key)

        starts = [s for s in self.pset.start_time_ms if s is not None]
        map_finish = now_ms + flush_wall_ms
        now = map_finish + merge_ms
        job_start = min(starts) if starts else now
        local_ms = self.pset.processing_ms
        map_wall = max(0.0, map_finish - job_start)
        self._emit_result(
            q,
            skyline_size=g,
            optimality=optimality_mean(surv, counts, self.config.num_partitions),
            ingestion=max(0.0, map_wall - local_ms),
            local_ms=local_ms,
            global_ms=now - map_finish,
            total_ms=now - job_start,
            latency_ms=now - q.dispatch_ms,
            points=pts if self.config.emit_skyline_points else None,
            degraded=degraded,
        )

    # -- failure detection -------------------------------------------------

    def check_timeouts(self, now_ms: float | None = None) -> int:
        """Finalize overdue queries as partial results (the watchdog the
        reference lacks). A timed-out query emits with ``"partial": true``
        and ``"missing_partitions"`` listing the non-reporting partitions;
        its pending barrier entries are withdrawn. Returns the number of
        queries timed out."""
        timeout = self.config.query_timeout_ms
        if timeout <= 0:
            return 0
        # an overlapped merge's query is still in _inflight; land it before
        # the scan so the watchdog can't double-finalize it as partial
        self._harvest_inflight()
        if not self._inflight:
            return 0
        if now_ms is None:
            now_ms = time.time() * 1000.0
        overdue = [
            q for q in self._inflight.values() if now_ms - q.dispatch_ms > timeout
        ]
        for q in overdue:
            missing = [
                p
                for p in range(self.config.num_partitions)
                if p not in q.partials
            ]
            for p in missing:
                self._pending_queries[p] = [
                    pq for pq in self._pending_queries[p] if pq is not q
                ]
            self._finalize(q, now_ms, partial_missing=missing)
        return len(overdue)

    # -- results ----------------------------------------------------------

    def poll_results(self) -> list[dict]:
        self._harvest_inflight()
        out, self._results = self._results, []
        return out

    @property
    def inflight_queries(self) -> int:
        return len(self._inflight)

    # -- observability ----------------------------------------------------

    def stats(self, include_skyline_counts: bool = False) -> dict:
        """Live engine counters — the role the Flink Web UI plays for the
        reference (SURVEY.md §5, docker-compose.yml:26), as a poll-able dict.

        ``include_skyline_counts=True`` adds exact per-partition skyline
        sizes at the cost of one device sync; leave False on hot paths.
        """
        if self.pset.has_unsynced_ingest:
            self.pset.sync_ingest_bookkeeping()
        # counters below must describe a settled state, not a merge mid-air
        self._harvest_inflight()
        tree_info = self.pset.last_tree_info or {}
        out = {
            "records_in": self.records_in,
            "dropped": self.dropped,
            "prefiltered": self.prefiltered,
            "inflight_queries": len(self._inflight),
            "pending_flush_rows": self.pset.pending_rows_total,
            "processing_ms": self.pset.processing_ms,
            "partitions": {
                "records_seen": self.pset.records_seen.tolist(),
                "max_seen_id": self.pset.max_seen_id.tolist(),
            },
            "meshed": self.mesh is not None,
            "merge_cache": {
                "hits": self.pset.merge_cache_hits,
                "misses": self.pset.merge_cache_misses,
                "delta_merges": self.pset.merge_delta_merges,
                "delta_rows": self.pset.merge_delta_rows,
                "last_dirty_fraction": self.pset.last_dirty_fraction,
            },
            "merge_tree": {
                "merges": self.pset.merge_tree_merges,
                "levels": tree_info.get("levels", 0),
                "partitions_pruned": self.pset.merge_partitions_pruned,
                "pruned_fraction": tree_info.get("pruned_fraction", 0.0),
                "candidates_per_level": tree_info.get(
                    "candidates_per_level", []
                ),
            },
            "flush_cascade": self.pset.flush_cascade_stats(),
        }
        if self._explain_on:
            out["explain"] = self.telemetry.explain.doc()
        if self.auditor is not None:
            out["audit"] = self.telemetry.audit.doc()
        if self.freshness is not None:
            out["freshness"] = self.freshness.stats()
        if self.workload is not None:
            out["workload"] = self.workload.stats()
        if self.profiler is not None:
            phase = self.tracer.report().get("flush/merge_kernel")
            out["kernel_profile"] = self.profiler.doc(
                phase_total_ms=phase["total_ms"] if phase else None
            )
        if include_skyline_counts:
            out["partitions"]["skyline_counts"] = (
                self.pset.sky_counts().tolist()
            )
        return out
