"""Device-resident ingest: routing, window assembly, and SFS block slicing
run on the accelerator instead of host numpy.

The host ingest path (engine ``process_records`` + ``PartitionSet`` pending
lists) computes partition ids, routes rows, sum-sorts and pads blocks in
numpy, then uploads each padded block — ~1.2 s of host work per 1M-row
window through the profiling breakdown (BENCH_r03). This module is the
keyBy-inside-the-dataflow equivalent (the reference keeps its shuffle inside
the Flink job graph, FlinkSkyline.java:138): raw chunks upload once as they
arrive (overlapping parse and transport), partition ids / per-chunk barrier
stats are computed on device, the flush-time (pid, coordinate-sum) sort and
segment bounds are one device launch, and the SFS rounds read their blocks
directly out of the sorted device window via ``dynamic_slice`` — no host
assembly and no per-block ``device_put``.

Owner: ``stream.batched.PartitionSet`` (``ingest="device"``). All kernels
here are stateless jits; static shapes come from power-of-two chunk/window
buckets so executables are bounded and cached.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from skyline_tpu.ops.dispatch import on_tpu
from skyline_tpu.ops.sfs import pallas_interpret, sfs_round_core
from skyline_tpu.parallel.partitioners import partition_ids

# Padding tail appended to the sorted window so a B-row dynamic_slice
# starting at any valid row offset never clamps backward (dynamic_slice
# shifts the start when the slice would run past the end — which would
# desynchronize the block from its validity mask). Must be >= the largest
# SFS block size used by the flush loops.
SORT_TAIL = 65536


@functools.partial(
    jax.jit,
    static_argnames=("algo", "num_partitions", "domain_max"),
    donate_argnums=(0, 1),
)
def ingest_chunk(
    window,
    pidbuf,
    chunk,
    ids,
    nvalid,
    offset,
    *,
    algo: str,
    num_partitions: int,
    domain_max: float,
):
    """Append one uploaded chunk to the device window and route it.

    window: (cap, d) +inf-padded accumulation buffer (donated — updated in
    place); pidbuf: (cap,) int32, ``num_partitions`` sentinel for invalid
    rows (donated); chunk: (B, d) +inf-padded rows; ids: (B,) int32 record
    ids (-1 padding); nvalid/offset: dynamic scalars.

    Returns (window', pidbuf', stats (2, P)) where stats rows are the
    per-partition [row counts, max record ids] of THIS chunk — the engine's
    barrier bookkeeping (max-seen-id per partition, FlinkSkyline.java:276-283)
    synced lazily on the host only when a query needs it.
    """
    B = chunk.shape[0]
    valid = jnp.arange(B) < nvalid
    pids = partition_ids(chunk, algo, num_partitions, domain_max)
    pids = jnp.where(valid, pids, num_partitions).astype(jnp.int32)
    window = lax.dynamic_update_slice(
        window, chunk, (offset, jnp.zeros((), jnp.int32))
    )
    pidbuf = lax.dynamic_update_slice(pidbuf, pids, (offset,))
    counts = jax.ops.segment_sum(
        valid.astype(jnp.int32), pids, num_segments=num_partitions + 1
    )[:num_partitions]
    maxids = jax.ops.segment_max(
        jnp.where(valid, ids, -1), pids, num_segments=num_partitions + 1
    )[:num_partitions]
    return window, pidbuf, jnp.stack([counts, maxids])


@functools.partial(
    jax.jit, static_argnames=("n_bucket", "num_partitions", "tail")
)
def sort_window(
    window, pidbuf, nvalid, n_bucket: int, num_partitions: int, tail: int
):
    """Flush-time shuffle: order the accumulated window by (partition id,
    coordinate sum) and return per-partition segment bounds.

    Within each partition the rows come out in ascending coordinate-sum
    order — exactly the SFS append-only invariant (ops/sfs.py), so the
    flush loops can stream contiguous blocks straight from this buffer.
    Two stable argsorts compose the two-key order (int64 keys are
    unavailable without x64). Rows at or past ``nvalid`` are forced to the
    sentinel pid — the accumulation buffer is reused across windows, so
    rows beyond the current fill may hold stale pids from a previous,
    larger window. Invalid rows sort last; ``bounds[P]`` equals ``nvalid``.

    Returns (sorted (n_bucket + tail, d) with a +inf tail pad — see
    SORT_TAIL — and bounds (P + 1,) int32).
    """
    d = window.shape[1]
    w = lax.slice(window, (0, 0), (n_bucket, d))
    p = lax.slice(pidbuf, (0,), (n_bucket,))
    p = jnp.where(jnp.arange(n_bucket) < nvalid, p, num_partitions)
    sums = jnp.where(p < num_partitions, jnp.sum(w, axis=1), jnp.inf)
    o1 = jnp.argsort(sums, stable=True)
    o2 = jnp.argsort(p[o1], stable=True)
    order = o1[o2]
    ws = jnp.concatenate(
        [w[order], jnp.full((tail, d), jnp.inf, dtype=w.dtype)], axis=0
    )
    bounds = jnp.searchsorted(
        p[order], jnp.arange(num_partitions + 1, dtype=p.dtype)
    ).astype(jnp.int32)
    return ws, bounds


@functools.partial(
    jax.jit, static_argnames=("B", "active"), donate_argnums=(0,)
)
def sfs_round_at(sky_p, count, win, off, width, *, B: int, active: int):
    """One partition's SFS round reading its block out of the sorted device
    window: block = win[off : off + B], valid rows = first ``width``.
    The tail rows of a partition's final block belong to the NEXT partition
    in the sorted order — masked to +inf so they are inert as dominators
    and never appended. Drop-in device-window twin of
    ``ops.sfs.sfs_round_single``."""
    d = win.shape[1]
    block = lax.dynamic_slice(win, (off, jnp.zeros((), jnp.int32)), (B, d))
    bvalid = jnp.arange(B) < width
    block = jnp.where(bvalid[:, None], block, jnp.inf)
    return sfs_round_core(
        sky_p, count, block, bvalid, active, on_tpu(), pallas_interpret()
    )


@functools.partial(
    jax.jit, static_argnames=("B", "active"), donate_argnums=(0,)
)
def sfs_round_at_vmapped(sky, counts, win, offs, widths, *, B: int, active: int):
    """Vmapped ``sfs_round_at`` over all partitions (sky (P, cap, d),
    offs/widths (P,)) — one launch per round for balanced loads, each lane
    slicing its own block from the shared sorted window."""
    use_pallas = on_tpu()
    interp = pallas_interpret()
    d = win.shape[1]

    def core(s, c, off, width):
        block = lax.dynamic_slice(
            win, (off, jnp.zeros((), jnp.int32)), (B, d)
        )
        bvalid = jnp.arange(B) < width
        block = jnp.where(bvalid[:, None], block, jnp.inf)
        return sfs_round_core(s, c, block, bvalid, active, use_pallas, interp)

    return jax.vmap(core)(sky, counts, offs, widths)
