"""Device-resident ingest: routing, window assembly, and SFS block slicing
run on the accelerator instead of host numpy.

The host ingest path (engine ``process_records`` + ``PartitionSet`` pending
lists) computes partition ids, routes rows, sum-sorts and pads blocks in
numpy, then uploads each padded block — ~1.2 s of host work per 1M-row
window through the profiling breakdown (BENCH_r03). This module is the
keyBy-inside-the-dataflow equivalent (the reference keeps its shuffle inside
the Flink job graph, FlinkSkyline.java:138): raw chunks upload once as they
arrive (overlapping parse and transport), partition ids / per-chunk barrier
stats are computed on device, the flush-time (pid, coordinate-sum) sort and
segment bounds are one device launch, and the SFS rounds read their blocks
directly out of the sorted device window via ``dynamic_slice`` — no host
assembly and no per-block ``device_put``.

Owner: ``stream.batched.PartitionSet`` (``ingest="device"``). All kernels
here are stateless jits; static shapes come from power-of-two chunk/window
buckets so executables are bounded and cached.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from skyline_tpu.ops.dispatch import on_tpu
from skyline_tpu.ops.sfs import pallas_interpret, sfs_round_core
from skyline_tpu.parallel.partitioners import partition_ids

# Padding tail appended to the sorted window so a B-row dynamic_slice
# starting at any valid row offset never clamps backward (dynamic_slice
# shifts the start when the slice would run past the end — which would
# desynchronize the block from its validity mask). Must be >= the largest
# SFS block size used by the flush loops.
SORT_TAIL = 65536


@functools.partial(
    jax.jit,
    static_argnames=("algo", "num_partitions", "domain_max"),
    donate_argnums=(0, 1),
)
def ingest_chunk(
    window,
    pidbuf,
    chunk,
    ids,
    nvalid,
    offset,
    *,
    algo: str,
    num_partitions: int,
    domain_max: float,
):
    """Append one uploaded chunk to the device window and route it.

    window: (cap, d) +inf-padded accumulation buffer (donated — updated in
    place); pidbuf: (cap,) int32, ``num_partitions`` sentinel for invalid
    rows (donated); chunk: (B, d) +inf-padded rows; ids: (B,) int32 record
    ids (-1 padding); nvalid/offset: dynamic scalars.

    Returns (window', pidbuf', stats (2, P)) where stats rows are the
    per-partition [row counts, max record ids] of THIS chunk — the engine's
    barrier bookkeeping (max-seen-id per partition, FlinkSkyline.java:276-283)
    synced lazily on the host only when a query needs it.
    """
    B = chunk.shape[0]
    valid = jnp.arange(B) < nvalid
    pids = partition_ids(chunk, algo, num_partitions, domain_max)
    pids = jnp.where(valid, pids, num_partitions).astype(jnp.int32)
    window = lax.dynamic_update_slice(
        window, chunk, (offset, jnp.zeros((), jnp.int32))
    )
    pidbuf = lax.dynamic_update_slice(pidbuf, pids, (offset,))
    counts = jax.ops.segment_sum(
        valid.astype(jnp.int32), pids, num_segments=num_partitions + 1
    )[:num_partitions]
    maxids = jax.ops.segment_max(
        jnp.where(valid, ids, -1), pids, num_segments=num_partitions + 1
    )[:num_partitions]
    return window, pidbuf, jnp.stack([counts, maxids])


@functools.partial(
    jax.jit, static_argnames=("n_bucket", "num_partitions", "tail")
)
def sort_window(
    window, pidbuf, nvalid, n_bucket: int, num_partitions: int, tail: int
):
    """Flush-time shuffle: order the accumulated window by (partition id,
    coordinate sum) and return per-partition segment bounds.

    Within each partition the rows come out in ascending coordinate-sum
    order — exactly the SFS append-only invariant (ops/sfs.py), so the
    flush loops can stream contiguous blocks straight from this buffer.
    Two stable argsorts compose the two-key order (int64 keys are
    unavailable without x64). Rows at or past ``nvalid`` are forced to the
    sentinel pid — the accumulation buffer is reused across windows, so
    rows beyond the current fill may hold stale pids from a previous,
    larger window. Invalid rows sort last; ``bounds[P]`` equals ``nvalid``.

    Returns (sorted (n_bucket + tail, d) with a +inf tail pad — see
    SORT_TAIL — and bounds (P + 1,) int32).
    """
    d = window.shape[1]
    w = lax.slice(window, (0, 0), (n_bucket, d))
    p = lax.slice(pidbuf, (0,), (n_bucket,))
    p = jnp.where(jnp.arange(n_bucket) < nvalid, p, num_partitions)
    sums = jnp.where(p < num_partitions, jnp.sum(w, axis=1), jnp.inf)
    o1 = jnp.argsort(sums, stable=True)
    o2 = jnp.argsort(p[o1], stable=True)
    order = o1[o2]
    ws = jnp.concatenate(
        [w[order], jnp.full((tail, d), jnp.inf, dtype=w.dtype)], axis=0
    )
    bounds = jnp.searchsorted(
        p[order], jnp.arange(num_partitions + 1, dtype=p.dtype)
    ).astype(jnp.int32)
    return ws, bounds


def _searchsorted_cols(sorted_cols, q):
    """Per-dim searchsorted: sorted_cols (M, d) ascending per column,
    q (N, d) queries -> dense ranks (N, d) int32 (int32 keeps rank sums
    exact past f32's 2^24 limit — ops/pallas_dominance._dom_tile_rank)."""
    return jax.vmap(
        lambda sc, col: jnp.searchsorted(sc, col, side="left"),
        in_axes=(1, 1),
        out_axes=1,
    )(sorted_cols, q).astype(jnp.int32)


def rank_flush_enabled() -> bool:
    """Rank-cascade SFS flush: enabled when the rank kernels can run (TPU,
    or interpret mode for tests) and ``SKYLINE_RANK_CASCADE`` is not 0.
    Read lazily at trace/flush time."""
    from skyline_tpu.ops import cascade
    from skyline_tpu.ops.dispatch import on_tpu

    return cascade.gate("mask_rank_pallas") and (on_tpu() or pallas_interpret())


@functools.partial(
    jax.jit,
    static_argnames=("n_bucket", "active_old", "univ_bucket"),
)
def rank_window(
    ws,
    sky,
    counts,
    n_bucket: int,
    active_old: int,
    univ_bucket: int,
):
    """Rank preprocessing for the rank-cascade SFS flush: the compared
    universe is the sorted window's rows PLUS every partition's live
    skyline prefix (old survivors act as dominators against new blocks and
    as cleanup victims, so they must share the rank space — dense ranks
    are exact only over universe members, ops/pallas_dominance.py).

    ws: (n_bucket + tail, d) sorted window; sky: (P, cap, d) with
    ``active_old`` bounding live prefixes (0 = fresh set, universe is the
    window alone). Invalid rows are +inf and rank as the max (inert).

    Returns (sorted_dims (univ_bucket, d) — per-dim ascending universe for
    ranking arbitrary universe members later (sky prefixes per round), and
    ws_ranks (same leading extent as ws, d + 1) — the window rows' ranks
    with the rank-sum as the last column, sliceable exactly like ``ws``
    (its +inf tail rows rank as the max: inert).
    """
    P, cap, d = sky.shape
    w = lax.slice(ws, (0, 0), (n_bucket, d))
    if active_old:
        act = lax.slice(sky, (0, 0, 0), (P, active_old, d)).reshape(
            P * active_old, d
        )
        # rows at or past each partition's count are +inf already (compact
        # / SFS-append invariants) except garbage is impossible: both flush
        # paths pad with +inf. Mask defensively against counts anyway.
        ok = (
            jnp.arange(active_old)[None, :] < counts[:, None]
        ).reshape(P * active_old)
        act = jnp.where(ok[:, None], act, jnp.inf)
        univ = jnp.concatenate([w, act], axis=0)
    else:
        univ = w
    pad = univ_bucket - univ.shape[0]
    if pad > 0:
        univ = jnp.concatenate(
            [univ, jnp.full((pad, d), jnp.inf, univ.dtype)], axis=0
        )
    sorted_dims = jnp.sort(univ, axis=0)
    ranks = _searchsorted_cols(sorted_dims, ws)
    rsum = jnp.sum(ranks, axis=1, keepdims=True, dtype=jnp.int32)
    return sorted_dims, jnp.concatenate([ranks, rsum], axis=1)


def _rank_rows(sorted_dims, rows):
    """Rank arbitrary universe-member rows against the per-dim sorted
    universe -> (N, d+1) int32 ranks+ranksum (transposed layout NOT
    applied)."""
    r = _searchsorted_cols(sorted_dims, rows)
    return jnp.concatenate(
        [r, jnp.sum(r, axis=1, keepdims=True, dtype=jnp.int32)], axis=1
    )


def _sfs_round_rank_core(
    sky_p, count, win, wr, sorted_dims, off, width, B: int, active: int,
    interp: bool,
):
    """Rank-cascade SFS round body: dominance passes over dense ranks,
    append in value space. The sky's active prefix is re-ranked in-jit per
    round (d searchsorteds over ``active`` rows — amortized against the
    O(B x active) pairwise pass)."""
    from skyline_tpu.ops.pallas_dominance import (
        dominated_by_any_rank_pallas,
        dominated_by_rank_pallas,
    )

    d = win.shape[1]
    zero = jnp.zeros((), jnp.int32)
    block = lax.dynamic_slice(win, (off, zero), (B, d))
    block_r = lax.dynamic_slice(wr, (off, zero), (B, d + 1))
    bvalid = jnp.arange(B) < width
    block = jnp.where(bvalid[:, None], block, jnp.inf)
    # invalid tail rows: force ranks to the max so they are inert exactly
    # like +inf values (their true ranks belong to the NEXT partition's
    # rows, which are live universe members and would not be inert)
    block_r = jnp.where(
        bvalid[:, None], block_r, jnp.int32(sorted_dims.shape[0] * (d + 1))
    )
    sky_act = lax.slice(sky_p, (0, 0), (active, d))
    sky_ok = jnp.arange(active) < count
    sky_r = _rank_rows(sorted_dims, sky_act)
    block_rt = block_r.T
    keep = bvalid & ~dominated_by_any_rank_pallas(
        block_rt, bvalid, triangular=True, interpret=interp
    )
    keep = keep & ~dominated_by_rank_pallas(
        sky_r.T, sky_ok, block_rt, interpret=interp
    )
    from skyline_tpu.ops.dominance import compact

    vals, _, m = compact(block, keep, B)
    sky_p = lax.dynamic_update_slice(sky_p, vals, (count, zero))
    return sky_p, count + m


@functools.partial(
    jax.jit, static_argnames=("B", "active"), donate_argnums=(0,)
)
def sfs_round_at_rank(
    sky_p, count, win, wr, sorted_dims, off, width, *, B: int, active: int
):
    """Single-partition rank-cascade round (see ``_sfs_round_rank_core``)."""
    return _sfs_round_rank_core(
        sky_p, count, win, wr, sorted_dims, off, width, B, active,
        pallas_interpret(),
    )


@functools.partial(
    jax.jit, static_argnames=("B", "active"), donate_argnums=(0,)
)
def sfs_round_at_rank_vmapped(
    sky, counts, win, wr, sorted_dims, offs, widths, *, B: int, active: int
):
    """Vmapped rank-cascade round over all partitions."""
    interp = pallas_interpret()

    def core(s, c, off, width):
        return _sfs_round_rank_core(
            s, c, win, wr, sorted_dims, off, width, B, active, interp
        )

    return jax.vmap(core)(sky, counts, offs, widths)


@functools.partial(
    jax.jit,
    static_argnames=("old_active", "active"),
    donate_argnums=(0,),
)
def sfs_cleanup_rank(
    sky, counts, old_counts, sorted_dims, old_active: int, active: int
):
    """Rank-cascade twin of ``ops.sfs.sfs_cleanup``: prune old rows
    dominated by newly appended rows, comparing in rank space (both row
    sets are universe members — old prefixes were folded into the rank
    universe by ``rank_window``)."""
    from skyline_tpu.ops.dominance import compact
    from skyline_tpu.ops.pallas_dominance import dominated_by_rank_pallas

    interp = pallas_interpret()
    P, cap, d = sky.shape

    def core(s, c, old_c):
        act = lax.slice(s, (0, 0), (active, d))
        new_ok = (jnp.arange(active) >= old_c) & (jnp.arange(active) < c)
        old = lax.slice(s, (0, 0), (old_active, d))
        act_r = _rank_rows(sorted_dims, act)
        old_r = _rank_rows(sorted_dims, old)
        old_dom = dominated_by_rank_pallas(
            act_r.T, new_ok, old_r.T, interpret=interp
        )
        old_keep = (jnp.arange(old_active) < old_c) & ~old_dom
        keep = jnp.zeros((cap,), dtype=bool)
        keep = keep.at[:active].set(new_ok)
        keep = keep.at[:old_active].set(old_keep | new_ok[:old_active])
        vals, _, cnt = compact(s, keep, cap)
        return vals, cnt.astype(jnp.int32)

    return jax.vmap(core)(sky, counts, old_counts)


@functools.partial(
    jax.jit, static_argnames=("B", "active", "mp"), donate_argnums=(0,)
)
def sfs_round_at(sky_p, count, win, off, width, *, B: int, active: int, mp: bool = False):
    """One partition's SFS round reading its block out of the sorted device
    window: block = win[off : off + B], valid rows = first ``width``.
    The tail rows of a partition's final block belong to the NEXT partition
    in the sorted order — masked to +inf so they are inert as dominators
    and never appended. Drop-in device-window twin of
    ``ops.sfs.sfs_round_single`` — ``mp`` (static) threads the
    mixed-precision pass and the bf16-resolved count rides third."""
    d = win.shape[1]
    block = lax.dynamic_slice(win, (off, jnp.zeros((), jnp.int32)), (B, d))
    bvalid = jnp.arange(B) < width
    block = jnp.where(bvalid[:, None], block, jnp.inf)
    return sfs_round_core(
        sky_p, count, block, bvalid, active, on_tpu(), pallas_interpret(), mp
    )


@functools.partial(
    jax.jit, static_argnames=("B", "active", "mp"), donate_argnums=(0,)
)
def sfs_round_at_vmapped(
    sky, counts, win, offs, widths, *, B: int, active: int, mp: bool = False
):
    """Vmapped ``sfs_round_at`` over all partitions (sky (P, cap, d),
    offs/widths (P,)) — one launch per round for balanced loads, each lane
    slicing its own block from the shared sorted window."""
    use_pallas = on_tpu()
    interp = pallas_interpret()
    d = win.shape[1]

    def core(s, c, off, width):
        block = lax.dynamic_slice(
            win, (off, jnp.zeros((), jnp.int32)), (B, d)
        )
        bvalid = jnp.arange(B) < width
        block = jnp.where(bvalid[:, None], block, jnp.inf)
        return sfs_round_core(
            s, c, block, bvalid, active, use_pallas, interp, mp
        )

    return jax.vmap(core)(sky, counts, offs, widths)
