"""Sliding-window streaming engine: partitioned continuous skyline.

First-class engine mode for BASELINE config #4 (count-based sliding window,
high overlap) with the same external surface as ``SkylineEngine``:
``process_records`` / ``process_trigger`` / ``poll_results`` / ``stats``,
the same partitioners, id-barrier trigger semantics and result JSON — so the
worker, collector, and deploy stack drive it unchanged. The reference has no
eviction at all (its skyline spans the unbounded stream), so this whole mode
is a capability extension built on the bucket-ring decomposition of
``stream/sliding.py``.

Semantics. The stream is cut into global slides of ``slide`` tuples (by
arrival order, exactly — incoming batches are split at slide boundaries
before routing). A window is the last ``K = window_size / slide`` closed
buckets. Each partition keeps a device ring of its OWN rows per bucket
(bucket skylines computed once at close — the merge law makes the union
exact, SURVEY.md §4); eviction is a ring-slot overwrite. A query trigger
answers over the current window plus the in-progress slide's rows (bucket-
granular eviction: between ``window_size`` and ``window_size + slide - 1``
most recent tuples — the same contract as ``SlidingSkyline.current_skyline``).

TPU shape: rings are stacked ``(P, K, C, d)``; a slide close is ONE vmapped
jitted launch for all partitions (bucket skyline + ring write + window-union
skyline + compact). Under a ``mesh`` the P axis is sharded and XLA's GSPMD
partitions the same program across devices (the kernels here are pure XLA —
scan-based — precisely so the meshed path needs no shard_map).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from skyline_tpu.bridge.wire import parse_trigger
from skyline_tpu.metrics.tracing import NULL_TRACER
from skyline_tpu.ops.block_skyline import skyline_mask_scan
from skyline_tpu.ops.dispatch import skyline_keep_np
from skyline_tpu.ops.dominance import compact
from skyline_tpu.parallel.partitioners import partition_ids_np
from skyline_tpu.stream.engine import (
    EngineConfig,
    _QueryState,
    echo_record_count,
    optimality_mean,
)
from skyline_tpu.utils.buckets import next_pow2


@functools.partial(
    jax.jit, static_argnames=("use_pallas",), donate_argnums=(0, 1)
)
def _slide_step_batched(
    rings, ring_valids, slot, rows, rows_valid, use_pallas: bool = False
):
    """Close one global slide across all partitions in one launch.

    rings (P, K, C, d), ring_valids (P, K, C), slot scalar int32 (same ring
    position for every partition — slides are global), rows (P, C, d)
    padded, rows_valid (P, C). Returns (rings', ring_valids', win_sky
    (P, K*C, d), win_valid (P, K*C), win_counts (P,)) with each partition's
    window skyline compacted to the front of its flat buffer.

    ``use_pallas`` switches the two skyline passes to the VMEM-tiled
    triangular Pallas kernel — the single-device TPU fast path (the
    window-union pass is the slide cost at north-star shapes: at 8-D the
    bucket skylines barely shrink, so the union is nearly K full buckets).
    The meshed path keeps the pure-XLA scan kernels so GSPMD can partition
    the P axis without a shard_map (module docstring).
    """
    if rings.shape[-1] <= 2:
        # d <= 2: sort-sweep (ops/sweep2d.py) beats both pairwise kernels
        # on every backend; vmaps cleanly over the partition axis
        from skyline_tpu.ops.sweep2d import skyline_mask_sweep

        mask = skyline_mask_sweep
    elif use_pallas:
        from skyline_tpu.ops.pallas_dominance import skyline_mask_pallas
        from skyline_tpu.ops.sfs import pallas_interpret

        mask = functools.partial(
            skyline_mask_pallas, interpret=pallas_interpret()
        )
    else:
        mask = skyline_mask_scan

    def core(ring, ring_valid, r, rv):
        k, c, d = ring.shape
        bucket_keep = mask(r, rv)
        bvals, bvalid, _ = compact(r, bucket_keep, c)
        ring = ring.at[slot].set(bvals)
        ring_valid = ring_valid.at[slot].set(bvalid)
        flat = ring.reshape(k * c, d)
        fvalid = ring_valid.reshape(k * c)
        wkeep = mask(flat, fvalid)
        sky, sky_valid, count = compact(flat, wkeep, k * c)
        return ring, ring_valid, sky, sky_valid, count.astype(jnp.int32)

    return jax.vmap(core, in_axes=(0, 0, 0, 0))(rings, ring_valids, rows, rows_valid)


class SlidingEngine:
    """Partitioned sliding-window skyline engine (see module docstring)."""

    def __init__(
        self,
        config: EngineConfig,
        window_size: int,
        slide: int,
        mesh=None,
        emit_per_slide: bool = False,
        tracer=None,
        telemetry=None,
    ):
        if window_size % slide != 0:
            raise ValueError(
                f"window_size {window_size} must be a multiple of slide {slide}"
            )
        self.config = config
        self.window_size = window_size
        self.slide = slide
        self.k = window_size // slide
        self.mesh = mesh
        self.emit_per_slide = emit_per_slide
        self.tracer = tracer if tracer is not None else NULL_TRACER
        # same contract as SkylineEngine: optional telemetry hub for
        # latency histograms, per-query trace ids, and spans
        self.telemetry = telemetry
        P = config.num_partitions
        # start capacity at the balanced-routing bucket (2x headroom over
        # slide/P); grows when routing skew overflows it
        self._cap = next_pow2(max(2 * slide // max(P, 1), 64), min_cap=128)
        # single-device TPU: VMEM-tiled triangular Pallas kernel for the
        # bucket + window-union skyline passes (see _slide_step_batched) —
        # only once the flat window clears the kernel's 2048-row tile pad,
        # below which the scan kernel's exact-size passes win
        from skyline_tpu.ops.dispatch import on_tpu

        self._use_pallas = (
            mesh is None and on_tpu() and self.k * self._cap >= 8192
        )
        self._sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            axis = mesh.axis_names[0]
            if P % int(mesh.shape[axis]):
                raise ValueError(
                    f"num_partitions {P} must be divisible by mesh axis "
                    f"size {mesh.shape[axis]}"
                )
            self._sharding = NamedSharding(mesh, PartitionSpec(axis))
        self._rings = self._put(
            np.full((P, self.k, self._cap, config.dims), np.inf, np.float32)
        )
        self._ring_valids = self._put(
            np.zeros((P, self.k, self._cap), dtype=bool)
        )
        # per-partition current-window skylines (device cache from the last
        # slide close) + exact survivor counts on host
        self._win_sky = None
        self._win_host: np.ndarray | None = None  # host cache of _win_sky
        self._win_counts = np.zeros(P, dtype=np.int64)
        self._slot = 0
        self._slides_closed = 0
        # current slide's routed rows, per partition (host)
        self._pend: list[list[np.ndarray]] = [[] for _ in range(P)]
        self._pend_rows = np.zeros(P, dtype=np.int64)
        self._slide_fill = 0  # tuples of the in-progress slide
        self.records_in = 0
        self.dropped = 0
        self.prefiltered = 0
        self.max_seen_id = np.full(P, -1, dtype=np.int64)
        self.records_seen = np.zeros(P, dtype=np.int64)
        self.start_time_ms: list[float | None] = [None] * P
        self.processing_ns = 0
        self._pending_queries: dict[int, list[_QueryState]] = {
            i: [] for i in range(P)
        }
        self._inflight: dict[str, _QueryState] = {}
        self._results: list[dict] = []
        # serving plane (serve/snapshot.py) — same contract as SkylineEngine
        self.snapshots = None

    def attach_snapshots(self, store) -> None:
        """Publish each answered window's global skyline to ``store``."""
        self.snapshots = store

    def _put(self, arr):
        if self._sharding is not None:
            return jax.device_put(arr, self._sharding)
        return jnp.asarray(arr)

    # -- data plane -------------------------------------------------------

    def process_records(
        self, ids, values, now_ms: float | None = None, event_ms=None
    ) -> None:
        """Split the batch at global slide boundaries, route each segment,
        close slides as they fill. ``event_ms`` is accepted for call-site
        parity with ``SkylineEngine`` and ignored — the freshness lineage
        covers the tumbling engine only (RUNBOOK §2j)."""
        tel = self.telemetry
        if tel is None:
            return self._process_records(ids, values, now_ms)
        t0 = time.perf_counter_ns()
        try:
            return self._process_records(ids, values, now_ms)
        finally:
            end = time.perf_counter_ns()
            tel.histogram("ingest_batch_ms").observe((end - t0) / 1e6)
            tel.spans.record(
                "ingest", t0, end, args={"rows": int(values.shape[0])}
            )

    def _process_records(self, ids, values, now_ms: float | None = None) -> None:
        if values.shape[0] == 0:
            return
        if now_ms is None:
            now_ms = time.time() * 1000.0
        self.records_in += values.shape[0]
        if self.snapshots is not None:
            self.snapshots.note_ingest(int(ids.max()))
        pos = 0
        n = values.shape[0]
        # now_ms advances through routing answers and slide closes: wall
        # spent in either (merge compile, slide-step kernels) must be seen
        # by later answers in the same call or total < local becomes
        # possible (the same invariant SkylineEngine threads through
        # _recheck_pending/_answer)
        while pos < n:
            take = min(self.slide - self._slide_fill, n - pos)
            now_ms = self._route(
                ids[pos : pos + take], values[pos : pos + take], now_ms
            )
            self._slide_fill += take
            pos += take
            if self._slide_fill == self.slide:
                now_ms = self._close_slide(now_ms)
                self._slide_fill = 0

    def _route(self, ids, values, now_ms: float) -> float:
        cfg = self.config
        with self.tracer.phase("route"):
            pids = partition_ids_np(
                values, cfg.algo, cfg.num_partitions, cfg.domain_max
            )
            order = np.argsort(pids, kind="stable")
            s_pids, s_vals, s_ids = pids[order], values[order], ids[order]
            bounds = np.searchsorted(
                s_pids, np.arange(cfg.num_partitions + 1)
            )
            for p in range(cfg.num_partitions):
                lo, hi = bounds[p], bounds[p + 1]
                if lo == hi:
                    continue
                if self.start_time_ms[p] is None:
                    self.start_time_ms[p] = now_ms
                self.max_seen_id[p] = max(
                    self.max_seen_id[p], int(s_ids[lo:hi].max())
                )
                self.records_seen[p] += hi - lo
                self._pend[p].append(np.array(s_vals[lo:hi]))
                self._pend_rows[p] += hi - lo
                now_ms = self._recheck_pending(p, now_ms)
        return now_ms

    def _close_slide(self, now_ms: float) -> float:
        t0 = time.perf_counter_ns()
        P = self.config.num_partitions
        d = self.config.dims
        max_rows = int(self._pend_rows.max())
        if max_rows > self._cap:
            self._grow(next_pow2(max_rows, min_cap=128))
        rows = np.full((P, self._cap, d), np.inf, dtype=np.float32)
        rvalid = np.zeros((P, self._cap), dtype=bool)
        for p in range(P):
            if self._pend[p]:
                r = (
                    self._pend[p][0]
                    if len(self._pend[p]) == 1
                    else np.concatenate(self._pend[p], axis=0)
                )
                rows[p, : r.shape[0]] = r
                rvalid[p, : r.shape[0]] = True
        self._pend = [[] for _ in range(P)]
        self._pend_rows[:] = 0
        with self.tracer.phase("slide/step"):
            (
                self._rings,
                self._ring_valids,
                self._win_sky,
                _win_valid,
                counts,
            ) = _slide_step_batched(
                self._rings,
                self._ring_valids,
                jnp.asarray(self._slot, dtype=jnp.int32),
                self._put(rows),
                self._put(rvalid),
                use_pallas=self._use_pallas,
            )
            self._win_counts = np.asarray(counts, dtype=np.int64)
        self._win_host = None  # device cache replaced; host copy is stale
        self._slot = (self._slot + 1) % self.k
        self._slides_closed += 1
        step_ns = time.perf_counter_ns() - t0
        self.processing_ns += step_ns
        now_ms = now_ms + step_ns / 1e6  # the close's wall advances the clock
        if self.emit_per_slide:
            q = _QueryState(
                qid=f"slide-{self._slides_closed - 1}",
                payload=f"slide-{self._slides_closed - 1},{self.records_in}",
                required=0,
                dispatch_ms=now_ms,
            )
            now_ms = self._answer_window(q, now_ms)
        return now_ms

    def _grow(self, new_cap: int) -> None:
        """Routing skew overflowed a ring's row capacity: grow all rings
        (rare; preserves closed buckets)."""
        P = self.config.num_partitions
        d = self.config.dims
        pad = jnp.full(
            (P, self.k, new_cap - self._cap, d), jnp.inf, dtype=jnp.float32
        )
        self._rings = self._put(jnp.concatenate([self._rings, pad], axis=2))
        vpad = jnp.zeros((P, self.k, new_cap - self._cap), dtype=bool)
        self._ring_valids = self._put(
            jnp.concatenate([self._ring_valids, vpad], axis=2)
        )
        self._cap = new_cap
        # growth can push the flat window past the Pallas tile-pad
        # threshold; re-evaluate the fast-path gate (constructor note)
        from skyline_tpu.ops.dispatch import on_tpu

        self._use_pallas = (
            self.mesh is None and on_tpu() and self.k * self._cap >= 8192
        )

    # -- control plane ----------------------------------------------------

    def process_trigger(self, payload: str, now_ms: float | None = None) -> None:
        if now_ms is None:
            now_ms = time.time() * 1000.0
        qid, required = parse_trigger(payload)
        q = _QueryState(
            qid=qid, payload=payload, required=required, dispatch_ms=now_ms
        )
        if self.telemetry is not None:
            q.trace_id = self.telemetry.mint_trace_id()
            q.span_t0_ns = time.perf_counter_ns()
        self._inflight[payload] = q
        ready = all(
            self.max_seen_id[p] >= required or self.max_seen_id[p] == -1
            for p in range(self.config.num_partitions)
        )
        if ready:
            self._answer_window(q, now_ms)
        else:
            for p in range(self.config.num_partitions):
                if not (
                    self.max_seen_id[p] >= required
                    or self.max_seen_id[p] == -1
                ):
                    self._pending_queries[p].append(q)

    def _recheck_pending(self, p: int, now_ms: float) -> float:
        """Drop cleared barriers for partition ``p``; a query answers once
        no partition's pending list holds it anymore. Returns the advanced
        clock (answer merges can take real wall; later answers in the same
        call must not time-travel before them)."""
        still = []
        unblocked = []
        for q in self._pending_queries[p]:
            if self.max_seen_id[p] >= q.required:
                unblocked.append(q)
            else:
                still.append(q)
        self._pending_queries[p] = still
        for q in unblocked:
            if not any(
                q in lst for lst in self._pending_queries.values()
            ):
                now_ms = self._answer_window(q, now_ms)
        return now_ms

    # -- answering --------------------------------------------------------

    def _current_partials(self):
        """Per-partition current window contributions (host arrays) plus a
        per-partition flag: does the contribution still need a local
        skyline prune (True unless it came straight from the exact
        window-skyline cache with no pending rows)."""
        P = self.config.num_partitions
        d = self.config.dims
        parts = []
        need_prune = [False] * P
        if self._win_sky is not None:
            if self._win_host is None:
                with self.tracer.phase("query/snapshot_transfer"):
                    self._win_host = np.asarray(self._win_sky)
            for p in range(P):
                parts.append(self._win_host[p, : self._win_counts[p]])
        else:
            # _win_sky is None only before the first slide closes (_grow
            # invalidates it, but _close_slide recomputes it in the same
            # call before anyone can observe the gap)
            assert self._slides_closed == 0
            parts = [np.empty((0, d), np.float32) for _ in range(P)]
        for p in range(P):
            if self._pend[p]:
                pend = np.concatenate(self._pend[p], axis=0)
                parts[p] = np.concatenate([parts[p], pend], axis=0)
                need_prune[p] = True
        return parts, need_prune

    def _answer_window(self, q: _QueryState, now_ms: float) -> float:
        t0 = time.perf_counter_ns()
        parts, need_prune = self._current_partials()
        P = self.config.num_partitions
        # local pass: prune each contribution to its partition's window
        # skyline (already exact when served from the slide-close cache)
        local = []
        for p in range(P):
            arr = parts[p]
            if arr.shape[0] and need_prune[p]:
                arr = arr[skyline_keep_np(arr)]
            local.append(arr)
        sizes = [a.shape[0] for a in local]
        union = (
            np.concatenate(local, axis=0)
            if any(sizes)
            else np.empty((0, self.config.dims), np.float32)
        )
        origins = np.concatenate(
            [np.full(s, p, dtype=np.int32) for p, s in enumerate(sizes)]
        ) if any(sizes) else np.empty((0,), np.int32)
        keep = (
            skyline_keep_np(union)
            if union.shape[0]
            else np.zeros((0,), dtype=bool)
        )
        global_sky = union[keep]
        surv = np.bincount(origins[keep], minlength=P)
        merge_end_ns = time.perf_counter_ns()
        merge_ms = (merge_end_ns - t0) / 1e6
        if self.telemetry is not None:
            self.telemetry.spans.record(
                "merge", t0, merge_end_ns, trace_id=q.trace_id,
                args={"skyline_size": int(global_sky.shape[0])},
            )
            self.telemetry.histogram("global_merge_ms").observe(merge_ms)
        now = now_ms + merge_ms

        starts = [s for s in self.start_time_ms if s is not None]
        job_start = min(starts) if starts else now
        local_ms = self.processing_ns / 1e6
        map_wall = max(0.0, now_ms - job_start)
        result = {
            "query_id": q.qid,
            "record_count": echo_record_count(q.payload),
            "skyline_size": int(global_sky.shape[0]),
            "optimality": optimality_mean(surv, sizes, P),
            "ingestion_time_ms": int(max(0.0, map_wall - local_ms)),
            "local_processing_time_ms": int(local_ms),
            "global_processing_time_ms": int(merge_ms),
            "total_processing_time_ms": int(now - job_start),
            "query_latency_ms": int(now - q.dispatch_ms),
            "window_size": self.window_size,
            "slide": self.slide,
            "slides_closed": self._slides_closed,
            "window_filled": self._slides_closed >= self.k,
        }
        if self.config.emit_skyline_points:
            result["skyline_points"] = global_sky.tolist()
        if self.snapshots is not None:
            meta = {}
            if q.trace_id is not None:
                meta["trace_id"] = q.trace_id
            p0 = time.perf_counter_ns()
            self.snapshots.publish(
                global_sky,
                query_id=q.qid,
                # window identity: unchanged (records_in, slides_closed)
                # means the recompute is byte-identical, so the store can
                # dedupe repeat publishes instead of minting a version
                source_key=(self.records_in, self._slides_closed),
                slides_closed=self._slides_closed,
                window_filled=self._slides_closed >= self.k,
                **meta,
            )
            if self.telemetry is not None:
                self.telemetry.spans.record(
                    "publish", p0, time.perf_counter_ns(), trace_id=q.trace_id
                )
        if self.telemetry is not None:
            if q.trace_id is not None:
                result["trace_id"] = q.trace_id
            self.telemetry.histogram("query_latency_ms").observe(
                result["query_latency_ms"]
            )
            if q.span_t0_ns:
                self.telemetry.spans.record(
                    "query", q.span_t0_ns, time.perf_counter_ns(),
                    trace_id=q.trace_id,
                    args={"query_id": q.qid,
                          "skyline_size": int(global_sky.shape[0])},
                )
        self._results.append(result)
        self._inflight.pop(q.payload, None)
        return now

    # -- results / observability ------------------------------------------

    def poll_results(self) -> list[dict]:
        out, self._results = self._results, []
        return out

    def check_timeouts(self, now_ms: float | None = None) -> int:
        """Sliding triggers answer from current state; a deferred barrier
        can still time out into a partial answer over what exists."""
        timeout = self.config.query_timeout_ms
        if timeout <= 0 or not self._inflight:
            return 0
        if now_ms is None:
            now_ms = time.time() * 1000.0
        overdue = [
            q
            for q in self._inflight.values()
            if now_ms - q.dispatch_ms > timeout
        ]
        for q in overdue:
            for lst in self._pending_queries.values():
                if q in lst:
                    lst.remove(q)
            self._answer_window(q, now_ms)
            self._results[-1]["partial"] = True
        return len(overdue)

    @property
    def inflight_queries(self) -> int:
        return len(self._inflight)

    def stats(self, include_skyline_counts: bool = False) -> dict:
        out = {
            "mode": "sliding",
            # which skyline-mask kernel the slide step runs: "pallas" means
            # the VMEM-tiled triangular kernels WITH the sorted-order tile
            # skip (ops/pallas_dominance.py), the fast path the tree merge
            # shares; "sweep"/"scan" are the d<=2 and portable fallbacks
            "mask_kernel": (
                "sweep"
                if self.config.dims <= 2
                else ("pallas" if self._use_pallas else "scan")
            ),
            "records_in": self.records_in,
            "dropped": self.dropped,
            "prefiltered": self.prefiltered,
            "inflight_queries": len(self._inflight),
            "window_size": self.window_size,
            "slide": self.slide,
            "slides_closed": self._slides_closed,
            "pending_flush_rows": int(self._pend_rows.sum()),
            "processing_ms": self.processing_ns / 1e6,
            "partitions": {
                "records_seen": self.records_seen.tolist(),
                "max_seen_id": self.max_seen_id.tolist(),
            },
            "meshed": self.mesh is not None,
        }
        if include_skyline_counts:
            out["partitions"]["skyline_counts"] = self._win_counts.tolist()
        return out
