"""Streaming layer: windowing, record-id query barrier, and the engine."""

from skyline_tpu.stream.batched import PartitionSet, PartitionView
from skyline_tpu.stream.engine import EngineConfig, SkylineEngine

__all__ = ["PartitionSet", "PartitionView", "EngineConfig", "SkylineEngine"]
