"""Streaming layer: windowing, record-id query barrier, and the engine."""

from skyline_tpu.stream.window import PartitionState
from skyline_tpu.stream.engine import EngineConfig, SkylineEngine

__all__ = ["PartitionState", "EngineConfig", "SkylineEngine"]
