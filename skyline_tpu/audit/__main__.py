"""CLI for the audit plane: replay a divergence repro bundle offline.

    python -m skyline_tpu.audit replay artifacts/audit/bundle-v41-1
    python -m skyline_tpu.audit replay <bundle> --json

Replay is deterministic and self-contained — the bundle carries the
checkpoint, both skylines, the EXPLAIN plan, and the knob snapshot — so
it runs on any machine with the package installed, no access to the
original deployment:

1. re-derive the published-vs-oracle diff from the frozen arrays and
   check it matches the manifest (``reproduced``: the divergence is a
   property of the evidence, not of the machine that caught it);
2. restore the checkpoint and re-run the FAST PATH (flush + global
   merge, plan attached) against a FRESH host-oracle recompute of the
   restored state (``engine_diverges``: True means the engine itself
   deterministically reproduces the bug from this state; False means
   the engine is sound and only the published bytes lied — e.g. the
   ``audit.corrupt`` drill, or snapshot-layer corruption);
3. print a decision-level diff of the bundled EXPLAIN plan vs the
   replay's plan (which merge path, which prunes, which cache state),
   plus the first differing row.

Exit 0 when the bundle's diff reproduces offline, 2 when it does not
(stale or inconsistent evidence), 1 on usage/load errors.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def replay(bundle_path: str) -> dict:
    """Re-run one bundle; returns the verdict document."""
    from skyline_tpu.audit import canonical_rows, first_diff
    from skyline_tpu.audit.bundle import load_bundle
    from skyline_tpu.ops.dominance import skyline_np
    from skyline_tpu.telemetry.explain import QueryPlan
    from skyline_tpu.utils.checkpoint import load_engine

    b = load_bundle(bundle_path)
    manifest = b["manifest"]

    # 1. the frozen evidence, re-derived from scratch
    recomputed = first_diff(b["published"], b["oracle"])
    reproduced = (
        recomputed is not None and recomputed == manifest.get("first_diff")
    )

    # 2. fast path vs fresh oracle from the restored state
    engine = load_engine(b["checkpoint"])
    engine.pset.flush_all()  # fold any restored pendings in first
    replay_plan = QueryPlan("replay", "replay")
    engine.pset.set_explain(replay_plan)
    _, _, _, pts = engine.pset.global_merge_stats(emit_points=True)
    fast = (
        np.asarray(pts, dtype=np.float32)
        if pts is not None
        else np.empty((0, engine.pset.dims), dtype=np.float32)
    )
    skies, _ = engine.pset.audit_state()
    union = np.concatenate(skies, axis=0) if skies else fast
    # offline replay is the court of appeal: always the quadratic oracle,
    # independent of whatever SKYLINE_AUDIT_ORACLE picked online
    oracle_ck = np.asarray(skyline_np(union), dtype=np.float32)
    engine_diff = first_diff(fast, oracle_ck)

    # 3. does the restored state still produce the published bytes?
    state_matches_published = (
        canonical_rows(fast).tobytes()
        == canonical_rows(b["published"]).tobytes()
    )

    return {
        "bundle": bundle_path,
        "version": manifest.get("version"),
        "trace_id": manifest.get("trace_id"),
        "reproduced": bool(reproduced),
        "recomputed_first_diff": recomputed,
        "manifest_first_diff": manifest.get("first_diff"),
        "engine_diverges": engine_diff is not None,
        "engine_first_diff": engine_diff,
        "state_matches_published": bool(state_matches_published),
        "replay_plan": replay_plan.to_doc(),
        "bundled_plan": b["plan"],
    }


def _print_human(v: dict) -> None:
    print(f"bundle   {v['bundle']}")
    print(f"snapshot version {v['version']}  trace {v['trace_id']}")
    print(
        "reproduced: "
        + ("YES — published vs oracle diff matches the manifest"
           if v["reproduced"]
           else "NO — frozen evidence does not re-derive the manifest diff")
    )
    d = v["recomputed_first_diff"]
    if d is not None:
        print(
            f"  first diff at row {d['index']}: "
            f"published={d['published_row']} oracle={d['oracle_row']} "
            f"({d['published_rows']} vs {d['oracle_rows']} rows)"
        )
    if v["engine_diverges"]:
        e = v["engine_first_diff"]
        print(
            "engine: DIVERGES from the oracle on the restored state "
            f"(first diff at row {e['index']}) — deterministic engine bug"
        )
    else:
        print(
            "engine: sound on the restored state — the published bytes "
            "lied (snapshot-layer corruption"
            + ("" if v["state_matches_published"]
               else " or post-publish state drift")
            + ")"
        )
    from skyline_tpu.telemetry.explain import format_diff, format_plan

    if v["bundled_plan"] is not None:
        print("-- decision diff (bundled plan vs replay) --")
        print(format_diff(v["bundled_plan"], v["replay_plan"]))
    else:
        print("-- replay plan (no bundled plan retained) --")
        print(format_plan(v["replay_plan"]))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m skyline_tpu.audit",
        description="Replay an audit divergence repro bundle offline.",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("replay", help="re-run one bundle deterministically")
    rp.add_argument("bundle", help="bundle directory (see RUNBOOK §2l)")
    rp.add_argument(
        "--json", action="store_true", help="emit the verdict as JSON"
    )
    args = ap.parse_args(argv)

    v = replay(args.bundle)
    if args.json:
        print(json.dumps(v, indent=2))
    else:
        _print_human(v)
    return 0 if v["reproduced"] else 2


if __name__ == "__main__":
    raise SystemExit(main())
