"""Host oracles for the audit plane — the independent answers published
skylines are shadow-verified against.

Two implementations, selected by ``SKYLINE_AUDIT_ORACLE``:

- ``quadratic`` — ``ops.dominance.skyline_np``: the O(n²d) float64
  pairwise oracle the audit plane shipped with. At full sample it costs
  ~766ms/check on the bench union (194.5% tax), which is why
  SKYLINE_AUDIT_SAMPLE had to be dialed down.
- ``sorted`` (default) — this module's sorted band scan: group
  numerically-equal rows with a lexicographic sort, order group
  representatives by float64 row sum, then sweep fixed-size sum-ordered
  *bands* against the survivor set (a dominator's fixed-order float64
  sum is never greater than its victim's — rounding is monotone — so
  every cross-band domination points backward) and close each band
  with an exact both-direction pairwise tile (which covers equal-sum
  ambiguity inside the band). Full-rate shadow verification drops
  under the 100ms/check budget.

This is deliberately an independent implementation, not a port of
``ops/sorted_sfs.py`` (which the engine itself may be executing): no
dedup via ``np.unique``, no growing block schedule, no
distinct-implies-strict shortcut — every pairwise verdict here is the
full ``all(<=) & any(<)`` check in float64, like the quadratic oracle.
An oracle that shares code with the system under test can only confirm
its own bugs; tests gate the two oracles against each other
(oracle-of-the-oracle), and the quadratic one stays available behind
the knob for exactly that purpose.

Same contract as ``skyline_np``: rows in, surviving rows out (original
bytes, original relative order); duplicates all survive; NaN rows
neither dominate nor are dominated; invalidity is the caller's problem
(the auditor passes the already-published union).
"""

from __future__ import annotations

import numpy as np

from skyline_tpu.analysis.registry import env_str

__all__ = ["oracle_kind", "oracle_fn", "sorted_skyline_np"]

_VCHUNK = 512  # victims per dominated-check tile (bounds the n*m*d tmp)
_DCHUNK = 2048  # dominators per survivor-sweep tile (early-exit grain)
_BAND = 1024  # candidates advanced per scan step (sum-ordered band)


def oracle_kind() -> str:
    """``SKYLINE_AUDIT_ORACLE``: which host oracle the auditor trusts."""
    v = env_str("SKYLINE_AUDIT_ORACLE", "sorted")
    return v if v in ("sorted", "quadratic") else "sorted"


def oracle_fn():
    """The selected rows-in/rows-out oracle callable."""
    if oracle_kind() == "quadratic":
        from skyline_tpu.ops.dominance import skyline_np

        return skyline_np
    return sorted_skyline_np


def _any_dominates(doms: np.ndarray, victims: np.ndarray) -> np.ndarray:
    """(m,) bool: victim j is fully dominated (``all(<=) & any(<)``) by
    some dominator row. Chunked over victims to bound the broadcast."""
    out = np.zeros(victims.shape[0], bool)
    for j in range(0, victims.shape[0], _VCHUNK):
        v = victims[None, j : j + _VCHUNK, :]
        le = np.all(doms[:, None, :] <= v, axis=2)
        lt = np.any(doms[:, None, :] < v, axis=2)
        out[j : j + _VCHUNK] = (le & lt).any(axis=0)
    return out


def sorted_skyline_np(x) -> np.ndarray:
    """Skyline rows of ``x`` via the run-partitioned sorted scan."""
    rows = np.asarray(x)
    n = rows.shape[0]
    if n == 0:
        return rows[:0].copy()
    xs = rows.astype(np.float64)  # f32 -> f64 is exact; comparisons agree
    keep = np.zeros(n, bool)

    nanrow = np.isnan(xs).any(axis=1)
    keep[nanrow] = True  # NaN rows always survive
    vidx = np.flatnonzero(~nanrow)
    if vidx.size == 0:
        return rows[keep]
    xv = xs[vidx]

    # group numerically-equal rows (lexsort compares values, so -0.0 and
    # +0.0 land in one group — correct: dominance is numeric)
    order = np.lexsort(xv.T)
    xo = xv[order]
    same = np.zeros(order.size, bool)
    if order.size > 1:
        same[1:] = np.all(xo[1:] == xo[:-1], axis=1)
    gid_sorted = np.cumsum(~same) - 1
    gid = np.empty(order.size, np.int64)
    gid[order] = gid_sorted
    reps = order[~same]  # first member of each group, in lexsort order
    R = xv[reps]

    with np.errstate(invalid="ignore"):
        sums = R.sum(axis=1)
    special = np.isnan(sums)  # mixed ±inf rows: no sort key, see below
    core = np.flatnonzero(~special)
    core = core[np.argsort(sums[core], kind="stable")]

    g_alive = np.zeros(reps.size, bool)
    # survivors live in ONE doubling array, swept in _DCHUNK tiles, and
    # candidates advance in fixed-size sum-ordered bands rather than one
    # equal-sum run at a time: a per-run python loop degenerates to
    # O(runs²) interpreter overhead when nearly every row has a distinct
    # sum (anti-correlated low-d unions). Correctness doesn't need run
    # boundaries — the survivor sweep is the full both-direction check
    # (a larger-sum band member can never pass all(<=) against a
    # smaller-sum victim, so the in-band pairwise tile is exact, and a
    # dead band member's kills are covered by dominance transitivity).
    dcols = xv.shape[1]
    surv_arr = np.empty((0, dcols), np.float64)
    s_count = 0
    i = 0
    while i < core.size:
        j = min(i + _BAND, core.size)
        band = core[i:j]
        cand = R[band]
        alive = np.ones(band.size, bool)
        for lo in range(0, s_count, _DCHUNK):
            hi = min(lo + _DCHUNK, s_count)  # never sweep unfilled capacity
            alive &= ~_any_dominates(surv_arr[lo:hi], cand)
            if not alive.any():
                break
        if alive.any() and band.size > 1:
            a = np.flatnonzero(alive)
            alive[a[_any_dominates(cand, cand[a])]] = False
        if alive.any():
            new = cand[alive]
            need = s_count + new.shape[0]
            if need > surv_arr.shape[0]:
                cap = max(1024, surv_arr.shape[0])
                while cap < need:
                    cap *= 2
                grown = np.empty((cap, dcols), np.float64)
                grown[:s_count] = surv_arr[:s_count]
                surv_arr = grown
            surv_arr[s_count:need] = new
            s_count = need
            g_alive[band[alive]] = True
        i = j

    if special.any():
        spec = np.flatnonzero(special)
        for gsi in spec:  # as victims: against every other group rep
            others = np.delete(np.arange(reps.size), gsi)
            if not _any_dominates(R[others], R[gsi][None, :]).any():
                g_alive[gsi] = True
        live = np.flatnonzero(g_alive & ~special)  # ...and as dominators
        if live.size:
            dead = _any_dominates(R[spec], R[live])
            g_alive[live[dead]] = False

    keep[vidx] = g_alive[gid]
    return rows[keep]
