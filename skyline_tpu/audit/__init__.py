"""Online audit plane (ISSUE 10): sampled shadow verification of
published skylines against the independent host oracle.

Every answer the engine serves rides a cascade of byte-identity-critical
shortcuts — grid prefilter, bf16 margin pass, witness-pruned tournament
tree, epoch-keyed merge cache — each verified offline by property tests
and A/B benchmarks. This plane closes the loop ONLINE: in the serving
process, a knob-controlled fraction of published snapshots
(``SKYLINE_AUDIT_SAMPLE``) is recomputed from the engine's partition
state through an independent numpy oracle (``audit/oracle.py``; the
``SKYLINE_AUDIT_ORACLE`` knob picks the default full-rate sorted scan
or the original O(n²d) quadratic oracle, kept as the oracle-of-the-
oracle) and compared byte-for-byte after canonical row ordering.

A divergence increments ``skyline_audit_divergence_total``, burns the
``audit_divergence`` SLO, and freezes a self-contained repro bundle
under ``SKYLINE_AUDIT_DIR`` (checkpoint + WAL slice + EXPLAIN plan +
knob snapshot + both skylines — see ``bundle.py``), replayable offline
via ``python -m skyline_tpu.audit replay <bundle>``. Synthetic canaries
(``canary.py``) with hand-known answers exercise every merge decision
path even when organic traffic is idle.

Validity discipline: a check only runs when the snapshot's
``source_key`` (the partition-epoch key at merge time) still equals the
live epoch key — under overlapped merges the state can advance past the
published bytes, and auditing a moved state would fabricate
divergences. Moved-state samples count as ``audit.skips``, never as
checks. The whole plane is host-side and post-publish: nothing enters
jit and a check never perturbs the state it verifies
(``PartitionSet.audit_state`` does not flush).
"""

from __future__ import annotations

import time

import numpy as np


def canonical_rows(a) -> np.ndarray:
    """Contiguous float32 rows in canonical (lexicographic) order, so two
    path-dependent row orderings of the same point set compare
    byte-for-byte."""
    a = np.ascontiguousarray(np.asarray(a, dtype=np.float32))
    if a.shape[0] <= 1:
        return a
    return np.ascontiguousarray(a[np.lexsort(a.T[::-1])])


def first_diff(published: np.ndarray, oracle: np.ndarray) -> dict | None:
    """First differing row between two canonically-ordered skylines, as a
    JSON-able record (None when byte-identical)."""
    pub = canonical_rows(published)
    orc = canonical_rows(oracle)
    if pub.shape == orc.shape and pub.tobytes() == orc.tobytes():
        return None
    m = min(pub.shape[0], orc.shape[0])
    idx = m  # default: one side is a strict prefix of the other
    for i in range(m):
        if pub[i].tobytes() != orc[i].tobytes():
            idx = i
            break
    return {
        "index": int(idx),
        "published_row": (
            pub[idx].tolist() if idx < pub.shape[0] else None
        ),
        "oracle_row": orc[idx].tolist() if idx < orc.shape[0] else None,
        "published_rows": int(pub.shape[0]),
        "oracle_rows": int(orc.shape[0]),
    }


class Auditor:
    """Engine-owned background auditor: organic sampled checks + canaries.

    Created by ``SkylineEngine.__init__`` when ``SKYLINE_AUDIT`` is on
    and a telemetry hub is attached; the engine calls ``maybe_check``
    at the tail of every result emission (off the jitted path, after the
    answer is already out the door) and the worker drives
    ``maybe_canary`` from its idle loop. Engine-thread only — no lock.
    """

    def __init__(self, engine, telemetry):
        from skyline_tpu.analysis.registry import env_float, env_str

        self.engine = engine
        self.telemetry = telemetry
        self.sample = env_float("SKYLINE_AUDIT_SAMPLE", 1.0)
        self.canary_interval_s = env_float("SKYLINE_AUDIT_CANARY_S", 300.0)
        self.bundle_dir = env_str("SKYLINE_AUDIT_DIR", "artifacts/audit")
        # deterministic sampling accumulator — same trigger sequence, same
        # audited subset, every run (no RNG on the serving path)
        self._acc = 0.0
        self._last_canary_s: float | None = None
        self._bundle_seq = 0
        # the worker points this at its WAL directory post-construction so
        # divergence bundles can freeze the segment slice; None = no WAL
        self.wal_dir: str | None = None

    # -- organic sampled checks -------------------------------------------

    def maybe_check(self, q) -> None:
        """Sampling gate: called per emitted result; runs ``check`` every
        ``1/sample`` results (deterministic accumulator)."""
        if self.sample <= 0.0:
            return
        self._acc += min(self.sample, 1.0)
        if self._acc < 1.0:
            return
        self._acc -= 1.0
        self.check(q)

    def check(self, q=None) -> dict | None:
        """Shadow-verify the latest published snapshot against the host
        oracle; returns the check record (None when no check could run).

        Observability must never take the answer down: callers wrap this
        defensively (engine) or let it raise (tests/replay).
        """
        store = self.engine.snapshots
        snap = store.latest() if store is not None else None
        if snap is None:
            return None
        tel = self.telemetry
        trace_id = snap.meta.get("trace_id")
        if snap.meta.get("partial"):
            # a chip-degraded snapshot (RUNBOOK §2p) is honestly marked —
            # it is the surviving chips' exact skyline, which by
            # construction differs from the full oracle, so checking it
            # would count marked degradation as a lying answer
            tel.inc("audit.skips")
            tel.flight.note(
                "audit.skip", reason="partial_snapshot",
                version=int(snap.version), trace_id=trace_id,
            )
            return None
        source_key = snap.source_key
        epoch_key = self.engine.pset.epoch_key
        if source_key is not None and source_key != epoch_key:
            # overlapped ingest flushed past the published bytes — the
            # snapshot is no longer a function of the live state, so a
            # comparison would fabricate a divergence
            tel.inc("audit.skips")
            tel.flight.note(
                "audit.skip", reason="state_moved", version=int(snap.version),
                trace_id=trace_id,
            )
            return None
        t0 = time.perf_counter_ns()
        skies, _ = self.engine.pset.audit_state()
        union = (
            np.concatenate([s for s in skies], axis=0)
            if skies
            else np.empty((0, self.engine.pset.dims), dtype=np.float32)
        )
        from skyline_tpu.audit.oracle import oracle_fn, oracle_kind

        oracle = np.asarray(oracle_fn()(union), dtype=np.float32)
        published = np.asarray(snap.points, dtype=np.float32)
        diff = first_diff(published, oracle)
        ok = diff is None
        tel.inc("audit.checks")
        record = {
            "kind": "organic",
            "ok": ok,
            "trace_id": trace_id,
            "version": int(snap.version),
            "digest": snap.digest,
            "oracle": oracle_kind(),
            "published_rows": int(published.shape[0]),
            "oracle_rows": int(oracle.shape[0]),
            "first_diff": diff,
            "bundle": None,
        }
        if not ok:
            tel.inc("audit.divergence")
            record["bundle"] = self._freeze_bundle(snap, oracle, diff, q)
        tel.audit.add(record)
        # satellite: checks and divergences join /explain and /trace via
        # the audited snapshot's trace_id
        tel.spans.record(
            "audit/divergence" if not ok else "audit/check",
            t0, time.perf_counter_ns(), trace_id=trace_id, tid=4,
            args={"version": int(snap.version), "ok": ok},
        )
        tel.flight.note(
            "audit.divergence" if not ok else "audit.check",
            ok=ok, version=int(snap.version), trace_id=trace_id,
            bundle=record["bundle"],
        )
        return record

    def _freeze_bundle(self, snap, oracle, diff, q) -> str | None:
        """Freeze a divergence repro bundle; never raises (bundle failure
        must not mask the divergence signal that triggered it)."""
        try:
            from skyline_tpu.audit.bundle import freeze_bundle

            self._bundle_seq += 1
            plan_doc = None
            if self.telemetry.explain is not None and snap.meta.get(
                "trace_id"
            ):
                plan_doc = self.telemetry.explain.by_trace(
                    snap.meta["trace_id"]
                )
            if plan_doc is None:
                plan_doc = self.telemetry.explain.by_version(
                    int(snap.version)
                )
            return freeze_bundle(
                self.engine, snap, oracle, diff,
                out_dir=self.bundle_dir,
                seq=self._bundle_seq,
                plan_doc=plan_doc,
                wal_dir=self.wal_dir,
            )
        except Exception:
            self.telemetry.inc("audit.bundle_errors")
            return None

    # -- synthetic canaries -----------------------------------------------

    def maybe_canary(self, now_s: float | None = None) -> bool:
        """Idle-loop hook: run one canary sweep when the interval elapsed
        (0 disables). Returns True when a sweep ran."""
        if self.canary_interval_s <= 0.0:
            return False
        now = time.monotonic() if now_s is None else now_s
        if self._last_canary_s is None:
            # first idle tick arms the timer; the sweep itself waits one
            # full interval so startup isn't front-loaded with canary work
            self._last_canary_s = now
            return False
        if now - self._last_canary_s < self.canary_interval_s:
            return False
        self._last_canary_s = now
        self.run_canaries()
        return True

    def run_canaries(self) -> list[dict]:
        """One sweep of every merge-path canary; returns the records."""
        from skyline_tpu.audit.canary import run_canaries

        return run_canaries(self.telemetry)
