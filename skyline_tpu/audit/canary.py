"""Correctness canaries: known-answer micro-states for every merge path.

Organic audit checks only cover the paths live traffic happens to take —
an idle deployment, or one whose workload never dirties a partition
subset, could carry a silently broken ``tree_delta`` or ``cache_hit``
path for days. Each canary here builds a tiny deterministic
``PartitionSet`` whose exact skyline is KNOWN BY CONSTRUCTION (no oracle
in the loop), steers the merge down one specific decision path, and
compares the emitted points byte-for-byte against the hand-computed
answer. The ``host`` canary closes the remaining gap by checking the
audit oracle itself (``ops.dominance.skyline_np``) against a known
answer, so a broken oracle cannot silently vouch for broken fast paths.

Known-answer construction: any set of DISTINCT points with EQUAL
coordinate sum is mutually non-dominated (componentwise ``a <= b`` with
``a != b`` forces ``sum(a) < sum(b)``), so "parents on the sum-S plane
plus strictly-dominated chaff at parent + 0.25" has skyline == parents,
exactly, in float32. Path steering uses only state shape — d=2 avoids
the tournament tree, a repeated merge hits the epoch cache, a
single-partition re-flush lands under the delta cutoff — never knob
mutation, so canaries verify the paths PRODUCTION is configured to run.

Driven by the worker's idle loop every ``SKYLINE_AUDIT_CANARY_S``
seconds (``Auditor.maybe_canary``) and directly by tests/smoke scripts
via ``Auditor.run_canaries``.
"""

from __future__ import annotations

import time

import numpy as np

from skyline_tpu.audit import canonical_rows, first_diff

_P = 4  # canary partition count; 1-of-4 dirty = 0.25 < the 0.75 cutoff
_N_PARENTS = 8
_CHAFF_DELTA = 0.25


def _parents(d: int, n: int = _N_PARENTS) -> np.ndarray:
    """``n`` distinct float32 points on the sum-S plane (S = 3n): the
    exact skyline of every canary state that embeds them."""
    s = 3.0 * n
    out = np.zeros((n, d), dtype=np.float32)
    for k in range(n):
        out[k, 0] = float(k)
        if d > 1:
            out[k, 1] = float((2 * k) % n)
        if d > 2:
            # dump the remainder into the last coord; middle coords stay 0
            out[k, d - 1] = s - out[k, :2].sum()
        else:
            out[k, 1] = s - out[k, 0]
    return out


def _micro_state(d: int) -> tuple[np.ndarray, np.ndarray]:
    """(all rows, expected skyline) for one canary state: parents plus one
    strictly-dominated chaff row per parent."""
    parents = _parents(d)
    chaff = parents + np.float32(_CHAFF_DELTA)
    rows = np.concatenate([parents, chaff], axis=0)
    return np.ascontiguousarray(rows), parents


def _mk_pset(d: int):
    from skyline_tpu.stream.batched import PartitionSet

    return PartitionSet(_P, d, buffer_size=256)


def _fill(pset, rows: np.ndarray) -> None:
    """Round-robin the rows across partitions (a chaff row usually lands
    away from its dominating parent, so it survives the partition-local
    skyline and only dies in the global merge — the interesting case)."""
    for p in range(_P):
        sub = np.ascontiguousarray(rows[p::_P])
        if sub.shape[0]:
            pset.add_batch(p, sub, max_id=rows.shape[0], now_ms=0.0)
    pset.flush_all()


def _merge_with_plan(pset) -> tuple[np.ndarray, str | None]:
    """Global merge with a throwaway EXPLAIN plan attached, so the canary
    can report which decision path the merge ACTUALLY took."""
    from skyline_tpu.telemetry.explain import QueryPlan

    plan = QueryPlan("canary", "canary")
    pset.set_explain(plan)
    _, _, g, pts = pset.global_merge_stats(emit_points=True)
    taken = (plan.merge or {}).get("path")
    if pts is None:
        pts = np.empty((0, pset.dims), dtype=np.float32)
    return np.asarray(pts, dtype=np.float32), taken


def _verdict(pts: np.ndarray, expected: np.ndarray, taken) -> tuple[bool, dict]:
    diff = first_diff(pts, expected)
    return diff is None, {
        "taken": taken,
        "rows": int(np.asarray(pts).shape[0]),
        "expected_rows": int(expected.shape[0]),
        "first_diff": diff,
    }


def _canary_flat() -> tuple[bool, dict]:
    """d=2 keeps the tournament tree structurally out (tree needs d>2), so
    a cold merge takes the flat union pass."""
    rows, expected = _micro_state(2)
    pset = _mk_pset(2)
    _fill(pset, rows)
    pts, taken = _merge_with_plan(pset)
    return _verdict(pts, expected, taken)


def _canary_tree() -> tuple[bool, dict]:
    """d=3 cold merge: the pruned tournament tree (when enabled)."""
    rows, expected = _micro_state(3)
    pset = _mk_pset(3)
    _fill(pset, rows)
    pts, taken = _merge_with_plan(pset)
    return _verdict(pts, expected, taken)


def _canary_cache_hit() -> tuple[bool, dict]:
    """Merge twice with no flush in between: the second answer must come
    from the epoch-keyed cache, byte-identical."""
    rows, expected = _micro_state(3)
    pset = _mk_pset(3)
    _fill(pset, rows)
    _merge_with_plan(pset)  # warm the cache
    pts, taken = _merge_with_plan(pset)
    return _verdict(pts, expected, taken)


def _canary_tree_delta() -> tuple[bool, dict]:
    """Dirty exactly one of four partitions after a cached merge (0.25 <=
    the delta cutoff): the incremental ``cached global ∪ dirty skylines``
    merge, routed through the tree. The new rows are one fresh parent on
    the same sum plane (joins the skyline) plus its chaff."""
    rows, expected = _micro_state(3)
    pset = _mk_pset(3)
    _fill(pset, rows)
    _merge_with_plan(pset)  # prime the cache
    new_parent = np.zeros((1, 3), dtype=np.float32)
    new_parent[0, 0] = float(_N_PARENTS)  # distinct first coord
    new_parent[0, 2] = 3.0 * _N_PARENTS - new_parent[0, 0]
    extra = np.concatenate(
        [new_parent, new_parent + np.float32(_CHAFF_DELTA)], axis=0
    )
    pset.add_batch(0, np.ascontiguousarray(extra), max_id=99, now_ms=0.0)
    pset.flush_all()
    pts, taken = _merge_with_plan(pset)
    return _verdict(pts, np.concatenate([expected, new_parent]), taken)


def _canary_host() -> tuple[bool, dict]:
    """The audit oracles themselves against a hand-known answer — a
    broken oracle must not silently vouch for broken fast paths. Both
    the quadratic and the sorted-scan oracle must agree with the known
    answer regardless of which one SKYLINE_AUDIT_ORACLE selects."""
    from skyline_tpu.audit.oracle import sorted_skyline_np
    from skyline_tpu.ops.dominance import skyline_np

    rows, expected = _micro_state(3)
    for fn in (skyline_np, sorted_skyline_np):
        pts = np.asarray(fn(rows), dtype=np.float32)
        ok, detail = _verdict(pts, expected, "host")
        if not ok:
            detail = {**detail, "oracle": fn.__name__}
            return ok, detail
    return ok, detail


# every merge decision path the engine can take (stream/batched.py path
# literals + the engine's per-partition host fallback)
CANARIES: tuple[tuple[str, object], ...] = (
    ("flat", _canary_flat),
    ("tree", _canary_tree),
    ("cache_hit", _canary_cache_hit),
    ("tree_delta", _canary_tree_delta),
    ("host", _canary_host),
)


def run_canaries(telemetry) -> list[dict]:
    """One sweep: run every canary, fold outcomes into the audit plane
    (counters, coverage map, verdict ring, flight + span rings)."""
    records = []
    for name, fn in CANARIES:
        t0 = time.perf_counter_ns()
        try:
            ok, detail = fn()
        except Exception as e:  # a crashing canary IS a failing canary
            ok, detail = False, {"error": repr(e), "taken": None}
        telemetry.inc("audit.checks")
        telemetry.inc("audit.canary_runs")
        if not ok:
            telemetry.inc("audit.divergence")
        telemetry.audit.record_canary(name, ok)
        rec = {"kind": "canary", "path": name, "ok": ok, **detail}
        telemetry.audit.add(rec)
        telemetry.flight.note(
            "audit.canary", path=name, ok=ok, taken=detail.get("taken")
        )
        telemetry.spans.record(
            "audit/canary", t0, time.perf_counter_ns(), tid=4,
            args={"path": name, "ok": ok},
        )
        records.append(rec)
    return records
