"""Divergence repro bundles: everything needed to re-run a failed audit
check on another machine, frozen at detection time.

Anatomy of ``<SKYLINE_AUDIT_DIR>/bundle-v<version>-<seq>/``:

- ``manifest.json``   — schema, trace_id, snapshot version + digest, the
  first differing row, row counts, the full registry knob snapshot
  (set value + declared default for every declared knob — the exact
  configuration the divergence happened under), and the WAL segment
  names captured.
- ``checkpoint.npz``  — the engine state via ``utils.checkpoint
  .save_engine`` (atomic, CRC-guarded; the same writer the resilience
  plane uses), so replay restores the partition skylines that produced
  the divergence.
- ``published.npy`` / ``oracle.npy`` — both skylines, verbatim.
- ``explain.json``    — the diverging query's EXPLAIN plan (null when
  the plan ring already evicted it), for the decision-level diff.
- ``wal/``            — a copy of the live WAL segments at detection
  time (absent when the worker runs without resilience).

``python -m skyline_tpu.audit replay <bundle>`` (``__main__.py``)
consumes this layout offline.
"""

from __future__ import annotations

import json
import os
import shutil
import time

import numpy as np

BUNDLE_SCHEMA = 1
MANIFEST = "manifest.json"


def freeze_bundle(
    engine,
    snap,
    oracle: np.ndarray,
    diff: dict | None,
    *,
    out_dir: str,
    seq: int,
    plan_doc: dict | None = None,
    wal_dir: str | None = None,
) -> str:
    """Write one self-contained repro bundle; returns its directory."""
    root = os.path.join(out_dir, f"bundle-v{int(snap.version)}-{seq}")
    n = 0
    while os.path.exists(root):  # never clobber earlier evidence
        n += 1
        root = os.path.join(
            out_dir, f"bundle-v{int(snap.version)}-{seq}.{n}"
        )
    os.makedirs(root)

    from skyline_tpu.utils.checkpoint import save_engine

    save_engine(
        engine,
        os.path.join(root, "checkpoint.npz"),
        extra_meta={"audit_bundle": True, "snapshot_version": int(snap.version)},
    )
    np.save(
        os.path.join(root, "published.npy"),
        np.asarray(snap.points, dtype=np.float32),
    )
    np.save(
        os.path.join(root, "oracle.npy"),
        np.asarray(oracle, dtype=np.float32),
    )
    with open(os.path.join(root, "explain.json"), "w") as f:
        json.dump(plan_doc, f, indent=2)

    wal_segments = []
    if wal_dir is not None and os.path.isdir(wal_dir):
        from skyline_tpu.resilience.wal import list_segments

        os.makedirs(os.path.join(root, "wal"), exist_ok=True)
        for _, seg_path in list_segments(wal_dir):
            shutil.copy2(seg_path, os.path.join(root, "wal"))
            wal_segments.append(os.path.basename(seg_path))

    manifest = {
        "schema": BUNDLE_SCHEMA,
        "created_ms": round(time.time() * 1000.0, 1),
        "trace_id": snap.meta.get("trace_id"),
        "query_id": snap.meta.get("query_id"),
        "version": int(snap.version),
        "digest": snap.digest,
        "dims": int(engine.pset.dims),
        "published_rows": int(np.asarray(snap.points).shape[0]),
        "oracle_rows": int(np.asarray(oracle).shape[0]),
        "first_diff": diff,
        "knobs": knob_snapshot(),
        "wal_segments": wal_segments,
        "has_explain": plan_doc is not None,
    }
    tmp = os.path.join(root, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(root, MANIFEST))
    return root


def knob_snapshot() -> list[dict]:
    """Every declared knob's set value (None = unset) + declared default —
    the exact configuration a divergence happened under."""
    from skyline_tpu.analysis.registry import KNOBS, env_str

    out = []
    for k in KNOBS:
        out.append({
            "name": k.name,
            "value": env_str(k.name),  # lint: allow-raw-env
            "default": k.default,
        })
    return out


def load_bundle(path: str) -> dict:
    """Read a bundle directory back into memory for replay."""
    with open(os.path.join(path, MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("schema") != BUNDLE_SCHEMA:
        raise ValueError(
            f"unsupported bundle schema {manifest.get('schema')!r} in {path}"
        )
    published = np.load(os.path.join(path, "published.npy"))
    oracle = np.load(os.path.join(path, "oracle.npy"))
    plan_doc = None
    explain_path = os.path.join(path, "explain.json")
    if os.path.exists(explain_path):
        with open(explain_path) as f:
            plan_doc = json.load(f)
    return {
        "path": path,
        "manifest": manifest,
        "published": published,
        "oracle": oracle,
        "plan": plan_doc,
        "checkpoint": os.path.join(path, "checkpoint.npz"),
    }
