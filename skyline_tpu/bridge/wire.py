"""Wire formats — byte-compatible with the reference's Kafka payloads.

Data plane:    CSV lines ``"id,v1,...,vd"``        (unified_producer.py:174)
Control plane: trigger lines ``"queryId,requiredRecordCount"``
               (unified_producer.py:184; a payload with no comma parses to
               required=0 → immediate execution, query_trigger.py:21-26)
Result plane:  one JSON object per query with the reference's field names and
               order (FlinkSkyline.java:631-648), plus ``query_latency_ms``
               which the reference computes but never emits
               (FlinkSkyline.java:588; metrics_collector.py:101 reads it and
               always got 0 — fixed here) and optional ``skyline_points``
               (the reference's commented-out visualization block,
               FlinkSkyline.java:612-623).

Malformed data lines are dropped, mirroring ``ServiceTuple.fromString``
returning null + the non-null filter (ServiceTuple.java:89-104,
FlinkSkyline.java:104). Rows containing NaN/inf are also rejected so they can
never enter windows (the +inf padding convention reserves non-finite values).
"""

from __future__ import annotations

import json

import numpy as np


def parse_tuple_lines(lines, dims: int):
    """Parse data-plane CSV lines into (ids int64 (M,), values float32 (M, d)).

    Lines that are malformed (wrong field count, non-numeric, non-finite
    values) are silently dropped, like the reference's fromString-null filter.
    Returns (ids, values, n_dropped).

    Uses the C++ fast parser (skyline_tpu.native) when available — ingest is
    the documented dominant cost at stream rates (pdf §5.5) — with this
    Python loop as the semantics-defining fallback.
    """
    if not isinstance(lines, list):
        lines = list(lines)
    if lines:
        from skyline_tpu import native

        if native.get_lib() is not None:
            text = ("\n".join(lines)).encode("utf-8", errors="replace")
            out = native.parse_tuples_native(text, dims, max_rows=len(lines))
            if out is not None:
                return out
    ids = []
    rows = []
    dropped = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue  # blank lines are skipped, not counted as malformed
        parts = line.split(",")
        if len(parts) != dims + 1:
            dropped += 1
            continue
        try:
            rid = int(parts[0])
            vals = [float(p) for p in parts[1:]]
        except ValueError:
            dropped += 1
            continue
        if not (-(2**63) <= rid < 2**63):
            # out-of-int64-range ids are malformed, not a batch-killing
            # numpy OverflowError
            dropped += 1
            continue
        if not all(np.isfinite(v) for v in vals):
            dropped += 1
            continue
        ids.append(rid)
        rows.append(vals)
    if not ids:
        return (
            np.empty((0,), dtype=np.int64),
            np.empty((0, dims), dtype=np.float32),
            dropped,
        )
    return (
        np.asarray(ids, dtype=np.int64),
        np.asarray(rows, dtype=np.float32),
        dropped,
    )


def format_tuple_line(record_id: int, values) -> str:
    return f"{record_id}," + ",".join(str(float(v)) for v in values)


def parse_trigger(payload: str):
    """Parse ``"qid,requiredCount"``; a count-less payload means required=0
    (immediate execution) per query_trigger.py:21-26 / FlinkSkyline.java:333-334."""
    parts = payload.strip().split(",")
    qid = parts[0]
    try:
        required = int(parts[1]) if len(parts) > 1 else 0
    except ValueError:
        required = 0
    return qid, required


def format_trigger(qid, required_count: int) -> str:
    return f"{qid},{required_count}"


RESULT_FIELDS = (
    "query_id",
    "record_count",
    "skyline_size",
    "optimality",
    "ingestion_time_ms",
    "local_processing_time_ms",
    "global_processing_time_ms",
    "total_processing_time_ms",
    "query_latency_ms",
)


def format_result(result: dict) -> str:
    """Serialize a result dict as the reference's JSON doc (field order kept
    for byte-level familiarity; optimality rendered with 4 decimals like the
    reference's %.4f, FlinkSkyline.java:634)."""
    out = {}
    for k in RESULT_FIELDS:
        if k in result:
            out[k] = result[k]
    if "optimality" in out:
        out["optimality"] = float(f"{out['optimality']:.4f}")
    # extension fields beyond the reference schema (partial-result marker,
    # missing_partitions, skyline_points, and trace_id — the telemetry
    # span-correlation key minted at trigger ingestion) ride along after
    # the known fields, so reference-parity consumers are untouched
    for k, v in result.items():
        if k not in out:
            out[k] = v
    return json.dumps(out)


def parse_result(line: str) -> dict:
    return json.loads(line)
