"""In-memory topic bus — the fake-Kafka bridge for tests and single-process runs.

Provides the same minimal produce/consume surface the worker needs from Kafka
(SURVEY.md §4's "end-to-end single-host tests with fake Kafka"): named topics,
append-only logs, per-consumer offsets, at-least-once in-order delivery —
mirroring the reference's topic semantics (ordered per partition,
FlinkSkyline.java:84-97) without a broker.
"""

from __future__ import annotations

import itertools
import threading
from collections import defaultdict


class MemoryBus:
    """Thread-safe named append-only string logs with offset-based consumers."""

    def __init__(self):
        self._topics: dict[str, list[str]] = defaultdict(list)
        self._lock = threading.Lock()
        self._consumer_seq = itertools.count()
        self._offsets: dict[tuple, int] = {}

    def produce(self, topic: str, message: str) -> None:
        with self._lock:
            self._topics[topic].append(message)

    def produce_many(self, topic: str, messages) -> None:
        with self._lock:
            self._topics[topic].extend(messages)

    def consumer(self, topic: str, from_beginning: bool = True) -> "MemoryConsumer":
        """New consumer handle; ``from_beginning=False`` mirrors Kafka's
        offsets=latest (query topic, FlinkSkyline.java:95)."""
        with self._lock:
            cid = next(self._consumer_seq)
            start = 0 if from_beginning else len(self._topics[topic])
            self._offsets[(topic, cid)] = start
        return MemoryConsumer(self, topic, cid)

    def _poll(self, topic: str, cid: int, max_records: int) -> list[str]:
        with self._lock:
            off = self._offsets[(topic, cid)]
            log = self._topics[topic]
            batch = log[off : off + max_records]
            self._offsets[(topic, cid)] = off + len(batch)
        return batch

    def _position(self, topic: str, cid: int) -> int:
        with self._lock:
            return self._offsets[(topic, cid)]

    def _seek(self, topic: str, cid: int, offset: int) -> None:
        with self._lock:
            self._offsets[(topic, cid)] = max(0, int(offset))

    def size(self, topic: str) -> int:
        with self._lock:
            return len(self._topics[topic])


class MemoryConsumer:
    def __init__(self, bus: MemoryBus, topic: str, cid: int):
        self._bus = bus
        self.topic = topic
        self._cid = cid

    def poll(self, max_records: int = 65536) -> list[str]:
        return self._bus._poll(self.topic, self._cid, max_records)

    def position(self) -> int:
        """Offset of the next record this consumer will receive — same
        contract as ``KafkaLiteConsumer.position`` (the resilience layer's
        commit/replay currency)."""
        return self._bus._position(self.topic, self._cid)

    def seek(self, offset: int) -> None:
        return self._bus._seek(self.topic, self._cid, offset)
