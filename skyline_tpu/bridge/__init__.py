"""Transport plane: in-memory bus (tests/local) and Kafka (gated), plus the worker."""

from skyline_tpu.bridge.memory import MemoryBus

__all__ = ["MemoryBus", "SkylineWorker"]


def __getattr__(name):
    # SkylineWorker imports the engine, which imports bridge.wire; resolving
    # the worker lazily keeps that cycle out of package-import time.
    if name == "SkylineWorker":
        from skyline_tpu.bridge.worker import SkylineWorker

        return SkylineWorker
    raise AttributeError(name)
