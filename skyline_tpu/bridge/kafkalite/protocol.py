"""Kafka wire-protocol primitives (pure Python, no dependencies).

Implements the subset of the REAL Kafka protocol needed for a
producer/consumer data plane — the same wire format Kafka 3.7 brokers
(the reference's docker-setup pin, docker-compose.yml:4) speak:

- request/response framing: ``int32 size`` + header
  (``api_key int16, api_version int16, correlation_id int32,
  client_id nullable-string``)
- primitive codecs: big-endian ints, (nullable) strings, (nullable) bytes,
  arrays, zigzag varints/varlongs
- **RecordBatch v2** (magic=2) encode/decode, including the CRC32C
  checksum over attributes..end — the current on-disk/on-wire record
  format (KIP-98). Compression attributes are not implemented (codec 0
  only), matching the reference harness which never enables compression.

Only NON-FLEXIBLE api versions are used by kafkalite (flexible versions
add tagged fields + compact encodings): Produce v3, Fetch v4, Metadata v1,
ListOffsets v1, ApiVersions v0. A real broker accepts all of these, and a
real modern client can talk to the embedded broker after ApiVersions
negotiation caps it to the same set.
"""

from __future__ import annotations

import struct

# api keys (the Kafka protocol's stable ids)
API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_API_VERSIONS = 18

# error codes
ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3
ERR_MESSAGE_TOO_LARGE = 10
ERR_UNSUPPORTED_VERSION = 35

# ListOffsets sentinel timestamps
TS_LATEST = -1
TS_EARLIEST = -2


# -- CRC32C (Castagnoli) ----------------------------------------------------
# slice-by-8 tables: ~one order of magnitude over the byte-at-a-time loop in
# CPython, which matters because the checksum runs on every produced and
# consumed batch. A native crc32c module is preferred when importable.

_CRC32C_POLY = 0x82F63B78


def _make_crc32c_tables():
    t0 = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        t0.append(c)
    tables = [t0]
    for k in range(1, 8):
        prev = tables[k - 1]
        tables.append([t0[prev[n] & 0xFF] ^ (prev[n] >> 8) for n in range(256)])
    return tables


_T = _make_crc32c_tables()


def _crc32c_py(data: bytes) -> int:
    crc = 0xFFFFFFFF
    t0, t1, t2, t3, t4, t5, t6, t7 = _T
    n = len(data)
    i = 0
    end8 = n - (n % 8)
    while i < end8:
        crc ^= (
            data[i]
            | (data[i + 1] << 8)
            | (data[i + 2] << 16)
            | (data[i + 3] << 24)
        )
        crc = (
            t7[crc & 0xFF]
            ^ t6[(crc >> 8) & 0xFF]
            ^ t5[(crc >> 16) & 0xFF]
            ^ t4[(crc >> 24) & 0xFF]
            ^ t3[data[i + 4]]
            ^ t2[data[i + 5]]
            ^ t1[data[i + 6]]
            ^ t0[data[i + 7]]
        )
        i += 8
    while i < n:
        crc = t0[(crc ^ data[i]) & 0xFF] ^ (crc >> 8)
        i += 1
    return crc ^ 0xFFFFFFFF


def _resolve_crc32c():
    """Fastest available CRC32C: the crc32c wheel, else the repo's native
    helper (hardware CRC32 instruction, skyline_tpu/native/fastcsv.cpp),
    else the pure-Python slice-by-8 loop. Resolved once on first call."""
    try:  # pragma: no cover - wheel not in the baked image
        from crc32c import crc32c as wheel  # type: ignore

        return wheel
    except ImportError:
        pass
    try:
        from skyline_tpu.native import crc32c_native

        if crc32c_native(b"probe") is not None:
            return crc32c_native
    except Exception:  # pragma: no cover - any native failure -> Python
        pass
    return _crc32c_py


_records_encoder_impl: list | None = None


def _records_encoder():
    """The native value-only record-frame encoder, resolved once (None when
    the native lib is unavailable — callers then keep the Python loop
    without re-probing per batch)."""
    global _records_encoder_impl
    if _records_encoder_impl is None:
        fn = None
        try:
            from skyline_tpu.native import encode_records_native, get_lib

            lib = get_lib()
            if lib is not None and hasattr(lib, "sky_encode_records"):
                fn = encode_records_native
        except Exception:  # pragma: no cover - any native failure -> Python
            fn = None
        _records_encoder_impl = [fn]
    return _records_encoder_impl[0]


_crc32c_impl = None


def crc32c(data: bytes) -> int:
    global _crc32c_impl
    if _crc32c_impl is None:
        _crc32c_impl = _resolve_crc32c()
    return _crc32c_impl(data)


# -- primitive writers ------------------------------------------------------


class Writer:
    __slots__ = ("_parts",)

    def __init__(self):
        self._parts: list[bytes] = []

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(b)
        return self

    def int8(self, v: int) -> "Writer":
        return self.raw(struct.pack(">b", v))

    def int16(self, v: int) -> "Writer":
        return self.raw(struct.pack(">h", v))

    def int32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">i", v))

    def int64(self, v: int) -> "Writer":
        return self.raw(struct.pack(">q", v))

    def uint32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">I", v))

    def boolean(self, v: bool) -> "Writer":
        return self.int8(1 if v else 0)

    def string(self, s: str | None) -> "Writer":
        if s is None:
            return self.int16(-1)
        b = s.encode("utf-8")
        return self.int16(len(b)).raw(b)

    def bytes_(self, b: bytes | None) -> "Writer":
        if b is None:
            return self.int32(-1)
        return self.int32(len(b)).raw(b)

    def array(self, items, write_item) -> "Writer":
        if items is None:
            return self.int32(-1)
        self.int32(len(items))
        for it in items:
            write_item(self, it)
        return self

    def varint(self, v: int) -> "Writer":
        # zigzag int32/64
        z = (v << 1) ^ (v >> 63)
        while True:
            b = z & 0x7F
            z >>= 7
            if z:
                self._parts.append(bytes((b | 0x80,)))
            else:
                self._parts.append(bytes((b,)))
                return self

    def build(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def _take(self, n: int) -> bytes:
        b = self.data[self.pos : self.pos + n]
        if len(b) != n:
            raise EOFError(f"need {n} bytes at {self.pos}, have {len(b)}")
        self.pos += n
        return b

    def int8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def int16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def int32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def int64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def uint32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def boolean(self) -> bool:
        return self.int8() != 0

    def string(self) -> str | None:
        n = self.int16()
        if n < 0:
            return None
        return self._take(n).decode("utf-8")

    def bytes_(self) -> bytes | None:
        n = self.int32()
        if n < 0:
            return None
        return self._take(n)

    def array(self, read_item) -> list | None:
        n = self.int32()
        if n < 0:
            return None
        return [read_item(self) for _ in range(n)]

    def varint(self) -> int:
        shift = 0
        z = 0
        while True:
            b = self._take(1)[0]
            z |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (z >> 1) ^ -(z & 1)

    def remaining(self) -> int:
        return len(self.data) - self.pos


# -- request/response framing ----------------------------------------------


def encode_request(
    api_key: int,
    api_version: int,
    correlation_id: int,
    client_id: str | None,
    body: bytes,
) -> bytes:
    w = Writer()
    w.int16(api_key).int16(api_version).int32(correlation_id).string(client_id)
    payload = w.build() + body
    return struct.pack(">i", len(payload)) + payload


def encode_response(correlation_id: int, body: bytes) -> bytes:
    payload = struct.pack(">i", correlation_id) + body
    return struct.pack(">i", len(payload)) + payload


def read_frame(sock) -> bytes | None:
    """Read one length-prefixed frame from a socket; None on clean EOF."""
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            if hdr:
                raise EOFError("partial frame header")
            return None
        hdr += chunk
    (size,) = struct.unpack(">i", hdr)
    buf = bytearray()
    while len(buf) < size:
        chunk = sock.recv(min(65536, size - len(buf)))
        if not chunk:
            raise EOFError("connection closed mid-frame")
        buf += chunk
    return bytes(buf)


# -- RecordBatch v2 ---------------------------------------------------------
# layout (KIP-98): baseOffset int64 | batchLength int32 |
# partitionLeaderEpoch int32 | magic int8 (=2) | crc uint32 (CRC32C of
# everything after this field) | attributes int16 | lastOffsetDelta int32 |
# baseTimestamp int64 | maxTimestamp int64 | producerId int64 |
# producerEpoch int16 | baseSequence int32 | numRecords int32 | records


def _uvarint(z: int) -> bytes:
    """Unsigned LEB128 of an already-zigzagged value."""
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_record_batch(
    records: list[tuple[bytes | None, bytes | None]],
    base_offset: int = 0,
    base_timestamp: int = 0,
) -> bytes:
    """records: list of (key, value); headers always empty (the harness
    uses value-only messages, unified_producer.py:174).

    The record loop is the producer data plane's hot path (one iteration
    per message) — built with preassembled byte fragments and a zigzag
    varint inline fast path instead of per-record Writer objects
    (~2.5x, benchmarks/e2e_transport.py drives it)."""
    n_records = len(records)
    parts: list[bytes] = []
    loop_records = records
    if _records_encoder() is not None and all(
        k is None and v is not None for k, v in records
    ):
        # the data plane: value-only messages — one native call builds all
        # record frames (byte-identical; golden-bytes tested)
        native_blob = _records_encoder()([v for _, v in records])
        if native_blob is not None:
            parts.append(native_blob)
            loop_records = []
    for i, (key, value) in enumerate(loop_records):
        # attributes=0, timestampDelta=0, offsetDelta=zigzag(i)
        rb = b"\x00\x00" + (
            bytes((i << 1,)) if i < 64 else _uvarint(i << 1)
        )
        rb += b"\x01" if key is None else _uvarint(len(key) << 1) + key
        rb += b"\x01" if value is None else _uvarint(len(value) << 1) + value
        rb += b"\x00"  # headers count
        parts.append(_uvarint(len(rb) << 1))
        parts.append(rb)
    records_bytes = b"".join(parts)
    return _wrap_record_batch(
        records_bytes, n_records, base_offset, base_timestamp
    )


def _wrap_record_batch(
    records_bytes: bytes,
    n_records: int,
    base_offset: int,
    base_timestamp: int,
) -> bytes:
    """RecordBatch v2 header + CRC around preassembled record frames."""
    after_crc = (
        Writer()
        .int16(0)  # attributes: no compression, create-time timestamps
        .int32(n_records - 1)  # lastOffsetDelta
        .int64(base_timestamp)
        .int64(base_timestamp)
        .int64(-1)  # producerId
        .int16(-1)  # producerEpoch
        .int32(-1)  # baseSequence
        .int32(n_records)
        .raw(records_bytes)
        .build()
    )
    crc = crc32c(after_crc)
    tail = Writer().int32(-1).int8(2).uint32(crc).raw(after_crc).build()
    # batchLength counts partitionLeaderEpoch(4)+magic(1)+crc(4)+after_crc
    return Writer().int64(base_offset).int32(len(tail)).raw(tail).build()


def encode_record_batch_blob(
    blob: bytes,
    offsets,
    base_offset: int = 0,
    base_timestamp: int = 0,
) -> bytes | None:
    """RecordBatch v2 straight from a value blob + prefix offsets (record i
    is ``blob[offsets[i]:offsets[i+1]]``, key=None) — the zero-rejoin twin
    of ``encode_record_batch`` for the native produce plane. Returns None
    when the native encoder is unavailable (callers slice and fall back)."""
    from skyline_tpu.native import encode_records_from_blob

    records_bytes = encode_records_from_blob(blob, offsets)
    if records_bytes is None:
        return None
    return _wrap_record_batch(
        records_bytes, len(offsets) - 1, base_offset, base_timestamp
    )


def iter_batch_spans(data: bytes):
    """Yield ``(start, length, n_records)`` for each complete RecordBatch v2
    blob in ``data``, reading only fixed-offset header fields (no record
    parse). Network-supplied lengths/counts are clamped: a batchLength
    below the v2 header size (49) or past the buffer ends iteration (a
    malformed frame must not spin or walk the log backward), and negative
    numRecords counts as 0."""
    pos = 0
    n = len(data)
    while pos + 61 <= n:
        (batch_len,) = struct.unpack_from(">i", data, pos + 8)
        if batch_len < 49 or pos + 12 + batch_len > n:
            break
        # numRecords sits at base(8)+len(4) + leaderEpoch(4)+magic(1)+crc(4)
        # +attributes(2)+lastOffsetDelta(4)+baseTs(8)+maxTs(8)+producerId(8)
        # +producerEpoch(2)+baseSequence(4) = offset 57
        (cnt,) = struct.unpack_from(">i", data, pos + 57)
        yield pos, 12 + batch_len, max(cnt, 0)
        pos += 12 + batch_len


def count_records(data: bytes) -> int:
    """Total record count of a concatenation of RecordBatch v2 blobs (see
    ``iter_batch_spans`` for the clamping rules)."""
    return sum(cnt for _, _, cnt in iter_batch_spans(data))


def decode_record_batches(
    data: bytes, verify_crc: bool = True
) -> list[tuple[int, bytes | None, bytes | None]]:
    """Decode a concatenation of RecordBatch v2 blobs into
    ``[(absolute_offset, key, value), ...]``. Tolerates a trailing partial
    batch (brokers may truncate at fetch max_bytes)."""
    out: list[tuple[int, bytes | None, bytes | None]] = []
    r = Reader(data)
    while r.remaining() >= 12:
        base_offset = r.int64()
        batch_len = r.int32()
        if r.remaining() < batch_len:
            break  # truncated tail
        batch = Reader(r.data, r.pos)
        r.pos += batch_len
        batch.int32()  # partitionLeaderEpoch
        magic = batch.int8()
        if magic != 2:
            raise ValueError(f"unsupported record magic {magic}")
        crc = batch.uint32()
        after = batch.data[batch.pos : batch.pos + batch_len - 9]
        if verify_crc and crc32c(after) != crc:
            raise ValueError("record batch CRC32C mismatch")
        batch.int16()  # attributes
        batch.int32()  # lastOffsetDelta
        batch.int64()  # baseTimestamp
        batch.int64()  # maxTimestamp
        batch.int64()  # producerId
        batch.int16()  # producerEpoch
        batch.int32()  # baseSequence
        n = batch.int32()
        # hot loop: records are decoded with inlined varint reads over the
        # raw buffer (one Reader + several method calls per record costs
        # ~2x the whole decode at 10^5 records/fetch; this loop and
        # ``check_crcs=False`` together roughly double consumer throughput)
        buf = data
        p = batch.pos
        append = out.append
        for _ in range(n):
            z = buf[p]  # record length varint
            p += 1
            if z & 0x80:
                shift = 7
                z &= 0x7F
                while True:
                    b = buf[p]
                    p += 1
                    z |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
            rec_end = p + ((z >> 1) ^ -(z & 1))
            p += 1  # attributes
            while buf[p] & 0x80:  # timestampDelta (skipped)
                p += 1
            p += 1
            z = buf[p]  # offsetDelta
            p += 1
            if z & 0x80:
                shift = 7
                z &= 0x7F
                while True:
                    b = buf[p]
                    p += 1
                    z |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
            offset_delta = (z >> 1) ^ -(z & 1)
            z = buf[p]  # key length
            p += 1
            if z & 0x80:
                shift = 7
                z &= 0x7F
                while True:
                    b = buf[p]
                    p += 1
                    z |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
            klen = (z >> 1) ^ -(z & 1)
            if klen >= 0:
                key = buf[p : p + klen]
                p += klen
            else:
                key = None
            z = buf[p]  # value length
            p += 1
            if z & 0x80:
                shift = 7
                z &= 0x7F
                while True:
                    b = buf[p]
                    p += 1
                    z |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
            vlen = (z >> 1) ^ -(z & 1)
            if vlen >= 0:
                value = buf[p : p + vlen]
                p += vlen
            else:
                value = None
            append((base_offset + offset_delta, key, value))
            p = rec_end  # headers (if any) are skipped
    return out
