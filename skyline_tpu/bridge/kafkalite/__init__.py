"""kafkalite: a dependency-free Kafka wire-protocol client + embedded broker.

The J9 transport (FlinkSkyline.java:84-97, 177-183) exercised for REAL —
actual TCP, actual Kafka framing, actual RecordBatch v2 with CRC32C — in an
image without kafka-python or a JVM broker. ``bridge.kafka.KafkaBus``
prefers kafka-python when installed and falls back to these clients, so the
same CLI flags drive either stack.
"""

from skyline_tpu.bridge.kafkalite.broker import Broker
from skyline_tpu.bridge.kafkalite.client import (
    KafkaLiteConsumer,
    KafkaLiteError,
    KafkaLiteProducer,
    MessageSizeTooLargeError,
)

__all__ = [
    "Broker",
    "KafkaLiteConsumer",
    "KafkaLiteError",
    "KafkaLiteProducer",
    "MessageSizeTooLargeError",
]
