"""Pure-Python Kafka producer/consumer over the real wire protocol.

The client half of kafkalite (see protocol.py): enough of a Kafka client
to run the reference's data plane — value-only string messages on
single-partition topics, earliest/latest offset reset, client-side
``max_request_size`` enforcement mirroring kafka-python's (and the
reference result sink's ``max.request.size=10485760``,
FlinkSkyline.java:177-183). Talks to any broker supporting the
non-flexible api versions in protocol.py: the embedded ``broker.Broker``
or a real Kafka <= 3.x.

Partitioning: all records go to partition 0. The reference's topics are
single-partition (docker-compose auto-creation defaults), and the engine
does its own spatial partitioning downstream — Kafka partitions were never
the parallelism mechanism in this system (SURVEY.md §2.6).
"""

from __future__ import annotations

import socket
import threading
import time

from skyline_tpu.bridge.kafkalite import protocol as P


class KafkaLiteError(Exception):
    pass


class MessageSizeTooLargeError(KafkaLiteError):
    pass


class KafkaLiteConnectionError(KafkaLiteError):
    """The broker connection died (reset, refused, closed mid-frame)."""


class _Connection:
    """One framed request/response socket with correlation-id matching.

    Transport faults (connection reset, broker restart) are retried with
    bounded exponential backoff: the socket is torn down, re-dialed, and
    the request re-sent. Every request in this protocol subset is
    idempotent except Produce, and the producer's ``flush`` already
    restores unacked records on error — a duplicate Produce can only
    happen when the broker acked and the ack was lost in transit, the
    standard at-least-once window every Kafka client has with retries on.
    """

    def __init__(
        self,
        bootstrap: str,
        client_id: str,
        timeout_s: float = 30.0,
        retries: int | None = None,
        backoff_s: float | None = None,
    ):
        from skyline_tpu.analysis.registry import env_float, env_int

        host, _, port = bootstrap.partition(":")
        self._addr = (host, int(port or 9092))
        self._timeout_s = timeout_s
        self._retries = env_int("SKYLINE_KAFKA_RETRIES", 5) if retries is None else retries
        self._backoff_s = (
            env_float("SKYLINE_KAFKA_BACKOFF_S", 0.05)
            if backoff_s is None else backoff_s
        )
        self.reconnects = 0
        self._sock: socket.socket | None = None
        self.client_id = client_id
        self._corr = 0
        self._lock = threading.Lock()
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(self._addr, timeout=self._timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def request(self, api_key: int, api_version: int, body: bytes) -> P.Reader:
        with self._lock:
            last: Exception | None = None
            for attempt in range(self._retries + 1):
                if attempt:
                    time.sleep(self._backoff_s * (2.0 ** (attempt - 1)))
                    self.reconnects += 1
                try:
                    if self._sock is None:
                        self._connect()
                    self._corr += 1
                    corr = self._corr
                    self._sock.sendall(
                        P.encode_request(
                            api_key, api_version, corr, self.client_id, body
                        )
                    )
                    frame = P.read_frame(self._sock)
                    if frame is None:
                        raise KafkaLiteConnectionError("broker closed connection")
                except (OSError, KafkaLiteConnectionError) as e:
                    last = e
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    continue
                r = P.Reader(frame)
                got = r.int32()
                if got != corr:
                    # protocol corruption, not a transport fault: don't retry
                    raise KafkaLiteError(f"correlation mismatch {got} != {corr}")
                return r
            raise KafkaLiteConnectionError(
                f"broker at {self._addr[0]}:{self._addr[1]} unreachable after "
                f"{self._retries} retries: {last}"
            ) from last

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class KafkaLiteProducer:
    """Batching producer: ``send`` buffers, ``flush`` ships one Produce
    request per topic (one RecordBatch v2 per partition)."""

    def __init__(
        self,
        bootstrap: str,
        max_request_size: int = 10_485_760,
        linger_records: int = 4096,
        client_id: str = "kafkalite-producer",
    ):
        self._conn = _Connection(bootstrap, client_id)
        self.max_request_size = max_request_size
        self.linger_records = linger_records
        self._buf: dict[str, list[bytes]] = {}
        self._lock = threading.Lock()

    def send(self, topic: str, value: str | bytes) -> None:
        v = value.encode("utf-8") if isinstance(value, str) else value
        if len(v) > self.max_request_size:
            raise MessageSizeTooLargeError(
                f"{len(v)} bytes > max_request_size {self.max_request_size}"
            )
        with self._lock:
            self._buf.setdefault(topic, []).append(v)
            should_flush = len(self._buf[topic]) >= self.linger_records
        if should_flush:
            self.flush()

    def send_many(self, topic: str, values) -> None:
        """Batch ``send``: one lock acquisition + size check per slice
        instead of per record (the per-record path is ~45% of producer CLI
        time at stream rates). Buffers are filled in ``linger_records``
        slices so flushed batches stay the same size ``send`` produces."""
        vs = [v.encode("utf-8") if isinstance(v, str) else v for v in values]
        for v in vs:
            if len(v) > self.max_request_size:
                raise MessageSizeTooLargeError(
                    f"{len(v)} bytes > max_request_size "
                    f"{self.max_request_size}"
                )
        i, n = 0, len(vs)
        while i < n:
            with self._lock:
                buf = self._buf.setdefault(topic, [])
                room = max(self.linger_records - len(buf), 1)
                buf.extend(vs[i : i + room])
                should_flush = len(buf) >= self.linger_records
            i += room
            if should_flush:
                self.flush()

    def send_blob(self, topic: str, blob: bytes, offsets) -> None:
        """Produce a whole formatted batch from one value blob + prefix
        offsets (record i = ``blob[offsets[i]:offsets[i+1]]``) without
        materializing per-record bytes objects — the zero-copy pairing for
        the native CSV formatter (native/fastcsv.cpp). Splits into
        max_request_size-bounded RecordBatches; falls back to ``send_many``
        when the native record encoder is unavailable. Flushes buffered
        sends first so ordering with ``send`` is preserved."""
        import numpy as np

        from skyline_tpu.bridge.kafkalite.protocol import (
            encode_record_batch_blob,
        )

        self.flush()
        offs = np.ascontiguousarray(offsets, dtype=np.int64)
        n = offs.shape[0] - 1
        if n <= 0:
            return
        # greedy grouping under the request cap, counting per-record frame
        # overhead at its bound (native encoder sizing); the conservative
        # headroom only shrinks groups — a single record is judged by its
        # ACTUAL encoded batch below, so records near the cap that
        # send/send_many would accept are accepted here too
        from skyline_tpu.native import RECORD_FRAME_OVERHEAD

        budget = max(self.max_request_size - 4096, 1)
        adj = offs + RECORD_FRAME_OVERHEAD * np.arange(n + 1, dtype=np.int64)
        i = 0
        while i < n:
            j = int(np.searchsorted(adj, adj[i] + budget, side="right")) - 1
            j = max(j, i + 1)
            batch = encode_record_batch_blob(
                blob, offs[i : j + 1],
                base_timestamp=int(time.time() * 1000),
            )
            if batch is not None and len(batch) > self.max_request_size:
                if j > i + 1:  # conservative group overshot: halve and retry
                    budget = max(budget // 2, 1)
                    continue
                raise MessageSizeTooLargeError(
                    f"single record encodes to {len(batch)} bytes "
                    f"> max_request_size {self.max_request_size}"
                )
            if batch is None:
                # native encoder unavailable: per-record fallback
                ot = offs.tolist()
                self.send_many(
                    topic, [blob[ot[k] : ot[k + 1]] for k in range(i, n)]
                )
                self.flush()
                return
            self._produce_batch(topic, batch)
            i = j

    def flush(self) -> None:
        with self._lock:
            buf, self._buf = self._buf, {}
        pending = dict(buf)  # un-sent topics restored if a send fails
        try:
            self._flush_topics(buf, pending)
        except Exception:
            # put every unacked record back so a caller catching the error
            # can retry flush() without losing data (kafka-python keeps
            # unacked batches across transient faults too)
            with self._lock:
                for topic, values in pending.items():
                    self._buf.setdefault(topic, [])[:0] = values
            raise

    def _flush_topics(self, buf: dict, pending: dict) -> None:
        for topic, values in buf.items():
            if not values:
                pending.pop(topic, None)
                continue
            batch = P.encode_record_batch(
                [(None, v) for v in values],
                base_timestamp=int(time.time() * 1000),
            )
            if len(batch) > self.max_request_size:
                # not retryable as-is: restoring would wedge every retry
                pending.pop(topic, None)
                raise MessageSizeTooLargeError(
                    f"batch of {len(values)} records is {len(batch)} bytes "
                    f"> max_request_size {self.max_request_size}"
                )
            try:
                self._produce_batch(topic, batch)
            except MessageSizeTooLargeError:
                # acked as failed: do NOT restore (a too-large batch
                # would wedge every retry); drop it like kafka-python
                pending.pop(topic, None)
                raise
            pending.pop(topic, None)  # acked: nothing to restore for this topic

    def _produce_batch(self, topic: str, batch: bytes) -> None:
        """One Produce request carrying one preassembled RecordBatch."""
        body = (
            P.Writer()
            .string(None)  # transactional_id
            .int16(1)  # acks
            .int32(30_000)  # timeout_ms
            .array(
                [(topic, batch)],
                lambda w, t: w.string(t[0]).array(
                    [(0, t[1])],
                    lambda w, p: w.int32(p[0]).bytes_(p[1]),
                ),
            )
            .build()
        )
        r = self._conn.request(P.API_PRODUCE, 3, body)

        def read_pr(rr: P.Reader):
            part = rr.int32()
            err = rr.int16()
            base = rr.int64()
            rr.int64()  # log_append_time
            return part, err, base

        responses = r.array(
            lambda rr: (rr.string(), rr.array(read_pr))
        )
        for _name, prs in responses or []:
            for _part, err, _base in prs or []:
                if err == P.ERR_MESSAGE_TOO_LARGE:
                    raise MessageSizeTooLargeError(
                        f"broker rejected batch for {topic}: message too large"
                    )
                if err != P.ERR_NONE:
                    raise KafkaLiteError(
                        f"produce to {topic} failed: error {err}"
                    )

    def close(self) -> None:
        self.flush()
        self._conn.close()


class KafkaLiteConsumer:
    """Single-topic, partition-0 consumer with earliest/latest reset."""

    def __init__(
        self,
        topic: str,
        bootstrap: str,
        auto_offset_reset: str = "earliest",
        client_id: str = "kafkalite-consumer",
        fetch_max_bytes: int = 16 * 1024 * 1024,
        check_crcs: bool = False,
    ):
        """``check_crcs``: verify each fetched batch's CRC32C before
        decoding. Off by default — TCP already checksums the stream and the
        pure-Python CRC is ~35% of fetch decode time (kafka-python exposes
        the same knob as ``check_crcs``); the wire-compat tests pin CRC
        correctness on both the produce and the log-storage side."""
        self.topic = topic
        self.check_crcs = check_crcs
        self._conn = _Connection(bootstrap, client_id)
        self._reset = auto_offset_reset
        # _offset is the FETCH position (next offset to request from the
        # broker), not the consumed position: it advances past records that
        # were decoded into _pending but not yet delivered to the caller.
        # Anything offset-visible to users (position(), a future commit or
        # seek) must go through the delivered position, which backs out the
        # undelivered pending records.
        self._offset: int | None = None
        # decoded-but-undelivered records: a fetch response can carry far
        # more than one poll's max_records (16 MB of 2-D tuples is ~600k
        # lines); without this buffer every poll would re-fetch and
        # re-decode the same blob just to deliver its next 64k slice
        self._pending: list[str] = []
        # None = unprobed; set once on first poll_arrays (static per process)
        self._arrays_ok: bool | None = None
        self.fetch_max_bytes = fetch_max_bytes
        # Metadata request auto-creates the topic on the embedded broker,
        # matching the reference's auto-create reliance
        self._conn.request(
            P.API_METADATA,
            1,
            P.Writer().array([topic], lambda w, t: w.string(t)).build(),
        )
        # resolve the reset position NOW: a latest-reset consumer must skip
        # only what predates its subscription, not what predates its first
        # poll (the reference's query consumer relies on this,
        # FlinkSkyline.java:92-97)
        self._position()

    def _position(self) -> int:
        if self._offset is None:
            ts = P.TS_EARLIEST if self._reset == "earliest" else P.TS_LATEST
            body = (
                P.Writer()
                .int32(-1)  # replica_id
                .array(
                    [(self.topic, [(0, ts)])],
                    lambda w, t: w.string(t[0]).array(
                        t[1], lambda w, p: w.int32(p[0]).int64(p[1])
                    ),
                )
                .build()
            )
            r = self._conn.request(P.API_LIST_OFFSETS, 1, body)

            def read_pr(rr: P.Reader):
                return rr.int32(), rr.int16(), rr.int64(), rr.int64()

            responses = r.array(lambda rr: (rr.string(), rr.array(read_pr)))
            offset = 0
            for _name, prs in responses or []:
                for _part, err, _ts, off in prs or []:
                    if err != P.ERR_NONE:
                        raise KafkaLiteError(f"list_offsets error {err}")
                    offset = off
            self._offset = offset
        return self._offset

    def position(self) -> int:
        """The consumer-visible position: the offset of the next record the
        CALLER will receive — the fetch position minus the decoded-but-
        undelivered pending records. This (not ``_offset``) is the value an
        offset commit or position report must use."""
        return self._position() - len(self._pending)

    def seek(self, offset: int) -> None:
        """Reposition to ``offset`` (consumer-visible coordinates). Drops
        any decoded-but-undelivered records — after a seek the next poll
        delivers exactly the record at ``offset``. This is the WAL-replay
        entry point: resume from the last committed position."""
        self._pending.clear()
        self._offset = max(0, int(offset))

    def _fetch(self, offset: int, timeout_ms: int) -> list[bytes]:
        """One fetch request at ``offset``; returns the raw RecordBatch
        blobs (usually one). OFFSET_OUT_OF_RANGE (log truncated/reset under
        us) re-resolves the position for the next poll and yields no blob —
        ``_pending`` is structurally empty whenever a fetch runs (both poll
        flavors early-return/drain it first), so already-decoded records
        were served before the reset was observable: the normal
        at-least-once behavior."""
        body = (
            P.Writer()
            .int32(-1)  # replica_id
            .int32(timeout_ms)  # max_wait
            .int32(1)  # min_bytes
            .int32(self.fetch_max_bytes)
            .int8(0)  # isolation_level
            .array(
                [(self.topic, [(0, offset, self.fetch_max_bytes)])],
                lambda w, t: w.string(t[0]).array(
                    t[1],
                    lambda w, p: w.int32(p[0]).int64(p[1]).int32(p[2]),
                ),
            )
            .build()
        )
        r = self._conn.request(P.API_FETCH, 4, body)
        r.int32()  # throttle_time_ms

        def read_pr(rr: P.Reader):
            part = rr.int32()
            err = rr.int16()
            hw = rr.int64()
            rr.int64()  # last_stable_offset
            rr.array(lambda a: (a.int64(), a.int64()))  # aborted txns
            blob = rr.bytes_() or b""
            return part, err, hw, blob

        responses = r.array(lambda rr: (rr.string(), rr.array(read_pr)))
        blobs: list[bytes] = []
        for _name, prs in responses or []:
            for _part, err, _hw, blob in prs or []:
                if err == P.ERR_OFFSET_OUT_OF_RANGE:
                    self._offset = None
                    continue
                if err != P.ERR_NONE:
                    raise KafkaLiteError(f"fetch error {err}")
                if blob:
                    blobs.append(blob)
        return blobs

    def poll(
        self, max_records: int = 65536, timeout_ms: int = 100
    ) -> list[str]:
        if self._pending:
            out = self._pending[:max_records]
            del self._pending[:max_records]
            return out
        offset = self._position()
        out: list[str] = []
        for blob in self._fetch(offset, timeout_ms):
            # decode the WHOLE blob once: records past max_records go to
            # the pending buffer (served by later polls), not back to the
            # broker for a redundant re-fetch + re-decode
            for abs_off, _key, value in P.decode_record_batches(
                blob, verify_crc=self.check_crcs
            ):
                if abs_off < offset:
                    continue
                target = out if len(out) < max_records else self._pending
                # errors="replace", not strict: a non-UTF-8 value must
                # degrade to a dropped/malformed record downstream exactly
                # like poll_arrays() counts it — not raise and kill the
                # consume loop while the array plane survives the same
                # record (ADVICE.md round 5)
                target.append((value or b"").decode("utf-8", errors="replace"))
                self._offset = abs_off + 1
        return out

    def poll_arrays(self, dims: int, timeout_ms: int = 100):
        """Data-plane poll straight to numpy: one fetch, decoded AND
        CSV-parsed in native code (``native.parse_recordbatches_native``)
        into ``(ids (n,) int64, values (n, dims) float32, dropped)`` — the
        consume-plane twin of the producer's ``send_blob``, with zero
        per-record Python objects between broker and engine. Returns None
        when the native library is unavailable (callers fall back to
        ``poll()`` + line parsing). If line-based ``poll()`` left
        decoded-but-undelivered records pending, those are drained first
        through the line parser so mixing the APIs stays ordered. Unlike
        ``poll()`` there is no pending buffer: the whole fetch blob is
        parsed and delivered in one call (the worker drains the topic
        anyway), so ``max_records`` slicing does not apply."""
        import numpy as np

        from skyline_tpu.bridge.wire import parse_tuple_lines
        from skyline_tpu.native import parse_recordbatches_native

        if self._arrays_ok is None:  # availability is static per process
            self._arrays_ok = parse_recordbatches_native(b"", 0, 1) is not None
        if not self._arrays_ok:
            return None
        if self._pending:
            lines, self._pending = self._pending, []
            return parse_tuple_lines(lines, dims)
        offset = self._position()
        chunks: list[tuple] = []
        for blob in self._fetch(offset, timeout_ms):
            ids, values, dropped, next_off = parse_recordbatches_native(
                blob, offset, dims, verify_crc=self.check_crcs
            )
            if next_off > offset:
                self._offset = next_off
            chunks.append((ids, values, dropped))
        if not chunks:
            return (
                np.empty(0, dtype=np.int64),
                np.empty((0, dims), dtype=np.float32),
                0,
            )
        if len(chunks) == 1:
            return chunks[0]
        return (
            np.concatenate([c[0] for c in chunks]),
            np.concatenate([c[1] for c in chunks]),
            sum(c[2] for c in chunks),
        )

    def close(self) -> None:
        self._conn.close()
