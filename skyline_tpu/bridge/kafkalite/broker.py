"""Embedded Kafka broker speaking the real wire protocol (see protocol.py).

The reference deploys Kafka 3.7.2 in KRaft mode with 10 MB message caps
(docker-setup/docker-compose.yml:2-21); this broker stands in for it where
no JVM/docker exists: an in-process (or standalone, see ``main``) TCP
server with in-memory single-replica logs, auto-created topics, and the
same ``message.max.bytes`` enforcement (``ERR_MESSAGE_TOO_LARGE`` past the
cap). It serves kafkalite clients and any real Kafka client restricted to
the implemented api versions (Produce<=3, Fetch<=4, Metadata<=1,
ListOffsets<=1, ApiVersions 0).

Not implemented (not needed by the harness): consumer groups/coordination,
transactions, compression, multi-broker replication, TLS/SASL.
"""

from __future__ import annotations

import socketserver
import struct
import threading
import time

from skyline_tpu.bridge.kafkalite import protocol as P

DEFAULT_MAX_MESSAGE_BYTES = 10_485_760  # docker-compose.yml:20-21


class _PartitionLog:
    """Append-only in-memory log of record batches."""

    __slots__ = ("batches", "next_offset", "lock")

    def __init__(self):
        # (base_offset, last_offset, batch_bytes)
        self.batches: list[tuple[int, int, bytes]] = []
        self.next_offset = 0
        self.lock = threading.Lock()

    def append(self, batch_bytes: bytes) -> int:
        """Re-stamp the batch's base offset to the log end; returns it.

        CRC is NOT verified here: consumers verify on decode, and for the
        in-process producer the checksum was computed a microsecond ago —
        re-verifying would just double the data plane's checksum cost.
        Record counting reads only the fixed-offset header fields
        (numRecords at byte 57 of each batch, per the v2 layout) — a full
        record decode per produce would make the broker's data plane pay
        the parse cost twice."""
        spans = list(P.iter_batch_spans(batch_bytes))
        n_records = sum(cnt for _, _, cnt in spans)
        if not n_records:
            return self.next_offset
        with self.lock:
            base = self.next_offset
            # rewrite each batch's baseOffset in place (first 8 bytes of a
            # batch); crc does not cover it, so no re-checksum is needed —
            # exactly why the v2 format excludes baseOffset from the crc.
            # Multi-batch record sets (legal from real clients) restamp
            # every batch so fetch offsets stay monotonic.
            parts = []
            off = base
            for start, length, cnt in spans:
                parts.append(struct.pack(">q", off))
                parts.append(batch_bytes[start + 8 : start + length])
                off += cnt
            stamped = b"".join(parts)
            last = base + n_records - 1
            self.batches.append((base, last, stamped))
            self.next_offset = last + 1
            return base

    def read_from(self, offset: int, max_bytes: int) -> bytes:
        out = []
        size = 0
        with self.lock:
            for base, last, blob in self.batches:
                if last < offset:
                    continue
                if out and size + len(blob) > max_bytes:
                    break
                out.append(blob)
                size += len(blob)
                if size >= max_bytes:
                    break
        return b"".join(out)


class _BrokerState:
    def __init__(self, max_message_bytes: int):
        self.topics: dict[str, dict[int, _PartitionLog]] = {}
        self.lock = threading.Lock()
        self.max_message_bytes = max_message_bytes

    def partition(self, topic: str, part: int, create: bool = True) -> _PartitionLog | None:
        with self.lock:
            t = self.topics.get(topic)
            if t is None:
                if not create:
                    return None
                t = self.topics[topic] = {}
            log = t.get(part)
            if log is None:
                if not create:
                    return None
                log = t[part] = _PartitionLog()
            return log


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        state: _BrokerState = self.server.state  # type: ignore[attr-defined]
        while True:
            try:
                frame = P.read_frame(self.request)
            except (EOFError, ConnectionError, OSError):
                return
            if frame is None:
                return
            r = P.Reader(frame)
            api_key = r.int16()
            api_version = r.int16()
            corr = r.int32()
            r.string()  # client_id
            try:
                body = self._dispatch(state, api_key, api_version, r)
            except Exception:
                # malformed request: drop the connection (a real broker
                # logs + closes too)
                return
            self.request.sendall(P.encode_response(corr, body))

    def _dispatch(self, state, api_key, api_version, r: P.Reader) -> bytes:
        if api_key == P.API_API_VERSIONS:
            # KIP-511: a v>0 (possibly flexible) ApiVersions request must be
            # answered UNSUPPORTED_VERSION in the v0 body so real clients
            # retry with v0 instead of misparsing a v0 body as flexible
            if api_version > 0:
                return (
                    P.Writer()
                    .int16(P.ERR_UNSUPPORTED_VERSION)
                    .array([], lambda w, _i: None)
                    .build()
                )
            return self._api_versions()
        if api_key == P.API_METADATA and api_version <= 1:
            return self._metadata(state, r)
        if api_key == P.API_PRODUCE and api_version <= 3:
            return self._produce(state, r)
        if api_key == P.API_FETCH and api_version <= 4:
            return self._fetch(state, r)
        if api_key == P.API_LIST_OFFSETS and api_version <= 1:
            return self._list_offsets(state, r)
        # honest refusal for anything newer/unknown
        return P.Writer().int16(P.ERR_UNSUPPORTED_VERSION).build()

    def _api_versions(self) -> bytes:
        w = P.Writer()
        w.int16(P.ERR_NONE)
        supported = [
            (P.API_PRODUCE, 0, 3),
            (P.API_FETCH, 0, 4),
            (P.API_LIST_OFFSETS, 0, 1),
            (P.API_METADATA, 0, 1),
            (P.API_API_VERSIONS, 0, 0),
        ]
        w.array(
            supported,
            lambda w, it: w.int16(it[0]).int16(it[1]).int16(it[2]),
        )
        return w.build()

    def _metadata(self, state: _BrokerState, r: P.Reader) -> bytes:
        topics = r.array(lambda rr: rr.string())
        host, port = self.server.server_address[:2]  # type: ignore[attr-defined]
        with state.lock:
            known = sorted(state.topics)
        if topics is None or len(topics) == 0:
            names = known
        else:
            names = topics
            # Metadata auto-creates requested topics (the broker config the
            # reference relies on: producers/consumers never create topics
            # explicitly)
            for t in names:
                state.partition(t, 0, create=True)
        w = P.Writer()
        w.array(
            [(0, str(host), int(port), None)],
            lambda w, b: w.int32(b[0]).string(b[1]).int32(b[2]).string(b[3]),
        )
        w.int32(0)  # controller_id

        def write_topic(w: P.Writer, name: str):
            with state.lock:
                parts = sorted(state.topics.get(name, {0: None}))
            w.int16(P.ERR_NONE).string(name).boolean(False)
            w.array(
                parts,
                lambda w, p: (
                    w.int16(P.ERR_NONE)
                    .int32(p)
                    .int32(0)  # leader
                    .array([0], lambda w, rid: w.int32(rid))  # replicas
                    .array([0], lambda w, rid: w.int32(rid))  # isr
                ),
            )

        w.array(names, write_topic)
        return w.build()

    def _produce(self, state: _BrokerState, r: P.Reader) -> bytes:
        r.string()  # transactional_id
        r.int16()  # acks (all treated as acks=1: append then respond)
        r.int32()  # timeout_ms
        topic_results = []

        def read_partition(rr: P.Reader):
            part = rr.int32()
            record_set = rr.bytes_()
            return part, record_set

        def read_topic(rr: P.Reader):
            name = rr.string()
            parts = rr.array(read_partition)
            return name, parts

        for name, parts in r.array(read_topic) or []:
            part_results = []
            for part, record_set in parts or []:
                if record_set is not None and len(record_set) > state.max_message_bytes:
                    part_results.append((part, P.ERR_MESSAGE_TOO_LARGE, -1))
                    continue
                log = state.partition(name, part, create=True)
                base = log.append(record_set) if record_set else log.next_offset
                part_results.append((part, P.ERR_NONE, base))
            topic_results.append((name, part_results))

        w = P.Writer()
        w.array(
            topic_results,
            lambda w, t: w.string(t[0]).array(
                t[1],
                lambda w, pr: (
                    w.int32(pr[0]).int16(pr[1]).int64(pr[2]).int64(-1)
                ),  # partition, error, base_offset, log_append_time
            ),
        )
        w.int32(0)  # throttle_time_ms
        return w.build()

    def _fetch(self, state: _BrokerState, r: P.Reader) -> bytes:
        r.int32()  # replica_id
        max_wait_ms = r.int32()
        min_bytes = r.int32()
        r.int32()  # max_bytes (request-level)
        r.int8()  # isolation_level

        def read_partition(rr: P.Reader):
            return rr.int32(), rr.int64(), rr.int32()  # part, offset, max_bytes

        def read_topic(rr: P.Reader):
            return rr.string(), rr.array(read_partition)

        requests = r.array(read_topic) or []

        def collect(create: bool):
            results, total = [], 0
            for name, parts in requests:
                part_results = []
                for part, offset, pmax in parts or []:
                    log = state.partition(name, part, create=create)
                    if log is None:
                        part_results.append(
                            (part, P.ERR_UNKNOWN_TOPIC_OR_PARTITION, 0, b"")
                        )
                        continue
                    if offset > log.next_offset:
                        part_results.append(
                            (part, P.ERR_OFFSET_OUT_OF_RANGE, log.next_offset, b"")
                        )
                        continue
                    blob = log.read_from(offset, pmax)
                    total += len(blob)
                    part_results.append((part, P.ERR_NONE, log.next_offset, blob))
                results.append((name, part_results))
            return results, total

        results, total = collect(create=True)
        if total < max(min_bytes, 1):
            # honor max_wait/min_bytes long-polling in spirit: short bounded
            # waits so idle consumers don't spin the broker
            deadline = time.time() + min(max_wait_ms, 500) / 1000.0
            while total < max(min_bytes, 1) and time.time() < deadline:
                time.sleep(0.005)
                results, total = collect(create=False)

        w = P.Writer()
        w.int32(0)  # throttle_time_ms
        w.array(
            results,
            lambda w, t: w.string(t[0]).array(
                t[1],
                lambda w, pr: (
                    w.int32(pr[0])
                    .int16(pr[1])
                    .int64(pr[2])  # high_watermark
                    .int64(pr[2])  # last_stable_offset
                    .array([], lambda w, _a: None)  # aborted_transactions
                    .bytes_(pr[3])
                ),
            ),
        )
        return w.build()

    def _list_offsets(self, state: _BrokerState, r: P.Reader) -> bytes:
        r.int32()  # replica_id

        def read_partition(rr: P.Reader):
            return rr.int32(), rr.int64()  # partition, timestamp

        def read_topic(rr: P.Reader):
            return rr.string(), rr.array(read_partition)

        results = []
        for name, parts in r.array(read_topic) or []:
            part_results = []
            for part, ts in parts or []:
                log = state.partition(name, part, create=True)
                if ts == P.TS_EARLIEST:
                    first = log.batches[0][0] if log.batches else 0
                    part_results.append((part, P.ERR_NONE, 0, first))
                else:  # latest (or timestamp lookup, answered as latest)
                    part_results.append((part, P.ERR_NONE, -1, log.next_offset))
            results.append((name, part_results))

        w = P.Writer()
        w.array(
            results,
            lambda w, t: w.string(t[0]).array(
                t[1],
                lambda w, pr: (
                    w.int32(pr[0]).int16(pr[1]).int64(pr[2]).int64(pr[3])
                ),
            ),
        )
        return w.build()


class Broker:
    """In-process broker: ``with Broker() as b: ... b.address``."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        max_message_bytes: int = DEFAULT_MAX_MESSAGE_BYTES,
        state: "_BrokerState | None" = None,
    ):
        """``state``: carry an existing ``_BrokerState`` (topic logs) into a
        new broker instance — the broker-restart half of the client
        reconnect tests, standing in for Kafka's on-disk log surviving a
        broker bounce."""

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._server.state = (  # type: ignore[attr-defined]
            state if state is not None else _BrokerState(max_message_bytes)
        )
        self._thread: threading.Thread | None = None

    @property
    def state(self) -> _BrokerState:
        return self._server.state  # type: ignore[attr-defined]

    @property
    def address(self) -> str:
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> "Broker":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self) -> "Broker":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv=None):
    """Standalone broker CLI (the docker-compose Kafka service's role for
    bare-metal bring-up): ``python -m skyline_tpu.bridge.kafkalite.broker
    [--host H] [--port P] [--max-message-bytes N]``."""
    import argparse

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9092)
    ap.add_argument(
        "--max-message-bytes", type=int, default=DEFAULT_MAX_MESSAGE_BYTES
    )
    args = ap.parse_args(argv)
    b = Broker(args.host, args.port, args.max_message_bytes)
    import sys

    print(f"kafkalite broker listening on {b.address}", file=sys.stderr)
    try:
        b._server.serve_forever()
    except KeyboardInterrupt:
        b.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
