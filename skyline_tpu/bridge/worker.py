"""jax_skyline_worker: the bridge between the transport plane and the engine.

The TPU-side counterpart of the reference's Flink job process: consumes the
data topic (earliest) and query topic (latest), feeds the ``SkylineEngine``,
and produces one JSON result per completed query on the output topic
(FlinkSkyline.java job wiring :84-97, :177-183). Works over any bus exposing
``produce``/``consumer`` (MemoryBus or KafkaBus).
"""

from __future__ import annotations

import sys
import time

from skyline_tpu.bridge.wire import format_result, parse_tuple_lines
from skyline_tpu.stream.engine import EngineConfig, SkylineEngine

# Reference topic names (FlinkSkyline.java:68-70)
INPUT_TOPIC = "input-tuples"
QUERY_TOPIC = "queries"
OUTPUT_TOPIC = "output-skyline"


class SkylineWorker:
    def __init__(
        self,
        bus,
        config: EngineConfig,
        input_topic: str = INPUT_TOPIC,
        query_topic: str = QUERY_TOPIC,
        output_topic: str = OUTPUT_TOPIC,
        mesh=None,
        stats_port: int | None = None,
        window_size: int = 0,
        slide: int = 0,
        emit_per_slide: bool = False,
        max_drain_polls: int = 256,
        tracer=None,
        serve_port: int | None = None,
        serve_config=None,
        telemetry=None,
        trace_ring: int = 4096,
        trace_out: str | None = None,
        jax_profile_dir: str | None = None,
    ):
        """``mesh``: optional ``jax.sharding.Mesh`` — partition state shards
        across its devices (multi-chip streaming). ``stats_port``: serve
        live /stats + /healthz JSON on this port (0 picks a free one; None
        disables) — the Flink-Web-UI role for this stack. ``window_size`` +
        ``slide`` (both > 0) switch the worker to the sliding-window engine
        (``stream.sliding_engine``), same transport and result planes.
        ``max_drain_polls``: cap on trigger-pending data re-polls per step
        (see ``step``); at the 65536-row default poll size the default cap
        drains up to ~16.7M rows before a trigger is applied anyway.
        ``serve_port``: start the query-serving plane (``serve/``) on this
        port (0 picks a free one; None disables): the engine publishes
        every completed global skyline as a versioned snapshot, and
        ``GET /skyline`` / ``POST /query`` / ``GET /deltas`` serve reads,
        forced merges, and delta catch-up with admission control.
        ``serve_config``: a ``serve.ServeConfig`` overriding the admission
        and ring knobs (its ``port`` is overridden by ``serve_port``).
        ``tracer``: optional ``metrics.tracing.Tracer``; by default the
        worker traces its own loop (transport poll / parse / engine phases)
        with ``sync_device=False`` so the breakdown is observable in
        ``/stats`` without perturbing the async device pipeline.
        ``telemetry``: optional shared ``telemetry.Telemetry`` hub; the
        worker always has one (created here when not given, span ring sized
        ``trace_ring``) and threads it through the engine and both HTTP
        servers — latency histograms + per-query spans cost one lock each.
        ``trace_out``: write the span ring as Chrome trace-event JSON to
        this path on ``close()`` (load at https://ui.perfetto.dev).
        ``jax_profile_dir``: opt-in — wrap each forced-query injection
        (POST /query) in ``jax.profiler.trace`` writing to this directory,
        so a device-level profile of exactly one consistency merge can be
        captured from a live worker."""
        from skyline_tpu.metrics.tracing import Tracer
        from skyline_tpu.telemetry import Telemetry

        self.bus = bus
        self.max_drain_polls = max_drain_polls
        self.tracer = tracer if tracer is not None else Tracer(sync_device=False)
        self.telemetry = (
            telemetry if telemetry is not None
            else Telemetry(span_capacity=trace_ring)
        )
        self.trace_out = trace_out
        self._jax_profile_dir = jax_profile_dir
        self._phase_snapshot_ms: dict[str, float] = {}
        self._last_phase_report_s = 0.0
        # None = undecided, True = zero-copy array plane, False = line plane
        self._arrays_plane: bool | None = None
        # (ids, values) tail of an oversized array batch, served in
        # max_records micro-batches by subsequent _poll_data calls
        self._data_carry: tuple | None = None
        if window_size:
            from skyline_tpu.stream.sliding_engine import SlidingEngine

            self.engine = SlidingEngine(
                config,
                window_size=window_size,
                slide=slide,
                mesh=mesh,
                emit_per_slide=emit_per_slide,
                tracer=self.tracer,
                telemetry=self.telemetry,
            )
        else:
            self.engine = SkylineEngine(
                config, mesh=mesh, tracer=self.tracer, telemetry=self.telemetry
            )
        self.output_topic = output_topic
        self._data = bus.consumer(input_topic, from_beginning=True)
        self._queries = bus.consumer(query_topic, from_beginning=False)
        self.results_emitted = 0
        self.serve_server = None
        self._serve_bridge = None
        if serve_port is not None:
            from skyline_tpu.serve import (
                DeltaRing,
                QueryBridge,
                ServeConfig,
                SkylineServer,
                SnapshotStore,
            )

            scfg = serve_config if serve_config is not None else ServeConfig()
            store = SnapshotStore(history=scfg.history)
            ring = DeltaRing(store, capacity=scfg.delta_ring)
            self.engine.attach_snapshots(store)
            self._serve_bridge = QueryBridge()
            try:
                self.serve_server = SkylineServer(
                    store,
                    deltas=ring,
                    admission=scfg.admission(),
                    stats_cb=self.stats,
                    bridge=self._serve_bridge,
                    port=serve_port,
                    host=scfg.host,
                    telemetry=self.telemetry,
                    read_cache=scfg.read_cache_entries,
                )
            except OSError as e:
                # like /stats: the serving plane is optional — a port
                # conflict must not take the ingest plane down
                self.engine.snapshots = None
                self._serve_bridge = None
                print(
                    f"skyline worker: serve port {serve_port} unavailable "
                    f"({e}); continuing without the serving plane",
                    file=sys.stderr,
                )
        self.stats_server = None
        if stats_port is not None:
            from skyline_tpu.metrics.httpstats import StatsServer

            try:
                self.stats_server = StatsServer(
                    self.stats, stats_port, telemetry=self.telemetry
                )
            except OSError as e:
                # observability is optional: a port conflict must not take
                # the worker (and with it the whole deploy stack) down
                print(
                    f"skyline worker: stats port {stats_port} unavailable "
                    f"({e}); continuing without /stats",
                    file=sys.stderr,
                )

    def stats(self) -> dict:
        """Engine counters + worker I/O counters (served by /stats)."""
        out = self.engine.stats()
        out["results_emitted"] = self.results_emitted
        out["phase_breakdown_ms"] = {
            k: round(v["total_ms"], 1) for k, v in self.tracer.report().items()
        }
        # latency distributions (ingest batch / merge / query latency /
        # serve reads): p50/p90/p99 summaries, the dashboard's tiles
        out["latency_ms"] = self.telemetry.latency_snapshot()
        if self.serve_server is not None:
            out["serve"] = self.serve_server.admission.stats()
            out["snapshot_store"] = self.serve_server.store.stats()
        return out

    def close(self) -> None:
        if getattr(self, "_closed", False):
            return  # idempotent: callers and teardown paths may both close
        self._closed = True
        if self.trace_out:
            try:
                n = self.telemetry.spans.write_chrome(self.trace_out)
                print(
                    f"skyline worker: wrote {n} trace span(s) to "
                    f"{self.trace_out}",
                    file=sys.stderr,
                )
            except OSError as e:
                print(
                    f"skyline worker: --trace-out {self.trace_out} failed: {e}",
                    file=sys.stderr,
                )
        if self.stats_server is not None:
            self.stats_server.close()
        if self.serve_server is not None:
            self.serve_server.close()

    def _poll_data(self, max_records: int):
        """One data-topic poll as ``(ids, values, dropped, got)`` where
        ``got`` counts raw records received (parsed + dropped — the idle /
        drain-bound signal). Prefers the transport's zero-copy array plane
        (kafkalite ``poll_arrays``: fetch blob -> native RecordBatch walk +
        CSV parse -> numpy, no per-record Python objects); falls back to
        line ``poll()`` + ``parse_tuple_lines`` for transports without it
        (MemoryBus, kafka-python) or when the native library is absent.
        The choice is latched on first resolution."""
        import numpy as np

        dims = self.engine.config.dims
        if self._data_carry is not None:
            # tail of a previous oversized array batch: serve the next
            # max_records micro-batch, preserving step()'s chunk contract
            ids, values = self._data_carry
            head_i, head_v = ids[:max_records], values[:max_records]
            self._data_carry = (
                (ids[max_records:], values[max_records:])
                if ids.shape[0] > max_records
                else None
            )
            return head_i, head_v, 0, head_i.shape[0]
        if self._arrays_plane is not False:
            poll_arrays = getattr(self._data, "poll_arrays", None)
            if poll_arrays is None:
                self._arrays_plane = False
            else:
                res = poll_arrays(dims)
                if res is None:  # native lib unavailable: latch line path
                    self._arrays_plane = False
                else:
                    self._arrays_plane = True
                    ids, values, dropped = res
                    if ids.shape[0] > max_records:
                        # one fetch can carry ~10-100x max_records; keep
                        # engine micro-batches at the documented size
                        self._data_carry = (
                            ids[max_records:],
                            values[max_records:],
                        )
                        ids, values = ids[:max_records], values[:max_records]
                    return ids, values, dropped, ids.shape[0] + dropped
        lines = self._data.poll(max_records)
        if not lines:
            return (
                np.empty(0, dtype=np.int64),
                np.empty((0, dims), dtype=np.float32),
                0,
                0,
            )
        with self.tracer.phase("worker/parse"):
            ids, values, dropped = parse_tuple_lines(lines, dims)
        return ids, values, dropped, len(lines)

    def step(self, max_records: int = 65536) -> int:
        """One poll cycle: snapshot triggers, ingest data, then apply the
        triggers. Returns the number of messages processed (0 == idle).

        Ordering matters: triggers are POLLED before data but APPLIED after
        it, and when a trigger arrived the data topic is DRAINED (polled
        until empty) first. A producer acks its data before sending the
        trigger that refers to it, so a visible trigger implies that data
        is committed at the broker; draining ingests all of it — including
        bursts larger than ``max_records`` — before the trigger runs. The
        reverse order (data first) has a race: the data fetch can complete
        empty just before a produce burst while the trigger fetch ~100 ms
        later sees the burst's trigger, and every still-empty partition
        then answers the query through the empty-partition fast path (the
        reference's :351 heuristic) — a premature empty result for a
        stream that was already produced. The kafkalite fetch is
        synchronous (an empty poll means no committed data at the offset),
        so the drain closes the race fully there; transports whose poll
        can return transiently empty mid-fetch (kafka-python) keep a
        narrowed version of it.

        The drain is BOUNDED at ``max_drain_polls`` re-polls: against a
        producer that sustains the stream indefinitely, an until-empty
        drain would starve the trigger, ``check_timeouts()``, and result
        emission forever. Hitting the bound applies the trigger against
        everything ingested so far — partitions that have data defer via
        the id-barrier until their required ids arrive, so the residual
        exposure is only the reference's own empty-partition fast-path
        heuristic (FlinkSkyline.java:351) for a partition that got nothing
        in ``max_drain_polls * max_records`` drained rows.
        """
        with self.tracer.phase("worker/poll"):
            triggers = self._queries.poll(max_records)
            ids, values, dropped, got = self._poll_data(max_records)
        total_lines = 0
        drains = 0
        while got:
            total_lines += got
            self.engine.dropped += dropped
            if ids.shape[0]:
                with self.tracer.phase("worker/ingest"):
                    self.engine.process_records(ids, values)
            if not triggers:
                break  # no trigger pending: one poll per cycle as before
            if drains >= self.max_drain_polls:
                # bounded drain: guarantee trigger/timeout progress. With an
                # immediate (required=0) trigger pending this means the query
                # answers against a TRUNCATED ingest — say so loudly, and
                # point at the knob (--max-drain-polls) that raises the bound
                print(
                    f"skyline worker: drain bound hit after {drains + 1} polls "
                    f"({total_lines} rows) with {len(triggers)} trigger(s) "
                    "pending — the stream may exceed "
                    "max_drain_polls * max_records; queries with an id "
                    "barrier defer safely, but an immediate (required=0) "
                    "trigger will answer against the rows drained so far. "
                    "Raise --max-drain-polls for larger finite streams.",
                    file=sys.stderr,
                )
                break
            drains += 1
            with self.tracer.phase("worker/poll"):
                ids, values, dropped, got = self._poll_data(max_records)
        with self.tracer.phase("worker/query"):
            for t in triggers:
                self.engine.process_trigger(t)
            if self._serve_bridge is not None:
                # forced consistency merges from POST /query run on this
                # thread, after bus triggers — the engine stays single-owner
                self._inject_serve_queries()
            self.engine.check_timeouts()
        results = self.engine.poll_results()
        if self._serve_bridge is not None:
            # serve-plane results return to their HTTP waiters, not the bus
            results = self._serve_bridge.fulfill(results)
        for result in results:
            self.bus.produce(self.output_topic, format_result(result))
            self.results_emitted += 1
            self._report_phases()
        return total_lines + len(triggers)

    def _inject_serve_queries(self) -> None:
        """Run the serve-plane's queued forced merges; with
        ``jax_profile_dir`` set, wrap the injection in ``jax.profiler.trace``
        so exactly one POST /query's device work lands in a profile."""
        if self._jax_profile_dir and self._serve_bridge.pending_injections:
            try:
                import jax

                with jax.profiler.trace(self._jax_profile_dir):
                    self._serve_bridge.inject(self.engine)
                return
            except Exception as e:  # profiling is opt-in observability:
                # never let a profiler failure shed the query itself
                print(
                    f"skyline worker: jax.profiler.trace failed ({e}); "
                    "running injection unprofiled",
                    file=sys.stderr,
                )
        self._serve_bridge.inject(self.engine)

    def _report_phases(self) -> None:
        """Per-result stderr breakdown: the DELTA of each phase since the
        previous report, so each line attributes only the wall spent since
        the last answered query (worker/* rows are the loop's own
        accounting; engine rows — partition_ids/route/flush/query — nest
        inside them). Rate-limited to one line per second so per-slide
        sliding emissions don't flood stderr; /stats always serves the
        cumulative totals."""
        now = time.monotonic()
        if now - self._last_phase_report_s < 1.0:
            return
        self._last_phase_report_s = now
        totals = {
            k: v["total_ms"] for k, v in self.tracer.report().items()
        }
        delta = {
            k: round(ms - self._phase_snapshot_ms.get(k, 0.0))
            for k, ms in totals.items()
            if ms - self._phase_snapshot_ms.get(k, 0.0) >= 0.5
        }
        self._phase_snapshot_ms = totals
        if delta:
            print(f"skyline worker: phase_breakdown_ms={delta}",
                  file=sys.stderr, flush=True)

    def run_forever(self, idle_sleep_s: float = 0.01, stop_after_idle_s: float | None = None):
        """Poll loop; optionally exits after ``stop_after_idle_s`` of silence."""
        idle_since = None
        while True:
            n = self.step()
            if n == 0:
                now = time.time()
                if idle_since is None:
                    idle_since = now
                elif stop_after_idle_s is not None and now - idle_since > stop_after_idle_s:
                    return
                time.sleep(idle_sleep_s)
            else:
                idle_since = None


def main(argv=None):
    """CLI: run the worker against a Kafka broker with reference-style flags
    (the `flink run` equivalent of README_Ubuntu_Setup.md's job launch)."""
    from skyline_tpu.bridge.kafka import KafkaBus
    from skyline_tpu.utils.compile_cache import enable_compile_cache
    from skyline_tpu.utils.config import parse_job_args

    cfg = parse_job_args(argv)
    # restarted workers reuse every previously compiled executable
    # (SKYLINE_COMPILE_CACHE overrides the location)
    enable_compile_cache()
    bus = KafkaBus(cfg.bootstrap)
    worker = SkylineWorker(
        bus,
        cfg.engine_config(),
        input_topic=cfg.input_topic,
        query_topic=cfg.query_topic,
        output_topic=cfg.output_topic,
        mesh=cfg.build_mesh(),
        stats_port=cfg.stats_port if cfg.stats_port > 0 else None,
        window_size=cfg.window_size,
        slide=cfg.slide,
        emit_per_slide=cfg.emit_per_slide,
        max_drain_polls=cfg.max_drain_polls,
        serve_port=cfg.serve_port if cfg.serve_port >= 0 else None,
        serve_config=cfg.serve_config() if cfg.serve_port >= 0 else None,
        trace_ring=cfg.trace_ring,
        trace_out=cfg.trace_out or None,
        jax_profile_dir=cfg.jax_profile_dir or None,
    )
    print(
        f"skyline worker: algo={cfg.algo} partitions={cfg.engine_config().num_partitions} "
        f"dims={cfg.dims} broker={cfg.bootstrap} mesh={cfg.mesh or 'off'}"
        + (f" stats=:{worker.stats_server.port}" if worker.stats_server else "")
        + (f" serve=:{worker.serve_server.port}" if worker.serve_server else ""),
        file=sys.stderr,
    )
    try:
        worker.run_forever()
    finally:
        worker.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
