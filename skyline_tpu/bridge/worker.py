"""jax_skyline_worker: the bridge between the transport plane and the engine.

The TPU-side counterpart of the reference's Flink job process: consumes the
data topic (earliest) and query topic (latest), feeds the ``SkylineEngine``,
and produces one JSON result per completed query on the output topic
(FlinkSkyline.java job wiring :84-97, :177-183). Works over any bus exposing
``produce``/``consumer`` (MemoryBus or KafkaBus).
"""

from __future__ import annotations

import os
import socket
import sys
import time
import uuid

from skyline_tpu.bridge.wire import format_result, parse_tuple_lines
from skyline_tpu.resilience.faults import fault_point, install_from_env
from skyline_tpu.resilience.wal import batch_digest
from skyline_tpu.stream.engine import EngineConfig, SkylineEngine

# Reference topic names (FlinkSkyline.java:68-70)
INPUT_TOPIC = "input-tuples"
QUERY_TOPIC = "queries"
OUTPUT_TOPIC = "output-skyline"


class SkylineWorker:
    def __init__(
        self,
        bus,
        config: EngineConfig,
        input_topic: str = INPUT_TOPIC,
        query_topic: str = QUERY_TOPIC,
        output_topic: str = OUTPUT_TOPIC,
        mesh=None,
        mesh_chips: int = 0,
        cluster_hosts: int = 0,
        stats_port: int | None = None,
        window_size: int = 0,
        slide: int = 0,
        emit_per_slide: bool = False,
        max_drain_polls: int = 256,
        tracer=None,
        serve_port: int | None = None,
        serve_config=None,
        telemetry=None,
        trace_ring: int = 4096,
        trace_out: str | None = None,
        jax_profile_dir: str | None = None,
        resilience=None,
        replicas: int = 0,
    ):
        """``mesh``: optional ``jax.sharding.Mesh`` — partition state shards
        across its devices (multi-chip streaming). ``mesh_chips``: > 0
        runs the sharded streaming engine (``distributed/``) — the
        partition set splits into that many per-chip groups and queries
        are answered by the two-level tournament merge; byte-identical
        results, mutually exclusive with ``mesh`` and sliding-window
        mode (RUNBOOK §2n). ``stats_port``: serve
        live /stats + /healthz JSON on this port (0 picks a free one; None
        disables) — the Flink-Web-UI role for this stack. ``window_size`` +
        ``slide`` (both > 0) switch the worker to the sliding-window engine
        (``stream.sliding_engine``), same transport and result planes.
        ``max_drain_polls``: cap on trigger-pending data re-polls per step
        (see ``step``); at the 65536-row default poll size the default cap
        drains up to ~16.7M rows before a trigger is applied anyway.
        ``serve_port``: start the query-serving plane (``serve/``) on this
        port (0 picks a free one; None disables): the engine publishes
        every completed global skyline as a versioned snapshot, and
        ``GET /skyline`` / ``POST /query`` / ``GET /deltas`` serve reads,
        forced merges, and delta catch-up with admission control;
        ``GET /explain`` (also on the stats port, and inline via
        ``/skyline?explain=1``) returns the per-query EXPLAIN plan that
        produced an answer (telemetry/explain.py, RUNBOOK §2k).
        ``serve_config``: a ``serve.ServeConfig`` overriding the admission
        and ring knobs (its ``port`` is overridden by ``serve_port``).
        ``tracer``: optional ``metrics.tracing.Tracer``; by default the
        worker traces its own loop (transport poll / parse / engine phases)
        with ``sync_device=False`` so the breakdown is observable in
        ``/stats`` without perturbing the async device pipeline.
        ``telemetry``: optional shared ``telemetry.Telemetry`` hub; the
        worker always has one (created here when not given, span ring sized
        ``trace_ring``) and threads it through the engine and both HTTP
        servers — latency histograms + per-query spans cost one lock each.
        ``trace_out``: write the span ring as Chrome trace-event JSON to
        this path on ``close()`` (load at https://ui.perfetto.dev).
        ``jax_profile_dir``: opt-in — wrap each forced-query injection
        (POST /query) in ``jax.profiler.trace`` writing to this directory,
        so a device-level profile of exactly one consistency merge can be
        captured from a live worker.
        ``resilience``: a ``resilience.ResilienceConfig`` enabling crash
        safety — on construction the worker restores the newest valid
        checkpoint, replays the WAL (digest-verified, exactly the committed
        spans) to the crashed incarnation's exact position, re-seats the
        serving plane's snapshot + delta ring, then records every consumed
        span and published delta to a fresh WAL segment; periodic
        checkpoints truncate the log. None (default) keeps the reference's
        lose-everything behavior."""
        from skyline_tpu.metrics.tracing import Tracer
        from skyline_tpu.telemetry import Telemetry

        if mesh_chips and mesh is not None:
            raise ValueError("mesh and mesh_chips are mutually exclusive")
        if mesh_chips and window_size:
            raise ValueError(
                "sliding-window mode does not support mesh_chips"
            )
        if cluster_hosts and mesh is not None:
            raise ValueError("mesh and cluster_hosts are mutually exclusive")
        if cluster_hosts and window_size:
            raise ValueError(
                "sliding-window mode does not support cluster_hosts"
            )
        self.mesh_chips = int(mesh_chips)
        self.cluster_hosts = int(cluster_hosts)
        self.bus = bus
        self.max_drain_polls = max_drain_polls
        self.tracer = tracer if tracer is not None else Tracer(sync_device=False)
        self.telemetry = (
            telemetry if telemetry is not None
            else Telemetry(span_capacity=trace_ring)
        )
        self.trace_out = trace_out
        self._jax_profile_dir = jax_profile_dir
        self._phase_snapshot_ms: dict[str, float] = {}
        self._last_phase_report_s = 0.0
        # None = undecided, True = zero-copy array plane, False = line plane
        self._arrays_plane: bool | None = None
        # (ids, values) tail of an oversized array batch, served in
        # max_records micro-batches by subsequent _poll_data calls
        self._data_carry: tuple | None = None
        # -- crash safety (resilience=None keeps all of this inert) -------
        self.resilience = resilience
        self._ckpt_mgr = None
        self._wal = None
        self._chip_wal = None
        self._lease_plane = None
        self._lease_keeper = None
        self._opslog = None
        self._deposed = False
        self._snap_store = None
        self._serve_ring = None
        self._bodystore = None
        self._data_pos = 0  # consumed data-topic records (replay currency)
        self._query_pos = 0  # consumed query-topic records
        self._dirty = False  # work since the last checkpoint
        self._last_ckpt_s = time.monotonic()
        self._stop_requested = False
        self._recovered: dict | None = None
        restored_engine = None
        restored_meta = None
        wal_records: list = []
        wal_torn = 0
        if resilience is not None:
            if window_size:
                raise ValueError(
                    "sliding-window mode does not support crash safety "
                    "(utils/checkpoint.py covers the tumbling engine only)"
                )
            install_from_env()  # arm SKYLINE_FAULT_PLAN (parse-once)
            from skyline_tpu.resilience import WAL_SUBDIR
            from skyline_tpu.resilience.checkpoints import CheckpointManager
            from skyline_tpu.resilience.wal import read_records

            self._ckpt_mgr = CheckpointManager(
                resilience.checkpoint_dir,
                retain=resilience.checkpoint_retain,
                telemetry=self.telemetry,
            )
            hit = self._ckpt_mgr.restore_latest(
                mesh=mesh, mesh_chips=mesh_chips,
                cluster_hosts=cluster_hosts, tracer=self.tracer,
                telemetry=self.telemetry,
            )
            ckpt_path = None
            if hit is not None:
                restored_engine, restored_meta, ckpt_path = hit
            self._wal_dir = os.path.join(resilience.checkpoint_dir, WAL_SUBDIR)
            wal_records, wal_torn = read_records(self._wal_dir)
            # sharded group-consistency check: at the highest barrier seq
            # common to all chip journals, every chip must agree on the
            # global epoch digest; divergence raises WalReplayError here,
            # BEFORE any replay could publish from inconsistent groups
            from skyline_tpu.resilience.chip_wal import verify_chip_barriers

            chip_verdict = verify_chip_barriers(self._wal_dir)
            if hit is not None or wal_records:
                self._recovered = {
                    "checkpoint": ckpt_path,
                    "wal_records": len(wal_records),
                    "wal_torn_segments": wal_torn,
                    "replayed_batches": 0,
                }
                if chip_verdict["chips"]:
                    self._recovered["chip_barriers"] = chip_verdict
        if window_size:
            from skyline_tpu.stream.sliding_engine import SlidingEngine

            self.engine = SlidingEngine(
                config,
                window_size=window_size,
                slide=slide,
                mesh=mesh,
                emit_per_slide=emit_per_slide,
                tracer=self.tracer,
                telemetry=self.telemetry,
            )
        elif restored_engine is not None:
            # the checkpoint carries its full EngineConfig; trust it over the
            # passed config so a restarted incarnation can't silently change
            # result semantics mid-stream
            self.engine = restored_engine
        elif cluster_hosts:
            # multi-host cluster ingest (RUNBOOK §2r): mesh_chips becomes
            # the per-host chip count, so --cluster-hosts 4 --mesh-chips 2
            # runs the full three-level tournament
            from skyline_tpu.cluster import ClusterEngine

            self.engine = ClusterEngine(
                config, hosts=cluster_hosts,
                chips_per_host=mesh_chips or 1, tracer=self.tracer,
                telemetry=self.telemetry,
            )
        elif mesh_chips:
            from skyline_tpu.distributed import ShardedEngine

            self.engine = ShardedEngine(
                config, chips=mesh_chips, tracer=self.tracer,
                telemetry=self.telemetry,
            )
        else:
            self.engine = SkylineEngine(
                config, mesh=mesh, tracer=self.tracer, telemetry=self.telemetry
            )
        self.output_topic = output_topic
        self._data = bus.consumer(input_topic, from_beginning=True)
        self._queries = bus.consumer(query_topic, from_beginning=False)
        self.results_emitted = 0
        if resilience is not None:
            # warm the learned-dispatch planes BEFORE replay so the replay
            # flushes themselves run under the checkpointed winners
            # instead of re-paying cold exploration (PR 18 scoping note)
            self._restore_dispatch_state(restored_meta)
            self._replay(restored_meta, wal_records)
        self.serve_server = None
        self._serve_bridge = None
        if serve_port is not None:
            from skyline_tpu.serve import (
                DeltaRing,
                QueryBridge,
                ServeConfig,
                SkylineServer,
                SnapshotStore,
            )

            scfg = serve_config if serve_config is not None else ServeConfig()
            store = SnapshotStore(history=scfg.history)
            ring = DeltaRing(store, capacity=scfg.delta_ring)
            self.engine.attach_snapshots(store)
            self._serve_bridge = QueryBridge()
            self._snap_store = store
            self._serve_ring = ring
            # zero-copy body store (RUNBOOK §2u): wire bodies serialize
            # once per publish, off the read path. With resilience the
            # store file lands beside the WAL so --replicas / --replica-of
            # processes map the primary's exact bytes; without a WAL dir
            # it stays in-process (publish-time serialization still wins).
            from skyline_tpu.analysis.registry import env_bool

            if env_bool("SKYLINE_BODYSTORE", True):
                from skyline_tpu.serve.bodystore import BodyStore

                wal_dir = getattr(self, "_wal_dir", None)
                self._bodystore = BodyStore(
                    os.path.join(wal_dir, "bodystore.dat")
                    if wal_dir is not None
                    else None
                ).attach(store)
            try:
                self.serve_server = SkylineServer(
                    store,
                    deltas=ring,
                    admission=scfg.admission(),
                    stats_cb=self.stats,
                    bridge=self._serve_bridge,
                    port=serve_port,
                    host=scfg.host,
                    telemetry=self.telemetry,
                    read_cache=scfg.read_cache_entries,
                    bodystore=self._bodystore,
                )
            except OSError as e:
                # like /stats: the serving plane is optional — a port
                # conflict must not take the ingest plane down
                self.engine.snapshots = None
                self._serve_bridge = None
                self._snap_store = None
                self._serve_ring = None
                if self._bodystore is not None:
                    self._bodystore.close()
                    self._bodystore = None
                print(
                    f"skyline worker: serve port {serve_port} unavailable "
                    f"({e}); continuing without the serving plane",
                    file=sys.stderr,
                )
        if resilience is not None:
            if self._snap_store is not None:
                self._restore_serve(wal_records)
            from skyline_tpu.analysis.registry import env_float
            from skyline_tpu.resilience.wal import WalWriter

            wal_kw = dict(
                segment_bytes=resilience.wal_segment_bytes,
                fsync=resilience.wal_fsync,
                telemetry=self.telemetry,
                # live replica tailers pin segment retention (barrier skips
                # segments they haven't consumed); stale acks expire so a
                # dead replica can't pin the log forever
                tailer_ttl_s=env_float("SKYLINE_WAL_TAILER_TTL_S", 600.0),
            )
            # durable cross-process ops journal (RUNBOOK §2s): every
            # control-plane transition this process performs — lease
            # acquire, demotion, quarantine, degraded publish — lands
            # beside the WAL so a post-mortem reconstructs the fleet's
            # causal timeline across processes
            from skyline_tpu.telemetry.opslog import OpsLog, opslog_enabled

            if opslog_enabled():
                self._opslog = OpsLog(self._wal_dir, telemetry=self.telemetry)
                self.telemetry.opslog = self._opslog
                pset = getattr(self.engine, "pset", None)
                if pset is not None and hasattr(pset, "attach_opslog"):
                    pset.attach_opslog(self._opslog)
            if cluster_hosts:
                # write-path HA (RUNBOOK §2r): this worker is the lease
                # holder; every WAL frame carries its fencing token, and
                # the instant another primary is promoted over us every
                # append is rejected at the WAL layer
                from skyline_tpu.cluster import (
                    FencedWalWriter,
                    LeaseKeeper,
                    LeasePlane,
                )

                self._lease_plane = LeasePlane(self._wal_dir)
                # globally unique holder id: pid alone collides across
                # containers (pid 1) or hosts sharing the WAL dir, and
                # LeasePlane.acquire treats a same-named holder as self —
                # a collision would depose a live primary instead of
                # refusing to start
                self._lease_keeper = LeaseKeeper(
                    self._lease_plane,
                    f"worker-{socket.gethostname()}-{os.getpid()}"
                    f"-{uuid.uuid4().hex[:8]}",
                    telemetry=self.telemetry,
                )
                if self._lease_keeper.acquire() is None:
                    held = self._lease_plane.read_lease()
                    raise ValueError(
                        "write lease is held by "
                        f"{held.holder!r} (epoch {held.epoch}); refusing to "
                        "start a second primary against the same WAL"
                    )
                if self._opslog is not None:
                    self._opslog.record(
                        "lease_acquired",
                        epoch=self._lease_keeper.epoch,
                        fence=self._lease_plane.read_fence(),
                        holder=self._lease_keeper.holder,
                    )
                self._wal = FencedWalWriter(
                    self._wal_dir,
                    self._lease_keeper.epoch,
                    plane=self._lease_plane,
                    opslog=self._opslog,
                    **wal_kw,
                )
                status = getattr(self.telemetry, "cluster", None)
                if status is not None:
                    status.node_id = self._lease_keeper.holder
                    status.role = "primary"
                    status.lease_cb = self._lease_plane.doc
            else:
                self._wal = WalWriter(self._wal_dir, **wal_kw)
            # WAL replication-plane families (RUNBOOK §2s): retained
            # segments plus per-tailer ack age — a growing ack age is a
            # stalled replica still pinning retention
            def _wal_plane_series(wal=self._wal, wal_dir=self._wal_dir):
                from skyline_tpu.resilience.wal import ack_ages_s

                gauges: dict = {}
                st = wal.stats()
                gauges["wal_segments_retained"] = [
                    ((), float(st.get("segments_retained", 0)))
                ]
                ages = ack_ages_s(wal_dir)
                if ages:
                    gauges["wal_tail_ack_age_s"] = [
                        ((("tailer", t),), round(age, 3))
                        for t, age in sorted(ages.items())
                    ]
                return {}, gauges

            self.telemetry.replication.append(_wal_plane_series)
            # chip-local WAL segments for the sharded engine: per-chip
            # flush lineage + merge-time consistency barriers (policy
            # "merge", the default), or checkpoint-time barriers only
            # ("checkpoint"); "off" skips the plane entirely
            if self.mesh_chips and not cluster_hosts:
                from skyline_tpu.ops.dispatch import chip_barrier_policy
                from skyline_tpu.resilience.chip_wal import ChipWalPlane

                policy = chip_barrier_policy()
                if policy != "off":
                    self._chip_wal = ChipWalPlane(
                        self._wal_dir,
                        self.mesh_chips,
                        segment_bytes=resilience.wal_segment_bytes,
                        fsync=resilience.wal_fsync,
                        telemetry=self.telemetry,
                    )
                    if policy == "merge":
                        self.engine.pset.attach_chip_wal(self._chip_wal)
            # subscribe AFTER the serve restore so re-seating the head never
            # logs a bogus everything-entered delta
            if self._snap_store is not None:
                self._snap_store.on_publish(self._wal_on_publish)
            # divergence repro bundles freeze the live WAL segment slice;
            # without resilience the auditor's wal_dir stays None and
            # bundles simply omit the wal/ directory
            auditor = getattr(self.engine, "auditor", None)
            if auditor is not None:
                auditor.wal_dir = self._wal_dir
            self._wal.append(
                {
                    "type": "start",
                    "data_off": self._data_pos,
                    "query_off": self._query_pos,
                }
            )
            self._wal.flush(force=True)
        # WAL-tailing read replicas (serve/replica.py): each gets its own
        # SnapshotStore + ring + HTTP port, bootstraps from the newest
        # barrier in the WAL and live-tails publish deltas. In-process
        # spawn is the embedded/test mode; production runs them as separate
        # processes (--replica-of) so an engine death leaves them serving.
        self.replicas = []
        if replicas:
            if resilience is None or self._snap_store is None:
                raise ValueError(
                    "replicas require resilience (--checkpoint-dir) and the "
                    "serve plane (--serve)"
                )
            from skyline_tpu.serve.replica import SkylineReplica

            # in-process replicas share the worker's hub for the labeled
            # replica families, the worker's ops journal, and see the
            # primary head directly for replica_lag_versions
            store = self._snap_store
            for i in range(int(replicas)):
                self.replicas.append(
                    SkylineReplica(
                        self._wal_dir,
                        port=0,
                        serve_config=serve_config,
                        replica_id=f"replica-{i}",
                        telemetry=self.telemetry,
                        opslog=self._opslog,
                        primary_head_cb=lambda s=store: s.head_version,
                    )
                )
        self.stats_server = None
        if stats_port is not None:
            from skyline_tpu.metrics.httpstats import StatsServer

            try:
                self.stats_server = StatsServer(
                    self.stats, stats_port, telemetry=self.telemetry
                )
            except OSError as e:
                # observability is optional: a port conflict must not take
                # the worker (and with it the whole deploy stack) down
                print(
                    f"skyline worker: stats port {stats_port} unavailable "
                    f"({e}); continuing without /stats",
                    file=sys.stderr,
                )

    def stats(self) -> dict:
        """Engine counters + worker I/O counters (served by /stats)."""
        out = self.engine.stats()
        out["results_emitted"] = self.results_emitted
        out["phase_breakdown_ms"] = {
            k: round(v["total_ms"], 1) for k, v in self.tracer.report().items()
        }
        # latency distributions (ingest batch / merge / query latency /
        # serve reads): p50/p90/p99 summaries, the dashboard's tiles
        out["latency_ms"] = self.telemetry.latency_snapshot()
        if self.serve_server is not None:
            out["serve"] = self.serve_server.admission.stats()
            out["snapshot_store"] = self.serve_server.store.stats()
        if self._ckpt_mgr is not None:
            res = {
                "checkpoint": self._ckpt_mgr.stats(),
                "data_off": self._data_pos,
                "query_off": self._query_pos,
            }
            if self._wal is not None:
                res["wal"] = self._wal.stats()
            if self._lease_keeper is not None:
                res["lease"] = {
                    "holder": self._lease_keeper.holder,
                    "epoch": self._lease_keeper.epoch,
                    "deposed": self._deposed,
                    **self._lease_plane.doc(),
                }
            if self._chip_wal is not None:
                res["chip_wal"] = self._chip_wal.stats()
            if self._opslog is not None:
                res["ops"] = self._opslog.stats()
            if self._recovered is not None:
                res["recovered"] = self._recovered
            out["resilience"] = res
        return out

    def close(self) -> None:
        if getattr(self, "_closed", False):
            return  # idempotent: callers and teardown paths may both close
        self._closed = True
        if self.trace_out:
            try:
                n = self.telemetry.spans.write_chrome(self.trace_out)
                print(
                    f"skyline worker: wrote {n} trace span(s) to "
                    f"{self.trace_out}",
                    file=sys.stderr,
                )
            except OSError as e:
                print(
                    f"skyline worker: --trace-out {self.trace_out} failed: {e}",
                    file=sys.stderr,
                )
        if self.stats_server is not None:
            self.stats_server.close()
        if self.serve_server is not None:
            self.serve_server.close()
        if self._bodystore is not None:
            self._bodystore.close()
        for replica in getattr(self, "replicas", []):
            replica.close()
        if self._wal is not None:
            try:
                self._wal.close()
            except OSError:
                pass
            self._wal = None
        if self._chip_wal is not None:
            try:
                self._chip_wal.close()
            except OSError:
                pass
            self._chip_wal = None
        if self._opslog is not None:
            self._opslog.close()
            self._opslog = None

    # -- crash recovery ----------------------------------------------------

    def _replay(self, meta: dict | None, records: list) -> None:
        """Rebuild the exact pre-crash ingest state: seek the data consumer
        to the checkpoint's committed offset, then re-ingest every WAL
        ``batch`` span (poll exactly ``hi - lo`` records, digest-verified)
        in the same per-call chunks the crashed incarnation used — with the
        restored engine as the base, the post-replay state is byte-identical
        to the uninterrupted run's at the same offset. The query consumer is
        re-seated to the last committed position so triggers that were
        polled but whose step never committed are re-polled (at-least-once
        trigger processing over exactly-once state)."""
        import numpy as np

        from skyline_tpu.resilience.wal import WalReplayError, batch_digest

        data_base = 0
        query_off = None
        if meta is not None:
            extra = meta.get("extra", {})
            data_base = int(extra.get("data_off", 0))
            if "query_off" in extra:
                query_off = int(extra["query_off"])
        for rec in records:
            if rec.get("type") in ("start", "commit", "ckpt") and "query_off" in rec:
                query_off = int(rec["query_off"])
        if meta is None and not records:
            # first boot: anchor the positions (notably the query topic's
            # latest-reset offset, which only exists as a live position now)
            self._data_pos = self._pos_of(self._data)
            self._query_pos = self._pos_of(self._queries)
            return
        self._seek(self._data, data_base)
        pos = data_base
        replayed = 0
        dims = self.engine.config.dims
        for rec in records:
            if rec.get("type") != "batch":
                continue
            lo, hi, digest = int(rec["lo"]), int(rec["hi"]), rec["digest"]
            if hi <= data_base:
                continue  # already folded into the restored checkpoint
            if lo < data_base:
                raise WalReplayError(
                    f"batch span [{lo},{hi}) straddles checkpoint offset "
                    f"{data_base}"
                )
            if lo != pos:
                raise WalReplayError(
                    f"gap in WAL: expected a batch at offset {pos}, "
                    f"found [{lo},{hi})"
                )
            need = hi - lo
            got_total, dropped = 0, 0
            ids_parts: list = []
            val_parts: list = []
            while got_total < need:
                ids, values, dr, got = self._poll_data(need - got_total)
                if got == 0:
                    raise WalReplayError(
                        f"bus ended at offset {pos + got_total} while "
                        f"replaying to {hi}"
                    )
                got_total += got
                dropped += dr
                if ids.shape[0]:
                    ids_parts.append(ids)
                    val_parts.append(values)
            if got_total != need:
                raise WalReplayError(
                    f"replay chunk misalignment: span [{lo},{hi}) yielded "
                    f"{got_total} records"
                )
            ids = (
                np.concatenate(ids_parts)
                if ids_parts else np.empty(0, dtype=np.int64)
            )
            values = (
                np.concatenate(val_parts)
                if val_parts else np.empty((0, dims), dtype=np.float32)
            )
            if batch_digest(ids, values) != digest:
                self.telemetry.inc("wal.digest_mismatch")
                raise WalReplayError(
                    f"replay digest mismatch for span [{lo},{hi}): the bus "
                    "does not hold the bytes the WAL committed"
                )
            self.engine.dropped += dropped
            if ids.shape[0]:
                self.engine.process_records(ids, values)
            pos = hi
            replayed += 1
            self.telemetry.inc("wal.replayed")
        self._data_pos = pos
        if query_off is not None:
            self._seek(self._queries, query_off)
            self._query_pos = query_off
        else:
            self._query_pos = self._pos_of(self._queries)
        if self._recovered is not None:
            self._recovered["replayed_batches"] = replayed
        if replayed or meta is not None:
            print(
                f"skyline worker: recovered — checkpoint "
                f"{'yes' if meta is not None else 'no'}, replayed {replayed} "
                f"WAL batch(es) to data offset {pos}",
                file=sys.stderr,
            )

    @staticmethod
    def _seek(consumer, offset: int) -> None:
        seek = getattr(consumer, "seek", None)
        if seek is None:
            raise RuntimeError(
                "crash safety requires a seekable consumer (MemoryBus or "
                f"kafkalite); {type(consumer).__name__} has no seek()"
            )
        seek(offset)

    @staticmethod
    def _pos_of(consumer) -> int:
        position = getattr(consumer, "position", None)
        return int(position()) if position is not None else 0

    def _restore_serve(self, records: list) -> None:
        """Re-seat the serving plane from the WAL: head points from the last
        checkpoint barrier's inlined snapshot plus every delta after it
        (byte-exact — delta records carry the published row order), the
        delta ring from the same delta records, version numbering
        continuous. Until a live publish lands, reads carry
        ``"restored": true``."""
        import numpy as np

        from skyline_tpu.resilience.wal import rows_from_b64
        from skyline_tpu.serve.deltas import Delta, apply_delta_record

        base = None
        base_idx = -1
        for i, rec in enumerate(records):
            if rec.get("type") == "ckpt" and "snap" in rec:
                base, base_idx = rec["snap"], i
        delta_recs = [
            r for r in records[base_idx + 1 :] if r.get("type") == "delta"
        ]
        if base is None and not delta_recs:
            return
        d = int(base["d"] if base is not None else delta_recs[0]["d"])
        points = (
            rows_from_b64(base["rows"], d)
            if base is not None
            else np.empty((0, d), dtype=np.float32)
        )
        version = int(base["version"]) if base is not None else 0
        watermark = int(base.get("watermark_id", -1)) if base is not None else -1
        event_wm = base.get("event_wm_ms") if base is not None else None
        meta = dict(base.get("meta", {})) if base is not None else {}
        ring_deltas = []
        for rec in delta_recs:
            entered = rows_from_b64(rec["entered"], int(rec["d"]))
            left = rows_from_b64(rec["left"], int(rec["d"]))
            ring_deltas.append(
                Delta(int(rec["from"]), int(rec["to"]), entered, left)
            )
            points = apply_delta_record(points, rec)
            version = int(rec["to"])
            watermark = int(rec.get("wm", watermark))
            event_wm = rec.get("ewm", event_wm)
            meta = dict(rec.get("meta", {}))
        self._snap_store.restore_state(
            points, version, watermark_id=watermark, event_wm_ms=event_wm,
            meta=meta,
        )
        if event_wm is not None:
            # the engine's tracker resumes from the recovered watermark, so
            # a restored run's published watermarks match the uninterrupted
            # run's (monotone-max; never regresses past replayed batches)
            fr = getattr(self.engine, "freshness", None)
            if fr is not None:
                fr.restore(event_wm)
        if self._serve_ring is not None:
            self._serve_ring.seed(ring_deltas, version)
        print(
            f"skyline worker: serving plane restored at version {version} "
            f"({points.shape[0]} point(s), {len(ring_deltas)} delta(s))",
            file=sys.stderr,
        )

    def _wal_on_publish(self, prev, snap) -> None:
        """Persist each published snapshot transition so ``/deltas``
        subscribers survive a restart (the delta ring's WAL shadow)."""
        if self._wal is None:
            return
        from skyline_tpu.serve.deltas import delta_wal_record

        self._wal.append(delta_wal_record(prev, snap))

    def _barrier_record(self) -> dict:
        rec = {
            "type": "ckpt",
            "data_off": self._data_pos,
            "query_off": self._query_pos,
        }
        snap = (
            self._snap_store.latest() if self._snap_store is not None else None
        )
        if snap is not None:
            from skyline_tpu.serve.deltas import snapshot_wal_record

            rec["snap"] = snapshot_wal_record(snap)
        return rec

    def _dispatch_state(self) -> dict:
        """The learned-dispatch extra-meta block: kernel-profiler state
        (hub profiler + the PartitionSet's separate flush-chooser
        profiler) and the dispatch tuner's learned pins/overrides. All
        JSON-safe; absent planes contribute nothing."""
        out: dict = {}
        prof = getattr(self.engine, "profiler", None)
        if prof is not None and hasattr(prof, "export_state"):
            out["profiler"] = prof.export_state()
        pset = getattr(self.engine, "pset", None)
        fprof = getattr(pset, "_flush_prof", None) if pset is not None else None
        if fprof is not None and hasattr(fprof, "export_state"):
            out["flush_profiler"] = fprof.export_state()
        tuner = getattr(self.engine, "tuner", None)
        if tuner is not None:
            out["tuner"] = tuner.state_doc()
        return out

    def _restore_dispatch_state(self, meta: dict | None) -> None:
        """Re-adopt the checkpointed learned-dispatch state into the LIVE
        engine's planes (the restored engine shares the hub profiler the
        checkpoint exported from). Live measurements win over restored
        ones; the tuner re-validates every pin against the cascade
        table's oracle rule."""
        if meta is None:
            return
        extra = meta.get("extra", {})
        prof = getattr(self.engine, "profiler", None)
        if prof is not None and hasattr(prof, "restore_state"):
            prof.restore_state(extra.get("profiler"))
        fstate = extra.get("flush_profiler")
        pset = getattr(self.engine, "pset", None)
        if fstate and pset is not None:
            if getattr(pset, "_flush_prof", None) is None:
                from skyline_tpu.telemetry.profiler import KernelProfiler

                pset._flush_prof = KernelProfiler()
            pset._flush_prof.restore_state(fstate)
        tuner = getattr(self.engine, "tuner", None)
        if tuner is not None:
            tuner.restore(extra.get("tuner"))

    def checkpoint_now(self) -> str | None:
        """Atomic checkpoint + WAL barrier (rotate, log the serve head,
        truncate everything the checkpoint now covers)."""
        if self._ckpt_mgr is None:
            return None
        path = self._ckpt_mgr.save(
            self.engine,
            extra_meta={
                "data_off": self._data_pos,
                "query_off": self._query_pos,
                # learned-dispatch plane (ISSUE 20): profiler EMAs (hub +
                # the flush chooser's separate per-set profiler) and the
                # tuner's pins/overrides ride the checkpoint so a
                # supervised restart resumes tuned instead of paying the
                # cold exploration flushes again
                **self._dispatch_state(),
            },
        )
        if self._wal is not None:
            self._wal.barrier(self._barrier_record())
        if self._chip_wal is not None:
            # the chip journals rotate with the main WAL (the checkpoint
            # supersedes older segments); the snap blob stays in the main
            # WAL only — chip journals carry positions, not rows
            self._chip_wal.checkpoint_barrier(
                {
                    "type": "ckpt",
                    "data_off": self._data_pos,
                    "query_off": self._query_pos,
                }
            )
        self._last_ckpt_s = time.monotonic()
        self._dirty = False
        return path

    def _maybe_checkpoint(self) -> None:
        if self._ckpt_mgr is None or not self._dirty:
            return
        interval = self.resilience.checkpoint_interval_s
        if interval <= 0:  # shutdown/manual-only mode
            return
        if time.monotonic() - self._last_ckpt_s >= interval:
            self.checkpoint_now()

    def shutdown(self) -> None:
        """Clean exit (SIGTERM/SIGINT): final checkpoint, force-fsync the
        WAL, close every server — a restart from this state replays
        nothing and loses nothing. A DEPOSED worker skips the final
        checkpoint: its WAL barrier would be rejected at the fence anyway,
        and the promoted primary now owns the durable state."""
        if self._ckpt_mgr is not None and self._dirty and not self._deposed:
            self.checkpoint_now()
        if self._wal is not None:
            self._wal.flush(force=True)
        self.close()

    def _maybe_renew_lease(self) -> None:
        """Renew the write lease when due; on deposition (a higher epoch
        on disk, or the fence moved past ours) demote instead of writing
        on — the honest half of the promotion drill."""
        if self._lease_keeper is None or self._deposed:
            return
        from skyline_tpu.cluster import LeaseLostError

        try:
            self._lease_keeper.maybe_renew()
        except LeaseLostError as e:
            if self._opslog is not None:
                self._opslog.record(
                    "lease_renew_lost",
                    epoch=self._lease_keeper.epoch,
                    fence=self._lease_plane.read_fence(),
                    error=str(e),
                )
            self._demote(str(e))

    def _demote(self, reason: str) -> None:
        """This worker lost the write path: stop ingesting, mark the role,
        and let the loop exit WITHOUT a final checkpoint (the fence
        rejects our barrier; the promoted primary owns durability now)."""
        self._deposed = True
        self._stop_requested = True
        self.telemetry.inc("cluster.demotions")
        if self._opslog is not None:
            self._opslog.record(
                "demoted",
                epoch=(
                    self._lease_keeper.epoch
                    if self._lease_keeper is not None else None
                ),
                fence=(
                    self._lease_plane.read_fence()
                    if self._lease_plane is not None else None
                ),
                reason=reason,
            )
        status = getattr(self.telemetry, "cluster", None)
        if status is not None:
            status.role = "deposed"
        print(
            f"skyline worker: write lease lost ({reason}); demoting — "
            "no further WAL appends, no final checkpoint",
            file=sys.stderr,
        )

    def _signal_handler(self, signum, frame) -> None:
        self._stop_requested = True
        print(
            f"skyline worker: signal {signum} received; finishing the "
            "current step then checkpointing",
            file=sys.stderr,
        )

    def _poll_data(self, max_records: int):
        """One data-topic poll as ``(ids, values, dropped, got)`` where
        ``got`` counts raw records received (parsed + dropped — the idle /
        drain-bound signal). Prefers the transport's zero-copy array plane
        (kafkalite ``poll_arrays``: fetch blob -> native RecordBatch walk +
        CSV parse -> numpy, no per-record Python objects); falls back to
        line ``poll()`` + ``parse_tuple_lines`` for transports without it
        (MemoryBus, kafka-python) or when the native library is absent.
        The choice is latched on first resolution."""
        import numpy as np

        dims = self.engine.config.dims
        if self._data_carry is not None:
            # tail of a previous oversized array batch: serve the next
            # max_records micro-batch, preserving step()'s chunk contract
            ids, values = self._data_carry
            head_i, head_v = ids[:max_records], values[:max_records]
            self._data_carry = (
                (ids[max_records:], values[max_records:])
                if ids.shape[0] > max_records
                else None
            )
            return head_i, head_v, 0, head_i.shape[0]
        if self._arrays_plane is not False:
            poll_arrays = getattr(self._data, "poll_arrays", None)
            if poll_arrays is None:
                self._arrays_plane = False
            else:
                res = poll_arrays(dims)
                if res is None:  # native lib unavailable: latch line path
                    self._arrays_plane = False
                else:
                    self._arrays_plane = True
                    ids, values, dropped = res
                    if ids.shape[0] > max_records:
                        # one fetch can carry ~10-100x max_records; keep
                        # engine micro-batches at the documented size
                        self._data_carry = (
                            ids[max_records:],
                            values[max_records:],
                        )
                        ids, values = ids[:max_records], values[:max_records]
                    return ids, values, dropped, ids.shape[0] + dropped
        lines = self._data.poll(max_records)
        if not lines:
            return (
                np.empty(0, dtype=np.int64),
                np.empty((0, dims), dtype=np.float32),
                0,
                0,
            )
        with self.tracer.phase("worker/parse"):
            ids, values, dropped = parse_tuple_lines(lines, dims)
        return ids, values, dropped, len(lines)

    def step(self, max_records: int = 65536) -> int:
        """One poll cycle: snapshot triggers, ingest data, then apply the
        triggers. Returns the number of messages processed (0 == idle).

        Ordering matters: triggers are POLLED before data but APPLIED after
        it, and when a trigger arrived the data topic is DRAINED (polled
        until empty) first. A producer acks its data before sending the
        trigger that refers to it, so a visible trigger implies that data
        is committed at the broker; draining ingests all of it — including
        bursts larger than ``max_records`` — before the trigger runs. The
        reverse order (data first) has a race: the data fetch can complete
        empty just before a produce burst while the trigger fetch ~100 ms
        later sees the burst's trigger, and every still-empty partition
        then answers the query through the empty-partition fast path (the
        reference's :351 heuristic) — a premature empty result for a
        stream that was already produced. The kafkalite fetch is
        synchronous (an empty poll means no committed data at the offset),
        so the drain closes the race fully there; transports whose poll
        can return transiently empty mid-fetch (kafka-python) keep a
        narrowed version of it.

        The drain is BOUNDED at ``max_drain_polls`` re-polls: against a
        producer that sustains the stream indefinitely, an until-empty
        drain would starve the trigger, ``check_timeouts()``, and result
        emission forever. Hitting the bound applies the trigger against
        everything ingested so far — partitions that have data defer via
        the id-barrier until their required ids arrive, so the residual
        exposure is only the reference's own empty-partition fast-path
        heuristic (FlinkSkyline.java:351) for a partition that got nothing
        in ``max_drain_polls * max_records`` drained rows.
        """
        fault_point("kafka.poll")
        self._maybe_renew_lease()
        if self._deposed:
            return 0  # a deposed primary must not ingest another frame
        with self.tracer.phase("worker/poll"):
            triggers = self._queries.poll(max_records)
            ids, values, dropped, got = self._poll_data(max_records)
        self._query_pos += len(triggers)
        total_lines = 0
        drains = 0
        while got:
            if self._wal is not None:
                # the span is logged BEFORE ingest: a crash inside the merge
                # replays it; in-memory effects of the crashed attempt are
                # discarded wholesale, so state stays exactly-once
                self._wal.append(
                    {
                        "type": "batch",
                        "lo": self._data_pos,
                        "hi": self._data_pos + got,
                        "digest": batch_digest(ids, values),
                    }
                )
            self._data_pos += got
            total_lines += got
            self.engine.dropped += dropped
            if ids.shape[0]:
                with self.tracer.phase("worker/ingest"):
                    # wire tuples carry no producer timestamps, so the poll
                    # wall time is the batch's event-time stamp — a
                    # processing-time proxy the freshness lineage documents
                    # as such (RUNBOOK §2j)
                    self.engine.process_records(
                        ids, values, event_ms=time.time() * 1000.0
                    )
            if not triggers:
                break  # no trigger pending: one poll per cycle as before
            if drains >= self.max_drain_polls:
                # bounded drain: guarantee trigger/timeout progress. With an
                # immediate (required=0) trigger pending this means the query
                # answers against a TRUNCATED ingest — say so loudly, and
                # point at the knob (--max-drain-polls) that raises the bound
                print(
                    f"skyline worker: drain bound hit after {drains + 1} polls "
                    f"({total_lines} rows) with {len(triggers)} trigger(s) "
                    "pending — the stream may exceed "
                    "max_drain_polls * max_records; queries with an id "
                    "barrier defer safely, but an immediate (required=0) "
                    "trigger will answer against the rows drained so far. "
                    "Raise --max-drain-polls for larger finite streams.",
                    file=sys.stderr,
                )
                break
            drains += 1
            with self.tracer.phase("worker/poll"):
                ids, values, dropped, got = self._poll_data(max_records)
        with self.tracer.phase("worker/query"):
            for t in triggers:
                self.engine.process_trigger(t)
            if self._serve_bridge is not None:
                # forced consistency merges from POST /query run on this
                # thread, after bus triggers — the engine stays single-owner
                self._inject_serve_queries()
            self.engine.check_timeouts()
        results = self.engine.poll_results()
        if self._serve_bridge is not None:
            # serve-plane results return to their HTTP waiters, not the bus
            results = self._serve_bridge.fulfill(results)
        for result in results:
            self.bus.produce(self.output_topic, format_result(result))
            self.results_emitted += 1
            self._report_phases()
        work = total_lines + len(triggers)
        if work and self._wal is not None:
            # the step's durability point: positions commit (and, under the
            # batch fsync policy, everything above reaches the platter)
            self._wal.append(
                {
                    "type": "commit",
                    "data_off": self._data_pos,
                    "query_off": self._query_pos,
                }
            )
            self._wal.flush()
        if work:
            self._dirty = True
        self._maybe_checkpoint()
        return work

    def _inject_serve_queries(self) -> None:
        """Run the serve-plane's queued forced merges; with
        ``jax_profile_dir`` set, wrap the injection in ``jax.profiler.trace``
        so exactly one POST /query's device work lands in a profile."""
        if self._jax_profile_dir and self._serve_bridge.pending_injections:
            try:
                import jax

                with jax.profiler.trace(self._jax_profile_dir):
                    self._serve_bridge.inject(self.engine)
                return
            except Exception as e:  # profiling is opt-in observability:
                # never let a profiler failure shed the query itself
                print(
                    f"skyline worker: jax.profiler.trace failed ({e}); "
                    "running injection unprofiled",
                    file=sys.stderr,
                )
        self._serve_bridge.inject(self.engine)

    def _report_phases(self) -> None:
        """Per-result stderr breakdown: the DELTA of each phase since the
        previous report, so each line attributes only the wall spent since
        the last answered query (worker/* rows are the loop's own
        accounting; engine rows — partition_ids/route/flush/query — nest
        inside them). Rate-limited to one line per second so per-slide
        sliding emissions don't flood stderr; /stats always serves the
        cumulative totals."""
        now = time.monotonic()
        if now - self._last_phase_report_s < 1.0:
            return
        self._last_phase_report_s = now
        totals = {
            k: v["total_ms"] for k, v in self.tracer.report().items()
        }
        delta = {
            k: round(ms - self._phase_snapshot_ms.get(k, 0.0))
            for k, ms in totals.items()
            if ms - self._phase_snapshot_ms.get(k, 0.0) >= 0.5
        }
        self._phase_snapshot_ms = totals
        if delta:
            print(f"skyline worker: phase_breakdown_ms={delta}",
                  file=sys.stderr, flush=True)

    def run_forever(
        self,
        idle_sleep_s: float = 0.01,
        stop_after_idle_s: float | None = None,
        install_signal_handlers: bool | None = None,
    ):
        """Poll loop; optionally exits after ``stop_after_idle_s`` of silence.

        With crash safety on (and by default only then), SIGTERM/SIGINT are
        handled gracefully: the current step finishes, a final checkpoint +
        WAL fsync land, the servers close, and the loop returns — a restart
        from that state replays nothing and loses nothing."""
        if install_signal_handlers is None:
            install_signal_handlers = self.resilience is not None
        if install_signal_handlers:
            import signal

            try:
                for sig in (signal.SIGTERM, signal.SIGINT):
                    signal.signal(sig, self._signal_handler)
            except ValueError:
                pass  # not the main thread (embedded runs): flag-only stop
        idle_since = None
        while True:
            if self._stop_requested:
                self.shutdown()
                return
            try:
                n = self.step()
            except Exception as e:
                from skyline_tpu.cluster import WalFencedError

                if not isinstance(e, WalFencedError):
                    raise
                # an append raced the promotion past the renew check: the
                # frame was rejected at the WAL layer (counted, loud) —
                # demote and exit without the final checkpoint
                self._demote(str(e))
                continue
            if n == 0:
                self._maybe_renew_lease()
                now = time.time()
                if idle_since is None:
                    idle_since = now
                elif stop_after_idle_s is not None and now - idle_since > stop_after_idle_s:
                    return
                # idle ticks drive the correctness canaries: with no
                # organic traffic to audit, the synthetic known-answer
                # micro-states keep every merge path under verification
                auditor = getattr(self.engine, "auditor", None)
                if auditor is not None:
                    auditor.maybe_canary()
                # idle ticks also drive the chip-health plane (RUNBOOK
                # §2p): staleness scoring plus failover of any chip that
                # quarantined since the last merge — recovery must not
                # wait for organic traffic
                health = getattr(self.engine, "health", None)
                if health is not None:
                    health.tick()
                    pset = getattr(self.engine, "pset", None)
                    if pset is not None and hasattr(pset, "maybe_failover"):
                        pset.maybe_failover()
                # idle ticks drive the dispatch tuner too: a quiet stream
                # still closes workload epochs, and the controller must
                # converge (or revert on SLO burn) without a query
                tuner = getattr(self.engine, "tuner", None)
                if tuner is not None:
                    tuner.maybe_tune()
                time.sleep(idle_sleep_s)
            else:
                idle_since = None


def main(argv=None):
    """CLI: run the worker against a Kafka broker with reference-style flags
    (the `flink run` equivalent of README_Ubuntu_Setup.md's job launch)."""
    from skyline_tpu.bridge.kafka import KafkaBus
    from skyline_tpu.utils.compile_cache import enable_compile_cache
    from skyline_tpu.utils.config import parse_job_args

    cfg = parse_job_args(argv)
    if cfg.replica_of:
        # standalone read replica: no Kafka, no engine — bootstrap from the
        # primary's WAL directory and tail it until signalled
        from skyline_tpu.serve.replica import run_replica

        return run_replica(
            cfg.replica_of,
            port=cfg.serve_port if cfg.serve_port >= 0 else 0,
            serve_config=cfg.serve_config(),
        )
    # restarted workers reuse every previously compiled executable
    # (SKYLINE_COMPILE_CACHE overrides the location)
    enable_compile_cache()
    bus = KafkaBus(cfg.bootstrap)
    worker = SkylineWorker(
        bus,
        cfg.engine_config(),
        input_topic=cfg.input_topic,
        query_topic=cfg.query_topic,
        output_topic=cfg.output_topic,
        mesh=cfg.build_mesh(),
        mesh_chips=cfg.mesh_chips,
        cluster_hosts=cfg.cluster_hosts,
        stats_port=cfg.stats_port if cfg.stats_port > 0 else None,
        window_size=cfg.window_size,
        slide=cfg.slide,
        emit_per_slide=cfg.emit_per_slide,
        max_drain_polls=cfg.max_drain_polls,
        serve_port=cfg.serve_port if cfg.serve_port >= 0 else None,
        serve_config=cfg.serve_config() if cfg.serve_port >= 0 else None,
        trace_ring=cfg.trace_ring,
        trace_out=cfg.trace_out or None,
        jax_profile_dir=cfg.jax_profile_dir or None,
        resilience=cfg.resilience_config(),
        replicas=cfg.replicas,
    )
    print(
        f"skyline worker: algo={cfg.algo} partitions={cfg.engine_config().num_partitions} "
        f"dims={cfg.dims} broker={cfg.bootstrap} mesh={cfg.mesh or 'off'}"
        f" chips={cfg.mesh_chips or 'off'}"
        f" cluster={cfg.cluster_hosts or 'off'}"
        + (f" stats=:{worker.stats_server.port}" if worker.stats_server else "")
        + (f" serve=:{worker.serve_server.port}" if worker.serve_server else "")
        + (f" checkpoints={cfg.checkpoint_dir}" if cfg.checkpoint_dir else "")
        + (
            " replicas=" + ",".join(f":{r.port}" for r in worker.replicas)
            if getattr(worker, "replicas", None)
            else ""
        ),
        file=sys.stderr,
    )
    try:
        worker.run_forever()
    finally:
        worker.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
