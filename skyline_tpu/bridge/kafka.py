"""Kafka transport: the production bridge onto the reference's topics.

Mirrors the reference's Kafka wiring exactly — bootstrap ``localhost:9092``,
data topic consumed from earliest, query topic from latest, 10 MB max request
size on the result producer (FlinkSkyline.java:84-97, 177-183;
docker-setup/docker-compose.yml:20-21) — so the reference's own Python
harness (producers, collector) works unchanged against this engine.

Backend selection: kafka-python when installed (a real JVM broker
deployment), otherwise the bundled pure-Python ``kafkalite`` client, which
speaks the same wire protocol (RecordBatch v2, Produce/Fetch/Metadata/
ListOffsets) against either a real broker or the embedded
``kafkalite.Broker``. Both paths expose the same produce/consumer surface
as ``MemoryBus``.
"""

from __future__ import annotations

DEFAULT_BOOTSTRAP = "localhost:9092"
MAX_REQUEST_SIZE = 10_485_760  # 10 MB, matching FlinkSkyline.java:179

try:  # pragma: no cover - exercised only where kafka-python is installed
    from kafka import KafkaConsumer as _KafkaConsumer
    from kafka import KafkaProducer as _KafkaProducer

    HAVE_KAFKA = True
except ImportError:
    _KafkaConsumer = None
    _KafkaProducer = None
    HAVE_KAFKA = False


class KafkaBus:
    """Same produce/consumer surface as MemoryBus, backed by a real broker
    over the Kafka wire protocol (kafka-python or bundled kafkalite)."""

    def __init__(self, bootstrap: str = DEFAULT_BOOTSTRAP):
        self.bootstrap = bootstrap
        if HAVE_KAFKA:  # pragma: no cover - not in the baked image
            self._producer = _KafkaProducer(
                bootstrap_servers=bootstrap,
                # str OR bytes: the producer CLI's native formatter emits
                # bytes lines (kafkalite's send accepts both natively)
                value_serializer=lambda s: (
                    s if isinstance(s, bytes) else s.encode("utf-8")
                ),
                max_request_size=MAX_REQUEST_SIZE,
            )
            self._lite = False
        else:
            from skyline_tpu.bridge.kafkalite import KafkaLiteProducer

            self._producer = KafkaLiteProducer(
                bootstrap, max_request_size=MAX_REQUEST_SIZE
            )
            self._lite = True

    def produce(self, topic: str, message: str) -> None:
        self._producer.send(topic, message)
        self._producer.flush()

    def produce_many(self, topic: str, messages) -> None:
        send_many = getattr(self._producer, "send_many", None)
        if send_many is not None:
            send_many(topic, messages)
        else:  # pragma: no cover - kafka-python path, not in the baked image
            for m in messages:
                self._producer.send(topic, m)
        self._producer.flush()

    def produce_blob(self, topic: str, blob: bytes, offsets) -> None:
        """Produce records from one value blob + prefix offsets (the native
        formatter's output) without per-record bytes objects where the
        backend supports it (kafkalite ``send_blob``)."""
        send_blob = getattr(self._producer, "send_blob", None)
        if send_blob is not None:
            send_blob(topic, blob, offsets)
            return
        ot = list(offsets)  # pragma: no cover - kafka-python path
        self.produce_many(
            topic, [blob[ot[i] : ot[i + 1]] for i in range(len(ot) - 1)]
        )

    def consumer(self, topic: str, from_beginning: bool = True):
        reset = "earliest" if from_beginning else "latest"
        if HAVE_KAFKA:  # pragma: no cover - not in the baked image
            c = _KafkaConsumer(
                topic,
                bootstrap_servers=self.bootstrap,
                auto_offset_reset=reset,
                value_deserializer=lambda b: b.decode("utf-8"),
            )
            return _KafkaConsumerAdapter(c)
        from skyline_tpu.bridge.kafkalite import KafkaLiteConsumer

        return KafkaLiteConsumer(topic, self.bootstrap, auto_offset_reset=reset)

    def close(self) -> None:
        self._producer.close()


class _KafkaConsumerAdapter:  # pragma: no cover - kafka-python only
    def __init__(self, consumer):
        self._consumer = consumer
        self.topic = next(iter(consumer.subscription()), None)

    def poll(self, max_records: int = 65536) -> list[str]:
        batches = self._consumer.poll(timeout_ms=100, max_records=max_records)
        out: list[str] = []
        for records in batches.values():
            out.extend(r.value for r in records)
        return out
