"""Kafka transport (gated): the production bridge onto the reference's topics.

Mirrors the reference's Kafka wiring exactly — bootstrap ``localhost:9092``,
data topic consumed from earliest, query topic from latest, 10 MB max request
size on the result producer (FlinkSkyline.java:84-97, 177-183;
docker-setup/docker-compose.yml:20-21) — so the reference's own Python
harness (producers, collector) works unchanged against this engine.

``kafka-python`` is not part of the baked image; everything here raises a
clear error at construction time if it is missing, and the rest of the
framework (MemoryBus path) never imports it.
"""

from __future__ import annotations

DEFAULT_BOOTSTRAP = "localhost:9092"
MAX_REQUEST_SIZE = 10_485_760  # 10 MB, matching FlinkSkyline.java:179

try:  # pragma: no cover - exercised only where kafka-python is installed
    from kafka import KafkaConsumer as _KafkaConsumer
    from kafka import KafkaProducer as _KafkaProducer

    HAVE_KAFKA = True
except ImportError:  # pragma: no cover
    _KafkaConsumer = None
    _KafkaProducer = None
    HAVE_KAFKA = False


def _require_kafka():
    if not HAVE_KAFKA:
        raise RuntimeError(
            "kafka-python is not installed; use skyline_tpu.bridge.memory.MemoryBus "
            "for in-process runs, or install kafka-python for a real broker"
        )


class KafkaBus:
    """Same produce/consumer surface as MemoryBus, backed by a real broker."""

    def __init__(self, bootstrap: str = DEFAULT_BOOTSTRAP):
        _require_kafka()
        self.bootstrap = bootstrap
        self._producer = _KafkaProducer(
            bootstrap_servers=bootstrap,
            value_serializer=lambda s: s.encode("utf-8"),
            max_request_size=MAX_REQUEST_SIZE,
        )

    def produce(self, topic: str, message: str) -> None:
        self._producer.send(topic, message)

    def produce_many(self, topic: str, messages) -> None:
        for m in messages:
            self._producer.send(topic, m)
        self._producer.flush()

    def consumer(self, topic: str, from_beginning: bool = True):
        _require_kafka()
        c = _KafkaConsumer(
            topic,
            bootstrap_servers=self.bootstrap,
            auto_offset_reset="earliest" if from_beginning else "latest",
            value_deserializer=lambda b: b.decode("utf-8"),
        )
        return _KafkaConsumerAdapter(c)


class _KafkaConsumerAdapter:
    def __init__(self, consumer):
        self._consumer = consumer
        self.topic = next(iter(consumer.subscription()), None)

    def poll(self, max_records: int = 65536) -> list[str]:
        batches = self._consumer.poll(timeout_ms=100, max_records=max_records)
        out: list[str] = []
        for records in batches.values():
            out.extend(r.value for r in records)
        return out
