"""Paper-figure replication — parity with python/graph_paper_figures.py.

Renders the reference's two headline figures (time-vs-dimensions,
optimality-vs-dimensions). The reference hardcodes its published numbers
(:28-42) — those are reproduced here as ``REFERENCE_*`` so the figures can
overlay reference-vs-TPU results; TPU numbers can be supplied from collector
CSVs (``--ours D:Label=file.csv``) or fall back to reference-only plots.

(The reference file's comment "Times for Dim 2, 4, 8" is wrong — the axis is
dimensions [2, 3, 4]; see SURVEY.md §6 caveat.)
"""

from __future__ import annotations

import argparse

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import pandas as pd

DIMENSIONS = [2, 3, 4]

# Published reference results, anti-correlated 1M (graph_paper_figures.py:28-42)
REFERENCE_TIME_MS = {
    "MR-Dim": [19544, 27264, 716996],
    "MR-Grid": [17593, 26601, 691882],
    "MR-Angle": [17282, 27015, 766937],
}
REFERENCE_OPTIMALITY = {
    "MR-Dim": [0.7379, 0.6742, 0.25],
    "MR-Grid": [0.5415, 0.5906, 0.25],
    "MR-Angle": [0.7453, 0.6652, 0.25],
}


def plot_paper_figures(
    ours_time: dict[int, dict[str, float]] | None = None,
    ours_opt: dict[int, dict[str, float]] | None = None,
    prefix: str = "",
):
    """Write figure_5_replication.png (time) and figure_7_replication.png
    (optimality); returns the two paths."""
    t_path = f"{prefix}figure_5_replication.png"
    plt.figure(figsize=(10, 5))
    for algo, times in REFERENCE_TIME_MS.items():
        plt.plot(DIMENSIONS, times, marker="o", label=f"{algo} (reference)")
    if ours_time:
        dims = sorted(ours_time)
        for algo in sorted({a for m in ours_time.values() for a in m}):
            ys = [ours_time[d].get(algo) for d in dims]
            plt.plot(dims, ys, marker="^", linestyle="-.", label=f"{algo} (tpu)")
    plt.title("Processing Time vs Dimensionality (Cardinality 1 Million)")
    plt.xlabel("Dimensions")
    plt.ylabel("Processing Time (ms)")
    plt.yscale("log")
    plt.legend()
    plt.grid(True)
    plt.savefig(t_path, dpi=120)
    plt.close()

    o_path = f"{prefix}figure_7_replication.png"
    plt.figure(figsize=(10, 5))
    for algo, opts in REFERENCE_OPTIMALITY.items():
        plt.plot(DIMENSIONS, opts, marker="s", linestyle="--", label=f"{algo} (reference)")
    if ours_opt:
        dims = sorted(ours_opt)
        for algo in sorted({a for m in ours_opt.values() for a in m}):
            ys = [ours_opt[d].get(algo) for d in dims]
            plt.plot(dims, ys, marker="^", linestyle="-.", label=f"{algo} (tpu)")
    plt.title("Local Skyline Optimality vs Dimensionality (Cardinality 1 Million)")
    plt.xlabel("Dimensions")
    plt.ylabel("Optimality Ratio")
    plt.legend()
    plt.grid(True)
    plt.savefig(o_path, dpi=120)
    plt.close()
    return t_path, o_path


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ours", nargs="*", default=[],
                    help="D:Label=file.csv — last row's TotalTime/Optimality per dim")
    ap.add_argument("--prefix", default="")
    a = ap.parse_args(argv)
    ours_time: dict[int, dict[str, float]] = {}
    ours_opt: dict[int, dict[str, float]] = {}
    for item in a.ours:
        dpart, _, rest = item.partition(":")
        label, _, path = rest.partition("=")
        if not (dpart.isdigit() and label and path):
            ap.error(f"malformed --ours {item!r}; want 'D:Label=file.csv'")
        df = pd.read_csv(path)
        last = df.iloc[-1]
        ours_time.setdefault(int(dpart), {})[label] = float(last["TotalTime(ms)"])
        ours_opt.setdefault(int(dpart), {})[label] = float(last["Optimality"])
    for p in plot_paper_figures(ours_time or None, ours_opt or None, a.prefix):
        print(p)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
