"""Figure tools — parity with the reference's four graph_*.py scripts.

All read the collector CSV schema (skyline_tpu.metrics.collector.CSV_HEADERS)
and write PNGs; matplotlib's Agg backend is forced so they run headless.
"""
