"""2D skyline visualizer — parity with python/graph_skyline_points_2d.py.

Reads one collector-CSV row, parses the ``SkylinePoints`` JSON, and renders a
scatter plus a post-step Pareto line with axes locked to the domain (the
reference locks 0-10000, :23-24, 83-84) so frontier quality is judged
against the origin, not the data range.
"""

from __future__ import annotations

import argparse
import json

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np
import pandas as pd


def plot_skyline(csv_file: str, row_index: int = -1, d_min: float = 0.0,
                 d_max: float = 10000.0, out: str | None = None) -> str:
    df = pd.read_csv(csv_file)
    row = df.iloc[row_index]
    pts = np.asarray(json.loads(row["SkylinePoints"]), dtype=float)
    if pts.size == 0:
        raise ValueError(
            "row has no SkylinePoints — run the engine with "
            "emit_skyline_points=True (the reference keeps the equivalent "
            "block commented out, FlinkSkyline.java:612-623)"
        )
    if pts.shape[1] != 2:
        raise ValueError(f"2D plot needs 2-dim points, got d={pts.shape[1]}")
    pts = pts[np.argsort(pts[:, 0], kind="stable")]

    fig, ax = plt.subplots(figsize=(8, 8))
    ax.scatter(pts[:, 0], pts[:, 1], c="red", s=12, zorder=3, label="skyline points")
    ax.step(pts[:, 0], pts[:, 1], where="post", linestyle=":", color="blue",
            zorder=2, label="dominance frontier")
    ax.set_xlim(d_min, d_max)
    ax.set_ylim(d_min, d_max)
    ax.set_xlabel("dimension 0")
    ax.set_ylabel("dimension 1")
    ax.set_title(
        f"Skyline (query {row.get('QueryID', '?')}, {len(pts)} points)"
    )
    ax.legend()
    ax.grid(alpha=0.3)
    out = out or f"skyline_viz_{row_index}.png"
    fig.savefig(out, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("csv_file")
    ap.add_argument("row_index", nargs="?", type=int, default=-1)
    ap.add_argument("--d-min", type=float, default=0.0)
    ap.add_argument("--d-max", type=float, default=10000.0)
    ap.add_argument("--out")
    a = ap.parse_args(argv)
    print(plot_skyline(a.csv_file, a.row_index, a.d_min, a.d_max, a.out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
