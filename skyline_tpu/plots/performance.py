"""Performance dashboard — parity with python/graph_ingestion_parallelism.py.

2x2 figure over one or more collector CSVs (multi-run comparison via
``Label=file.csv`` args, :122-134): ingestion time vs volume, total time vs
volume, optimality evolution, and a local-vs-global stacked bar for each
run's final batch (the steady-state breakdown, :80-83).
"""

from __future__ import annotations

import argparse
import sys

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import pandas as pd


def plot_performance(file_map: dict[str, str], out: str = "performance_analysis.png") -> str:
    fig, ((ax_ingest, ax_total), (ax_opt, ax_break)) = plt.subplots(
        2, 2, figsize=(14, 10)
    )
    fig.suptitle("Skyline Streaming Performance", fontsize=14)

    first = True
    for label, path in file_map.items():
        df = pd.read_csv(path).sort_values(by="Records")
        x = df["Records"] / 1_000_000
        ax_ingest.plot(x, df["IngestTime(ms)"], marker=".", label=label)
        ax_total.plot(x, df["TotalTime(ms)"] / 1000, marker="o", label=label)
        ax_opt.plot(x, df["Optimality"], marker="x", linestyle="--", label=label)
        last = df.iloc[-1]
        ax_break.bar(label, last["LocalTime(ms)"],
                     label="Local CPU" if first else "", color="skyblue")
        ax_break.bar(label, last["GlobalTime(ms)"], bottom=last["LocalTime(ms)"],
                     label="Global Merge" if first else "", color="orange")
        first = False

    ax_ingest.set_title("Ingestion Time vs Data Volume")
    ax_ingest.set_xlabel("Records (Millions)")
    ax_ingest.set_ylabel("Time (ms)")
    ax_total.set_title("Total Processing Time (Scalability)")
    ax_total.set_xlabel("Records (Millions)")
    ax_total.set_ylabel("Time (Seconds)")
    ax_opt.set_title("Local Optimality Ratio")
    ax_opt.set_xlabel("Records (Millions)")
    ax_opt.set_ylabel("Optimality (0.0 - 1.0)")
    ax_opt.set_ylim(0, 1.1)
    ax_break.set_title("Time Breakdown (Final Batch)")
    ax_break.set_ylabel("Time (ms)")
    for ax in (ax_ingest, ax_total, ax_opt):
        ax.legend()
        ax.grid(True, alpha=0.3)
    ax_break.legend()

    fig.tight_layout(rect=[0, 0.03, 1, 0.95])
    fig.savefig(out, dpi=120)
    plt.close(fig)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("runs", nargs="+", help="Label=file.csv ...")
    ap.add_argument("--out", default="performance_analysis.png")
    a = ap.parse_args(argv)
    files = {}
    for arg in a.runs:
        if "=" not in arg:
            print(f"skipping malformed arg {arg!r} (want Label=file.csv)", file=sys.stderr)
            continue
        label, path = arg.split("=", 1)
        files[label] = path
    if not files:
        ap.error("no valid Label=file.csv args")
    print(plot_performance(files, a.out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
