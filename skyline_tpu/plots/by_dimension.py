"""Per-dimension comparison — parity with python/graph_performance_by_dimension.py.

Side-by-side TotalTime-vs-records panels, one per dimensionality, each
overlaying the three partitioning strategies. The reference hardcodes its
CSV filename maps (:25-43); here the same structure is given on the command
line: ``--dim 2 MR-Dim=a.csv MR-Grid=b.csv ... --dim 3 ...``.
"""

from __future__ import annotations

import argparse

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import pandas as pd


def plot_by_dimension(dim_maps: dict[int, dict[str, str]],
                      out: str = "performance_by_dimension.png") -> str:
    dims = sorted(dim_maps)
    fig, axes = plt.subplots(1, len(dims), figsize=(6 * len(dims), 5), squeeze=False)
    for ax, d in zip(axes[0], dims):
        for label, path in dim_maps[d].items():
            df = pd.read_csv(path).sort_values(by="Records")
            ax.plot(df["Records"] / 1_000_000, df["TotalTime(ms)"] / 1000,
                    marker="o", label=label)
        ax.set_title(f"{d}D")
        ax.set_xlabel("Records (Millions)")
        ax.set_ylabel("Total Time (s)")
        ax.legend()
        ax.grid(True, alpha=0.3)
    fig.suptitle("Total Processing Time by Dimensionality")
    fig.tight_layout(rect=[0, 0.03, 1, 0.95])
    fig.savefig(out, dpi=120)
    plt.close(fig)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("spec", nargs="+",
                    help="alternating: --dim style groups as 'D:Label=file.csv'")
    ap.add_argument("--out", default="performance_by_dimension.png")
    a = ap.parse_args(argv)
    dim_maps: dict[int, dict[str, str]] = {}
    for item in a.spec:
        dpart, _, rest = item.partition(":")
        label, _, path = rest.partition("=")
        if not (dpart.isdigit() and label and path):
            ap.error(f"malformed spec {item!r}; want 'D:Label=file.csv'")
        dim_maps.setdefault(int(dpart), {})[label] = path
    print(plot_by_dimension(dim_maps, a.out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
