// Fast data-plane CSV parser: "id,v1,...,vd" lines -> (ids, values) arrays.
//
// The TPU-side ingest hot path. The reference's wire format is CSV strings on
// Kafka (unified_producer.py:174, parsed tuple-at-a-time by
// ServiceTuple.fromString, ServiceTuple.java:89-104); at stream rates the
// reference attributes ~80% of total processing time to ingest (pdf §5.5).
// This parser processes a whole poll batch as one contiguous byte buffer with
// no allocation, writing straight into caller-provided numpy buffers.
//
// Semantics parity with skyline_tpu.bridge.wire.parse_tuple_lines (which is
// also the fallback when this library isn't built): a line is dropped — not
// an error — when it has the wrong field count, a non-integer id, a
// non-numeric value, or any non-finite value (NaN/inf must never enter
// windows; +inf is reserved for padding).
//
// Build: see skyline_tpu/native/__init__.py (g++ -O3 -shared -fPIC).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// Parse an integer id; returns false on malformed or int64 overflow (an
// out-of-range id is a dropped line, matching the Python fallback).
bool parse_id(const char*& p, const char* end, int64_t& out) {
    bool neg = false;
    if (p < end && (*p == '-' || *p == '+')) {
        neg = (*p == '-');
        ++p;
    }
    if (p >= end || *p < '0' || *p > '9') return false;
    uint64_t v = 0;
    while (p < end && *p >= '0' && *p <= '9') {
        if (v > (UINT64_MAX - 9) / 10) return false;
        v = v * 10 + static_cast<uint64_t>(*p - '0');
        ++p;
    }
    const uint64_t limit =
        neg ? (static_cast<uint64_t>(INT64_MAX) + 1) : static_cast<uint64_t>(INT64_MAX);
    if (v > limit) return false;
    out = neg ? -static_cast<int64_t>(v - 1) - 1 : static_cast<int64_t>(v);
    return true;
}

// Fast float parse for the common integer-valued case (the generators stream
// integers); falls back to strtof for general decimals/exponents.
bool parse_value(const char*& p, const char* end, float& out) {
    const char* start = p;
    bool neg = false;
    if (p < end && (*p == '-' || *p == '+')) {
        neg = (*p == '-');
        ++p;
    }
    int64_t ip = 0;
    int digits = 0;
    while (p < end && *p >= '0' && *p <= '9' && digits < 18) {
        ip = ip * 10 + (*p - '0');
        ++p;
        ++digits;
    }
    if (digits > 0 && (p == end || *p == ',' || *p == '\n' || *p == '\r')) {
        out = static_cast<float>(neg ? -ip : ip);
        return true;
    }
    // general path (decimals, exponents, or >18 digits)
    char tmp[64];
    size_t n = 0;
    const char* q = start;
    while (q < end && *q != ',' && *q != '\n' && *q != '\r' && n < sizeof(tmp) - 1)
        tmp[n++] = *q++;
    if (n == 0) return false;
    tmp[n] = '\0';
    char* parsed_end = nullptr;
    float v = strtof(tmp, &parsed_end);
    if (parsed_end != tmp + n) return false;
    if (!std::isfinite(v)) return false;
    p = q;
    out = v;
    return true;
}

}  // namespace

extern "C" {

// Returns the number of parsed rows (<= max_rows); *dropped counts malformed
// lines. Stops early (remaining lines dropped-silently excluded from both
// counts) only if max_rows is hit — callers size max_rows to the line count.
int64_t sky_parse_tuples(const char* buf, int64_t len, int32_t dims,
                         int64_t max_rows, int64_t* ids, float* values,
                         int64_t* dropped) {
    const char* p = buf;
    const char* end = buf + len;
    int64_t rows = 0;
    int64_t bad = 0;
    while (p < end && rows < max_rows) {
        const char* line_end = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (line_end == nullptr) line_end = end;
        const char* q = p;
        const char* qe = line_end;
        if (qe > q && qe[-1] == '\r') --qe;  // tolerate CRLF

        bool ok = (qe > q);
        int64_t id = 0;
        if (ok) ok = parse_id(q, qe, id);
        float* row = values + rows * dims;
        for (int32_t k = 0; ok && k < dims; ++k) {
            ok = (q < qe && *q == ',');
            if (ok) ++q;
            if (ok) ok = parse_value(q, qe, row[k]);
        }
        if (ok && q != qe) ok = false;  // trailing junk / too many fields
        if (ok) {
            ids[rows] = id;
            ++rows;
        } else if (line_end > p) {
            ++bad;
        }
        p = line_end + 1;
    }
    *dropped = bad;
    return rows;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Kafka RecordBatch v2 produce-plane helpers (see bridge/kafkalite/protocol.py
// encode_record_batch): CRC32C over the post-crc batch region and the
// per-record frame loop for value-only records. Both byte-identical to the
// Python fallbacks — the golden-bytes tests pin the format.
// ---------------------------------------------------------------------------

namespace {

struct Crc32cTables {
    uint32_t t[8][256];
    Crc32cTables() {
        for (uint32_t i = 0; i < 256; ++i) {
            uint32_t c = i;
            for (int k = 0; k < 8; ++k)
                c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
            t[0][i] = c;
        }
        for (int k = 1; k < 8; ++k)
            for (uint32_t i = 0; i < 256; ++i)
                t[k][i] = t[0][t[k - 1][i] & 0xFF] ^ (t[k - 1][i] >> 8);
    }
};

uint32_t crc32c_sw(uint32_t crc, const uint8_t* p, int64_t n) {
    // C++11 guarantees thread-safe one-time construction of local statics
    // (ctypes releases the GIL, so concurrent first calls are real)
    static const Crc32cTables tables;
    const auto& crc32c_table = tables.t;
    while (n >= 8) {
        crc ^= static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
               (static_cast<uint32_t>(p[2]) << 16) |
               (static_cast<uint32_t>(p[3]) << 24);
        crc = crc32c_table[7][crc & 0xFF] ^ crc32c_table[6][(crc >> 8) & 0xFF] ^
              crc32c_table[5][(crc >> 16) & 0xFF] ^
              crc32c_table[4][(crc >> 24) & 0xFF] ^ crc32c_table[3][p[4]] ^
              crc32c_table[2][p[5]] ^ crc32c_table[1][p[6]] ^
              crc32c_table[0][p[7]];
        p += 8;
        n -= 8;
    }
    while (n-- > 0) crc = crc32c_table[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    return crc;
}

// LEB128 of an already-zigzagged value; returns bytes written.
inline int put_uvarint(uint8_t* out, uint64_t z) {
    int i = 0;
    while (z >= 0x80) {
        out[i++] = static_cast<uint8_t>(z) | 0x80;
        z >>= 7;
    }
    out[i++] = static_cast<uint8_t>(z);
    return i;
}

inline int uvarint_len(uint64_t z) {
    int i = 1;
    while (z >= 0x80) {
        z >>= 7;
        ++i;
    }
    return i;
}

}  // namespace

extern "C" uint32_t sky_crc32c(const uint8_t* data, int64_t n) {
#if defined(__SSE4_2__)
    uint32_t crc = 0xFFFFFFFFu;
    const uint8_t* p = data;
    while (n >= 8) {
        uint64_t v;
        std::memcpy(&v, p, 8);
        crc = static_cast<uint32_t>(__builtin_ia32_crc32di(crc, v));
        p += 8;
        n -= 8;
    }
    while (n-- > 0) crc = __builtin_ia32_crc32qi(crc, *p++);
    return crc ^ 0xFFFFFFFFu;
#else
    return crc32c_sw(0xFFFFFFFFu, data, n) ^ 0xFFFFFFFFu;
#endif
}

// Encode n value-only records (key=null, timestampDelta=0, offsetDelta=i,
// no headers) into `out`. `values` is the concatenation of the value byte
// strings; `offsets` has n+1 prefix offsets. Returns bytes written, or -1
// if out_cap would be exceeded (caller sizes out generously).
extern "C" int64_t sky_encode_records(const uint8_t* values,
                                      const int64_t* offsets, int64_t n,
                                      uint8_t* out, int64_t out_cap) {
    int64_t w = 0;
    for (int64_t i = 0; i < n; ++i) {
        const int64_t vlen = offsets[i + 1] - offsets[i];
        // body: attributes(1) + tsDelta(1) + offsetDelta + keyLen(1=null)
        //       + valueLen + value + headerCount(1)
        const uint64_t off_z = static_cast<uint64_t>(i) << 1;
        const uint64_t vlen_z = static_cast<uint64_t>(vlen) << 1;
        const int64_t body = 3 + uvarint_len(off_z) + uvarint_len(vlen_z) +
                             vlen + 1;
        const uint64_t body_z = static_cast<uint64_t>(body) << 1;
        if (w + uvarint_len(body_z) + body > out_cap) return -1;
        w += put_uvarint(out + w, body_z);
        out[w++] = 0x00;  // attributes
        out[w++] = 0x00;  // timestampDelta = 0
        w += put_uvarint(out + w, off_z);
        out[w++] = 0x01;  // key = null (zigzag(-1))
        w += put_uvarint(out + w, vlen_z);
        std::memcpy(out + w, values + offsets[i], static_cast<size_t>(vlen));
        w += vlen;
        out[w++] = 0x00;  // headers count
    }
    return w;
}

namespace {

// Reverse-digit int64 -> decimal ascii; returns the advanced write pointer.
inline char* write_i64(char* w, int64_t v) {
    if (v < 0) {
        *w++ = '-';
        // negate via unsigned to survive INT64_MIN
        uint64_t u = static_cast<uint64_t>(-(v + 1)) + 1;
        char tmp[20];
        int k = 0;
        do { tmp[k++] = static_cast<char>('0' + u % 10); u /= 10; } while (u);
        while (k) *w++ = tmp[--k];
        return w;
    }
    uint64_t u = static_cast<uint64_t>(v);
    char tmp[20];
    int k = 0;
    do { tmp[k++] = static_cast<char>('0' + u % 10); u /= 10; } while (u);
    while (k) *w++ = tmp[--k];
    return w;
}

}  // namespace

namespace {

// Bounds-checked zigzag varint read; false on truncation/overflow.
inline bool read_zigzag(const uint8_t*& p, const uint8_t* end, int64_t& out) {
    uint64_t z = 0;
    int shift = 0;
    while (p < end) {
        const uint8_t b = *p++;
        z |= static_cast<uint64_t>(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            out = static_cast<int64_t>((z >> 1) ^ (~(z & 1) + 1));
            return true;
        }
        shift += 7;
        if (shift > 63) return false;
    }
    return false;
}

inline uint32_t be32(const uint8_t* p) {
    return (static_cast<uint32_t>(p[0]) << 24) |
           (static_cast<uint32_t>(p[1]) << 16) |
           (static_cast<uint32_t>(p[2]) << 8) | p[3];
}

inline int64_t be64(const uint8_t* p) {
    return static_cast<int64_t>((static_cast<uint64_t>(be32(p)) << 32) |
                                be32(p + 4));
}

}  // namespace

extern "C" uint32_t sky_crc32c(const uint8_t* data, int64_t n);

// Consume-plane twin of sky_encode_records + sky_parse_tuples: walk a
// concatenation of RecordBatch v2 blobs (one fetch response's record set,
// bridge/kafkalite/protocol.py decode_record_batches) and CSV-parse each
// record's value straight into the caller's (ids, values) numpy buffers —
// zero per-record Python objects on the whole broker->engine path. Mirrors
// the Python decode exactly: tolerates a truncated trailing batch, skips
// records below `min_offset` (a fetch can return a batch starting before
// the requested offset), keys and headers are skipped via the record
// length, `*next_offset` tracks last-seen-abs+1 (the fetch position
// advance), malformed CSV values count into `*dropped`.
//
// Returns rows parsed (stops at max_rows; remaining records stay
// re-fetchable at *next_offset... callers size max_rows to len/9, the
// framing minimum, so a single pass always completes), or a negative
// error: -2 unsupported magic, -3 CRC32C mismatch (verify_crc=1),
// -4 malformed record framing inside a complete batch. All three raise in
// the Python wrapper, matching decode_record_batches' behavior.
extern "C" int64_t sky_parse_recordbatches(
    const uint8_t* buf, int64_t len, int64_t min_offset, int32_t dims,
    int32_t verify_crc, int64_t max_rows, int64_t* ids, float* values,
    int64_t* dropped, int64_t* next_offset) {
    const uint8_t* p = buf;
    const uint8_t* end = buf + len;
    int64_t rows = 0;
    int64_t bad = 0;
    while (end - p >= 12) {
        const int64_t base = be64(p);
        const int64_t blen = be32(p + 8);
        if (end - p - 12 < blen) break;  // truncated tail
        const uint8_t* batch = p + 12;
        p += 12 + blen;
        if (blen < 49) return -4;  // shorter than a v2 batch header
        if (batch[4] != 2) return -2;
        if (verify_crc &&
            sky_crc32c(batch + 9, blen - 9) != be32(batch + 5))
            return -3;
        const int64_t n = static_cast<int64_t>(be32(batch + 45));
        const uint8_t* q = batch + 49;
        const uint8_t* qe = batch + blen;
        for (int64_t i = 0; i < n; ++i) {
            int64_t rec_len, off_delta, klen, vlen, tmp;
            if (!read_zigzag(q, qe, rec_len)) return -4;
            // validate BEFORE forming rec_end: q + rec_len with a negative
            // or oversized rec_len from a corrupt varint is out-of-range
            // pointer arithmetic (UB) even if never dereferenced
            if (rec_len <= 0 || rec_len > qe - q) return -4;
            const uint8_t* rec_end = q + rec_len;
            ++q;  // attributes
            if (!read_zigzag(q, rec_end, tmp)) return -4;  // timestampDelta
            if (!read_zigzag(q, rec_end, off_delta)) return -4;
            if (!read_zigzag(q, rec_end, klen)) return -4;
            if (klen > 0) {
                if (rec_end - q < klen) return -4;
                q += klen;  // key skipped (data-plane records are value-only)
            }
            if (!read_zigzag(q, rec_end, vlen)) return -4;
            if (vlen > 0 && rec_end - q < vlen) return -4;
            const int64_t abs = base + off_delta;
            *next_offset = abs + 1;
            if (abs >= min_offset) {
                if (rows >= max_rows) {
                    *next_offset = abs;  // this record not consumed
                    *dropped = bad;
                    return rows;
                }
                bool ok = vlen > 0;
                if (ok) {
                    const char* v = reinterpret_cast<const char*>(q);
                    const char* ve = v + vlen;
                    int64_t id = 0;
                    ok = parse_id(v, ve, id);
                    float* row = values + rows * dims;
                    for (int32_t k = 0; ok && k < dims; ++k) {
                        ok = (v < ve && *v == ',');
                        if (ok) ++v;
                        if (ok) ok = parse_value(v, ve, row[k]);
                    }
                    if (ok && v != ve) ok = false;
                    if (ok) ids[rows++] = id;
                }
                if (!ok) ++bad;
            }
            q = rec_end;  // headers (if any) skipped via the record length
        }
    }
    *dropped = bad;
    return rows;
}

// Format n data-plane lines "id,v1,...,vd" (no separators between records —
// `offsets` carries the n+1 prefix offsets, so record i is
// out[offsets[i]:offsets[i+1]]). The produce-plane twin of sky_parse_tuples:
// the reference emits integer-valued tuples (unified_producer.py:174) and
// the Python producer casts to int64 before formatting, so values arrive
// here already as int64. Returns bytes written, or -1 if out_cap would be
// exceeded (callers size out at 21 bytes per field).
extern "C" int64_t sky_format_tuples(const int64_t* ids,
                                     const int64_t* values, int64_t n,
                                     int32_t dims, char* out, int64_t out_cap,
                                     int64_t* offsets) {
    char* w = out;
    const char* end = out + out_cap;
    const int64_t worst = (static_cast<int64_t>(dims) + 1) * 21;
    for (int64_t i = 0; i < n; ++i) {
        offsets[i] = w - out;
        if (end - w < worst) return -1;
        w = write_i64(w, ids[i]);
        const int64_t* row = values + i * dims;
        for (int32_t k = 0; k < dims; ++k) {
            *w++ = ',';
            w = write_i64(w, row[k]);
        }
    }
    offsets[n] = w - out;
    return w - out;
}

// ---------------------------------------------------------------------------
// Wire-body row serializer (serve/bodystore.py): the JSON points array and
// the format=csv line block the serving plane preserializes at publish time.
// Byte parity contract: mode 0 must equal json.dumps(points.tolist()) and
// mode 1 must equal "\n".join(wire.format_tuple_line(i, row)) — both reduce
// to CPython's float.__repr__, the shortest decimal string that round-trips
// to the double (each float32 widened to double first, exactly like
// tolist()/float()). glibc's printf is correctly rounded at any precision,
// so the minimal round-tripping "%.*e" precision (found by binary search —
// round-tripping is monotone in digit count) yields the same digit string
// as CPython's dtoa; only the presentation (fixed vs scientific, ".0"
// suffix, two-digit exponents) differs, and that is reformatted below under
// CPython's rules. A bits-keyed memo table makes steady-state publishes
// cheap: skyline rows mostly survive each merge, so the same float32 values
// recur version after version.

#include <cstdio>
#include <mutex>

namespace {

std::mutex g_repr_mutex;  // ctypes drops the GIL; the memo table needs one

struct ReprEnt {
    uint32_t bits;
    uint8_t len;  // 0 = empty slot (a real repr is never empty)
    char s[27];   // max: '-' + 17 digits + punctuation/exponent <= 25
};
ReprEnt g_repr_cache[1 << 16];

bool roundtrips(double v, int prec, char* buf) {
    snprintf(buf, 40, "%.*e", prec - 1, v);
    return strtod(buf, nullptr) == v;
}

// Positive finite v -> CPython repr; returns bytes written (no NUL).
int repr_positive(double v, char* out) {
    char buf[48];
    int lo = 1, hi = 17;
    while (lo < hi) {  // minimal digit count whose conversion round-trips
        const int mid = (lo + hi) / 2;
        if (roundtrips(v, mid, buf)) hi = mid; else lo = mid + 1;
    }
    snprintf(buf, sizeof buf, "%.*e", lo - 1, v);
    char digits[20];
    int k = 0;
    const char* p = buf;
    digits[k++] = *p++;
    if (*p == '.') {
        ++p;
        while (*p != 'e') digits[k++] = *p++;
    }
    while (*p != 'e') ++p;
    const int e10 = atoi(p + 1);
    // CPython float_repr: fixed notation for -4 <= e10 < 16, else
    // scientific with a sign and a >=2-digit exponent
    int n = 0;
    if (-4 <= e10 && e10 < 16) {
        if (e10 >= k - 1) {
            for (int i = 0; i < k; ++i) out[n++] = digits[i];
            for (int i = 0; i < e10 - (k - 1); ++i) out[n++] = '0';
            out[n++] = '.';
            out[n++] = '0';
        } else if (e10 >= 0) {
            for (int i = 0; i <= e10; ++i) out[n++] = digits[i];
            out[n++] = '.';
            for (int i = e10 + 1; i < k; ++i) out[n++] = digits[i];
        } else {
            out[n++] = '0';
            out[n++] = '.';
            for (int i = 0; i < -e10 - 1; ++i) out[n++] = '0';
            for (int i = 0; i < k; ++i) out[n++] = digits[i];
        }
    } else {
        out[n++] = digits[0];
        if (k > 1) {
            out[n++] = '.';
            for (int i = 1; i < k; ++i) out[n++] = digits[i];
        }
        out[n++] = 'e';
        int ae = e10;
        if (e10 >= 0) {
            out[n++] = '+';
        } else {
            out[n++] = '-';
            ae = -e10;
        }
        if (ae >= 100) {
            out[n++] = static_cast<char>('0' + ae / 100);
            ae %= 100;
        }
        out[n++] = static_cast<char>('0' + ae / 10);
        out[n++] = static_cast<char>('0' + ae % 10);
    }
    return n;
}

// One float32 -> its wire text. JSON spells non-finites the json.dumps way
// (NaN/Infinity); CSV spells them the str(float()) way (nan/inf).
int fmt_value(float f, char* w, bool json) {
    const double v = static_cast<double>(f);
    if (std::isnan(v)) {
        const char* s = json ? "NaN" : "nan";
        const int n = json ? 3 : 3;
        memcpy(w, s, n);
        return n;
    }
    if (std::isinf(v)) {
        const char* s = json ? (std::signbit(v) ? "-Infinity" : "Infinity")
                             : (std::signbit(v) ? "-inf" : "inf");
        const int n = static_cast<int>(strlen(s));
        memcpy(w, s, n);
        return n;
    }
    uint32_t bits;
    memcpy(&bits, &f, 4);
    ReprEnt& e = g_repr_cache[(bits * 2654435761u) >> 16];
    if (e.len && e.bits == bits) {
        memcpy(w, e.s, e.len);
        return e.len;
    }
    char* p = w;
    if (std::signbit(v)) *p++ = '-';
    if (f == 0.0f) {
        p[0] = '0';
        p[1] = '.';
        p[2] = '0';
        p += 3;
    } else {
        p += repr_positive(std::signbit(v) ? -v : v, p);
    }
    const int n = static_cast<int>(p - w);
    e.bits = bits;
    e.len = static_cast<uint8_t>(n);
    memcpy(e.s, w, n);
    return n;
}

}  // namespace

// Serialize a (k, d) float32 row block into one wire body. mode 0: the JSON
// points array `[[a, b], [c, d]]` with json.dumps' default ", " separators;
// mode 1: format=csv lines `i,v1,...,vd` joined by '\n' (ids are the row
// enumeration, matching the serve handler). Returns bytes written, or -1 if
// out_cap would be exceeded (callers size at ~30 bytes/field and fall back
// to Python formatting on -1).
extern "C" int64_t sky_format_rows(const float* vals, int64_t k, int32_t d,
                                   int32_t mode, char* out, int64_t out_cap) {
    std::lock_guard<std::mutex> guard(g_repr_mutex);
    char* w = out;
    const char* end = out + out_cap;
    if (mode == 0) {
        if (end - w < 2) return -1;
        *w++ = '[';
        for (int64_t i = 0; i < k; ++i) {
            if (end - w < 4) return -1;
            if (i) {
                *w++ = ',';
                *w++ = ' ';
            }
            *w++ = '[';
            const float* row = vals + i * d;
            for (int32_t j = 0; j < d; ++j) {
                if (end - w < 32) return -1;
                if (j) {
                    *w++ = ',';
                    *w++ = ' ';
                }
                w += fmt_value(row[j], w, true);
            }
            if (end - w < 2) return -1;
            *w++ = ']';
        }
        *w++ = ']';
    } else {
        for (int64_t i = 0; i < k; ++i) {
            if (end - w < 24) return -1;
            if (i) *w++ = '\n';
            w = write_i64(w, i);
            const float* row = vals + i * d;
            for (int32_t j = 0; j < d; ++j) {
                if (end - w < 32) return -1;
                *w++ = ',';
                w += fmt_value(row[j], w, false);
            }
        }
    }
    return w - out;
}
