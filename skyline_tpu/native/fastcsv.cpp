// Fast data-plane CSV parser: "id,v1,...,vd" lines -> (ids, values) arrays.
//
// The TPU-side ingest hot path. The reference's wire format is CSV strings on
// Kafka (unified_producer.py:174, parsed tuple-at-a-time by
// ServiceTuple.fromString, ServiceTuple.java:89-104); at stream rates the
// reference attributes ~80% of total processing time to ingest (pdf §5.5).
// This parser processes a whole poll batch as one contiguous byte buffer with
// no allocation, writing straight into caller-provided numpy buffers.
//
// Semantics parity with skyline_tpu.bridge.wire.parse_tuple_lines (which is
// also the fallback when this library isn't built): a line is dropped — not
// an error — when it has the wrong field count, a non-integer id, a
// non-numeric value, or any non-finite value (NaN/inf must never enter
// windows; +inf is reserved for padding).
//
// Build: see skyline_tpu/native/__init__.py (g++ -O3 -shared -fPIC).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// Parse an integer id; returns false on malformed or int64 overflow (an
// out-of-range id is a dropped line, matching the Python fallback).
bool parse_id(const char*& p, const char* end, int64_t& out) {
    bool neg = false;
    if (p < end && (*p == '-' || *p == '+')) {
        neg = (*p == '-');
        ++p;
    }
    if (p >= end || *p < '0' || *p > '9') return false;
    uint64_t v = 0;
    while (p < end && *p >= '0' && *p <= '9') {
        if (v > (UINT64_MAX - 9) / 10) return false;
        v = v * 10 + static_cast<uint64_t>(*p - '0');
        ++p;
    }
    const uint64_t limit =
        neg ? (static_cast<uint64_t>(INT64_MAX) + 1) : static_cast<uint64_t>(INT64_MAX);
    if (v > limit) return false;
    out = neg ? -static_cast<int64_t>(v - 1) - 1 : static_cast<int64_t>(v);
    return true;
}

// Fast float parse for the common integer-valued case (the generators stream
// integers); falls back to strtof for general decimals/exponents.
bool parse_value(const char*& p, const char* end, float& out) {
    const char* start = p;
    bool neg = false;
    if (p < end && (*p == '-' || *p == '+')) {
        neg = (*p == '-');
        ++p;
    }
    int64_t ip = 0;
    int digits = 0;
    while (p < end && *p >= '0' && *p <= '9' && digits < 18) {
        ip = ip * 10 + (*p - '0');
        ++p;
        ++digits;
    }
    if (digits > 0 && (p == end || *p == ',' || *p == '\n' || *p == '\r')) {
        out = static_cast<float>(neg ? -ip : ip);
        return true;
    }
    // general path (decimals, exponents, or >18 digits)
    char tmp[64];
    size_t n = 0;
    const char* q = start;
    while (q < end && *q != ',' && *q != '\n' && *q != '\r' && n < sizeof(tmp) - 1)
        tmp[n++] = *q++;
    if (n == 0) return false;
    tmp[n] = '\0';
    char* parsed_end = nullptr;
    float v = strtof(tmp, &parsed_end);
    if (parsed_end != tmp + n) return false;
    if (!std::isfinite(v)) return false;
    p = q;
    out = v;
    return true;
}

}  // namespace

extern "C" {

// Returns the number of parsed rows (<= max_rows); *dropped counts malformed
// lines. Stops early (remaining lines dropped-silently excluded from both
// counts) only if max_rows is hit — callers size max_rows to the line count.
int64_t sky_parse_tuples(const char* buf, int64_t len, int32_t dims,
                         int64_t max_rows, int64_t* ids, float* values,
                         int64_t* dropped) {
    const char* p = buf;
    const char* end = buf + len;
    int64_t rows = 0;
    int64_t bad = 0;
    while (p < end && rows < max_rows) {
        const char* line_end = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        if (line_end == nullptr) line_end = end;
        const char* q = p;
        const char* qe = line_end;
        if (qe > q && qe[-1] == '\r') --qe;  // tolerate CRLF

        bool ok = (qe > q);
        int64_t id = 0;
        if (ok) ok = parse_id(q, qe, id);
        float* row = values + rows * dims;
        for (int32_t k = 0; ok && k < dims; ++k) {
            ok = (q < qe && *q == ',');
            if (ok) ++q;
            if (ok) ok = parse_value(q, qe, row[k]);
        }
        if (ok && q != qe) ok = false;  // trailing junk / too many fields
        if (ok) {
            ids[rows] = id;
            ++rows;
        } else if (line_end > p) {
            ++bad;
        }
        p = line_end + 1;
    }
    *dropped = bad;
    return rows;
}

}  // extern "C"
