"""Native fast-path loader (ctypes): builds fastcsv.so on first use.

``parse_tuples_native(text, dims)`` parses a newline-joined batch of
data-plane lines into (ids, values, dropped) measured 11-13x faster than
the Python line loop (1.37M vs 0.12M lines/s at 100k 8-D lines —
artifacts/kernels_{cpu,tpu}.json, benchmarks/kernels.py). Returns None from
``get_lib()`` (and the wire module falls back to Python parsing) if no
compiler is available or the build fails — the framework never
hard-requires the native component.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "fastcsv.cpp")
_SO = os.path.join(_HERE, "fastcsv.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-o", _SO, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def get_lib():
    """The loaded ctypes library, building it if needed; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.sky_parse_tuples.restype = ctypes.c_int64
        lib.sky_parse_tuples.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64),
        ]
        # produce-plane helpers (absent from pre-rework .so builds, hence
        # the hasattr guards in the accessors below)
        if hasattr(lib, "sky_crc32c"):
            lib.sky_crc32c.restype = ctypes.c_uint32
            lib.sky_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        if hasattr(lib, "sky_encode_records"):
            lib.sky_encode_records.restype = ctypes.c_int64
            lib.sky_encode_records.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64,
            ]
        if hasattr(lib, "sky_format_tuples"):
            lib.sky_format_tuples.restype = ctypes.c_int64
            lib.sky_format_tuples.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
            ]
        if hasattr(lib, "sky_format_rows"):
            lib.sky_format_rows.restype = ctypes.c_int64
            lib.sky_format_rows.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64,
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.c_char_p,
                ctypes.c_int64,
            ]
        if hasattr(lib, "sky_parse_recordbatches"):
            lib.sky_parse_recordbatches.restype = ctypes.c_int64
            lib.sky_parse_recordbatches.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
            ]
        _lib = lib
    return _lib


def parse_tuples_native(text: bytes, dims: int, max_rows: int):
    """Parse a newline-separated byte buffer. Returns (ids, values, dropped)
    or None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    ids = np.empty(max_rows, dtype=np.int64)
    values = np.empty((max_rows, dims), dtype=np.float32)
    dropped = ctypes.c_int64(0)
    n = lib.sky_parse_tuples(
        text,
        len(text),
        dims,
        max_rows,
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(dropped),
    )
    return ids[:n], values[:n], int(dropped.value)


def crc32c_native(data: bytes):
    """CRC32C (Castagnoli) via the native lib (hardware CRC instruction on
    x86); None if the library or symbol is unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "sky_crc32c"):
        return None
    return int(lib.sky_crc32c(data, len(data)))


def format_tuples_native(ids: np.ndarray, values: np.ndarray):
    """Format data-plane lines ``"id,v1,...,vd"`` from int64 arrays
    (ids (n,), values (n, d)) — the produce-plane twin of
    ``parse_tuples_native``. Returns ``(blob, offsets)`` where record i is
    ``blob[offsets[i]:offsets[i+1]]``, or None if the library or symbol is
    unavailable (callers fall back to Python formatting)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "sky_format_tuples"):
        return None
    ids = np.ascontiguousarray(ids, dtype=np.int64)
    values = np.ascontiguousarray(values, dtype=np.int64)
    n, d = values.shape
    out = np.empty(n * (d + 1) * 21 + 64, dtype=np.uint8)
    offsets = np.empty(n + 1, dtype=np.int64)
    w = lib.sky_format_tuples(
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        d,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.shape[0],
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if w < 0:
        return None
    return out[:w].tobytes(), offsets


ROWS_JSON = 0
ROWS_CSV = 1


def format_rows_native(points: np.ndarray, mode: int):
    """Serialize a (k, d) float32 row block into one wire body — the serve
    plane's publish-time body serializer (serve/bodystore.py). ``mode``
    ``ROWS_JSON`` yields the JSON points array byte-identical to
    ``json.dumps(points.tolist())``; ``ROWS_CSV`` yields the ``format=csv``
    block byte-identical to newline-joined ``wire.format_tuple_line(i, row)``.
    Returns bytes, or None if the library or symbol is unavailable (callers
    fall back to the Python encoders)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "sky_format_rows"):
        return None
    pts = np.ascontiguousarray(points, dtype=np.float32)
    k, d = pts.shape
    # 27 bytes of float repr + separators/brackets per field, plus row ids
    cap = k * (d + 1) * 32 + 64
    buf = ctypes.create_string_buffer(cap)
    n = lib.sky_format_rows(
        pts.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        k,
        d,
        int(mode),
        buf,
        cap,
    )
    if n < 0:
        return None
    return buf.raw[:n]


# per-record frame overhead bound used to size native encode outputs and
# the blob produce path's batch grouping: <=2B length + 3 fixed +
# <=2B offsetDelta + <=2B valueLen + 1 header count, padded generously
RECORD_FRAME_OVERHEAD = 24


def encode_records_from_blob(blob: bytes, offsets):
    """Kafka RecordBatch v2 record frames straight from a value blob +
    prefix offsets (record i = ``blob[offsets[i]:offsets[i+1]]``; offsets
    may be absolute into a larger blob — the native encoder reads
    ``values + offsets[i]`` directly). None if unavailable."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "sky_encode_records"):
        return None
    offs = np.ascontiguousarray(offsets, dtype=np.int64)
    n = offs.shape[0] - 1
    out = np.empty(
        int(offs[-1] - offs[0]) + RECORD_FRAME_OVERHEAD * n + 64,
        dtype=np.uint8,
    )
    w = lib.sky_encode_records(
        blob,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.shape[0],
    )
    if w < 0:
        return None
    return out[:w].tobytes()


def parse_recordbatches_native(
    blob: bytes, min_offset: int, dims: int, verify_crc: bool = False
):
    """Consume-plane zero-copy path: one fetch response's RecordBatch v2
    blob -> (ids (n,) int64, values (n, d) float32, dropped, next_offset)
    with the CSV values parsed in native code — no per-record Python
    objects between broker and engine (the twin of the produce plane's
    ``format_tuples_native`` + ``encode_records_from_blob``). Skips records
    below ``min_offset`` (a fetch can return a batch that starts earlier
    than the requested offset); ``next_offset`` is the fetch-position
    advance. Returns None if the library or symbol is unavailable; raises
    ValueError on corrupt framing/CRC exactly like
    bridge/kafkalite/protocol.py decode_record_batches."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "sky_parse_recordbatches"):
        return None
    # framing minimum is ~10 bytes/record (7 frame + "0,0"), so len/9 rows
    # always covers a single-pass parse of the whole blob
    max_rows = len(blob) // 9 + 1
    ids = np.empty(max_rows, dtype=np.int64)
    values = np.empty((max_rows, dims), dtype=np.float32)
    dropped = ctypes.c_int64(0)
    next_off = ctypes.c_int64(min_offset)
    n = lib.sky_parse_recordbatches(
        blob,
        len(blob),
        min_offset,
        dims,
        1 if verify_crc else 0,
        max_rows,
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(dropped),
        ctypes.byref(next_off),
    )
    if n == -2:
        raise ValueError("unsupported record magic")
    if n == -3:
        raise ValueError("record batch CRC32C mismatch")
    if n < 0:
        raise ValueError(f"malformed record batch (native rc={n})")
    # copy the filled prefix: a slice view would pin the whole len/9-row
    # buffer (sized for the framing minimum, 3-6x the real row count at
    # 8-D) for as long as the engine holds the batch
    return (
        ids[:n].copy(),
        values[:n].copy(),
        int(dropped.value),
        int(next_off.value),
    )


def encode_records_native(values: list[bytes]):
    """Kafka RecordBatch v2 record frames for value-only records (the
    produce-plane hot loop); None if unavailable. Byte-identical to the
    Python loop in bridge/kafkalite/protocol.py (golden-bytes tested)."""
    n = len(values)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(v) for v in values], out=offsets[1:])
    return encode_records_from_blob(b"".join(values), offsets)
