"""Lease/fencing plane: write-path high availability (RUNBOOK §2r).

The write path has exactly one owner at a time — the engine that appends
to the WAL. This module makes ownership EXPLICIT and REVOCABLE without
ever allowing two writers to interleave frames:

- ``LeasePlane`` manages two tiny JSON files beside the WAL segments:
  ``lease.json`` (who owns the write path, under which monotonic epoch,
  renewed until when) and ``fence.json`` (the minimum epoch the WAL still
  accepts). Both are written atomically (tmp + ``os.replace``) and
  fsynced, so a torn write can never produce a half-lease.
- ``FencedWalWriter`` is a ``WalWriter`` that carries the holder's epoch:
  every frame is stamped with the fencing token (``rec["fence"]``), and
  every append first checks the fence — a deposed primary's append is
  REJECTED with ``WalFencedError`` at the WAL layer, loudly counted
  (``cluster.fenced_writes`` → ``skyline_cluster_fenced_writes_total``),
  never silently dropped. The check is one ``os.stat`` per append
  (re-parsed only when the fence file changes), so the hot path costs
  about as much as the frame's own ``os.write``.
- ``ClusterSupervisor`` watches the lease from the read side: when it
  expires (primary dead or wedged), it raises the fence PAST the dead
  holder's epoch FIRST — from that instant the deposed primary cannot
  append even if it wakes up — then promotes the most-caught-up replica
  under the new epoch. Correctness of the promoted head needs no new
  machinery: replicas fold digest-verified deltas (PR 15), so the
  promoted serve state is byte-identical to the deposed primary's last
  durable publish by construction.

Ordering is the whole proof: fence BEFORE lease BEFORE promote. A crash
between any two steps leaves the system safe — a raised fence without a
new lease just means the next supervisor tick promotes again under a
higher epoch.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time

from skyline_tpu.resilience.faults import fault_point
from skyline_tpu.resilience.wal import WalError, WalWriter, list_segments

LEASE_FILE = "lease.json"
FENCE_FILE = "fence.json"


class LeaseLostError(WalError):
    """The holder's lease is gone: a higher epoch exists on disk (another
    writer was promoted) or the fence moved past the holder. The holder
    must demote itself to a replica; its writer will reject appends."""


class WalFencedError(WalError):
    """An append from a fenced (deposed) writer epoch. The frame was NOT
    written — rejection happens before the write syscall."""


def _now_ms() -> float:
    return time.time() * 1000.0


@dataclasses.dataclass
class LeaseRecord:
    epoch: int
    holder: str
    renewed_ms: float
    ttl_ms: float

    def expired(self, now_ms: float) -> bool:
        return now_ms - self.renewed_ms > self.ttl_ms

    def doc(self) -> dict:
        return {
            "epoch": self.epoch,
            "holder": self.holder,
            "renewed_ms": self.renewed_ms,
            "ttl_ms": self.ttl_ms,
        }


class LeasePlane:
    """The on-disk lease + fence beside a WAL directory.

    ``clock``: optional ``() -> now_ms`` override so tests and drills can
    expire leases deterministically instead of sleeping through TTLs.
    """

    def __init__(self, wal_dir: str, clock=None):
        self.wal_dir = wal_dir
        os.makedirs(wal_dir, exist_ok=True)
        self.clock = clock if clock is not None else _now_ms
        self._lock = threading.Lock()
        # (st_ino, st_mtime_ns, st_size) -> parsed fence epoch, so the
        # per-append fence check is one stat, not one parse. st_ino is
        # load-bearing: os.replace lands a new inode every raise, so two
        # same-size fence docs inside one mtime granule (coarse-timestamp
        # filesystems) still invalidate the cache
        self._fence_sig = None
        self._fence_epoch = 0

    # -- file plumbing -----------------------------------------------------

    def _path(self, name: str) -> str:
        return os.path.join(self.wal_dir, name)

    def _write_json(self, name: str, doc: dict) -> None:
        path = self._path(name)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # -- lease -------------------------------------------------------------

    def read_lease(self) -> LeaseRecord | None:
        try:
            with open(self._path(LEASE_FILE), encoding="utf-8") as f:
                doc = json.load(f)
            return LeaseRecord(
                int(doc["epoch"]), str(doc["holder"]),
                float(doc["renewed_ms"]), float(doc["ttl_ms"]),
            )
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            return None

    def acquire(
        self, holder: str, ttl_ms: float, epoch: int | None = None
    ) -> LeaseRecord | None:
        """Take the lease. With ``epoch=None`` this is the polite path:
        refused (returns None) while another holder's lease is live, and
        the epoch always advances past the previous one — re-acquiring
        after one's own expiry bumps it too, because frames from the old
        epoch may still be racing toward the disk. With an explicit
        ``epoch`` (the supervisor's promotion path, fence already raised)
        the write is unconditional."""
        with self._lock:
            now = self.clock()
            cur = self.read_lease()
            if epoch is None:
                if cur is not None and cur.holder != holder and not cur.expired(now):
                    return None
                epoch = max(
                    (cur.epoch if cur is not None else 0), self.read_fence()
                ) + 1
            rec = LeaseRecord(int(epoch), holder, now, float(ttl_ms))
            self._write_json(LEASE_FILE, rec.doc())
            return rec

    def renew(self, rec: LeaseRecord) -> LeaseRecord:
        """Refresh ``rec``'s expiry. Raises ``LeaseLostError`` when disk
        disagrees — a higher epoch (someone promoted over us) or a fence
        past our epoch. Deposition is detected HERE, not at the append
        (though the append check also holds, belt and braces)."""
        with self._lock:
            cur = self.read_lease()
            if cur is not None and (
                cur.epoch > rec.epoch or cur.holder != rec.holder
            ):
                raise LeaseLostError(
                    f"lease lost: disk holds epoch {cur.epoch} "
                    f"({cur.holder!r}), we are epoch {rec.epoch}"
                )
            if self.read_fence() > rec.epoch:
                raise LeaseLostError(
                    f"lease lost: fence {self.read_fence()} is past our "
                    f"epoch {rec.epoch}"
                )
            out = LeaseRecord(rec.epoch, rec.holder, self.clock(), rec.ttl_ms)
            self._write_json(LEASE_FILE, out.doc())
            return out

    # -- fence -------------------------------------------------------------

    def read_fence(self) -> int:
        """Minimum epoch the WAL accepts (0 = never fenced). Stat-cached:
        the common case re-reads nothing."""
        path = self._path(FENCE_FILE)
        try:
            st = os.stat(path)
        except OSError:
            return 0
        sig = (st.st_ino, st.st_mtime_ns, st.st_size)
        if sig == self._fence_sig:
            return self._fence_epoch
        try:
            with open(path, encoding="utf-8") as f:
                epoch = int(json.load(f)["min_epoch"])
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            return self._fence_epoch  # torn mid-replace: keep the last good
        self._fence_sig, self._fence_epoch = sig, epoch
        return epoch

    def raise_fence(self, min_epoch: int) -> int:
        """Monotonically raise the fence to ``min_epoch`` (never lowers).
        After this returns, any writer below ``min_epoch`` gets
        ``WalFencedError`` on its next append.

        The fence doc also records the durable CUT — newest segment seq +
        its byte size at raise time. Everything durable before the cut is
        the legitimate history the promoted head drains; a deposed
        writer's frame that raced the check-then-write window necessarily
        lands at/past the cut with a below-fence epoch, and every reader
        (tailer, replay) skips it. That closes the race the writer-side
        check alone cannot: a primary paused between its fence check and
        its ``os.write`` can still land a frame, but no reader will ever
        fold it."""
        with self._lock:
            cur = self.read_fence()
            if min_epoch > cur:
                segs = list_segments(self.wal_dir)
                cut_seq, cut_pos = 0, 0
                if segs:
                    cut_seq = segs[-1][0]
                    try:
                        cut_pos = os.path.getsize(segs[-1][1])
                    except OSError:
                        cut_pos = 0
                self._write_json(
                    FENCE_FILE,
                    {
                        "min_epoch": int(min_epoch),
                        "cut_seq": int(cut_seq),
                        "cut_pos": int(cut_pos),
                    },
                )
                self._fence_sig = None  # force a re-read next check
            return max(cur, min_epoch)

    def fence_doc(self) -> dict | None:
        """The full fence file (min_epoch + the durable cut) for the ops
        journal's ``fence_raised`` record; None when never fenced."""
        try:
            with open(self._path(FENCE_FILE), encoding="utf-8") as f:
                doc = json.load(f)
            return {
                "min_epoch": int(doc.get("min_epoch", 0)),
                "cut_seq": int(doc.get("cut_seq", 0)),
                "cut_pos": int(doc.get("cut_pos", 0)),
            }
        except (OSError, ValueError, json.JSONDecodeError):
            return None

    def doc(self) -> dict:
        rec = self.read_lease()
        return {
            "lease": rec.doc() if rec is not None else None,
            "fence": self.read_fence(),
            "expired": (
                rec.expired(self.clock()) if rec is not None else None
            ),
        }


class FencedWalWriter(WalWriter):
    """A ``WalWriter`` bound to a lease epoch: every frame carries the
    fencing token, and appends from a fenced epoch are rejected BEFORE
    the write syscall — plus re-checked AFTER it, so an append that
    raced a fence raise is reported rejected rather than silently
    trusted (readers enforce the same verdict via the fence cut).
    ``barrier()`` is covered too, with its check before the segment
    rotation, so a deposed primary can neither stamp a checkpoint
    barrier nor truncate the promoted writer's fresh segment."""

    def __init__(
        self,
        directory: str,
        epoch: int,
        *,
        plane: LeasePlane | None = None,
        opslog=None,
        **kw,
    ):
        self.plane = plane if plane is not None else LeasePlane(directory)
        self.epoch = int(epoch)
        self.fenced_writes = 0
        self.opslog = opslog
        super().__init__(directory, **kw)

    def _ops_rejected(self, fence: int, where: str) -> None:
        # the zombie's own durable confession: a fenced append is exactly
        # the split-brain evidence the ops timeline must carry
        if self.opslog is not None:
            self.opslog.record(
                "zombie_append_rejected",
                epoch=self.epoch, fence=fence, where=where,
            )

    def _check_fence(self) -> None:
        fence = self.plane.read_fence()
        if fence > self.epoch:
            self.fenced_writes += 1
            if self._telemetry is not None:
                self._telemetry.inc("cluster.fenced_writes")
            self._ops_rejected(fence, "pre_append")
            fault_point("wal.stale_fence")
            raise WalFencedError(
                f"append rejected: writer epoch {self.epoch} is behind "
                f"fence {fence} (another primary was promoted)"
            )

    def append(self, rec: dict) -> None:
        self._check_fence()
        if "fence" not in rec:
            rec = dict(rec)
            rec["fence"] = self.epoch
        super().append(rec)
        # re-check AFTER the write: a fence raised inside the
        # check-then-write window means this frame sits at/past the
        # fence's durable cut, so every reader skips it — report the
        # append as rejected, not silently lost. (If the frame landed
        # just BEFORE the cut it is legitimate drained history; treating
        # an applied write as failed is the safe side of that ambiguity —
        # the deposed caller demotes and re-bootstraps from the WAL.)
        fence = self.plane.read_fence()
        if fence > self.epoch:
            self.fenced_writes += 1
            if self._telemetry is not None:
                self._telemetry.inc("cluster.fenced_writes")
            self._ops_rejected(fence, "post_append")
            raise WalFencedError(
                f"append raced a fence raise: writer epoch {self.epoch} is "
                f"behind fence {fence}; readers will not fold frames past "
                "the fence cut"
            )

    def barrier(self, rec: dict) -> None:
        # check BEFORE rotating: ``barrier`` opens segment seq+1 with
        # O_TRUNC first, which after a promotion can be the NEW primary's
        # live segment — a deposed writer must be stopped before that
        self._check_fence()
        super().barrier(rec)

    def stats(self) -> dict:
        out = super().stats()
        out["epoch"] = self.epoch
        out["fenced_writes"] = self.fenced_writes
        return out


class LeaseKeeper:
    """Primary-side lease maintenance: acquire at startup, renew on a
    cadence from the worker's step/idle hooks. ``maybe_renew`` raises
    ``LeaseLostError`` when deposed — the worker demotes instead of
    writing on."""

    def __init__(
        self,
        plane: LeasePlane,
        holder: str,
        ttl_ms: float | None = None,
        renew_ms: float | None = None,
        telemetry=None,
    ):
        from skyline_tpu.analysis.registry import env_float

        self.plane = plane
        self.holder = holder
        self.ttl_ms = (
            env_float("SKYLINE_CLUSTER_LEASE_TTL_MS", 3000.0)
            if ttl_ms is None
            else float(ttl_ms)
        )
        renew = (
            env_float("SKYLINE_CLUSTER_LEASE_RENEW_MS", 0.0)
            if renew_ms is None
            else float(renew_ms)
        )
        # a renew cadence slower than the TTL is self-deposition
        self.renew_ms = renew if renew > 0 else max(self.ttl_ms / 3.0, 1.0)
        self.telemetry = telemetry
        self.record: LeaseRecord | None = None

    def acquire(self) -> LeaseRecord | None:
        self.record = self.plane.acquire(self.holder, self.ttl_ms)
        return self.record

    @property
    def epoch(self) -> int:
        return self.record.epoch if self.record is not None else 0

    def maybe_renew(self, now_ms: float | None = None) -> bool:
        """Renew when due. Returns True when a renewal was written."""
        if self.record is None:
            return False
        now = self.plane.clock() if now_ms is None else now_ms
        if now - self.record.renewed_ms < self.renew_ms:
            return False
        t0 = time.perf_counter_ns()
        self.record = self.plane.renew(self.record)
        if self.telemetry is not None:
            self.telemetry.inc("cluster.lease_renewals")
            # renew latency is the lease plane's fsync tax; a p99 drift
            # here predicts spurious expiries before they happen
            self.telemetry.histogram(
                "cluster_lease_renew_ms", unit="ms"
            ).observe((time.perf_counter_ns() - t0) / 1e6)
        return True


class ClusterSupervisor:
    """Watches the lease beside a shared WAL and promotes the
    most-caught-up replica when it expires.

    ``replicas``: the ``serve.replica.SkylineReplica`` candidates (they
    all tail the same WAL, so after the promotion drain every candidate
    converges to the same durable tail; the head-version snapshot picks
    the one with the least catching up to do). ``tick()`` is the whole
    control loop — call it from a timer, an idle hook, or a drill.
    """

    def __init__(
        self,
        wal_dir: str,
        replicas,
        *,
        lease_ttl_ms: float | None = None,
        telemetry=None,
        clock=None,
        opslog=None,
    ):
        from skyline_tpu.analysis.registry import env_float

        self.plane = LeasePlane(wal_dir, clock=clock)
        self.replicas = list(replicas)
        self.lease_ttl_ms = (
            env_float("SKYLINE_CLUSTER_LEASE_TTL_MS", 3000.0)
            if lease_ttl_ms is None
            else float(lease_ttl_ms)
        )
        self.telemetry = telemetry
        self.opslog = opslog
        self.promotions = 0
        self.last_promotion: dict | None = None
        self._lock = threading.Lock()

    def _ops(self, type_: str, **fields) -> None:
        if self.opslog is not None:
            self.opslog.record(type_, **fields)

    def _promoted(self):
        return next(
            (r for r in self.replicas if getattr(r, "role", "replica") == "primary"),
            None,
        )

    def tick(self) -> dict | None:
        """One supervision step: renew on behalf of a replica we already
        promoted, otherwise check expiry and promote. Returns the
        promotion doc when a promotion happened this tick, else None."""
        with self._lock:
            now = self.plane.clock()
            rec = self.plane.read_lease()
            mine = self._promoted()
            if rec is not None and not rec.expired(now):
                if mine is None or rec.holder != mine.replica_id:
                    return None  # someone else's live lease: not ours to touch
                try:
                    self.plane.renew(rec)
                    return None
                except LeaseLostError:
                    # another supervisor fenced past our promotee: demote
                    # the zombie primary and fall through to re-promotion
                    # under a higher epoch instead of crashing the
                    # caller's timer loop
                    demote = getattr(mine, "demote", None)
                    if demote is not None:
                        demote()
                    if self.telemetry is not None:
                        self.telemetry.inc("cluster.renewals_lost")
                    self._ops(
                        "lease_renew_lost",
                        epoch=rec.epoch, holder=rec.holder,
                        fence=self.plane.read_fence(),
                    )
            # lease absent or expired: the write path is ownerless
            fault_point("cluster.lease_expire")
            t0 = time.perf_counter_ns()
            candidates = [
                r for r in self.replicas
                if getattr(r, "role", "replica") != "primary"
            ]
            if not candidates:
                return None
            best = max(candidates, key=lambda r: r.store.head_version)
            new_epoch = max(
                (rec.epoch if rec is not None else 0), self.plane.read_fence()
            ) + 1
            self._ops(
                "lease_expired",
                epoch=rec.epoch if rec is not None else None,
                holder=rec.holder if rec is not None else None,
            )
            # fence FIRST: from here the deposed epoch cannot append, so
            # nothing the old primary does can interleave with the drain
            tf = time.perf_counter_ns()
            self.plane.raise_fence(new_epoch)
            fence_ms = (time.perf_counter_ns() - tf) / 1e6
            cut = self.plane.fence_doc() or {}
            self._ops(
                "fence_raised",
                epoch=new_epoch, fence=new_epoch,
                cut_seq=cut.get("cut_seq"), cut_pos=cut.get("cut_pos"),
                wall_ms=round(fence_ms, 3),
            )
            lease = self.plane.acquire(
                best.replica_id, self.lease_ttl_ms, epoch=new_epoch
            )
            info = best.promote(new_epoch)
            wall_ms = (time.perf_counter_ns() - t0) / 1e6
            self.promotions += 1
            doc = {
                "epoch": lease.epoch,
                "holder": best.replica_id,
                "deposed": rec.holder if rec is not None else None,
                "time_to_promote_ms": round(wall_ms, 3),
                "head_version": info.get("head_version"),
                "head_digest": info.get("head_digest"),
                "at_ms": now,
            }
            self.last_promotion = doc
            if self.telemetry is not None:
                self.telemetry.inc("cluster.promotions")
                # real histograms, not one-shot bench numbers: /slo's
                # promote_p99 row and the sentinel read these
                self.telemetry.histogram(
                    "cluster_time_to_promote_ms", unit="ms"
                ).observe(wall_ms)
                self.telemetry.histogram(
                    "cluster_fence_raise_ms", unit="ms"
                ).observe(fence_ms)
            self._ops(
                "promoted",
                epoch=lease.epoch, holder=best.replica_id,
                deposed=doc["deposed"], head_version=doc["head_version"],
                wall_ms=doc["time_to_promote_ms"],
            )
            return doc

    def doc(self) -> dict:
        out = self.plane.doc()
        out.update({
            "promotions": self.promotions,
            "last_promotion": self.last_promotion,
            "members": [
                {
                    "id": r.replica_id,
                    "role": getattr(r, "role", "replica"),
                    "head_version": r.store.head_version,
                }
                for r in self.replicas
            ],
        })
        return out


class ClusterStatus:
    """The hub object behind ``GET /cluster`` on both HTTP surfaces
    (``telemetry.cluster``): membership, lease holder, epoch, last
    promotion, plus the multi-host coordinator block when one is
    attached. Callbacks keep it passive — serving a doc can never
    perturb the planes it describes."""

    def __init__(self, node_id: str = "", role: str = "primary"):
        self.node_id = node_id
        self.role = role
        self.lease_cb = None  # () -> dict (LeasePlane.doc / Supervisor.doc)
        self.coordinator_cb = None  # () -> dict (ClusterPartitionSet.cluster_stats)
        self.telemetry = None

    def doc(self) -> dict:
        out: dict = {"enabled": True, "node": self.node_id, "role": self.role}
        if self.lease_cb is not None:
            try:
                out.update(self.lease_cb())
            except Exception as e:  # observability must not 500 the plane
                out["lease_error"] = f"{type(e).__name__}: {e}"
        if self.coordinator_cb is not None:
            try:
                out["hosts"] = self.coordinator_cb()
            except Exception as e:
                out["hosts_error"] = f"{type(e).__name__}: {e}"
        if self.telemetry is not None:
            snap = dict(self.telemetry.counters.snapshot())
            out["fenced_writes"] = int(snap.get("cluster.fenced_writes", 0))
            out["promotions_counted"] = int(snap.get("cluster.promotions", 0))
        return out

    def labeled_series(self):
        """Host-labeled Prometheus families (mirrors the fleet plane's
        per-chip families): records/pruned counters and skyline-size
        gauges per host, from the coordinator's per-host block."""
        if self.coordinator_cb is None:
            return {}, {}
        try:
            stats = self.coordinator_cb()
        except Exception:
            return {}, {}
        last = stats.get("last") or {}
        counters: dict = {}
        gauges: dict = {}
        for row in last.get("per_host", []):
            labels = (("host", str(row["host"])),)
            counters.setdefault("host_records", []).append(
                (labels, float(row.get("records", 0)))
            )
            counters.setdefault("host_pruned", []).append(
                (labels, 1.0 if row.get("pruned") else 0.0)
            )
            gauges.setdefault("host_skyline_size", []).append(
                (labels, float(row.get("skyline", 0)))
            )
        return counters, gauges
