"""Cluster plane (RUNBOOK §2r): lease-fenced write-path HA + multi-host
partitioned ingest with a host-level tournament merge.

- ``lease``: the on-disk lease/fencing-token plane beside the WAL, the
  epoch-stamped ``FencedWalWriter``, and the ``ClusterSupervisor`` that
  promotes the most-caught-up replica when the primary's lease expires.
- ``merge``: the third tournament level — host roots, host witness
  summaries, and the cross-host pairwise ladder.
- ``coordinator``: ``ClusterPartitionSet`` (the partition-set facade over
  per-host members) and ``ClusterEngine`` (the drop-in engine over it),
  plus live partition-group migration between hosts.
"""

from skyline_tpu.cluster.coordinator import ClusterEngine, ClusterPartitionSet
from skyline_tpu.cluster.lease import (
    ClusterStatus,
    ClusterSupervisor,
    FencedWalWriter,
    LeaseKeeper,
    LeaseLostError,
    LeasePlane,
    LeaseRecord,
    WalFencedError,
)

__all__ = [
    "ClusterEngine",
    "ClusterPartitionSet",
    "ClusterStatus",
    "ClusterSupervisor",
    "FencedWalWriter",
    "LeaseKeeper",
    "LeaseLostError",
    "LeasePlane",
    "LeaseRecord",
    "WalFencedError",
]
