"""Multi-host partitioned ingest + the host-level tournament coordinator
(RUNBOOK §2r).

``ClusterPartitionSet`` is the sharded facade's pattern applied one level
up: host ``h`` owns the contiguous global partitions ``[h*G, (h+1)*G)``
with ``G = P / hosts``, and each member is a full engine-grade partition
set of its own — a ``ShardedPartitionSet`` when ``chips_per_host > 1``
(so a cluster query is a THREE-level tournament: partitions → chips →
hosts) or a flat ``PartitionSet`` at one chip. Members expose the same
merge surface (``global_merge_launch`` / ``global_merge_harvest`` /
``merge_points_device``), which is what makes the host level a dozen
lines of reuse instead of a new merge.

Byte contract (the acceptance grid): the cluster answer is byte-identical
(rows AND order) to the flat single-host merge for every host count ×
chip count × flush policy, because (a) members are contiguous in pid,
(b) each member root is already canonical over its own pids, and
(c) ``tree_pair_merge``'s stable compaction preserves (pid, storage-row)
order at the host level exactly as it does at the chip level. Flush
cadence is facade-global for the same reason it is in the sharded set —
flush points are part of the byte contract under the lazy policy.

Elastic rebalance: ``migrate(h)`` drains host ``h``, captures its slice
through ``audit_state`` (the checkpoint currency), rebuilds the member —
possibly at a DIFFERENT chip count — and restores byte-faithfully via
``restore_all``; ``checkpoint_slice``/``restore_slice`` do the same
through an on-disk npz so a group checkpointed on host A restores on
host B. In-process, swapping the member object already fences the
source (no pid routes to it afterwards); the cross-process write fence
is the lease plane's job (cluster/lease.py). Migrations are budgeted
(``SKYLINE_CLUSTER_MIGRATION_BUDGET``) so a flapping health signal
cannot thrash state between hosts forever.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib

import jax
import numpy as np

from skyline_tpu.cluster.merge import host_leaf, prune_hosts, tournament
from skyline_tpu.distributed.sharded import ShardedPartitionSet, epoch_hex
from skyline_tpu.metrics.tracing import NULL_TRACER
from skyline_tpu.ops import cascade
from skyline_tpu.stream.batched import PartitionSet, PartitionView
from skyline_tpu.stream.engine import SkylineEngine
from skyline_tpu.stream.window import (
    DEFAULT_BUFFER_SIZE,
    _next_pow2,
    tree_points_device,
    tree_stats_device,
)


def _migration_budget() -> int:
    from skyline_tpu.analysis.registry import env_int

    return env_int("SKYLINE_CLUSTER_MIGRATION_BUDGET", 8)


class _ClusterMergeHandle:
    """An in-flight three-level merge (host level async until harvest)."""

    __slots__ = (
        "key", "emit_points", "use_cache", "cached", "result", "stats",
        "root_vals", "explain", "host_info", "partial",
    )

    def __init__(self):
        self.cached = False
        self.result = None
        self.stats = None
        self.root_vals = None
        self.explain = None
        self.host_info = None
        self.partial = None

    def ready(self) -> bool:
        if self.cached:
            return True
        try:
            return bool(self.stats.is_ready())
        except AttributeError:
            return False


class ClusterPartitionSet:
    """Facade with the ``PartitionSet`` surface over per-host members.

    Global partition ``p`` lives on host ``p // group_size`` at local
    index ``p % group_size``. Flush-cadence bookkeeping is facade-global
    (the byte contract), and each member keeps its own chip-level
    machinery — witness summaries, merge caches, epoch subvectors —
    untouched.
    """

    def __init__(
        self,
        num_partitions: int,
        dims: int,
        buffer_size: int = DEFAULT_BUFFER_SIZE,
        *,
        hosts: int,
        chips_per_host: int = 1,
        initial_capacity: int = 0,
        tracer=None,
        flush_policy: str = "incremental",
        overlap_rows: int = 262144,
        window_capacity: int = 0,
        counters=None,
    ):
        if hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {hosts}")
        if num_partitions % hosts:
            raise ValueError(
                f"num_partitions {num_partitions} must be divisible by "
                f"hosts {hosts}"
            )
        group = num_partitions // hosts
        if chips_per_host < 1:
            raise ValueError(
                f"chips_per_host must be >= 1, got {chips_per_host}"
            )
        if chips_per_host > 1 and group % chips_per_host:
            raise ValueError(
                f"per-host group size {group} must be divisible by "
                f"chips_per_host {chips_per_host}"
            )
        self.num_partitions = num_partitions
        self.dims = dims
        self.buffer_size = buffer_size
        self.hosts = hosts
        self.group_size = group
        self.chips_per_host = chips_per_host
        self.flush_policy = flush_policy
        self.overlap_rows = overlap_rows
        self._initial_capacity = initial_capacity
        self._window_capacity = window_capacity
        self.mesh = None
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._counters = counters
        self._members = [self._build_member(chips_per_host) for _ in range(hosts)]
        self._member_chips = [chips_per_host] * hosts
        p = num_partitions
        # facade-global bookkeeping: identical flush-cadence inputs to the
        # single-device set (members keep their own mirrors)
        self._pending_rows = np.zeros(p, dtype=np.int64)
        self.max_seen_id = np.full(p, -1, dtype=np.int64)
        self.start_time_ms: list[float | None] = [None] * p
        self.records_seen = np.zeros(p, dtype=np.int64)
        self._processing_base_ns = 0
        self._profiler = None
        self._flight = None
        self._explain = None
        self._spans = None
        self._gm_cache: dict | None = None
        self.merge_cache_hits = 0
        self.merge_cache_misses = 0
        # shape parity with the engine's stats block (delta plane is
        # member-internal, the facade reports zeros)
        self.merge_delta_merges = 0
        self.merge_delta_rows = 0
        self.last_dirty_fraction: float | None = None
        self.last_tree_info: dict | None = None
        # host-level attribution
        self.cluster_merges = 0
        self.hosts_pruned_total = 0
        self.hosts_considered_total = 0
        self.rows_shipped_total = 0
        self.rows_saved_total = 0
        self.last_host_info: dict | None = None
        self.last_partial: dict | None = None
        # elastic rebalance
        self._host_locks = [threading.Lock() for _ in range(hosts)]
        self._health = None
        self._opslog = None
        self.migrations = 0
        self.last_migration: dict | None = None
        self.fenced_sources = 0

    def _build_member(self, chips: int):
        if chips > 1:
            return ShardedPartitionSet(
                self.group_size,
                self.dims,
                self.buffer_size,
                chips=chips,
                initial_capacity=self._initial_capacity,
                tracer=self.tracer,
                flush_policy=self.flush_policy,
                overlap_rows=self.overlap_rows,
                window_capacity=self._window_capacity,
                counters=self._counters,
            )
        return PartitionSet(
            self.group_size,
            self.dims,
            self.buffer_size,
            initial_capacity=self._initial_capacity,
            tracer=self.tracer,
            flush_policy=self.flush_policy,
            overlap_rows=self.overlap_rows,
            window_capacity=self._window_capacity,
            counters=self._counters,
        )

    # -- host addressing -----------------------------------------------------

    def _loc(self, p: int) -> tuple[int, int]:
        return divmod(p, self.group_size)

    # -- state versioning ------------------------------------------------------

    @property
    def epoch_key(self) -> bytes:
        return b"".join(m.epoch_key for m in self._members)

    # -- aggregate bookkeeping -------------------------------------------------

    @property
    def processing_ns(self) -> int:
        return self._processing_base_ns + sum(
            m.processing_ns for m in self._members
        )

    @processing_ns.setter
    def processing_ns(self, v: int) -> None:
        for m in self._members:
            m.processing_ns = 0
        self._processing_base_ns = int(v)

    @property
    def processing_ms(self) -> float:
        return self.processing_ns / 1e6

    @property
    def merge_tree_merges(self) -> int:
        return sum(m.merge_tree_merges for m in self._members)

    @property
    def merge_partitions_pruned(self) -> int:
        return sum(m.merge_partitions_pruned for m in self._members)

    @property
    def device_ingest(self) -> bool:
        return False

    @property
    def has_unsynced_ingest(self) -> bool:
        return False

    def sync_ingest_bookkeeping(self) -> None:  # device-ingest only
        return None

    @property
    def pending_rows_total(self) -> int:
        return int(self._pending_rows.sum())

    def _inc(self, name: str, n: int = 1) -> None:
        if self._counters is not None:
            self._counters.inc(name, n)

    def _fnote(self, kind: str, **fields) -> None:
        if self._flight is not None:
            self._flight.note(kind, **fields)

    # -- observability hooks ---------------------------------------------------

    def attach_observability(
        self, profiler=None, flight=None, fleet=None, spans=None
    ) -> None:
        self._profiler = profiler
        self._flight = flight
        self._spans = spans
        for m in self._members:
            m.attach_observability(profiler=profiler, flight=flight)

    def set_explain(self, plan) -> None:
        self._explain = plan

    def attach_chip_wal(self, plane) -> None:
        """Chip-WAL barriers are member-internal in a cluster (each host
        journals its own groups); the facade-level consistency story is
        the lease/fence plane plus barrier records in the main WAL."""
        return None

    def attach_health(self, health) -> None:
        """Attach a host-level health supervisor (the ``ChipHealth``
        scorer reused with host indices): quarantine decisions drive
        ``maybe_failover``'s live migrations."""
        self._health = health

    def attach_opslog(self, opslog) -> None:
        """Attach the durable cross-process ops journal (RUNBOOK §2s):
        host migrations and failovers become journal records beside the
        flight-ring notes."""
        self._opslog = opslog

    # -- ingest ----------------------------------------------------------------

    def add_batch(
        self, p: int, values: np.ndarray, max_id: int, now_ms: float
    ) -> None:
        n = values.shape[0]
        if n == 0:
            return
        if self.start_time_ms[p] is None:
            self.start_time_ms[p] = now_ms
        self.max_seen_id[p] = max(self.max_seen_id[p], int(max_id))
        self.records_seen[p] += n
        self._pending_rows[p] += n
        h, lp = self._loc(p)
        with self._host_locks[h]:
            self._members[h].add_batch(lp, values, max_id, now_ms)

    def maybe_flush(self) -> bool:
        """The single-device flush-cadence decision verbatim over the
        facade-global pending state, then a flush of EVERY host."""
        if self.flush_policy == "lazy":
            return False
        if self.flush_policy == "overlap":
            if self.pending_rows_total >= self.overlap_rows:
                self.flush_all(tighten=False)
                return True
            return False
        if int(self._pending_rows.max()) >= self.buffer_size:
            self.flush_all()
            return True
        return False

    def flush_all(self, tighten: bool = True) -> None:
        for h, m in enumerate(self._members):
            with self._host_locks[h]:
                m.flush_all(tighten)
        self._pending_rows[:] = 0

    def flush_cascade_stats(self) -> dict:
        docs = [m.flush_cascade_stats() for m in self._members]
        seen = sum(d["prefilter_seen"] for d in docs)
        dropped = sum(d["prefilter_dropped"] for d in docs)
        return {
            "prefilter_enabled": docs[0]["prefilter_enabled"],
            "mixed_precision": docs[0]["mixed_precision"],
            "prefilter_seen": seen,
            "prefilter_dropped": dropped,
            "prefilter_drop_fraction": (dropped / seen) if seen else 0.0,
            "bf16_resolved": sum(d["bf16_resolved"] for d in docs),
        }

    # -- three-level tournament merge ------------------------------------------

    def global_merge_stats(self, emit_points: bool = False):
        return self.global_merge_harvest(self.global_merge_launch(emit_points))

    def global_merge_launch(self, emit_points: bool = False):
        """Launch the cluster merge: per-host leaves harvest synchronously
        (each host's own two-level merge), the host witness prune decides
        who ships, and the host-level pairwise ladder + packed stats stay
        in flight until ``global_merge_harvest``."""
        self.maybe_failover()
        h = _ClusterMergeHandle()
        h.emit_points = emit_points
        h.key = self.epoch_key
        h.explain, self._explain = self._explain, None
        use_cache = cascade.merge_cache_on(False)
        h.use_cache = use_cache
        cache = self._gm_cache if use_cache else None
        if cache is not None and cache["key"] == h.key:
            self.merge_cache_hits += 1
            self._inc("cluster.cache_hit")
            h.cached = True
            h.result = (
                cache["counts"].copy(),
                cache["surv"].copy(),
                cache["g"],
                self._cached_points() if emit_points else None,
            )
            if h.explain is not None:
                h.explain.merge = {
                    "path": "cache_hit",
                    "cached": True,
                    "epoch_key": h.key.hex(),
                    "dirty_fraction": 0.0,
                    "dirty": [],
                    "clean": np.flatnonzero(cache["counts"] > 0).tolist(),
                    "skyline_size": int(cache["g"]),
                }
            return h
        self.merge_cache_misses += 1
        P, H, G, d = self.num_partitions, self.hosts, self.group_size, self.dims
        want_prune = cascade.gate("host_prune") and H > 1
        trace_id = h.explain.trace_id if h.explain is not None else None
        host_counts: list[np.ndarray] = []
        host_surv: list[np.ndarray] = []
        host_g: list[int] = []
        host_pts: list = []
        host_summary: list[np.ndarray | None] = []
        for hst, member in enumerate(self._members):
            t0 = time.perf_counter_ns()
            with self._host_locks[hst]:
                counts_h, surv_h, g_h, pts, summary = host_leaf(
                    member, want_prune
                )
            t1 = time.perf_counter_ns()
            host_counts.append(counts_h)
            host_surv.append(surv_h)
            host_g.append(g_h)
            host_pts.append(pts)
            host_summary.append(summary)
            if self._spans is not None:
                self._spans.record(
                    "host_merge", t0, t1, trace_id=trace_id, tid=hst + 1,
                    args={"host": hst, "level": "host", "skyline": int(g_h)},
                )
            if self._health is not None:
                self._health.note_merge_ok(hst, (t1 - t0) / 1e6)
        concat_counts = np.concatenate(host_counts)
        alive = np.array([g > 0 for g in host_g], dtype=bool)
        considered = int(alive.sum())
        pruned = np.zeros(H, dtype=bool)
        witness_of = np.full(H, -1, dtype=np.int64)
        if want_prune and considered > 1:
            pruned, witness_of = prune_hosts(host_summary, alive, d)
        npruned = int(pruned.sum())
        survivors = np.flatnonzero(alive & ~pruned)
        self.cluster_merges += 1
        self.hosts_pruned_total += npruned
        self.hosts_considered_total += considered
        self._inc("cluster.merges")
        self._inc("cluster.hosts_pruned", npruned)
        self._fnote(
            "cluster.merge", hosts=H, alive=considered, pruned=npruned,
            survivors=len(survivors),
        )
        if not len(survivors):
            h.cached = True
            h.result = (
                concat_counts.astype(np.int64),
                np.zeros(P, dtype=np.int64),
                0,
                np.empty((0, d), dtype=np.float32) if emit_points else None,
            )
            self._note_merge_info(
                h, host_g, considered, pruned, witness_of, survivors,
                0, [0], 0, 0,
            )
            return h
        # interconnect accounting: a pruned or empty host ships ZERO rows;
        # each survivor ships its padded root once (host 0's is resident)
        shipped = saved = 0
        leaves = []
        root_dev = jax.devices()[0]
        for hst in survivors:
            g = host_g[hst]
            w = host_pts[hst].shape[0]
            if hst != 0:
                shipped += w
            pid_np = np.zeros(w, dtype=np.int32)
            pid_np[:g] = np.repeat(
                np.arange(G, dtype=np.int32) + hst * G,
                host_surv[hst].astype(np.int64),
            )
            leaves.append((host_pts[hst], pid_np, g))
        for hst in np.flatnonzero(pruned):
            saved += host_pts[hst].shape[0]
        self.rows_shipped_total += shipped
        self.rows_saved_total += saved
        t2 = time.perf_counter_ns()
        root_vals, root_pids, root_cnt, levels, cand = tournament(
            leaves, root_dev
        )
        h.root_vals = root_vals
        counts_dev = jax.device_put(concat_counts.astype(np.int32), root_dev)
        h.stats = tree_stats_device(counts_dev, root_pids, root_cnt, P)
        try:
            h.stats.copy_to_host_async()
        except AttributeError:
            pass
        if self._spans is not None:
            self._spans.record(
                "cross_host_merge", t2, time.perf_counter_ns(),
                trace_id=trace_id, tid=0,
                args={"level": "cluster", "survivors": len(survivors),
                      "pruned": npruned, "levels": levels},
            )
        self._note_merge_info(
            h, host_g, considered, pruned, witness_of, survivors, levels,
            cand, shipped, saved,
        )
        return h

    def _note_merge_info(
        self, h, host_g, considered, pruned, witness_of, survivors, levels,
        cand, shipped, saved,
    ) -> None:
        H, G = self.hosts, self.group_size
        pruned_list = [
            {"host": int(c), "witness": int(witness_of[c])}
            for c in np.flatnonzero(pruned)
        ]
        per_host = []
        for hst in range(H):
            lo, hi = hst * G, (hst + 1) * G
            per_host.append({
                "host": hst,
                "chips": self._member_chips[hst],
                "skyline": int(host_g[hst]),
                "records": int(self.records_seen[lo:hi].sum()),
                "pending": int(self._pending_rows[lo:hi].sum()),
                "pruned": bool(pruned[hst]),
            })
        info = {
            "hosts": H,
            "group_size": G,
            "alive": considered,
            "pruned": pruned_list,
            "survivors": [int(c) for c in survivors],
            "levels": levels,
            "candidates_per_level": cand,
            "rows_shipped": int(shipped),
            "rows_saved": int(saved),
            "per_host": per_host,
        }
        self.last_host_info = info
        member_infos = [m.last_tree_info for m in self._members]
        intra_pruned = sum(
            i["partitions_pruned"] for i in member_infos if i is not None
        )
        self.last_tree_info = {
            "levels": max(
                (i["levels"] for i in member_infos if i is not None),
                default=0,
            ) + levels,
            "partitions_pruned": intra_pruned,
            "candidates_per_level": cand,
            "pruned_fraction": (
                intra_pruned / self.num_partitions
                if self.num_partitions else 0.0
            ),
        }
        if h.explain is not None:
            h.explain.merge = {
                "path": "cluster_tree",
                "cached": False,
                "epoch_key": h.key.hex(),
                "dirty_fraction": None,
                "dirty": list(range(self.num_partitions)),
                "clean": [],
            }
            h.explain.hosts = info

    def global_merge_harvest(self, handle):
        h = handle
        self.last_partial = h.partial
        if h.cached:
            return h.result
        P = self.num_partitions
        with self.tracer.phase("query/global_stats_sync"):
            svec = np.asarray(h.stats, dtype=np.int64)
        counts = svec[:P].copy()
        surv = svec[P: 2 * P].copy()
        g = int(svec[2 * P])
        if h.explain is not None and h.explain.merge is not None:
            h.explain.merge["skyline_size"] = g
        pts = None
        if h.use_cache:
            gcap = 2 * _next_pow2(max(g, 1))
            pts_dev = tree_points_device(h.root_vals, gcap)
            self._gm_cache = {
                "key": h.key,
                "counts": counts.copy(),
                "surv": surv.copy(),
                "g": g,
                "pts_dev": pts_dev,
                "pts_host": None,
            }
            if h.emit_points:
                pts = self._cached_points()
        elif h.emit_points:
            out_cap = _next_pow2(max(g, 1))
            with self.tracer.phase("query/points_transfer"):
                pts = np.asarray(
                    tree_points_device(h.root_vals, out_cap)
                )[:g].copy()
        return counts, surv, g, pts

    def _cached_points(self) -> np.ndarray:
        c = self._gm_cache
        if c["pts_host"] is None:
            with self.tracer.phase("query/points_transfer"):
                c["pts_host"] = np.asarray(c["pts_dev"])[: c["g"]].copy()
        return c["pts_host"].copy()

    # -- elastic rebalance -----------------------------------------------------

    def maybe_failover(self) -> list[int]:
        """Live-migrate every quarantined host's partition group onto
        fresh state (called at merge-launch entry and from worker idle
        ticks — the same hook discipline as chip failover). Returns the
        hosts migrated. No-op without an attached health supervisor."""
        if self._health is None:
            return []
        quarantined = self._health.quarantined()
        if not quarantined:
            return []
        healed = []
        for hst in quarantined:
            try:
                self.migrate(hst, reason="quarantined")
            except RuntimeError:
                self._fnote(
                    "cluster.migration_budget_exhausted", host=hst,
                    budget=_migration_budget(),
                )
                break
            self._health.heal(hst)
            healed.append(hst)
        return healed

    def migrate(
        self, hst: int, *, chips: int | None = None, reason: str = "manual"
    ) -> dict:
        """Drain → capture slice → restore on a fresh member (possibly at
        a different chip count) → fence the source. The slice currency is
        ``audit_state``/``restore_all`` — the byte-faithful checkpoint
        contract — so the next answer after a migration is byte-identical
        to an unmigrated run. Budgeted: raises ``RuntimeError`` once
        ``SKYLINE_CLUSTER_MIGRATION_BUDGET`` is spent."""
        if not 0 <= hst < self.hosts:
            raise ValueError(f"host {hst} out of range 0..{self.hosts - 1}")
        budget = _migration_budget()
        if self.migrations >= budget:
            raise RuntimeError(
                f"migration budget exhausted ({budget}); raise "
                "SKYLINE_CLUSTER_MIGRATION_BUDGET to allow more"
            )
        target_chips = self._member_chips[hst] if chips is None else int(chips)
        if target_chips > 1 and self.group_size % target_chips:
            raise ValueError(
                f"group size {self.group_size} not divisible by "
                f"chips {target_chips}"
            )
        t0 = time.perf_counter_ns()
        with self._host_locks[hst]:
            old = self._members[hst]
            old.flush_all()  # drain: pending rows fold into the skylines
            source_epoch = epoch_hex(old.epoch_key)
            skies, pendings = old.audit_state()
            grp = self._build_member(target_chips)
            grp.restore_all(skies, pendings)
            self._members[hst] = grp
            self._member_chips[hst] = target_chips
            # the drain folded this group's pending rows into its skylines;
            # the facade-global cadence inputs must agree with the member or
            # the next maybe_flush fires early — a flush-cadence deviation
            # the byte contract counts as observable
            for i, pd in enumerate(pendings):
                self._pending_rows[hst * self.group_size + i] = pd.shape[0]
        grp.attach_observability(profiler=self._profiler, flight=self._flight)
        self._gm_cache = None
        # the source member is unroutable the instant the swap lands; the
        # counter records that the old incarnation was deliberately fenced,
        # not leaked
        self.fenced_sources += 1
        self.migrations += 1
        wall_ms = (time.perf_counter_ns() - t0) / 1e6
        doc = {
            "host": hst,
            "chips": target_chips,
            "reason": reason,
            "wall_ms": round(wall_ms, 3),
            "source_epoch": source_epoch,
            "source_fenced": True,
        }
        self.last_migration = doc
        self._inc("cluster.migrations")
        self._fnote("cluster.migration", **doc)
        if self._opslog is not None:
            self._opslog.record("host_migrated", **doc)
        return doc

    def checkpoint_slice(self, hst: int, path: str) -> None:
        """Persist host ``hst``'s partition-group slice (post-drain) as a
        torn-proof npz: the portable half of a cross-host migration."""
        with self._host_locks[hst]:
            member = self._members[hst]
            member.flush_all()
            skies, pendings = member.audit_state()
        arrays: dict = {}
        for i, (s, pd) in enumerate(zip(skies, pendings)):
            arrays[f"sky_{i}"] = s
            arrays[f"pending_{i}"] = pd
        meta = {
            "host": hst,
            "group_size": self.group_size,
            "dims": self.dims,
        }
        crc = zlib.crc32(json.dumps(meta, sort_keys=True).encode())
        for k in sorted(arrays):
            crc = zlib.crc32(np.ascontiguousarray(arrays[k]).tobytes(), crc)
        meta["crc32"] = crc
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f,
                __meta__=np.frombuffer(
                    json.dumps(meta).encode(), dtype=np.uint8
                ),
                **arrays,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def restore_slice(
        self, hst: int, path: str, *, chips: int | None = None
    ) -> dict:
        """Restore a slice written by ``checkpoint_slice`` into host
        ``hst`` — at a possibly different chip count — and fence the
        member it replaces. Counts against the migration budget."""
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(bytes(z["__meta__"]).decode())
            if meta["group_size"] != self.group_size or meta["dims"] != self.dims:
                raise ValueError(
                    f"slice shape mismatch: checkpoint is "
                    f"{meta['group_size']}x{meta['dims']}, facade group is "
                    f"{self.group_size}x{self.dims}"
                )
            arrays = {k: z[k] for k in z.files if k != "__meta__"}
            scrubbed = {k: v for k, v in meta.items() if k != "crc32"}
            crc = zlib.crc32(json.dumps(scrubbed, sort_keys=True).encode())
            for k in sorted(arrays):
                crc = zlib.crc32(
                    np.ascontiguousarray(arrays[k]).tobytes(), crc
                )
            if crc != meta["crc32"]:
                raise ValueError(f"slice CRC mismatch in {path}")
            skies = [arrays[f"sky_{i}"] for i in range(self.group_size)]
            pendings = [
                arrays[f"pending_{i}"] for i in range(self.group_size)
            ]
        budget = _migration_budget()
        if self.migrations >= budget:
            raise RuntimeError(
                f"migration budget exhausted ({budget}); raise "
                "SKYLINE_CLUSTER_MIGRATION_BUDGET to allow more"
            )
        target_chips = self._member_chips[hst] if chips is None else int(chips)
        with self._host_locks[hst]:
            old = self._members[hst]
            source_epoch = epoch_hex(old.epoch_key)
            grp = self._build_member(target_chips)
            grp.restore_all(skies, pendings)
            self._members[hst] = grp
            self._member_chips[hst] = target_chips
            # facade-global pending bookkeeping tracks the restored slice
            # (checkpoint_slice drains first, so these are zeros), not the
            # replaced member's stale counts
            for i, pd in enumerate(pendings):
                self._pending_rows[hst * self.group_size + i] = pd.shape[0]
        grp.attach_observability(profiler=self._profiler, flight=self._flight)
        self._gm_cache = None
        self.fenced_sources += 1
        self.migrations += 1
        doc = {
            "host": hst,
            "chips": target_chips,
            "reason": "restore_slice",
            "from": path,
            "source_epoch": source_epoch,
            "source_fenced": True,
        }
        self.last_migration = doc
        self._inc("cluster.migrations")
        return doc

    # -- snapshots / audit / checkpoint ----------------------------------------

    def sky_counts(self) -> np.ndarray:
        return np.concatenate([m.sky_counts() for m in self._members])

    def snapshot(self, p: int) -> np.ndarray:
        self.flush_all()
        t0 = time.perf_counter_ns()
        h, lp = self._loc(p)
        out = self._members[h].skyline_host(lp)
        self._processing_base_ns += time.perf_counter_ns() - t0
        return out

    def skyline_host(self, p: int) -> np.ndarray:
        h, lp = self._loc(p)
        return self._members[h].skyline_host(lp)

    def pending_rows_of(self, p: int) -> np.ndarray:
        h, lp = self._loc(p)
        return self._members[h].pending_rows_of(lp)

    def audit_state(self) -> tuple[list[np.ndarray], list[np.ndarray]]:
        skies: list[np.ndarray] = []
        pendings: list[np.ndarray] = []
        for h, m in enumerate(self._members):
            with self._host_locks[h]:
                s, pd = m.audit_state()
            skies.extend(s)
            pendings.extend(pd)
        return skies, pendings

    def restore_all(
        self, skies: list[np.ndarray], pendings: list[np.ndarray]
    ) -> None:
        assert len(skies) == len(pendings) == self.num_partitions
        G = self.group_size
        for h, m in enumerate(self._members):
            with self._host_locks[h]:
                m.restore_all(
                    skies[h * G: (h + 1) * G],
                    pendings[h * G: (h + 1) * G],
                )
        self.max_seen_id[:] = -1
        self.start_time_ms = [None] * self.num_partitions
        self.records_seen[:] = 0
        self._processing_base_ns = 0
        for p, pending in enumerate(pendings):
            self._pending_rows[p] = pending.shape[0]
        self._gm_cache = None

    # -- stats -----------------------------------------------------------------

    def cluster_stats(self) -> dict:
        considered = self.hosts_considered_total
        shipped, saved = self.rows_shipped_total, self.rows_saved_total
        out = {
            "hosts": self.hosts,
            "group_size": self.group_size,
            "chips_per_host": list(self._member_chips),
            "merges": self.cluster_merges,
            "hosts_pruned": self.hosts_pruned_total,
            "hosts_considered": considered,
            "host_pruned_fraction": (
                self.hosts_pruned_total / considered if considered else 0.0
            ),
            "rows_shipped": shipped,
            "rows_saved": saved,
            "ship_saved_fraction": (
                saved / (shipped + saved) if (shipped + saved) else 0.0
            ),
            "cache": {
                "hits": self.merge_cache_hits,
                "misses": self.merge_cache_misses,
            },
            "last": self.last_host_info,
            "migrations": self.migrations,
            "migration_budget": _migration_budget(),
            "fenced_sources": self.fenced_sources,
            "last_migration": self.last_migration,
        }
        if self._health is not None:
            out["health"] = self._health.doc()
        return out


class ClusterEngine(SkylineEngine):
    """``SkylineEngine`` over the multi-host facade: same config, same
    wire results, same serving/audit planes — the published skyline is
    byte-identical to the single-host engine's at every host count."""

    def __init__(
        self, config, hosts: int, chips_per_host: int = 1, tracer=None,
        telemetry=None,
    ):
        if config.ingest == "device":
            raise ValueError(
                "ingest='device' is single-device only; the cluster "
                "engine routes on host"
            )
        self.cluster_hosts = int(hosts)
        self.chips_per_host = int(chips_per_host)
        super().__init__(config, mesh=None, tracer=tracer, telemetry=telemetry)
        self.pset = ClusterPartitionSet(
            config.num_partitions,
            config.dims,
            config.buffer_size,
            hosts=self.cluster_hosts,
            chips_per_host=self.chips_per_host,
            initial_capacity=config.initial_capacity,
            tracer=self.tracer,
            flush_policy=config.flush_policy,
            overlap_rows=config.overlap_rows,
            window_capacity=config.window_capacity,
            counters=telemetry.counters if telemetry is not None else None,
        )
        self.partitions = [
            PartitionView(self.pset, i) for i in range(config.num_partitions)
        ]
        self.pset.attach_observability(
            profiler=self.profiler,
            flight=telemetry.flight if telemetry is not None else None,
            spans=telemetry.spans if telemetry is not None else None,
        )
        # host-level health: the chip scorer generalizes — indices are
        # hosts here, and quarantine drives live migration instead of
        # chip failover
        from skyline_tpu.resilience.health import ChipHealth

        self.host_health = ChipHealth(self.cluster_hosts)
        self.pset.attach_health(self.host_health)
        if telemetry is not None:
            from skyline_tpu.cluster.lease import ClusterStatus

            status = getattr(telemetry, "cluster", None)
            if status is None:
                status = ClusterStatus(node_id=f"coordinator-{os.getpid()}")
                telemetry.cluster = status
            status.coordinator_cb = self.pset.cluster_stats
            status.telemetry = telemetry

    def stats(self, include_skyline_counts: bool = False) -> dict:
        out = super().stats(include_skyline_counts)
        out["cluster"] = self.pset.cluster_stats()
        return out
