"""Host-level (third) tournament: the chip witness/summary prefilter
lifted one level up (RUNBOOK §2r).

The sharded engine's two-level merge (distributed/sharded.py) already
proves the shape: level 1 builds each unit's local skyline root, a
(2d+2)-float summary row per unit feeds ``prune_witness_mask``, and only
surviving roots enter the pairwise ``tree_pair_merge`` ladder. Hosts are
just bigger units — each host's "root" is the result of its OWN
two-level merge (or flat merge at one chip), harvested through the
uniform ``global_merge_launch``/``merge_points_device`` surface both
``PartitionSet`` and ``ShardedPartitionSet`` expose.

Why byte-identity survives a third level: ``tree_pair_merge`` emits the
stable [a|b] compaction, so the FINAL root is always the global skyline
in ascending partition id with per-partition storage order — a canonical
form independent of the merge tree's shape. Any bracketing of hosts,
chips, or partitions converges to the same bytes, which is what the
host-count × chip-count × flush-policy identity grid asserts.

Communication accounting: a host's summary is 2d+2 floats; a host whose
summary is witness-dominated ships ZERO point rows to the coordinator
(``prefilter`` theory per arxiv 1611.00423's communication-minimal
cross-node skylines; witness machinery per arxiv 2411.14968). The
coordinator records shipped rows/bytes per host so the benchmark's
skewed leg can show the fraction saved.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from skyline_tpu.stream.window import (
    _active_bucket,
    partition_summaries_device,
    prune_witness_mask,
    tree_pair_merge,
)


def host_leaf(member, want_summary: bool):
    """One host's tournament leaf: launch + harvest the member's own
    merge, materialize the padded root points device-side, and (under
    the host prune) its (2d+2) summary row — the exact shape of
    ``ShardedPartitionSet._level1_chip``, one level up.

    Returns ``(counts, surv, g, pts_dev, summary)`` with ``pts_dev`` /
    ``summary`` None when the host is empty."""
    h = member.global_merge_launch(False)
    counts, surv, g, _ = member.global_merge_harvest(h)
    pts = None
    summary = None
    if g > 0:
        w = _active_bucket(max(g, 1))
        pts = member.merge_points_device(h, w)
        if want_summary:
            summary = np.asarray(
                partition_summaries_device(
                    pts[None],
                    jnp.asarray(np.array([g], dtype=np.int32)),
                    w,
                )
            )[0]
    return counts, surv, g, pts, summary


def prune_hosts(summaries: list, alive: np.ndarray, d: int):
    """Witness prune over host summaries: ``(pruned, witness_of)`` bool /
    int64 vectors over hosts. Dead hosts contribute +inf rows (they can
    neither prune nor be pruned — same convention as the chip level)."""
    rows = [
        s if s is not None else np.full(2 * d + 2, np.inf, dtype=np.float32)
        for s in summaries
    ]
    return prune_witness_mask(np.stack(rows), alive, d)


def tournament(leaves, root_dev):
    """Pairwise merge ladder over host leaves, adjacent pairs in
    ascending host order, odd tail passing through — identical bracket
    discipline to the cross-chip level, so the final root lands in the
    canonical ascending-pid order.

    ``leaves``: ``[(vals_dev, pids_np_int32, g), ...]`` per surviving
    host, ascending. Returns ``(root_vals, root_pids, root_cnt, levels,
    candidates_per_level)``."""
    nodes = []
    for vals, pid_np, g in leaves:
        nodes.append((
            jax.device_put(vals, root_dev),
            jax.device_put(pid_np, root_dev),
            jax.device_put(np.int32(g), root_dev),
            g,
        ))
    levels = 0
    cand = [len(nodes)]
    while len(nodes) > 1:
        levels += 1
        nxt = []
        for i in range(0, len(nodes) - 1, 2):
            av, ap, ac, aub = nodes[i]
            bv, bp, bc, bub = nodes[i + 1]
            out_cap = _active_bucket(max(aub + bub, 1))
            vals, pids, cnt = tree_pair_merge(av, ap, ac, bv, bp, bc, out_cap)
            nxt.append((vals, pids, cnt, min(aub + bub, out_cap)))
        if len(nodes) % 2:
            nxt.append(nodes[-1])
        nodes = nxt
        cand.append(len(nodes))
    root_vals, root_pids, root_cnt, _ = nodes[0]
    return root_vals, root_pids, root_cnt, levels, cand
