"""Backend dispatch for the dominance hot ops.

On TPU the Pallas kernel (VMEM-tiled, min/max cascade, triangular skip) is
the fast path — see artifacts/kernels_tpu.json (benchmarks/kernels.py) for
the measured Pallas-vs-scan table at several N. On CPU (tests, virtual
meshes) Pallas would need interpret mode, so the scan kernel is used.
Resolution happens once at first call.
"""

from __future__ import annotations

import functools

import jax


@functools.lru_cache(maxsize=1)
def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def rank_cascade() -> bool:
    """``SKYLINE_RANK_CASCADE`` selects the dense-rank dominance cascade
    for the self-skyline passes (ops/pallas_dominance.py rank kernels).
    Default OFF until the hardware A/B lands: the op-count argument (2 vs 3
    VPU ops/dim) favors ranks, but rank_transform's two sorts + searchsorted
    per pass are unmeasured on TPU — run ``benchmarks/rank_cascade.py``
    (queued in scripts/tpu_round5_measure.sh, writes
    artifacts/rank_cascade_ab.json) and flip the default only on a >=1.15x
    measured win. Read lazily at trace time; already-compiled executables
    are unaffected by later changes."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_RANK_CASCADE", False)


def merge_cache_enabled() -> bool:
    """``SKYLINE_MERGE_CACHE`` gates the epoch-keyed global-merge cache in
    ``stream/batched.py``: repeated query triggers between flushes reuse the
    previous merge's result (zero kernel launches), and partially-dirty
    states merge ``cached_global ∪ dirty skylines`` instead of the full
    union. Default ON — results are provably identical (merge law +
    transitivity, see PartitionSet.global_merge_stats); set ``0`` to force
    the from-scratch full merge on every trigger (the A/B baseline the
    equivalence tests and benchmarks/merge_cache.py compare against). Read
    lazily per query, so tests can flip it per-case."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_MERGE_CACHE", True)


def delta_dirty_cutoff() -> float:
    """``SKYLINE_DELTA_CUTOFF``: max dirty-partition fraction for the
    delta-merge path. Above it the full union merge runs instead — once
    most partitions changed, ``cached_global ∪ dirty`` approaches the full
    union anyway and the delta assembly's extra executable shapes (one per
    dirty pattern) buy nothing. Default 0.75; ``0`` disables delta merges
    while keeping the exact-hit cache."""
    from skyline_tpu.analysis.registry import env_float

    return env_float("SKYLINE_DELTA_CUTOFF", 0.75)


def flush_stage_depth() -> int:
    """``SKYLINE_STAGE_DEPTH``: how many flush rounds the host stages ahead
    of the in-flight merge kernel (assemble + device_put issued before the
    previous round's kernel is awaited). 1 = double buffering (default);
    higher values deepen the pipeline at the cost of that many staged
    micro-batches resident in host+device memory; 0 disables staging
    (assemble-then-dispatch strictly in order, the pre-pipelining
    behavior)."""
    from skyline_tpu.analysis.registry import env_int

    return max(0, env_int("SKYLINE_STAGE_DEPTH", 1))


def merge_tree_enabled() -> bool:
    """``SKYLINE_MERGE_TREE`` gates the pruned tournament-tree global merge
    in ``stream/batched.py``: non-empty partitions (minus bound-pruned ones)
    merge pairwise up a binary tree so each level's quadratic kernel runs on
    a halved, already-pruned candidate set instead of one O(U²) pass over
    the full union. Default ON for d > 2 (d <= 2 keeps the sort-sweep flat
    path, which is strictly cheaper); set ``0`` to force the flat union
    merge — the A/B baseline tests/test_merge_tree.py and
    benchmarks/merge_cache.py compare against. Results are byte-identical
    either way (merge law + stable compaction order). Read lazily per
    query."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_MERGE_TREE", True)


def merge_prune_enabled() -> bool:
    """``SKYLINE_MERGE_PRUNE`` gates the O(P²·d) partition prefilter ahead
    of the tree merge: partition B is dropped wholesale when another
    partition's witness point (its min-row-sum survivor) dominates B's
    min-corner — then it dominates every point of B. The prune relation is
    a strict partial order (witness chains cannot cycle), so simultaneous
    pruning is sound and at least one partition always survives. Default
    ON; set ``0`` to feed every non-empty partition into the tree (the
    digest check in scripts/obs_smoke.sh compares both settings). Read
    lazily per query."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_MERGE_PRUNE", True)


def chip_prune_enabled() -> bool:
    """``SKYLINE_CHIP_PRUNE`` gates the CHIP-level witness prefilter in the
    sharded engine's two-level merge (``distributed/sharded.py``): each
    chip-local tournament root is summarized as one
    ``[min_corner | witness | sums]`` row and a chip whose min-corner is
    strictly dominated by another chip's witness point is skipped before
    any cross-chip transfer — whole device results never cross the
    interconnect. The soundness argument is the partition prune's
    (``merge_prune_enabled``) applied one level up, so the published bytes
    are identical either way. Default ON; set ``0`` to gather every
    non-empty chip (the A/B baseline benchmarks/sharded_engine.py and
    scripts/mesh_smoke.sh compare against). Read lazily per query."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_CHIP_PRUNE", True)


def host_prune_enabled() -> bool:
    """``SKYLINE_CLUSTER_HOST_PRUNE`` gates the HOST-level witness
    prefilter in the cluster coordinator's three-level merge
    (``cluster/merge.py``): each host's tournament root is summarized as
    one ``[min_corner | witness | sums]`` row, and a host whose
    min-corner is strictly dominated by another host's witness ships
    ZERO point rows to the coordinator — the chip prune
    (``chip_prune_enabled``) applied one level up, same soundness
    argument, so the published bytes are identical either way. Default
    ON; set ``0`` to gather every non-empty host (the A/B baseline
    benchmarks/cluster.py compares against). Read lazily per query."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_CLUSTER_HOST_PRUNE", True)


def chip_barrier_policy() -> str:
    """``SKYLINE_CHIP_BARRIER`` picks when the sharded engine writes its
    chip-consistency barrier records (``resilience/chip_wal.py``):
    ``merge`` (default) stamps every completed two-level merge with each
    chip's epoch digest so crash replay can verify all groups reconstruct
    the same global state; ``checkpoint`` writes barriers only at
    checkpoint time (fewer records, coarser replay verification);
    ``off`` disables the chip WAL plane entirely. Read lazily per
    attach/harvest."""
    from skyline_tpu.analysis.registry import env_str

    v = env_str("SKYLINE_CHIP_BARRIER", "merge")
    return v if v in ("merge", "checkpoint", "off") else "merge"


def chip_merge_deadline_ms() -> float:
    """``SKYLINE_CHIP_MERGE_DEADLINE_MS``: per-chip budget for one level-1
    tournament inside the sharded two-level merge. ``0`` (default)
    disables the bound — the historical synchronous loop, where one sick
    chip wedges the query. With a deadline the facade runs each chip's
    merge on a watchdog thread: a chip that misses the budget (after the
    ``SKYLINE_CHIP_MERGE_RETRIES``/``SKYLINE_CHIP_HEDGE_MS`` ladder) is
    EXCLUDED from this answer, the surviving-chips skyline publishes
    marked ``partial`` (RUNBOOK §2p), and ChipHealth quarantines the
    offender. Read lazily per merge launch."""
    from skyline_tpu.analysis.registry import env_float

    return max(0.0, env_float("SKYLINE_CHIP_MERGE_DEADLINE_MS", 0.0))


def failover_lock_ms() -> float:
    """``SKYLINE_CHIP_FAILOVER_LOCK_MS``: bounded wait for a chip's merge
    lock before ``failover`` captures the group's state. A slow merge
    attempt may still be computing inside the lock when its chip
    quarantines (``SKYLINE_CHIP_FAIL_THRESHOLD=1`` makes this the COMMON
    case); failover must wait it out — ``audit_state`` read concurrently
    would tear the state byte-identical healing rides on — but a truly
    wedged kernel must not stall failover forever, so past this bound
    the attempt is abandoned for this tick and retried at the next
    merge launch / worker idle tick. Read lazily per failover."""
    from skyline_tpu.analysis.registry import env_float

    return max(0.0, env_float("SKYLINE_CHIP_FAILOVER_LOCK_MS", 5000.0))


def chip_failover_enabled() -> bool:
    """``SKYLINE_CHIP_FAILOVER`` gates online partition-group failover
    (``distributed/sharded.py`` ``maybe_failover``): at merge-launch (and
    worker idle ticks) a quarantined chip's partition group is re-owned
    by a healthy chip — state carried over byte-faithfully, currency
    checked against the chip's WAL window since the last common barrier —
    and the slot heals, no stop-the-world restart. Default ON; set ``0``
    to leave quarantined chips excluded until an operator intervenes
    (answers stay degraded). Read lazily per launch."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_CHIP_FAILOVER", True)


def flush_prefilter_enabled() -> bool:
    """``SKYLINE_FLUSH_PREFILTER`` gates the quantized grid prefilter ahead
    of the flush merge path (``stream/batched.py``): each partition keeps a
    device-computed grid summary of its resident skyline (per-dim boundary
    ladder + representative-cell codes, refreshed async at flush tails), and
    incoming batch rows whose cell is strictly dominated by a representative
    cell are dropped on the host before any merge kernel launches — an
    O(B·C) byte-compare pass with C ≪ S. Sound by construction: a cell-level
    strict dominance certificate implies strict f32 dominance (see RUNBOOK
    §2g), and a stale summary only under-drops (skyline evolution preserves
    transitive dominators). Default ON; set ``0`` for the exact-only
    baseline (byte-identical output, asserted in tests/test_flush_cascade.py
    and scripts/obs_smoke.sh). Read lazily per flush."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_FLUSH_PREFILTER", True)


def mixed_precision_enabled() -> bool:
    """``SKYLINE_MIXED_PRECISION`` gates the bf16 margin pass inside the
    flush dominance kernels (``ops/sfs.py``, ``ops/pallas_dominance.py``,
    ``stream/window.py`` merge steps): pairs decided OUTSIDE an explicit
    bf16 error margin are final (bf16 runs at ~2× VPU throughput), only
    ambiguous pairs re-run in f32, so the result is bit-exact vs the pure
    f32 kernels (margin-correctness argument in RUNBOOK §2g). Default: ON
    on TPU, OFF elsewhere — XLA's CPU backend EMULATES bf16 (upcast +
    round-trip per op), which turns the "cheap" margin pass into a ~4×
    merge-kernel pessimization on the fallback (measured at n=128K 8D:
    6.1s → 23.1s). An explicit ``SKYLINE_MIXED_PRECISION=0``/``1`` always
    wins, on any backend. Threaded as a static jit argument from the flush
    orchestration, so flipping it per-call really switches executables
    (unlike trace-time env reads)."""
    from skyline_tpu.analysis.registry import env_bool

    # env_bool falls back to the default for unset/empty/unrecognized, so
    # the backend-derived default applies exactly when no explicit value set
    return env_bool("SKYLINE_MIXED_PRECISION", on_tpu())


def query_overlap_enabled() -> bool:
    """``SKYLINE_QUERY_OVERLAP`` gates the overlapped query sync in
    ``stream/engine.py``: a trigger launches the global merge and returns
    immediately, ingestion continues while the merge kernels run, and the
    result is harvested (the only blocking sync) at emission —
    ``poll_results`` / the next trigger / ``stats()``. Default ON for
    single-host engines; set ``0`` to restore the blocking
    launch-then-sync trigger path. Emitted results are identical either
    way. Read lazily per trigger."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_QUERY_OVERLAP", True)


def freshness_enabled() -> bool:
    """``SKYLINE_FRESHNESS`` gates the event-time freshness lineage
    (``telemetry/freshness.py``): per-batch event-time stamps carried
    host-side through ingest → flush → merge → publish → read, the
    ``skyline_freshness_lag_ms{stage=...}`` histograms, and the
    ``staleness_ms`` field on ``/skyline``. Pure host bookkeeping — a few
    float compares per micro-batch, nothing inside jit — so default ON;
    set ``0`` to drop even that (the A/B baseline in
    ``benchmarks/freshness.py``). Read lazily at engine construction."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_FRESHNESS", True)


def kernel_profile_enabled() -> bool:
    """``SKYLINE_KERNEL_PROFILE`` gates the per-dispatch-signature kernel
    profiler (``telemetry/profiler.py``): every ``flush/merge_kernel``
    dispatch is additionally timed under its (variant, d, N-bucket,
    backend, mp) signature and a ``kernel/<variant>`` span lands in the
    trace ring. Two ``perf_counter_ns`` reads + one lock per dispatch,
    host-side only; default ON, set ``0`` for the unprofiled baseline
    (``benchmarks/freshness.py`` A/B). Read lazily at engine
    construction."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_KERNEL_PROFILE", True)


def explain_enabled() -> bool:
    """``SKYLINE_EXPLAIN`` gates the per-query EXPLAIN plane
    (``telemetry/explain.py``): one ``QueryPlan`` minted per trigger and
    annotated host-side along launch → tree/prune → harvest → publish,
    served at ``GET /explain`` and inline via ``/skyline?explain=1``.
    Cost is a handful of counter snapshots and small dict writes per
    QUERY (zero per ingest batch, nothing inside jit), so default ON;
    set ``0`` for the no-plan baseline (``benchmarks/explain.py`` A/B).
    Read lazily at engine construction."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_EXPLAIN", True)


def audit_enabled() -> bool:
    """``SKYLINE_AUDIT`` gates the online audit plane (``audit/``): a
    sampled fraction of published snapshots (``SKYLINE_AUDIT_SAMPLE``)
    is recomputed from partition state through the independent host
    oracle and compared byte-for-byte, with divergences frozen into
    repro bundles under ``SKYLINE_AUDIT_DIR``. Checks run host-side
    after the answer is already published — nothing enters jit and the
    hot path only pays a sampling-accumulator update — so default ON;
    set ``0`` for the unaudited baseline (``benchmarks/audit.py`` A/B).
    Read lazily at engine construction."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_AUDIT", True)


def fleet_enabled() -> bool:
    """``SKYLINE_FLEET`` gates the per-chip fleet plane
    (``telemetry/fleet.py``) on the sharded engine: ingest/flush/merge
    accounting per partition group, level-2 prune outcomes, interconnect
    row counts, the imbalance index + skew ring, the per-chip child spans
    under the tournament merge, and ``GET /fleet``. Cost is a few list
    adds per flush/merge on the HOST side of an already host-orchestrated
    tournament (nothing inside jit; the identity law is unaffected —
    ``benchmarks/fleet.py`` asserts byte-identity), so default ON; set
    ``0`` for the unobserved baseline. No-op on flat (non-sharded)
    engines. Read lazily at engine construction."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_FLEET", True)


def workload_enabled() -> bool:
    """``SKYLINE_WORKLOAD`` gates the streaming workload characterizer
    (``telemetry/workload.py``): a bounded per-batch sample feeds
    per-dimension quantile sketches, a correlation estimate, and drift
    detection, classifying the stream uniform/correlated/anti_correlated
    — the regime tag EXPLAIN stamps on every answered query and the
    substrate the ROADMAP's auto-tuner will read. Cost is one numpy pass
    over at most ``SKYLINE_WORKLOAD_SAMPLE_CAP`` rows per ingest batch
    (host-side, nothing inside jit, skyline bytes untouched), so default
    ON; set ``0`` for the uncharacterized baseline
    (``benchmarks/fleet.py`` A/B). Read lazily at engine construction."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_WORKLOAD", True)


def tuner_enabled() -> bool:
    """``SKYLINE_TUNER`` gates the closed-loop dispatch tuner
    (``telemetry/tuner.py``): an online controller consuming the
    WorkloadCharacterizer regime + drift events, KernelProfiler EMAs, and
    SLO burn, and retuning cascade-table pins/knobs per (regime,
    signature) with bounded per-epoch moves. Safe by construction — it
    may only select table rows whose byte-identity oracle is registered
    (``ops/cascade.py``), explicit env knobs always beat its overrides,
    and it stays passive until a workload epoch closes AND
    ``SKYLINE_TUNER_EPOCH_S`` elapses — so default ON; set ``0`` for the
    static-dispatch baseline (``benchmarks/tuner.py`` A/B). Read lazily
    at engine construction."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_TUNER", True)


def profile_cost_enabled() -> bool:
    """``SKYLINE_PROFILE_COST`` additionally captures XLA
    ``cost_analysis()`` FLOPs/bytes per dispatch signature via a one-shot
    ahead-of-time lower+compile the first time each signature is seen.
    The AOT compile is seconds-expensive and its executable is discarded,
    so default OFF — flip on for a profiling session when ``/profile``
    should carry arithmetic-intensity columns. Read lazily per
    signature."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_PROFILE_COST", False)


def sorted_sfs_mode() -> str:
    """``SKYLINE_SORTED_SFS``: the sorted-order SFS dominance cascade for
    d > 2 (``ops/sorted_sfs.py`` — dedup + f64 sum-sort + blocked scan
    with exact in-block tiles for the ambiguous equal-sum band;
    byte-identical masks, see RUNBOOK §2m). ``auto`` (default) picks per
    (d, N, backend) signature from measured KernelProfiler wall data —
    each candidate runs once to seed its EMA, then the cheaper one wins;
    ``on`` forces the sorted host path, ``off`` keeps the device kernels
    only. Host NumPy, so it only ever applies to concrete (non-traced)
    arrays on non-TPU backends — inside jit and on TPU the device kernels
    always run. Read lazily per call."""
    from skyline_tpu.analysis.registry import env_str

    v = env_str("SKYLINE_SORTED_SFS", "auto")
    return v if v in ("auto", "on", "off") else "auto"


def device_cascade_mode() -> str:
    """``SKYLINE_DEVICE_CASCADE``: the device-side sorted dominance
    cascade (``ops/device_cascade.py`` — on-device dedup + f32 sum-key
    sort with a certified error radius + blocked buffer/band scans;
    byte-identical masks, see RUNBOOK §2t). Unlike the §2m host cascade
    it is pure lax over static shapes, so it applies ON TPU and INSIDE
    jit. ``auto`` (default) picks per (variant, d, N-bucket, backend,
    mp) signature from measured KernelProfiler wall data — concrete
    calls explore and record, traced call sites only swap it in on
    existing measured evidence (nothing records under a tracer); ``on``
    forces the cascade everywhere including under trace; ``off`` keeps
    the quadratic device kernels. Read lazily per call (trace time for
    jitted callers)."""
    from skyline_tpu.analysis.registry import env_str

    v = env_str("SKYLINE_DEVICE_CASCADE", "auto")
    return v if v in ("auto", "on", "off") else "auto"


def choose_variant(profiler, candidates, d: int, n: int, mp: bool = False):
    """Profiler-driven dispatch: pick among ``candidates`` (variant-name
    strings, preference-ordered) under signature (d, N-bucket, backend).

    Any candidate without measured wall data runs next (first listed
    wins), so each variant seeds its EMA exactly once per signature;
    after that the minimum EMA wins every time. Exploration is
    per-signature STICKY (``KernelProfiler.claim_explore``): the first
    caller to claim an unmeasured candidate runs it; until its record
    lands, other calls under the same signature fall back to the best
    measured candidate instead of re-paying the cold path — adding a new
    candidate row can no longer stall a hot flush loop repeatedly. With
    no profiler at all, the first candidate is the standing choice."""
    if profiler is None:
        return candidates[0]
    claim = getattr(profiler, "claim_explore", None)
    emas = []
    unmeasured = []
    for c in candidates:
        e = profiler.ema_ms(c, d, n, mp)
        if e is None:
            unmeasured.append(c)
        else:
            emas.append((e, c))
    if not unmeasured:
        return min(emas)[1]
    if claim is None:
        # foreign profiler without the claim API: legacy explore-first
        return unmeasured[0]
    for c in unmeasured:
        if claim(c, d, n, mp):
            return c
    # every unmeasured candidate is already claimed by an in-flight
    # exploration: serve measured data rather than stalling again
    if emas:
        return min(emas)[1]
    return candidates[0]


# the profiler skyline_mask_auto's host-path records into / chooses from;
# the engine shares its telemetry profiler here so /profile and EXPLAIN
# see mask dispatches too (tests and bare callers get a private default)
_MASK_PROFILER = None


def register_profiler(profiler) -> None:
    """Share an engine's KernelProfiler with the dispatch chooser (last
    registration wins — profiler data is observability, not state)."""
    global _MASK_PROFILER
    _MASK_PROFILER = profiler


def _mask_profiler():
    global _MASK_PROFILER
    if _MASK_PROFILER is None:
        from skyline_tpu.telemetry.profiler import KernelProfiler

        _MASK_PROFILER = KernelProfiler()
    return _MASK_PROFILER


def _is_concrete(x) -> bool:
    """True when ``x`` is a real array (host or committed device), not a
    tracer — the jit boundary the host path must never cross."""
    import jax

    return not isinstance(x, jax.core.Tracer)


def skyline_mask_auto(x, valid=None):
    """Survivor mask with the fastest kernel for the active backend.

    The variant decision lives in the declarative cascade table
    (``ops/cascade.py resolve_mask`` — env modes force/exclude first,
    ``auto`` races measured EMAs, traced calls swap only on evidence,
    tuner pins short-circuit the race); this function only EXECUTES the
    chosen row, with the historical recording discipline (auto races
    over concrete arrays sync + record for honest EMA walls, forced
    device paths and traced calls dispatch bare)."""
    if x.shape[1] <= 2:
        # d <= 2 needs no pairwise work at all: sort + prefix-min sweep
        # (ops/sweep2d.py), O(n log n) on every backend — at the 262k-row
        # union bucket that replaces ~69G pair-ops with one sort
        from skyline_tpu.ops.sweep2d import skyline_mask_sweep

        return skyline_mask_sweep(x, valid)
    from skyline_tpu.ops import cascade

    n, d = x.shape
    concrete = _is_concrete(x) and (valid is None or _is_concrete(valid))
    # mp only keys TPU signatures (the host races always recorded under
    # mp=False, even with SKYLINE_MIXED_PRECISION exported)
    mp = mixed_precision_enabled() if on_tpu() else False
    prof = _mask_profiler()
    variant, rec = cascade.resolve_mask(d, n, concrete, prof, mp=mp)

    if variant in ("mask_pallas", "mask_rank_pallas"):
        from skyline_tpu.ops.pallas_dominance import (
            skyline_mask_pallas,
            skyline_mask_rank_pallas,
        )

        kern = (
            skyline_mask_rank_pallas
            if variant == "mask_rank_pallas"
            else skyline_mask_pallas
        )
        if not rec:
            return kern(x, valid)
        with prof.record(variant, d, n, mp):
            out = kern(x, valid)
            out.block_until_ready()  # honest wall for the EMA compare
        return out
    if variant == "mask_device_cascade":
        from skyline_tpu.ops.device_cascade import device_cascade_mask

        if not rec:
            return device_cascade_mask(x, valid)
        with prof.record("mask_device_cascade", d, n, mp):
            out = device_cascade_mask(x, valid)
            out.block_until_ready()
        return out
    if variant == "sorted_sfs_mask":
        import jax.numpy as jnp
        import numpy as np

        from skyline_tpu.ops.sorted_sfs import sorted_skyline_mask_np

        with prof.record("sorted_sfs_mask", d, n):
            out = jnp.asarray(
                sorted_skyline_mask_np(
                    np.asarray(x),
                    None if valid is None else np.asarray(valid),
                )
            )
        return out
    from skyline_tpu.ops.block_skyline import skyline_mask_scan

    if not rec:
        return skyline_mask_scan(x, valid)
    with prof.record("mask_scan", d, n):
        out = skyline_mask_scan(x, valid)
        out.block_until_ready()  # honest wall for the EMA compare
    return out


def skyline_keep_np(x):
    """Survivor mask of a host (n, d) array via the backend's best kernel:
    pad to a tile-friendly power-of-two capacity, mask on device, slice
    back. The one shared implementation of the pad/mask/slice idiom (engine
    global merge, sliding-window buckets)."""
    import jax.numpy as jnp
    import numpy as np

    from skyline_tpu.utils.buckets import next_pow2

    n, d = x.shape
    if n == 0:
        return np.zeros((0,), dtype=bool)
    cap = next_pow2(n, min_cap=1024)
    pad = np.full((cap, d), np.inf, dtype=np.float32)
    pad[:n] = x
    valid = np.arange(cap) < n
    return np.asarray(skyline_mask_auto(jnp.asarray(pad), jnp.asarray(valid)))[:n]


def skyline_of_np(x, dims: int):
    """Exact skyline points of a host (n, d) array (see skyline_keep_np)."""
    import numpy as np

    if x.shape[0] == 0:
        return np.empty((0, dims), dtype=np.float32)
    return x[skyline_keep_np(x)]
