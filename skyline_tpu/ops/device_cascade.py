"""Device-side sorted dominance cascade (fully traced, jit-safe).

The host cascade (``ops/sorted_sfs.py``, RUNBOOK §2m) killed the quadratic
flush kernels on concrete non-TPU inputs, but its tracer guard left every
TPU / jitted path on the O(N²) SFS tiles. This module is the same
sort-and-scan structure expressed in pure lax ops with static shapes, so it
runs *inside* jit and on TPU:

1.  **Fold** ``-0.0 -> +0.0`` on a selection-only copy (comparisons are
    unaffected — ±0.0 compare equal — but equal tuples become bit-equal,
    which the dedup needs). Rows that are invalid or contain NaN are
    replaced wholesale with all-NaN: such rows never dominate and are never
    dominated (every NaN comparison is False), NaN keys sort last, and the
    padding rows need no separate handling.
2.  **One sort** (``jnp.lexsort``) with the f32 row sum as the primary key
    and the folded columns as tie-breakers — this yields the approximate
    dominance order AND makes exact duplicates adjacent.
3.  **Dedup** via adjacent-equal segment ids: only each segment's first row
    (the *representative*) is a candidate; every other member inherits the
    representative's fate at the end (duplicates survive or die together,
    matching ``skyline_mask``).
4.  **Blocked scan**: candidates stream through in sort order, each block
    pruned against (a) the grow-only buffer of surviving representatives
    from earlier blocks, (b) itself (full pairwise — see the radius note),
    and (c) the *ambiguous band* of later blocks whose certified key range
    overlaps this block's. Survivors append to the buffer.

**The f32 error-radius argument.** f64 is unavailable on TPU, so the sort
key is an f32 row sum, which is NOT exactly monotone under coordinate-wise
≤: a dominator can sort strictly after its victim when rounding flips the
key order. Instead of assuming exact ties we certify a per-row radius

    r_i = (d - 1) * 2**-23 * sum_k |x_ik|

which bounds |key_i − exact_sum_i|: a left-to-right f32 summation of d
terms has first-order error ≤ (d−1)·u·Σ|x_k| with unit roundoff u = 2⁻²⁴,
and doubling u to 2⁻²³ strictly absorbs the higher-order terms (valid for
any d the hardware can hold) plus the rounding of r itself. If w dominates
v then exact_sum(w) ≤ exact_sum(v), hence ``lo(w) = key−r ≤ hi(v) = key+r``
— so scanning every later block j with ``min_j(lo) ≤ max_b(hi)`` (exact
pairwise, rectangular tiles) catches every dominator the sort misplaced.
NaN keys (mixed ±inf rows) take lo=−inf/hi=+inf, i.e. their block is never
skipped; ±inf sums make r=+inf with the same effect. Nothing relies on
fp monotonicity.

The in-block self-prune deliberately uses the **full** (non-triangular)
pairwise tile: the triangular skip assumes a dominator never sorts more
than one tile after its victim, which equal-f32-key adversaries violate
(see RUNBOOK §2t) — the widened band subsumes that assumption.

**Why kills are sound**: a row is only ever dropped by exact strict
dominance from a real valid non-NaN row (the bf16 pre-drop under ``mp``
certifies a *subset* of true f32 dominance, RUNBOOK §2g). **Why the scan is
complete**: every truly-dominated candidate v has a true-survivor dominator
w (strict dominance is a strict partial order; follow the chain to a
maximal element). w is never killed, so if w sorts in an earlier block it
is in the buffer before v's block runs; same block → full self-prune;
later block → the certified band above. Hence the output mask equals
``skyline_mask`` exactly — byte-identity at mask, flush-append, and
published-digest level.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from skyline_tpu.ops.dominance import (
    PAD_VALUE,
    compact,
    dominated_by,
    strictly_dominated_bf16,
)
from skyline_tpu.utils.buckets import next_pow2

# bf16 pre-drop prefix (mirrors ops.sfs._MP_PREFIX): under mp, each block
# first drops rows certifiably dominated by the buffer's first rows —
# the cheapest rows to be dominated by, since they have the smallest sums
_MP_PREFIX = 512

# bumped at Python trace time inside the jitted core — a witness that the
# cascade really entered a jit trace (scripts/obs_smoke.sh asserts it goes
# up exactly when a fresh (shape, config) signature compiles)
_TRACE_COUNT = 0


def cascade_trace_count() -> int:
    """How many times the cascade core has been *traced* (not dispatched)."""
    return _TRACE_COUNT


def device_cascade_block() -> int:
    """``SKYLINE_DEVICE_CASCADE_BLOCK``: scan block size, rounded to a
    power of two (buffer chunks, self-prune tiles, and band tiles are all
    this size). Default 2048 — one Pallas col-tile."""
    from skyline_tpu.analysis.registry import env_int

    b = env_int("SKYLINE_DEVICE_CASCADE_BLOCK", 2048)
    return next_pow2(max(1, b), min_cap=8)


def _rows_equal_prev(xs: jax.Array) -> jax.Array:
    """eq[i] = row i equals row i-1 (NaN-aware: NaN slots match NaN slots;
    eq[0] is meaningless and masked by the caller)."""
    prev = jnp.roll(xs, 1, axis=0)
    return jnp.all((xs == prev) | (jnp.isnan(xs) & jnp.isnan(prev)), axis=1)


@functools.partial(
    jax.jit, static_argnames=("block", "mp", "use_pallas", "interpret")
)
def _cascade_core(x, valid, block: int, mp: bool, use_pallas: bool,
                  interpret: bool):
    """Survivor mask over padded (n_pad, d) points; n_pad % block == 0."""
    global _TRACE_COUNT
    _TRACE_COUNT += 1
    n_pad, d = x.shape
    nb = n_pad // block

    # selection-only copy: fold -0.0, neutralize invalid/NaN rows to
    # all-NaN (never dominate, never dominated, sort last)
    inert = ~valid | jnp.any(jnp.isnan(x), axis=1)
    xc = jnp.where(inert[:, None], jnp.float32(jnp.nan),
                   x + jnp.float32(0.0))

    key = jnp.sum(xc, axis=1)
    radius = jnp.float32((d - 1) * 2.0 ** -23) * jnp.sum(jnp.abs(xc), axis=1)
    lo = key - radius
    hi = key + radius
    lo = jnp.where(jnp.isnan(lo), -jnp.inf, lo)
    hi = jnp.where(jnp.isnan(hi), jnp.inf, hi)

    # one sort: sum key primary (approximate dominance order), folded
    # columns as tie-breakers (exact duplicates become adjacent)
    perm = jnp.lexsort([xc[:, j] for j in range(d - 1, -1, -1)] + [key])
    xs = xc[perm]
    valid_s = valid[perm]
    inert_s = inert[perm]

    iota = jnp.arange(n_pad)
    seg_start = (iota == 0) | ~_rows_equal_prev(xs)
    rep_idx = lax.cummax(jnp.where(seg_start, iota, 0))
    cand_ok = seg_start & ~inert_s
    # non-candidates (duplicate members, inert rows) become all-NaN rows:
    # dominance-neutral both ways, so the scan needs no validity vectors
    cand = jnp.where(cand_ok[:, None], xs, jnp.float32(jnp.nan))
    lo_s = jnp.where(cand_ok, lo[perm], jnp.inf)
    hi_s = jnp.where(cand_ok, hi[perm], -jnp.inf)
    block_lo = lo_s.reshape(nb, block).min(axis=1)
    block_hi = hi_s.reshape(nb, block).max(axis=1)

    prefix_n = min(_MP_PREFIX, n_pad)
    ones_blk = jnp.ones((block,), dtype=bool)

    if use_pallas:
        from skyline_tpu.ops.pallas_dominance import (
            dominated_by_any_pallas,
            dominated_by_pallas,
        )

    def body(carry, b):
        buf, count = carry
        blk = lax.dynamic_slice(cand, (b * block, 0), (block, d))
        alive = lax.dynamic_slice(cand_ok, (b * block,), (block,))

        if mp:
            # bf16 margin pre-drop against the buffer prefix (bit-exact:
            # certified True is a proof of f32 strict dominance)
            pref = lax.slice(buf, (0, 0), (prefix_n, d))
            pv = jnp.arange(prefix_n) < count
            alive = alive & ~strictly_dominated_bf16(blk, pref, x_valid=pv)

        # (a) resident survivor buffer, chunked; empty chunks skipped
        def chunk_body(c, alive):
            start = c * block

            def hit(a):
                chunk = lax.dynamic_slice(buf, (start, 0), (block, d))
                if use_pallas:
                    cv = (start + jnp.arange(block)) < count
                    dom = dominated_by_pallas(
                        chunk.T, cv, blk.T, interpret=interpret, mp=mp
                    )
                else:
                    # +inf fill rows never dominate; no validity needed
                    dom = dominated_by(blk, chunk)
                return a & ~dom

            return lax.cond(start < count, hit, lambda a: a, alive)

        alive = lax.fori_loop(0, nb, chunk_body, alive)

        # (b) in-block: FULL pairwise — the triangular skip's "dominator
        # within one tile" assumption fails under equal-f32-key collisions
        if use_pallas:
            dom_self = dominated_by_any_pallas(
                blk.T, ones_blk, triangular=False, interpret=interpret,
                mp=mp,
            )
        else:
            dom_self = dominated_by(blk, blk)
        alive = alive & ~dom_self

        # (c) ambiguous band: later blocks whose certified lo range
        # reaches back into this block's hi range (dominated rows acting
        # as dominators are fine — dominance is transitive)
        hi_b = block_hi[b]

        def band_body(j, alive):
            def hit(a):
                blk_j = lax.dynamic_slice(cand, (j * block, 0), (block, d))
                if use_pallas:
                    dom = dominated_by_pallas(
                        blk_j.T, ones_blk, blk.T, interpret=interpret,
                        mp=mp,
                    )
                else:
                    dom = dominated_by(blk, blk_j)
                return a & ~dom

            return lax.cond(block_lo[j] <= hi_b, hit, lambda a: a, alive)

        alive = lax.fori_loop(b + 1, nb, band_body, alive)

        # append surviving representatives (stable compaction keeps sort
        # order; count + block <= n_pad since count <= b*block)
        vals, _, cnt = compact(blk, alive, block)
        buf = lax.dynamic_update_slice(buf, vals, (count, 0))
        return (buf, count + cnt), alive

    buf0 = jnp.full((n_pad, d), PAD_VALUE, dtype=xc.dtype)
    (_, _), alive_blocks = lax.scan(
        body, (buf0, jnp.int32(0)), jnp.arange(nb)
    )
    alive_all = alive_blocks.reshape(n_pad)
    # members inherit their representative's fate; inert valid rows (NaN
    # rows) survive unconditionally per the engine's semantics
    keep_sorted = (alive_all[rep_idx] | inert_s) & valid_s
    return jnp.zeros((n_pad,), dtype=bool).at[perm].set(keep_sorted)


def device_cascade_mask(x, valid=None):
    """Survivor mask via the device cascade — semantically identical to
    ``skyline_mask`` / ``skyline_mask_auto`` (same rows, same order, the
    mask indexes the ORIGINAL row order). Safe to call on tracers: every
    step is lax ops over static shapes."""
    n, d = x.shape
    if n == 0:
        return jnp.zeros((0,), dtype=bool)
    from skyline_tpu.ops.dispatch import mixed_precision_enabled, on_tpu
    from skyline_tpu.ops.sfs import pallas_interpret

    interpret = bool(pallas_interpret())
    use_pallas = on_tpu() or interpret
    mp = mixed_precision_enabled()
    # Pallas tiles need lane-aligned blocks; the pure-jnp path can afford
    # small blocks (the band-widening soundness test forces tiny ones)
    blk = device_cascade_block()
    if use_pallas:
        blk = max(blk, 1024)
    n_pad = next_pow2(n, min_cap=1024 if use_pallas else 64)
    blk = min(blk, n_pad)
    x = jnp.asarray(x, dtype=jnp.float32)
    if valid is None:
        valid = jnp.ones((n,), dtype=bool)
    if n_pad != n:
        x = jnp.concatenate(
            [x, jnp.full((n_pad - n, d), PAD_VALUE, dtype=jnp.float32)]
        )
        valid = jnp.concatenate(
            [valid, jnp.zeros((n_pad - n,), dtype=bool)]
        )
    keep = _cascade_core(
        x, valid, block=blk, mp=mp, use_pallas=use_pallas,
        interpret=interpret,
    )
    return keep[:n]


def device_cascade_keep(rows, old):
    """Survivor mask of ``rows`` against a resident skyline ``old`` —
    survivors of ``old ∪ rows`` restricted to ``rows``, the exact set the
    device ``sfs_round`` appends (same contract as ``sorted_sfs_keep``,
    computed on device instead of host NumPy). Host in, host out."""
    import numpy as np

    rows = np.asarray(rows, dtype=np.float32)
    old = np.asarray(old, dtype=np.float32)
    if rows.shape[0] == 0:
        return np.zeros((0,), dtype=bool)
    if old.shape[0] == 0:
        return np.asarray(device_cascade_mask(jnp.asarray(rows)))
    union = np.concatenate([old, rows], axis=0)
    keep = np.asarray(device_cascade_mask(jnp.asarray(union)))
    return keep[old.shape[0]:]
