"""Sorted-order SFS dominance cascade for d > 2 — the host sibling of the
device SFS kernels (ISSUE 11).

``flush/merge_kernel`` was ~98% of the BENCH_r06 profiled window, and the
profile decomposes into exactly two cost modes (measured on the bench's
8-D anti-correlated mr-angle stream):

- the **duplicate-heavy partition**: the reference's 8-D anti-correlated
  generator clips ~44% of rows to the all-zero origin (negative sum
  targets truncate to 0), mr-angle routes every one of them to partition
  0, and duplicates never dominate each other — so 57k identical rows
  all survive while the dense SFS pays ~N²/2 pairwise work to discover
  zero dominations (5.95s of the 8.4s local flush);
- the **tiny-skyline heavy partition**: 63k spread-sum rows collapse to
  a 17-row skyline, but the block self-prune pays B² per block and the
  buffer pass never exploits that victims die against the first few
  strong dominators (1.59s).

The sorted cascade kills both modes exactly:

1. **dedup** — group byte-identical tuples (after normalizing -0.0 to
   +0.0 so numeric equality and byte equality coincide); every copy of a
   unique tuple shares one dominance verdict, so the all-zero partition
   collapses to a single candidate. After dedup, distinct rows that
   compare ``all(<=)`` are automatically strict somewhere, so the scan
   needs only one comparison per dimension per pair.
2. **sum-sorted scan** — sort unique tuples by their float64 row sum
   ascending (fixed-order rounding is monotone, so a dominator's key is
   <= its victim's key; ties are possible and are exactly the "ambiguous
   band") and stream them in blocks against a compact survivor buffer
   that only ever grows. Buffer chunks are visited smallest-sum-first —
   the strongest dominators — and dead victims are compressed out after
   every chunk, so a tiny-skyline stream does ~N·S work instead of
   N²/2.
3. **in-block pairwise tiles** — each block is closed with one exact
   dense pass over its own buffer-surviving rows. Because blocks are
   contiguous in sum order, every equal/near-sum ambiguous pair lands
   either in one block (caught here) or across blocks (caught by the
   full per-pair check of the buffer pass), with no epsilon to tune:
   soundness needs only "a dominator never sorts after its victim's
   block", which the monotone key guarantees.

Semantics are exactly ``ops.dominance.skyline_mask``: minimization,
``all(<=) & any(<)``, duplicates all survive, NaN rows neither dominate
nor are dominated (they always survive), +inf rows are dominance-neutral
dominators, invalid rows are excluded both as dominators and survivors.
Rows mixing +inf and -inf have a NaN sum — no usable sort key — and take
a tiny exact pairwise detour instead.

Everything here is selection-only host NumPy: no arithmetic ever touches
the returned rows, so byte-identity with the device kernels follows from
mask equality (asserted across the kind × d × N grid by
``benchmarks/sorted_sfs.py`` and ``tests/test_sorted_sfs.py``).

This path cannot run inside jit (it is host code; the jaxpr audit
asserts it never leaks into a trace) — ``dispatch.skyline_mask_auto``
only routes concrete non-TPU arrays here, and ``stream/batched.py``'s
lazy flush picks it per (d, N, backend) signature from measured
KernelProfiler wall data.
"""

from __future__ import annotations

import numpy as np

from skyline_tpu.analysis.registry import env_int

__all__ = [
    "sorted_skyline_mask_np",
    "sorted_sfs_keep",
    "sorted_sfs_block",
]


def sorted_sfs_block() -> int:
    """``SKYLINE_SORTED_SFS_BLOCK``: max scan-block width (rows per
    in-block exact tile). Blocks start at 1024 and double up to this cap
    — bigger blocks amortize the buffer pass, smaller ones keep the
    B×B in-block tile cheap when everything survives. The default is the
    flush buffer size that measured best on the bench grid."""
    return max(64, env_int("SKYLINE_SORTED_SFS_BLOCK", 8192))


# buffer chunk width for the strongest-first compression pass; fixed —
# small enough that the (chunk × alive) tile stays cache-resident, big
# enough that the per-chunk python overhead is noise
_CHUNK = 1024


def _dominated_any(dominators: np.ndarray, victims: np.ndarray) -> np.ndarray:
    """(m,) bool: victim j is dominated by SOME dominator row.

    Caller guarantees dominators and victims are distinct-as-tuples
    normalized rows (post-dedup, -0.0 folded into +0.0), so ``all(<=)``
    between different rows implies strictness and one comparison per
    dimension suffices. The per-dimension accumulate with an early bail
    keeps the peak intermediate at one (n, m) bool tile."""
    le = dominators[:, 0:1] <= victims[None, :, 0]
    for k in range(1, dominators.shape[1]):
        if not le.any():
            break
        le &= dominators[:, k : k + 1] <= victims[None, :, k]
    return le.any(axis=0)


def _self_prune(rows: np.ndarray) -> np.ndarray:
    """(b,) bool keep-mask of one block against itself (exact dense tile;
    rows are distinct normalized tuples, see ``_dominated_any``)."""
    b = rows.shape[0]
    if b <= 1:
        return np.ones(b, bool)
    le = rows[:, 0:1] <= rows[None, :, 0]
    for k in range(1, rows.shape[1]):
        le &= rows[:, k : k + 1] <= rows[None, :, k]
    np.fill_diagonal(le, False)
    return ~le.any(axis=0)


def _scan_unique(uniq: np.ndarray) -> np.ndarray:
    """Keep-mask over distinct normalized tuples — the sorted-order SFS
    scan itself (steps 2 and 3 of the module docstring)."""
    m, _ = uniq.shape
    keep = np.zeros(m, bool)
    with np.errstate(invalid="ignore"):
        s = uniq.astype(np.float64).sum(axis=1)
    special = np.isnan(s)  # mixed ±inf rows: no usable sort key
    core = np.flatnonzero(~special)
    order = np.argsort(s[core], kind="stable")
    core = core[order]
    U = uniq[core]
    k = core.size

    buf: list[np.ndarray] = []  # survivor arrays, ascending-sum order
    B_max = sorted_sfs_block()
    B = min(1024, B_max)
    i = 0
    while i < k:
        blk = U[i : i + B]
        pos = np.arange(i, min(i + B, k))
        alive = np.ones(blk.shape[0], bool)
        # buffer pass: strongest (smallest-sum) chunks first, victims
        # compressed out as soon as anything kills them
        for barr in buf:
            for j in range(0, barr.shape[0], _CHUNK):
                if not alive.any():
                    break
                ai = np.flatnonzero(alive)
                dead = _dominated_any(barr[j : j + _CHUNK], blk[ai])
                if dead.any():
                    alive[ai[dead]] = False
            if not alive.any():
                break
        # in-block exact tile: the ambiguous equal/near-sum band
        if alive.any():
            ai = np.flatnonzero(alive)
            alive[ai[~_self_prune(blk[ai])]] = False
        if alive.any():
            buf.append(blk[alive])
            keep[core[pos[alive]]] = True
        i += B
        B = min(B * 2, B_max)

    if special.any():
        # NaN-sum rows: exact pairwise both ways against everything.
        # These rows are vanishingly rare (a row must mix +inf and -inf),
        # so the dense detour is O(|special| * m).
        spec_idx = np.flatnonzero(special)
        for si in spec_idx:
            row = uniq[si]
            others = np.delete(np.arange(m), si)
            if not _dominated_any(uniq[others], row[None, :]).any():
                keep[si] = True
        # ...and as dominators over the core survivors
        surv = np.flatnonzero(keep & ~special)
        if surv.size:
            dead = _dominated_any(uniq[spec_idx], uniq[surv])
            keep[surv[dead]] = False
    return keep


def sorted_skyline_mask_np(x, valid=None) -> np.ndarray:
    """Exact survivor mask of an (n, d) host array — byte-for-byte the
    same mask ``ops.dominance.skyline_mask`` computes, via the sorted
    cascade (see module docstring). Returns an (n,) numpy bool array."""
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[0]
    out = np.zeros(n, bool)
    if n == 0:
        return out
    if valid is None:
        vidx = np.arange(n)
    else:
        vidx = np.flatnonzero(np.asarray(valid))
        if vidx.size == 0:
            return out
    xv = x[vidx]
    # NaN rows: never dominate, never dominated -> always survive
    nanrow = np.isnan(xv).any(axis=1)
    if nanrow.any():
        out[vidx[nanrow]] = True
        xv = xv[~nanrow]
        vidx = vidx[~nanrow]
        if vidx.size == 0:
            return out
    # fold -0.0 into +0.0 so byte dedup equals numeric dedup (the only
    # IEEE pair of distinct bit patterns that compare numerically equal);
    # selection-only: the fold never reaches the caller's rows
    xv = xv + np.float32(0.0)
    uniq, inv = np.unique(xv, axis=0, return_inverse=True)
    if uniq.shape[0] == 1:
        out[vidx] = True  # all duplicates of one tuple: everything lives
        return out
    out[vidx] = _scan_unique(uniq)[inv.reshape(-1)]
    return out


def sorted_sfs_keep(rows: np.ndarray, old: np.ndarray | None = None) -> np.ndarray:
    """Flush helper: keep-mask over ``rows`` of the survivors of
    ``old ∪ rows`` restricted to ``rows`` — exactly the set the device
    SFS rounds append (new-window rows not dominated by the resident
    skyline or by any other new row; old rows dominated by new ones are
    later removed by ``sfs_cleanup``, same as the device path)."""
    rows = np.asarray(rows, dtype=np.float32)
    if old is None or old.shape[0] == 0:
        return sorted_skyline_mask_np(rows)
    old = np.asarray(old, dtype=np.float32)
    union = np.concatenate([old, rows], axis=0)
    return sorted_skyline_mask_np(union)[old.shape[0] :]
