"""The declarative dispatch cascade table — one source of truth for every
variant/path/gate choice the engine makes.

Seven perf PRs grew a gated-variant matrix (sweep/SFS/sorted-SFS/device-
cascade, tree vs flat merge, grid prefilter, bf16, cache/delta, chip/host
prune) spread across ad-hoc env checks and two separate ``choose_variant``
call sites. This module collapses that into ONE table: each stage/variant
is a :class:`CascadeRow` declaring its applicability (backend, dimension
bounds, traced/meshed legality), the legacy knob that gates it, the
KernelProfiler signature its cost is measured under, the knobs a tuner may
move for it, and — crucially — the **byte-identity oracle** that proves
the row interchangeable with its siblings. The five legacy dispatch sites
(``dispatch.skyline_mask_auto``, the lazy-flush chooser, the global-merge
path decision, and the chip/host prune gates) all resolve here.

Resolution semantics are EXACTLY the historical ones (tests/
test_cascade_table.py pins the grid): explicit env modes force or exclude
rows first, ``auto`` races the applicable candidates through the measured
profiler EMAs (``dispatch.choose_variant`` sticky exploration), traced
call sites only swap on existing measured evidence. On top of that sit two
tuner surfaces, both inert until an online controller writes them:

- **pins**: a learned per-(stage, d, N-bucket, backend, mp) winner that
  short-circuits the EMA race. A pin is accepted ONLY for a row whose
  byte-identity oracle is registered in :data:`ORACLES` — the audit-plane
  hard rule — and only among the candidates the legacy logic would have
  raced anyway, so a pin can never select a row an env knob excluded.
- **overrides**: table-scoped knob values (delta cutoff, prefilter, tree)
  consulted by the ``_eff_*`` readers. An EXPLICIT env setting always
  wins: ``set_override`` refuses env-pinned knobs and the readers
  re-check at read time, so an operator export beats the controller
  mid-flight without a restart.

``table_doc()`` renders the whole thing (rows, oracles, pins, overrides)
for ``GET /dispatch`` on both HTTP surfaces.
"""

from __future__ import annotations

import dataclasses
import os
import threading

from skyline_tpu.ops.dispatch import (
    choose_variant,
    chip_prune_enabled,
    delta_dirty_cutoff,
    device_cascade_mode,
    flush_prefilter_enabled,
    host_prune_enabled,
    merge_cache_enabled,
    merge_prune_enabled,
    merge_tree_enabled,
    on_tpu,
    rank_cascade,
    sorted_sfs_mode,
)
from skyline_tpu.telemetry.profiler import n_bucket


@dataclasses.dataclass(frozen=True)
class CascadeRow:
    """One variant/path/gate of the dispatch cascade.

    ``name`` is the KernelProfiler variant signature where applicable
    (the closed vocabulary in ``stream/window.py KERNEL_VARIANTS``);
    ``gate`` names the legacy env knob that forces/excludes the row;
    ``oracle`` keys :data:`ORACLES` — rows without a registered oracle
    exist (observability) but can never be tuner-pinned."""

    name: str
    stage: str                      # mask | flush | merge | gate
    backends: tuple = ("*",)        # "tpu" | "host" (non-TPU) | "*"
    d_min: int = 1
    d_max: int | None = None        # inclusive; None = unbounded
    traced_ok: bool = True          # legal under a jax tracer
    mesh_ok: bool = False           # legal when a device mesh is attached
    gate: str | None = None         # controlling env knob, if any
    oracle: str | None = None       # byte-identity oracle id (ORACLES key)
    knobs: tuple = ()               # tuner-movable knobs scoped to the row
    doc: str = ""


# Byte-identity oracles: id -> how interchangeability with the row's
# sibling candidates is proven. The tuner's hard rule: it may only pin or
# re-knob rows whose oracle id appears here (audit-plane verifiable).
ORACLES: dict[str, str] = {
    "host_oracle": (
        "exact NumPy double-loop skyline (tests/conftest host oracle); "
        "every mask/flush variant's survivor set is asserted equal in "
        "tests/test_cascade_table.py and the sampled audit plane"
    ),
    "merge_digest": (
        "published-state digest equality across cache/delta/tree/flat "
        "merge paths (merge law + stable compaction order; "
        "scripts/obs_smoke.sh digest legs, tests/test_merge_tree.py)"
    ),
    "prune_identity": (
        "witness-dominance soundness: a pruned partition/chip/host "
        "contributes no skyline point, so pruned and unpruned merges "
        "publish identical bytes (RUNBOOK §2g/§2p; A/B digest checks in "
        "benchmarks and obs_smoke)"
    ),
}


TABLE: tuple[CascadeRow, ...] = (
    # -- mask stage (dispatch.skyline_mask_auto) ---------------------------
    CascadeRow(
        "mask_sweep", "mask", d_max=2, oracle="host_oracle",
        doc="d<=2 sort + prefix-min sweep; unconditional, every backend",
    ),
    CascadeRow(
        "mask_pallas", "mask", backends=("tpu",), d_min=3,
        oracle="host_oracle",
        doc="quadratic Pallas sum-sorted tiles (TPU default kernel)",
    ),
    CascadeRow(
        "mask_rank_pallas", "mask", backends=("tpu",), d_min=3,
        gate="SKYLINE_RANK_CASCADE", oracle="host_oracle",
        doc="Pallas dense-rank cascade; replaces mask_pallas when forced",
    ),
    CascadeRow(
        "mask_device_cascade", "mask", d_min=3,
        gate="SKYLINE_DEVICE_CASCADE", oracle="host_oracle",
        doc="device sorted dominance cascade; jit-safe, all backends",
    ),
    CascadeRow(
        "sorted_sfs_mask", "mask", backends=("host",), d_min=3,
        traced_ok=False, gate="SKYLINE_SORTED_SFS", oracle="host_oracle",
        doc="host sorted-order SFS cascade; concrete non-TPU arrays only",
    ),
    CascadeRow(
        "mask_scan", "mask", backends=("host",), d_min=3,
        oracle="host_oracle",
        doc="lax.scan dominance kernel; the non-TPU device fallback",
    ),
    # -- flush stage (PartitionSet._choose_lazy_path) ----------------------
    CascadeRow(
        "flush_sorted_sfs", "flush", backends=("host",), traced_ok=False,
        gate="SKYLINE_SORTED_SFS", oracle="host_oracle",
        doc="whole lazy flush via the host sorted cascade",
    ),
    CascadeRow(
        "flush_device_cascade", "flush", gate="SKYLINE_DEVICE_CASCADE",
        oracle="host_oracle",
        doc="whole lazy flush via the device sorted cascade; candidates "
            "only when the host cascade is out of play (TPU or sorted=off)",
    ),
    CascadeRow(
        "flush_sfs_vmapped", "flush", mesh_ok=True, oracle="host_oracle",
        doc="one vmapped SFS round per flush level (balanced loads)",
    ),
    CascadeRow(
        "flush_sfs_sequential", "flush", oracle="host_oracle",
        doc="per-partition SFS rounds (routing skew)",
    ),
    # -- merge stage (global_merge_launch path) ----------------------------
    CascadeRow(
        "merge_cache_hit", "merge", gate="SKYLINE_MERGE_CACHE",
        oracle="merge_digest",
        doc="epoch-keyed exact cache hit: zero kernel launches",
    ),
    CascadeRow(
        "merge_tree_delta", "merge", d_min=3, gate="SKYLINE_MERGE_TREE",
        oracle="merge_digest", knobs=("SKYLINE_DELTA_CUTOFF",),
        doc="cached_global ∪ dirty partitions up the pruned tree",
    ),
    CascadeRow(
        "merge_delta", "merge", gate="SKYLINE_MERGE_CACHE",
        oracle="merge_digest", knobs=("SKYLINE_DELTA_CUTOFF",),
        doc="flat cached_global ∪ dirty merge below the cutoff",
    ),
    CascadeRow(
        "merge_tree", "merge", d_min=3, gate="SKYLINE_MERGE_TREE",
        oracle="merge_digest", knobs=("SKYLINE_MERGE_PRUNE",),
        doc="pruned tournament tree over all live partitions",
    ),
    CascadeRow(
        "merge_flat", "merge", mesh_ok=True, oracle="merge_digest",
        doc="single O(U²) union pass; the unconditional fallback",
    ),
    # -- prune/prefilter gates ---------------------------------------------
    CascadeRow(
        "partition_prune", "gate", d_min=3, gate="SKYLINE_MERGE_PRUNE",
        oracle="prune_identity", knobs=("SKYLINE_MERGE_PRUNE",),
        doc="O(P²·d) witness prefilter ahead of the tree merge",
    ),
    CascadeRow(
        "chip_prune", "gate", gate="SKYLINE_CHIP_PRUNE", mesh_ok=True,
        oracle="prune_identity", knobs=("SKYLINE_CHIP_PRUNE",),
        doc="chip-level witness prefilter in the sharded two-level merge",
    ),
    CascadeRow(
        "host_prune", "gate", gate="SKYLINE_CLUSTER_HOST_PRUNE",
        mesh_ok=True, oracle="prune_identity",
        knobs=("SKYLINE_CLUSTER_HOST_PRUNE",),
        doc="host-level witness prefilter in the cluster merge",
    ),
    CascadeRow(
        "flush_prefilter", "gate", d_min=3,
        gate="SKYLINE_FLUSH_PREFILTER", oracle="prune_identity",
        knobs=("SKYLINE_FLUSH_PREFILTER",),
        doc="quantized grid prefilter ahead of the flush merge",
    ),
)

ROW_BY_NAME: dict[str, CascadeRow] = {r.name: r for r in TABLE}

# every knob any row declares tunable — the only names set_override accepts
TUNABLE_KNOBS: frozenset[str] = frozenset(
    k for r in TABLE for k in r.knobs
)

_lock = threading.Lock()
_overrides: dict[str, str] = {}        # guarded-by: _lock
_pins: dict[tuple, str] = {}           # guarded-by: _lock


def _env_pinned(name: str) -> bool:
    """True when the operator exported an explicit value for ``name`` —
    explicit env always beats a tuner override, checked at READ time so
    a mid-run export wins without a restart."""
    v = os.environ.get(name)  # lint: allow-raw-env
    return v is not None and v != ""


_BACKEND: str | None = None


def _backend() -> str:
    """Pin-key backend name — the SAME vocabulary as KernelProfiler
    signatures (``jax.default_backend()``: "cpu"/"tpu"/...), so a pin the
    tuner learned from profiler rows is found again at resolve time. The
    row-applicability ``backends`` field keeps its own coarser
    "tpu"/"host" vocabulary."""
    global _BACKEND
    if _BACKEND is None:
        try:
            import jax

            _BACKEND = jax.default_backend()
        except Exception:
            _BACKEND = "tpu" if on_tpu() else "host"
    return _BACKEND


# -- tuner override surface ------------------------------------------------

def set_override(name: str, value) -> bool:
    """Install a table-scoped knob override. Refused (False) for knobs no
    row declares tunable and for env-pinned knobs — the controller can
    only move levers the table scopes and the operator left floating."""
    if name not in TUNABLE_KNOBS or _env_pinned(name):
        return False
    with _lock:
        _overrides[name] = str(value)
    return True


def clear_override(name: str) -> None:
    with _lock:
        _overrides.pop(name, None)


def override(name: str) -> str | None:
    with _lock:
        return _overrides.get(name)


def overrides_doc() -> dict[str, str]:
    with _lock:
        return dict(_overrides)


def _eff_bool(name: str | None, legacy: bool) -> bool:
    if name is None or _env_pinned(name):
        return legacy
    ov = override(name)
    if ov is None:
        return legacy
    return ov.strip().lower() in ("1", "true", "on", "yes")


def _eff_float(name: str, legacy: float) -> float:
    if _env_pinned(name):
        return legacy
    ov = override(name)
    if ov is None:
        return legacy
    try:
        return float(ov)
    except ValueError:
        return legacy


# -- tuner pin surface -----------------------------------------------------

def pin(stage: str, variant: str, d: int, n: int, mp: bool = False,
        backend: str | None = None) -> bool:
    """Pin a learned winner for (stage, d, N-bucket, backend, mp). The
    audit-plane hard rule lives here: only rows with a REGISTERED
    byte-identity oracle are pinnable; anything else is refused."""
    row = ROW_BY_NAME.get(variant)
    if row is None or row.stage != stage:
        return False
    if row.oracle not in ORACLES:
        return False
    key = (stage, int(d), n_bucket(n), backend or _backend(), bool(mp))
    with _lock:
        _pins[key] = variant
    return True


def unpin(stage: str, d: int, n: int, mp: bool = False,
          backend: str | None = None) -> None:
    key = (stage, int(d), n_bucket(n), backend or _backend(), bool(mp))
    with _lock:
        _pins.pop(key, None)


def clear_pins(stage: str | None = None) -> None:
    with _lock:
        if stage is None:
            _pins.clear()
        else:
            for k in [k for k in _pins if k[0] == stage]:
                del _pins[k]


def pinned(stage: str, d: int, n: int, mp: bool = False) -> str | None:
    key = (stage, int(d), n_bucket(n), _backend(), bool(mp))
    with _lock:
        return _pins.get(key)


def pins_doc() -> list[dict]:
    with _lock:
        items = list(_pins.items())
    return [
        {"stage": k[0], "d": k[1], "n_bucket": k[2], "backend": k[3],
         "mp": k[4], "variant": v}
        for k, v in sorted(items)
    ]


# -- stage resolution (the five legacy dispatch sites) ---------------------

def resolve_mask(d: int, n: int, concrete: bool, profiler,
                 mp: bool = False) -> tuple[str, bool]:
    """The mask-stage row for one ``skyline_mask_auto`` call. Returns
    ``(variant, record)`` — ``record`` reproduces the legacy recording
    discipline exactly: auto races over concrete arrays (and the forced
    host cascade) record under the chooser profiler, forced device paths
    and traced calls do not."""
    if d <= 2:
        return "mask_sweep", False
    dc = device_cascade_mode()
    if on_tpu():
        dev = "mask_rank_pallas" if gate("mask_rank_pallas") else "mask_pallas"
        if dc == "off":
            return dev, False
        if dc == "on":
            return "mask_device_cascade", False
        if concrete:
            p = pinned("mask", d, n, mp)
            if p in (dev, "mask_device_cascade"):
                return p, True
            return (
                choose_variant(
                    profiler, (dev, "mask_device_cascade"), d, n, mp
                ),
                True,
            )
        # traced: nothing can record under a tracer, so the cascade only
        # swaps in on existing measured evidence for BOTH candidates
        if profiler is not None:
            e_dev = profiler.ema_ms(dev, d, n, mp)
            e_dc = profiler.ema_ms("mask_device_cascade", d, n, mp)
            if e_dev is not None and e_dc is not None and e_dc < e_dev:
                return "mask_device_cascade", False
        return dev, False
    mode = sorted_sfs_mode()
    if not concrete:
        if dc == "on":
            return "mask_device_cascade", False
        return "mask_scan", False
    if mode == "on":
        return "sorted_sfs_mask", True
    if mode != "off" and dc == "off":
        # the historical two-way host race (pre-device-cascade)
        cands = ("sorted_sfs_mask", "mask_scan")
        p = pinned("mask", d, n)
        if p in cands:
            return p, True
        return choose_variant(profiler, cands, d, n), True
    if dc == "on":
        return "mask_device_cascade", False
    if mode == "off" and dc == "off":
        return "mask_scan", False
    cands = ()
    if mode != "off":
        cands += ("sorted_sfs_mask",)
    cands += ("mask_scan", "mask_device_cascade")
    p = pinned("mask", d, n)
    if p in cands:
        return p, True
    return choose_variant(profiler, cands, d, n), True


def flush_chooser_active(meshed: bool) -> bool:
    """Whether any alternative flush row is in play for this set — the
    condition under which the caller must own a chooser profiler before
    calling :func:`resolve_flush` (legacy lazy-creation contract)."""
    if meshed:
        return False
    mode = "off" if on_tpu() else sorted_sfs_mode()
    return not (mode == "off" and device_cascade_mode() == "off")


def resolve_flush(device_variant: str, d: int, total_rows: int,
                  meshed: bool, profiler) -> str:
    """The flush-stage path for one lazy flush: ``"sorted_sfs"``,
    ``"device_cascade"``, or the device SFS ``device_variant``. The
    device cascade joins the race only when the host cascade is OUT of
    play (TPU or sorted=off) — the PR 18 scoping that keeps fresh host
    engines from paying a losing exploration flush."""
    if meshed:
        return device_variant
    mode = "off" if on_tpu() else sorted_sfs_mode()
    dc = device_cascade_mode()
    if mode == "off" and dc == "off":
        return device_variant
    if mode == "on":
        return "sorted_sfs"
    if dc == "on":
        return "device_cascade"
    cands = []
    if mode != "off":
        cands.append("flush_sorted_sfs")
    cands.append("flush_sfs_" + device_variant)
    if dc != "off" and mode == "off":
        cands.append("flush_device_cascade")
    p = pinned("flush", d, total_rows)
    if p in cands:
        chosen = p
    else:
        chosen = choose_variant(profiler, tuple(cands), d, total_rows)
    if chosen == "flush_sorted_sfs":
        return "sorted_sfs"
    if chosen == "flush_device_cascade":
        return "device_cascade"
    return device_variant


def merge_cache_on(meshed: bool) -> bool:
    """Cache-row applicability for this merge (meshed sets never cache)."""
    return (not meshed) and _eff_bool(
        "SKYLINE_MERGE_CACHE", merge_cache_enabled()
    )


def delta_cutoff() -> float:
    """Effective delta-merge dirty-fraction cutoff (tuner-movable)."""
    return _eff_float("SKYLINE_DELTA_CUTOFF", delta_dirty_cutoff())


def delta_applies(dirty_fraction: float) -> bool:
    return 0.0 < dirty_fraction <= delta_cutoff()


def merge_tree_on(meshed: bool, d: int) -> bool:
    return (not meshed) and d > 2 and _eff_bool(
        "SKYLINE_MERGE_TREE", merge_tree_enabled()
    )


def merge_path(use_tree: bool, delta: bool) -> str:
    """The merge-stage row name for one launch (cache_hit handled by the
    caller before any kernel work)."""
    return ("tree_delta" if delta and use_tree
            else "delta" if delta
            else "tree" if use_tree else "flat")


_GATE_LEGACY = {
    "mask_rank_pallas": rank_cascade,
    "partition_prune": merge_prune_enabled,
    "chip_prune": chip_prune_enabled,
    "host_prune": host_prune_enabled,
    "flush_prefilter": flush_prefilter_enabled,
}


def gate(name: str) -> bool:
    """Effective state of a boolean gate row (legacy env knob, then the
    tuner override when the env left it floating)."""
    row = ROW_BY_NAME[name]
    return _eff_bool(row.gate, _GATE_LEGACY[name]())


def applies(name: str, d: int | None = None, meshed: bool = False) -> bool:
    """Gate state AND the row's declared applicability — the one-call
    form for sites that used to inline ``mesh is None and dims > 2 and
    <knob>()`` (e.g. the flush grid prefilter)."""
    row = ROW_BY_NAME[name]
    if meshed and not row.mesh_ok:
        return False
    if d is not None:
        if d < row.d_min:
            return False
        if row.d_max is not None and d > row.d_max:
            return False
    return gate(name)


def table_doc() -> dict:
    """The ``GET /dispatch`` table block: every row with its declared
    applicability, the oracle registry, and the live tuner surfaces."""
    rows = []
    for r in TABLE:
        rows.append({
            "name": r.name,
            "stage": r.stage,
            "backends": list(r.backends),
            "d_min": r.d_min,
            "d_max": r.d_max,
            "traced_ok": r.traced_ok,
            "mesh_ok": r.mesh_ok,
            "gate": r.gate,
            "oracle": r.oracle,
            "knobs": list(r.knobs),
            "doc": r.doc,
        })
    return {
        "backend": _backend(),
        "rows": rows,
        "oracles": dict(ORACLES),
        "pins": pins_doc(),
        "overrides": overrides_doc(),
        "effective": {
            "merge_cache": merge_cache_on(False),
            "merge_tree_d4": merge_tree_on(False, 4),
            "delta_cutoff": delta_cutoff(),
            "partition_prune": gate("partition_prune"),
            "chip_prune": gate("chip_prune"),
            "host_prune": gate("host_prune"),
            "flush_prefilter": gate("flush_prefilter"),
        },
    }
