"""SFS (sort-filter-skyline) append rounds — the framework's fastest exact
skyline machinery for windows available in full.

Under minimization, ``a`` dominates ``b`` implies ``sum(a) < sum(b)``; after
sorting a window by coordinate sum ascending and streaming blocks in order,
the skyline buffer becomes APPEND-ONLY: every block survivor is globally
non-dominated (nothing later can dominate it), so there is no buffer
re-pruning and no re-compaction — one forward pass of O(N·S) dominance work.
This replaces the reference's tuple-at-a-time BNL loop
(SkylineLocalProcessor.processBuffer, FlinkSkyline.java:417-444).

These are pure device kernels (ops layer); the stateful streaming owner is
``stream.batched.PartitionSet`` (lazy flush policy), and the single-set
library form is ``ops.block_skyline.skyline_large``.

Host sibling: ``ops.sorted_sfs`` runs the same sum-sorted append scan in
NumPy with a dedup front end and exact in-block tiles for the equal-sum
band — byte-identical appends (same pre-sorted rows, same order, selection
only). On non-TPU backends the lazy flush picks between these rounds and
the host cascade per (d, N, backend) signature from measured profiler wall
data (``dispatch.choose_variant``; RUNBOOK §2m).

The jits donate the ``sky`` buffer so each append round updates the
full-capacity buffer in place instead of copying it (64 MB/round at the
north-star window; donation is a no-op with a warning on CPU, filtered in
tests/conftest.py). Callers must treat the passed-in buffer as consumed —
every call site reassigns ``sky, counts = sfs_*(sky, counts, ...)``. The
count carries are NOT donated: they are 4-byte scalars, and callers keep
references to earlier rounds' counts (``skyline_large``'s lag-2 reads)
that donation would invalidate.
"""

from __future__ import annotations

import functools
import jax
import jax.numpy as jnp
from jax import lax

from skyline_tpu.ops.dispatch import on_tpu
from skyline_tpu.ops.dominance import (
    compact,
    dominated_by,
    skyline_mask,
    strictly_dominated_bf16,
)

# Dominator-prefix length for the row-level bf16 pre-drop (mixed-precision
# stage 2): block rows certainly strictly-dominated by one of the first
# _MP_PREFIX skyline rows are dropped (masked to +inf) before the exact
# kernels. Sum-sorted skylines put the strongest dominators first, so a
# short prefix catches most dominated rows at O(B·prefix) cost — the full
# exact pass over survivors keeps the result bit-identical regardless.
_MP_PREFIX = 512


def pallas_interpret() -> bool:
    """Read lazily (at trace time, not import time): set
    ``SKYLINE_PALLAS_INTERPRET=1`` to run the Pallas kernels in interpret
    mode on CPU — how ``dryrun_multichip`` validates the
    shard_map-of-pallas_call lowering without TPU hardware. Evaluated when a
    kernel first traces; already-compiled executables are unaffected by
    later env changes."""
    from skyline_tpu.analysis.registry import env_bool

    return env_bool("SKYLINE_PALLAS_INTERPRET", False)


def sfs_round_core(sky, count, block, bvalid, active, use_pallas, interp, mp=False):
    """One SFS append round for one partition.

    sky: (cap, d) buffer whose first ``count`` rows are a skyline; block:
    (B, d) sum-sorted ascending (invalid rows padded +inf at the end), with
    all sums >= any previously appended block's in this SFS pass. Appends
    the block's survivors at ``count``. ``active`` (static) bounds the
    dominator prefix actually compared against — the capacity bucket of the
    current max count, so early rounds don't pay full-capacity passes.

    ``mp`` (static) enables the mixed-precision stage-2 pass: a bf16 margin
    pre-drop of block rows certainly strictly-dominated by a skyline prefix
    row (counted in the third return), plus the in-kernel bf16 first pass
    of the Pallas tri-kernels. Bit-exact vs ``mp=False``: a certified drop
    implies the exact sky-vs-block pass drops the row too, and any block
    row it would itself have pruned is strictly dominated by the same sky
    row (transitivity), so the survivor set and the stable compact order
    are unchanged. Returns ``(sky, count, resolved)``; ``resolved`` is the
    int32 count of bf16-certified drops (0 when ``mp=False``).

    Caller guarantees count + B <= cap (the compacted block writes B slots;
    rows past the survivor count are +inf padding landing on virgin rows).
    """
    cap, d = sky.shape
    sky_act = lax.slice(sky, (0, 0), (active, d))
    sky_ok = jnp.arange(active) < count
    resolved = jnp.zeros((), dtype=jnp.int32)
    if mp:
        limit = min(active, _MP_PREFIX)
        pre = strictly_dominated_bf16(
            block,
            lax.slice(sky, (0, 0), (limit, d)),
            jnp.arange(limit) < count,
        )
        pre = pre & bvalid
        resolved = jnp.sum(pre, dtype=jnp.int32)
        bvalid = bvalid & ~pre
        # +inf'd rows stay sum-sort-compatible for the triangular skip (a
        # replaced row only moves UP in sum, and its own column's verdict
        # is masked out by bvalid)
        block = jnp.where(bvalid[:, None], block, jnp.inf)
    if use_pallas:
        from skyline_tpu.ops.pallas_dominance import (
            dominated_by_any_pallas,
            dominated_by_pallas,
        )

        block_t = block.T
        keep = bvalid & ~dominated_by_any_pallas(
            block_t, bvalid, triangular=True, interpret=interp, mp=mp
        )
        keep = keep & ~dominated_by_pallas(
            sky_act.T, sky_ok, block_t, interpret=interp, mp=mp
        )
    else:
        keep = skyline_mask(block, bvalid)
        keep = keep & ~dominated_by(block, sky_act, x_valid=sky_ok)
    vals, _, m = compact(block, keep, block.shape[0])
    sky = lax.dynamic_update_slice(sky, vals, (count, 0))
    return sky, count + m, resolved


@functools.partial(
    jax.jit, static_argnames=("active", "mp"), donate_argnums=(0,)
)
def sfs_round(sky, counts, blocks, bvalids, active: int, mp: bool = False):
    """Vmapped SFS round over all partitions: sky (P, cap, d), counts (P,)
    int32, blocks (P, B, d), bvalids (P, B) -> (sky', counts', resolved
    (P,)). One device launch for the whole set — right when partitions
    carry comparable row counts (every vmap lane computes the full
    (B x active) passes whether its block is real or padding; see
    ``sfs_round_single`` for the skewed case). ``mp`` (static) threads the
    mixed-precision pass — a jit cache key, so flipping the env gate
    really switches executables."""
    use_pallas = on_tpu()
    interp = pallas_interpret()

    def core(s, c, b, bv):
        return sfs_round_core(s, c, b, bv, active, use_pallas, interp, mp)

    return jax.vmap(core)(sky, counts, blocks, bvalids)


@functools.partial(
    jax.jit, static_argnames=("active", "mp"), donate_argnums=(0,)
)
def sfs_round_single(sky_p, count, block, bvalid, active: int, mp: bool = False):
    """One partition's SFS round without the vmap lane dimension: sky_p
    (cap, d), count () int32, block (B, d), bvalid (B,). Under routing skew
    (one or two partitions holding most of the stream — mr-angle at 8D
    anti-correlated routes ~96% of rows to 2 of 8 partitions) the vmapped
    round pays P lanes of (B x active) work for one real lane; processing
    the heavy partitions individually costs exactly their own rows.
    Returns (sky', count', resolved)."""
    return sfs_round_core(
        sky_p, count, block, bvalid, active, on_tpu(), pallas_interpret(), mp
    )


def sfs_cleanup_core(s, c, old_c, old_active, active, use_pallas, interp):
    """One partition's old-vs-new prune after SFS rounds on non-empty
    initial state: old rows (prefix ``old_c``) may be dominated by newly
    appended rows (guaranteed non-dominated among themselves and not
    dominated BY the old rows); prune and re-compact. Returns
    (vals (cap, d), count)."""
    cap, d = s.shape
    act = lax.slice(s, (0, 0), (active, d))
    new_ok = (jnp.arange(active) >= old_c) & (jnp.arange(active) < c)
    old = lax.slice(s, (0, 0), (old_active, d))
    if use_pallas:
        from skyline_tpu.ops.pallas_dominance import dominated_by_pallas

        old_dom = dominated_by_pallas(act.T, new_ok, old.T, interpret=interp)
    else:
        old_dom = dominated_by(old, act, x_valid=new_ok)
    old_keep = (jnp.arange(old_active) < old_c) & ~old_dom
    keep = jnp.zeros((cap,), dtype=bool)
    keep = keep.at[:active].set(new_ok)
    keep = keep.at[:old_active].set(old_keep | new_ok[:old_active])
    vals, _, cnt = compact(s, keep, cap)
    return vals, cnt.astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("old_active", "active"),
    donate_argnums=(0,),
)
def sfs_cleanup(sky, counts, old_counts, old_active: int, active: int):
    """Vmapped ``sfs_cleanup_core`` over all partitions.
    ``old_active``/``active`` (static) are the capacity buckets of the old
    and final max counts — dominator and victim sets are sliced to them so
    a shrunken skyline in a grown buffer never pays full-capacity passes.
    Returns (sky', counts')."""
    use_pallas = on_tpu()
    interp = pallas_interpret()

    def core(s, c, old_c):
        return sfs_cleanup_core(
            s, c, old_c, old_active, active, use_pallas, interp
        )

    vals, cnt = jax.vmap(core)(sky, counts, old_counts)
    return vals, cnt
