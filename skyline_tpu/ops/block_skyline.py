"""Blockwise / large-window skyline computation.

Two tiers above the dense tile kernels in ``dominance.py``:

1. ``skyline_mask_blocked`` — fully jitted, static-shape, nested-``lax.scan``
   over (column-block, row-block) tiles with a sum-sort triangular pruning:
   under minimization, ``a`` dominates ``b`` implies ``sum(a) < sum(b)``, so
   after sorting by coordinate sum only earlier blocks can dominate later
   ones. Used for per-shard local skylines on the mesh (N up to ~10^5).

2. ``skyline_large`` — sort-filter-skyline (SFS) for full-size windows
   (N ~ 10^6): sort by sum ascending, stream blocks through the device, and
   maintain an append-only global-skyline buffer on device. Because
   dominators always have strictly smaller sums, every point that survives
   its block-prune is *globally* non-dominated and the buffer never needs
   re-pruning. Host control flow issues one async round per block
   (``ops.sfs.sfs_round_single`` — the same kernel the streaming engine's
   lazy flush policy uses for skewed partitions), tightening the dominator
   bound from lag-2 count reads that never stall the dispatch pipeline;
   this single-set form is the library op and the microbench subject
   (artifacts/kernels_*.json).

This replaces the reference's tuple-at-a-time BNL (FlinkSkyline.java:417-444),
whose O(|buffer| x |skyline|) pointer-chasing loop is the system's documented
hot loop (SURVEY.md §3.2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from skyline_tpu.ops.dominance import (
    PAD_VALUE,
    dominated_by,
    skyline_mask,
    strictly_dominated_bf16,
)
from skyline_tpu.utils.buckets import next_pow2

# Dominator-prefix length for the bf16 margin pre-pass of the scan
# fallbacks (mirrors ops/sfs._MP_PREFIX): victims certified strictly
# dominated by one of the first _MP_PREFIX dominator rows are final before
# the chunk scan runs, and their sums drop out of the victim_max bound so
# more dominator chunks clear the sum-skip. Certification is a proof of
# f32 dominance (ops/dominance.strictly_dominated_bf16), so OR-ing it into
# the scan verdict is bit-exact.
_MP_PREFIX = 512


def _sum_sort(x: jax.Array, valid: jax.Array):
    """Sort rows by coordinate sum ascending, invalid rows last.

    Returns (x_sorted, valid_sorted, inverse_permutation).
    """
    keys = jnp.where(valid, jnp.sum(x, axis=-1), jnp.inf)
    order = jnp.argsort(keys, stable=True)
    inv = jnp.argsort(order, stable=True)
    return x[order], valid[order], inv


@functools.partial(jax.jit, static_argnames=("block",))
def skyline_mask_blocked(x: jax.Array, valid: jax.Array | None = None, block: int = 2048):
    """Survivor mask over (N, d) points, tiled in ``block``-row chunks.

    Semantically identical to ``skyline_mask`` but never materializes more
    than a (block, block) pairwise tile, so it scales to N ~ 10^5 under jit.
    N is padded up to a multiple of ``block`` internally; the returned mask
    is in the caller's original row order.
    """
    n, d = x.shape
    if valid is None:
        valid = jnp.ones((n,), dtype=bool)
    nb = -(-n // block)  # ceil
    padded = nb * block
    if padded != n:
        pad_x = jnp.full((padded - n, d), PAD_VALUE, dtype=x.dtype)
        x = jnp.concatenate([x, pad_x], axis=0)
        valid = jnp.concatenate([valid, jnp.zeros((padded - n,), dtype=bool)], axis=0)

    xs, vs, inv = _sum_sort(x, valid)
    xb = xs.reshape(nb, block, d)
    vb = vs.reshape(nb, block)

    # Phase A: intra-block survivor masks, sequential over blocks to bound
    # peak memory at one (block, block) tile.
    mask_a = lax.map(lambda args: skyline_mask(args[0], args[1]), (xb, vb))

    # Phase B: cross-block triangular prune. Only blocks i <= j can hold
    # dominators of block j (sum-sorted). Phase-A survivors suffice as
    # dominators: a phase-A-dominated point's dominator also dominates
    # whatever it dominated (transitivity).
    block_ids = jnp.arange(nb)

    def col_step(_, j):
        yj = xb[j]

        def row_step(dom_j, i):
            # lax.cond genuinely skips the tile at runtime (the scan is not
            # vmapped), so the triangular prune halves the pairwise work.
            dom_j = lax.cond(
                i <= j,
                lambda d: d | dominated_by(yj, xb[i], x_valid=mask_a[i]),
                lambda d: d,
                dom_j,
            )
            return dom_j, None

        dom_j0 = jnp.zeros((block,), dtype=bool)
        dom_j, _ = lax.scan(row_step, dom_j0, block_ids)
        return None, mask_a[j] & ~dom_j

    _, keep = lax.scan(col_step, None, block_ids)
    keep = keep.reshape(padded)[inv]
    return keep[:n]


@functools.partial(jax.jit, static_argnames=("chunk", "mp"))
def skyline_mask_scan(
    x: jax.Array,
    valid: jax.Array | None = None,
    chunk: int = 0,
    mp: bool = False,
):
    """Survivor mask via a LINEAR scan of dominator chunks against all columns.

    Same O(N^2 d) comparisons as the dense/blocked kernels but organized as
    ``nb`` sequential steps of one (chunk, N) tile each — an order of
    magnitude fewer dispatches than the (nb^2)-step nested scan in
    ``skyline_mask_blocked``, which is latency-bound on TPU for N ~ 10^5
    (see artifacts/kernels_tpu.json for the measured scan-vs-blocked-vs-
    Pallas table). Peak per-step memory is one (chunk, N) bool tile, so
    ``chunk`` shrinks automatically as N grows.

    ``mp`` (static) prepends the bf16 margin pass: rows certified strictly
    dominated by a short dominator prefix are final before the scan and
    leave the victim_max bound, so more chunks clear the sum-skip. The
    returned mask is bit-identical either way.
    """
    n, d = x.shape
    if valid is None:
        valid = jnp.ones((n,), dtype=bool)
    if chunk <= 0:
        # keep the per-step (chunk, N) tile around ~2^28 bools (~256 MB)
        chunk = max(256, min(4096, (1 << 28) // max(n, 1)))
    nb = -(-n // chunk)
    padded = nb * chunk
    if padded != n:
        pad_x = jnp.full((padded - n, d), PAD_VALUE, dtype=x.dtype)
        xp = jnp.concatenate([x, pad_x], axis=0)
        vp = jnp.concatenate([valid, jnp.zeros((padded - n,), dtype=bool)], axis=0)
    else:
        xp, vp = x, valid
    rows = xp.reshape(nb, chunk, d)
    rvalid = vp.reshape(nb, chunk)

    if mp:
        limit = min(padded, _MP_PREFIX)
        certified = vp & strictly_dominated_bf16(
            xp, xp[:limit], vp[:limit]
        )
    else:
        certified = jnp.zeros((padded,), dtype=bool)

    # Sum-bound chunk skip (same argument as pallas_dominance._tile_sum_skip:
    # f32 addition is monotone, so a dominator's sum never exceeds its
    # victim's). A chunk whose smallest valid-row sum beats every valid
    # point's sum cannot dominate anything; lax.cond genuinely skips the
    # (chunk, N) tile at runtime (the scan is not vmapped). All-padding
    # chunks — capacity-bucket overshoot — always skip. Skipped chunks leave
    # invalid positions undominated, which `& vp` masks identically.
    # Certified victims drop out of the bound: a chunk only able to
    # dominate them is skippable because their verdict is already final.
    sums = jnp.where(vp, jnp.sum(xp, axis=-1), jnp.inf)
    chunk_min = jnp.min(sums.reshape(nb, chunk), axis=1)
    victim_max = jnp.max(jnp.where(vp & ~certified, sums, -jnp.inf))

    def step(dom, blk):
        rx, rv, mn = blk
        dom = lax.cond(
            mn > victim_max,
            lambda d: d,
            lambda d: d | dominated_by(xp, rx, x_valid=rv),
            dom,
        )
        return dom, None

    dom0 = jnp.zeros((padded,), dtype=bool)
    dom, _ = lax.scan(step, dom0, (rows, rvalid, chunk_min))
    return (~(dom | certified) & vp)[:n]


@functools.partial(jax.jit, static_argnames=("block", "mp"))
def dominated_by_blocked(
    y: jax.Array,
    x: jax.Array,
    x_valid: jax.Array | None = None,
    block: int = 8192,
    y_valid: jax.Array | None = None,
    mp: bool = False,
) -> jax.Array:
    """Like ``dominated_by`` but scans dominator set ``x`` in ``block``-row
    chunks so the pairwise tile never exceeds (len(y), block). Used for the
    cross-shard prune in the global merge, where the gathered dominator set is
    P times a shard, and for the tournament-tree pair merges on CPU.

    Dominator chunks whose smallest valid-row sum exceeds the largest victim
    sum are skipped outright (sum-bound prune, see ``skyline_mask_scan``).
    Passing ``y_valid`` tightens that bound to valid victims only — then
    positions with ``y_valid`` False may be reported undominated where the
    dense op would say dominated; callers must mask the result by victim
    validity (every call site in this repo already does). ``mp`` (static)
    prepends the bf16 margin pass over a short dominator prefix; certified
    victims are final (OR-ed into the result) and leave the victim_max
    bound — bit-identical either way."""
    n, d = x.shape
    if y.shape[0] == 0:
        return jnp.zeros((0,), dtype=bool)
    if x_valid is None:
        x_valid = jnp.ones((n,), dtype=bool)
    if mp:
        limit = min(n, _MP_PREFIX)
        certified = strictly_dominated_bf16(y, x[:limit], x_valid[:limit])
        if y_valid is not None:
            certified = certified & y_valid
    else:
        certified = jnp.zeros((y.shape[0],), dtype=bool)
    nb = -(-n // block)
    padded = nb * block
    if padded != n:
        pad_x = jnp.full((padded - n, d), PAD_VALUE, dtype=x.dtype)
        x = jnp.concatenate([x, pad_x], axis=0)
        x_valid = jnp.concatenate(
            [x_valid, jnp.zeros((padded - n,), dtype=bool)], axis=0
        )
    xb = x.reshape(nb, block, d)
    vb = x_valid.reshape(nb, block)

    xsums = jnp.where(x_valid, jnp.sum(x, axis=-1), jnp.inf)
    chunk_min = jnp.min(xsums.reshape(nb, block), axis=1)
    ysums = jnp.sum(y, axis=-1)
    if y_valid is not None:
        ysums = jnp.where(y_valid, ysums, -jnp.inf)
    ysums = jnp.where(certified, -jnp.inf, ysums)
    victim_max = jnp.max(ysums)

    def step(dom, chunk):
        cx, cv, mn = chunk
        dom = lax.cond(
            mn > victim_max,
            lambda d: d,
            lambda d: d | dominated_by(y, cx, x_valid=cv),
            dom,
        )
        return dom, None

    dom0 = jnp.zeros((y.shape[0],), dtype=bool)
    dom, _ = lax.scan(step, dom0, (xb, vb, chunk_min))
    return dom | certified


@functools.partial(jax.jit, static_argnames=("out_cap",))
def _slice_front(sky, out_cap: int):
    return lax.slice(sky, (0, 0), (out_cap, sky.shape[1]))


def skyline_large(
    x: np.ndarray,
    block: int = 0,
    dense_threshold: int = 8192,
    mp: bool | None = None,
) -> np.ndarray:
    """Exact skyline of an (N, d) numpy window: host sum-sort, device-side
    append-only SFS rounds (``ops.sfs.sfs_round_single``, Pallas kernels on
    TPU), pipeline-friendly lag-2 count syncs.

    Sum-sorting guarantees appended points are final — no later point can
    dominate an earlier one — so the buffer is append-only and the total
    work is O(N * S) dominance tests (S = skyline size) instead of the BNL's
    pointer-chasing loop or the naive O(N^2). The per-round dominator prefix
    is re-tightened from LAG-2 count reads: before issuing round r the host
    reads the survivor count of round r-2 — work the device already
    finished while later rounds queued — so the dominator bucket tracks the
    true skyline size (O(N*(S+B)) total) without ever stalling the dispatch
    pipeline on a high-latency device link. The old per-block-synced XLA
    form measured 74 s on the 1M x 8D anti-correlated window
    (artifacts/kernels_tpu.json); this form runs the same kernels/shapes as
    the engine's SFS flush, which does that window's whole local phase in
    ~4.9 s (artifacts/bench_tpu.json phase_breakdown_ms) — the refreshed
    skyline_large row lands in kernels_tpu.json with the next TPU
    microbench run.

    ``block=0`` scales the block with N on TPU (the same heuristic as the
    streaming engine's skewed-partition path: fewer dispatches for big
    windows, block self-prune cost grows only linearly in B); on CPU it
    stays at 8192 so the dense (block x active) dominance mask stays
    bounded.

    ``mp=None`` reads ``SKYLINE_MIXED_PRECISION`` per call (host-side, so
    flipping the env really switches executables); True/False pin the
    bf16-first cascade on/off. The result is bit-identical either way.
    """
    from skyline_tpu.ops.dispatch import mixed_precision_enabled, on_tpu
    from skyline_tpu.ops.sfs import sfs_round_single

    if mp is None:
        mp = mixed_precision_enabled()
    x = np.ascontiguousarray(x, dtype=np.float32)
    n, d = x.shape
    if n == 0:
        return x
    if n <= dense_threshold:
        keep = np.asarray(skyline_mask(jnp.asarray(x)))
        return x[keep]

    order = np.argsort(x.sum(axis=1), kind="stable")
    xs = x[order]

    if block <= 0:
        if on_tpu():
            block = next_pow2(
                min(n, max(16384, min(n // 8, 65536))), min_cap=1024
            )
        else:
            block = 8192
    nb = -(-n // block)
    # worst case (nothing dominated) the append prefix reaches n, and the
    # final round writes a full block at that offset
    cap = next_pow2(n + block, min_cap=1024)
    sky = jnp.full((cap, d), jnp.inf, dtype=jnp.float32)
    count = jnp.zeros((), dtype=jnp.int32)

    counts = []  # per-round device count scalars, for the lag-2 reads
    for rnd in range(nb):
        blk = xs[rnd * block : (rnd + 1) * block]
        w = blk.shape[0]
        if w < block:
            blk = np.concatenate(
                [blk, np.full((block - w, d), np.inf, dtype=np.float32)],
                axis=0,
            )
        bvalid = np.arange(block) < w
        if rnd >= 2:
            # count entering this round <= count after round r-2 plus the
            # rows appended by round r-1; reading counts[rnd-2] waits only
            # for work two rounds deep, which has already drained
            ub = int(counts[rnd - 2]) + block
        else:
            ub = rnd * block  # rows streamed so far bound the count
        active = min(cap, next_pow2(max(ub, 1), min_cap=1024))
        sky, count, _ = sfs_round_single(
            sky, count, jnp.asarray(blk), jnp.asarray(bvalid), active, mp
        )
        counts.append(count)

    k = int(count)  # the final sync
    out_cap = min(cap, next_pow2(max(k, 1), min_cap=1024))
    return np.asarray(_slice_front(sky, out_cap))[:k].copy()

